"""Flow telemetry — the tenant X-ray (ISSUE 20).

ROADMAP item 2 wants per-tenant weighted fairness at the OSD op queue;
nothing below the client could previously say *which tenant* an op,
byte, engine batch or fsync belonged to — the WPQ/dmclock seats know
only three static classes. This registry is the sensor half of that
item, the instrument-then-fix pattern of PR 14 (store X-ray) and
PR 16 (dispatch X-ray) aimed at multi-tenancy. Three planes:

1. **End-to-end cost attribution.** Clients tag ops with a tenant/flow
   label; the objecter rides it on MOSDOp (tail-tolerant appended
   field, per-entry on the batched frames) and every daemon attributes
   its owned costs to the flow: ops and bytes in/out, data-plane stage
   waits (the PR-6 StageClock vocabulary), op-queue credit per
   WPQ/dmclock seat, engine flush occupancy + HBM-staged bytes (the
   flow's share of each FlushGroup), store txn bytes with an amortized
   fsync share, and per-flow p50/p99 with histogram exemplars into
   kept traces.

2. **Fairness + starvation.** Demand (submitted) vs served
   (completed) is accounted per windowed interval; a Jain's index over
   per-flow service ratios scores the cluster, and a starvation
   detector flags any flow whose queued demand was served below a
   floor ratio for N consecutive windows — the ``FLOW_STARVATION``
   health check (mgr/health.py) raises HEALTH_ERR off it, riding the
   existing bundle -> autopsy chain.

3. **SLO burn rates.** Declarative per-flow SLO targets (p99 ms +
   error budget): every completed op is good/bad against its flow's
   target, and the burn rate is error_rate/budget — >1.0 means the
   budget exhausts before the window does.

The registry is process-wide (``flows`` in the PerfCounters
collection) like the store/dispatch/dataplane registries; per-flow
side tables are bounded with drop counters. The off-switch is the
tracer/tuner escape-hatch contract: with ``flows_enabled=false`` (or
``CEPH_TPU_FLOWS=0``) nothing materializes — no registry, no TLS
writes, no wire labels — pinned by tests/test_flow_telemetry.py.
Telemetry faults never cost an op.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ceph_tpu.utils.config import g_conf
from ceph_tpu.utils.perf_counters import PerfCounters, collection

#: one-line glossary served by ``dump_flows`` and BASELINE.md
GLOSSARY = {
    "flow": "tenant/flow label a client stamped on the op ('' = "
            "unattributed: pre-flows peer or untagged client)",
    "queue_credit": "WPQ/dmclock seat grants consumed by the flow's "
                    "ops at the sharded op queue",
    "stage_wait": "data-plane stage seconds attributed to the flow "
                  "(StageClock vocabulary, utils/stage_clock)",
    "flush_share": "fractional FlushGroup occupancy: the flow's "
                   "byte share of each engine flush it rode",
    "fsync_share": "amortized fsyncs: each store barrier fsync is "
                   "split across flows by txn bytes in the window",
    "service_ratio": "served/demand ops inside one fairness window",
    "jain_index": "(sum x)^2 / (n * sum x^2) over per-flow service "
                  "ratios: 1.0 = perfectly fair, 1/n = one flow "
                  "eats everything",
    "starved": "queued demand served below the floor ratio for N "
               "consecutive windows (flow_starvation_floor/windows)",
    "burn_rate": "SLO error rate / error budget (>1.0 burns the "
                 "budget faster than the window)",
}

#: bounded side tables — a hostile label stream must not grow memory
_MAX_FLOWS = 64
#: per-flow latency ring for p50/p99 (nearest-rank over recent ops)
_LAT_RING = 512

_tls = threading.local()


def _percentile(values: list[float], pct: float) -> float:
    """Nearest-rank percentile (load_gen's convention)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    k = max(0, min(len(ordered) - 1,
                   int(round(pct / 100.0 * len(ordered) + 0.5)) - 1))
    return ordered[k]


def jain_index(shares: list[float]) -> float:
    """Jain's fairness index over non-negative allocations."""
    xs = [max(float(x), 0.0) for x in shares]
    n = len(xs)
    if not n:
        return 1.0
    s = sum(xs)
    sq = sum(x * x for x in xs)
    if sq <= 0.0:
        return 1.0
    return (s * s) / (n * sq)


class FlowTelemetry:
    """One per process, like the store/dispatch/dataplane registries
    (the MiniCluster's daemons share the process)."""

    def __init__(self, name: str = "flows") -> None:
        self.name = name
        self._lock = threading.Lock()
        perf = collection().get(name)
        if perf is None:
            perf = collection().create(name)
            self._declare(perf)
        self.perf = perf
        #: label -> per-flow accounting entry (bounded)
        self._flows: dict[str, dict] = {}
        self._flows_dropped = 0
        #: store-barrier amortization window: label -> txn bytes
        #: accumulated since the last fsync
        self._fsync_window: dict[str, int] = {}
        #: completed fairness windows retained for the dashboard
        self._windows: deque[dict] = deque(maxlen=32)

    @staticmethod
    def _declare(perf: PerfCounters) -> None:
        perf.add_u64_counter("ops", "client ops attributed to a flow")
        perf.add_u64_counter("bytes_in",
                             "payload bytes in attributed to a flow")
        perf.add_u64_counter("bytes_out",
                             "payload bytes out attributed to a flow")
        perf.add_u64_counter("unattributed_ops",
                             "client ops arriving without a flow "
                             "label (pre-flows peers, untagged "
                             "clients)")
        perf.add_u64_counter("unattributed_bytes",
                             "payload bytes riding unattributed ops")
        perf.add_u64_counter("queue_credit",
                             GLOSSARY["queue_credit"])
        perf.add_time_avg("stage_wait", GLOSSARY["stage_wait"])
        perf.add_u64_counter("engine_staged_bytes",
                             "HBM-staged bytes attributed to flows")
        perf.add_u64_counter("flush_groups",
                             "engine FlushGroups with attributed "
                             "occupancy shares")
        perf.add_u64_counter("store_txn_bytes",
                             "store transaction bytes attributed to "
                             "flows")
        perf.add_u64_counter("fsyncs",
                             "store barrier fsyncs amortized across "
                             "flows")
        perf.add_histogram("op_lat_ms",
                           "attributed op completion latency (ms); "
                           "exemplars link buckets to kept traces")
        perf.add_u64_counter("windows",
                             "fairness windows rolled")
        perf.add_u64_counter("starved_windows",
                             "per-flow windows scored starved "
                             "(queued demand, service below floor)")
        perf.add_u64_counter("slo_breaches",
                             "completed ops over their flow's SLO "
                             "target")

    # -- per-flow table -------------------------------------------------
    def _ensure(self, label: str) -> dict | None:
        """Caller holds self._lock."""
        ent = self._flows.get(label)
        if ent is None:
            if len(self._flows) >= _MAX_FLOWS:
                self._flows_dropped += 1
                return None
            ent = self._flows[label] = {
                "ops": 0, "bytes_in": 0, "bytes_out": 0,
                "lat_ring": deque(maxlen=_LAT_RING),
                "credit": {}, "stage_wait_s": {},
                "engine_staged_bytes": 0, "flush_share": 0.0,
                "store_txn_bytes": 0, "fsync_share": 0.0,
                "demand_ops": 0, "served_ops": 0,
                "demand_bytes": 0, "served_bytes": 0,
                "win_demand": 0, "win_served": 0,
                "starve_streak": 0, "windows_starved": 0,
                "slo": None,
            }
        return ent

    # -- plane 1: cost attribution --------------------------------------
    def note_op(self, label: str, bytes_in: int = 0) -> None:
        """Daemon admission: one client op arrived carrying ``label``
        ('' = unattributed) with ``bytes_in`` payload bytes."""
        if not label:
            self.perf.inc("unattributed_ops")
            if bytes_in:
                self.perf.inc("unattributed_bytes", int(bytes_in))
            return
        self.perf.inc("ops")
        if bytes_in:
            self.perf.inc("bytes_in", int(bytes_in))
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["ops"] += 1
                ent["bytes_in"] += int(bytes_in)

    def note_op_done(self, label: str, bytes_out: int = 0,
                     latency_s: float | None = None,
                     trace_id: str | None = None,
                     stages=None) -> None:
        """Daemon completion: bytes out, the op's latency into the
        per-flow ring + the exemplar histogram, the op's own stage
        durations (``stages``: a ``{stage: seconds}`` dict or the
        ``[(stage, seconds)]`` list StageClock.own_durations returns;
        repeated stages accumulate), and the SLO good/bad verdict."""
        if not label:
            if bytes_out:
                self.perf.inc("unattributed_bytes", int(bytes_out))
            return
        if bytes_out:
            self.perf.inc("bytes_out", int(bytes_out))
        lat_ms = None
        if latency_s is not None and latency_s >= 0:
            lat_ms = latency_s * 1e3
            self.perf.hinc("op_lat_ms", lat_ms, exemplar=trace_id)
        agg: dict[str, float] = {}
        if stages:
            items = stages.items() if isinstance(stages, dict) \
                else stages
            for stage, dt in items:
                if dt > 0:
                    agg[stage] = agg.get(stage, 0.0) + float(dt)
            total = sum(agg.values())
            if total > 0:
                self.perf.tinc("stage_wait", total)
        breached = False
        with self._lock:
            ent = self._ensure(label)
            if ent is None:
                return
            ent["bytes_out"] += int(bytes_out)
            if lat_ms is not None:
                ent["lat_ring"].append(lat_ms)
            if agg:
                sw = ent["stage_wait_s"]
                for stage, dt in agg.items():
                    sw[stage] = sw.get(stage, 0.0) + dt
            slo = ent["slo"]
            if slo is not None and lat_ms is not None:
                if lat_ms > slo["p99_ms"]:
                    slo["bad"] += 1
                    breached = True
                else:
                    slo["good"] += 1
        if breached:
            self.perf.inc("slo_breaches")

    def note_queue_credit(self, label: str, seat: str,
                          credit: int = 1) -> None:
        """The flow's op consumed ``credit`` grants of a WPQ/dmclock
        ``seat`` (qos class) at the sharded op queue."""
        self.perf.inc("queue_credit", int(credit))
        if not label:
            return
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["credit"][seat] = \
                    ent["credit"].get(seat, 0) + int(credit)

    def note_engine_staged(self, label: str, nbytes: int) -> None:
        """The flow staged ``nbytes`` into the device engine's HBM
        window (producer-thread seam, device_engine.stage_*)."""
        if not label or nbytes <= 0:
            return
        self.perf.inc("engine_staged_bytes", int(nbytes))
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["engine_staged_bytes"] += int(nbytes)

    def note_flush_group(self, shares: dict[str, int]) -> None:
        """One engine FlushGroup flushed; ``shares`` maps flow label
        -> bytes it contributed. Each flow's fractional occupancy of
        the group accumulates into ``flush_share``."""
        total = sum(v for v in shares.values() if v > 0)
        if total <= 0:
            return
        self.perf.inc("flush_groups")
        with self._lock:
            for label, nbytes in shares.items():
                if not label or nbytes <= 0:
                    continue
                ent = self._ensure(label)
                if ent is not None:
                    ent["flush_share"] += nbytes / total

    def note_store_txn(self, label: str, nbytes: int) -> None:
        """The flow queued ``nbytes`` of store transaction; also feeds
        the fsync amortization window (:meth:`note_fsync`)."""
        if nbytes <= 0:
            return
        if label:
            self.perf.inc("store_txn_bytes", int(nbytes))
        with self._lock:
            if label:
                ent = self._ensure(label)
                if ent is not None:
                    ent["store_txn_bytes"] += int(nbytes)
            self._fsync_window[label or ""] = \
                self._fsync_window.get(label or "", 0) + int(nbytes)

    def note_fsync(self) -> None:
        """One store barrier fsync: amortize it across the flows whose
        txn bytes rode the window since the last fsync, proportional
        to bytes (the group-commit accounting PR 15 landed)."""
        self.perf.inc("fsyncs")
        with self._lock:
            window = self._fsync_window
            self._fsync_window = {}
            total = sum(window.values())
            if total <= 0:
                return
            for label, nbytes in window.items():
                if not label:
                    continue
                ent = self._ensure(label)
                if ent is not None:
                    ent["fsync_share"] += nbytes / total

    # -- plane 2: fairness windows --------------------------------------
    def note_demand(self, label: str, ops: int = 1,
                    nbytes: int = 0) -> None:
        """Client-side submit intent: the flow wants ``ops`` served."""
        if not label:
            return
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["demand_ops"] += int(ops)
                ent["demand_bytes"] += int(nbytes)
                ent["win_demand"] += int(ops)

    def note_served(self, label: str, ops: int = 1,
                    nbytes: int = 0) -> None:
        """Client-side completion: ``ops`` of the flow's demand were
        actually served."""
        if not label:
            return
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["served_ops"] += int(ops)
                ent["served_bytes"] += int(nbytes)
                ent["win_served"] += int(ops)

    def roll_window(self) -> dict:
        """Close one fairness window: score each flow's service ratio,
        advance starvation streaks (queued demand served below the
        floor), and retain the window for the dashboard. Called by
        the load generator / mgr tick / tests — never implicitly, so
        the accounting is deterministic."""
        floor = float(g_conf()["flow_starvation_floor"])
        self.perf.inc("windows")
        starved_now = []
        rows = {}
        with self._lock:
            for label, ent in self._flows.items():
                demand, served = ent["win_demand"], ent["win_served"]
                if demand <= 0:
                    ent["starve_streak"] = 0
                    continue
                ratio = served / demand
                rows[label] = {"demand": demand, "served": served,
                               "ratio": round(ratio, 4)}
                if ratio < floor:
                    ent["starve_streak"] += 1
                    ent["windows_starved"] += 1
                    starved_now.append(label)
                else:
                    ent["starve_streak"] = 0
                ent["win_demand"] = ent["win_served"] = 0
            window = {"flows": rows, "starved": starved_now}
            self._windows.append(window)
        if starved_now:
            self.perf.inc("starved_windows", len(starved_now))
        return window

    def starved_flows(self) -> dict[str, int]:
        """label -> consecutive starved windows, for flows at or past
        the ``flow_starvation_windows`` threshold."""
        need = int(g_conf()["flow_starvation_windows"])
        with self._lock:
            return {label: ent["starve_streak"]
                    for label, ent in self._flows.items()
                    if ent["starve_streak"] >= max(need, 1)}

    def fairness(self) -> dict:
        """Cumulative demand-vs-served shares + the Jain's index over
        per-flow service ratios."""
        with self._lock:
            flows = {label: dict(demand_ops=ent["demand_ops"],
                                 served_ops=ent["served_ops"])
                     for label, ent in self._flows.items()
                     if ent["demand_ops"] or ent["served_ops"]}
        total_demand = sum(f["demand_ops"] for f in flows.values())
        total_served = sum(f["served_ops"] for f in flows.values())
        ratios = []
        out = {}
        for label, f in sorted(flows.items()):
            ratio = f["served_ops"] / f["demand_ops"] \
                if f["demand_ops"] else 0.0
            ratios.append(ratio)
            out[label] = {
                "demand_ops": f["demand_ops"],
                "served_ops": f["served_ops"],
                "service_ratio": round(ratio, 4),
                "demand_share": round(
                    f["demand_ops"] / total_demand, 4)
                if total_demand else 0.0,
                "served_share": round(
                    f["served_ops"] / total_served, 4)
                if total_served else 0.0,
            }
        return {"flows": out,
                "jain_index": round(jain_index(ratios), 4)
                if ratios else 1.0}

    def starvation_report(self) -> dict:
        conf = g_conf()
        return {"floor": float(conf["flow_starvation_floor"]),
                "windows_needed":
                    int(conf["flow_starvation_windows"]),
                "starved": self.starved_flows(),
                "recent_windows": list(self._windows)[-8:]}

    # -- plane 3: SLO burn ----------------------------------------------
    def set_slo(self, label: str, p99_ms: float,
                error_budget: float | None = None) -> None:
        """Declare the flow's SLO: completed ops over ``p99_ms`` are
        budget burn; ``error_budget`` is the tolerated bad fraction
        (default ``flow_slo_error_budget``)."""
        if not label or p99_ms <= 0:
            return
        budget = float(error_budget
                       if error_budget is not None
                       else g_conf()["flow_slo_error_budget"])
        with self._lock:
            ent = self._ensure(label)
            if ent is not None:
                ent["slo"] = {"p99_ms": float(p99_ms),
                              "budget": max(budget, 1e-9),
                              "good": 0, "bad": 0}

    def slo_table(self) -> dict:
        with self._lock:
            rows = {}
            for label, ent in self._flows.items():
                slo = ent["slo"]
                if slo is None:
                    continue
                total = slo["good"] + slo["bad"]
                err = slo["bad"] / total if total else 0.0
                rows[label] = {
                    "target_p99_ms": slo["p99_ms"],
                    "error_budget": slo["budget"],
                    "ops": total,
                    "breaches": slo["bad"],
                    "error_rate": round(err, 5),
                    "burn_rate": round(err / slo["budget"], 3),
                }
        return rows

    # -- views -----------------------------------------------------------
    def flow_table(self) -> dict:
        """Per-flow cost table — the ``dump_flows`` core."""
        with self._lock:
            out = {}
            for label, ent in sorted(self._flows.items()):
                lats = list(ent["lat_ring"])
                out[label] = {
                    "ops": ent["ops"],
                    "bytes_in": ent["bytes_in"],
                    "bytes_out": ent["bytes_out"],
                    "p50_ms": round(_percentile(lats, 50), 3),
                    "p99_ms": round(_percentile(lats, 99), 3),
                    "queue_credit": dict(ent["credit"]),
                    "stage_wait_ms": {
                        st: round(s * 1e3, 3)
                        for st, s in sorted(
                            ent["stage_wait_s"].items())},
                    "engine_staged_bytes":
                        ent["engine_staged_bytes"],
                    "flush_share": round(ent["flush_share"], 3),
                    "store_txn_bytes": ent["store_txn_bytes"],
                    "fsync_share": round(ent["fsync_share"], 3),
                    "demand_ops": ent["demand_ops"],
                    "served_ops": ent["served_ops"],
                    "starve_streak": ent["starve_streak"],
                    "windows_starved": ent["windows_starved"],
                }
            dropped = self._flows_dropped
        return {"flows": out, "flows_dropped": dropped}

    def attribution(self) -> dict:
        """Coverage: what share of ops/bytes carried a flow label —
        gap_report's ``--tenants`` honesty row (>=95% is the ISSUE-20
        acceptance bar on the CPU quick run)."""
        c = self.perf.dump()
        ops_attr = c["ops"]
        ops_total = ops_attr + c["unattributed_ops"]
        bytes_attr = c["bytes_in"] + c["bytes_out"]
        bytes_total = bytes_attr + c["unattributed_bytes"]
        with self._lock:
            by_flow = {
                label: {"ops": ent["ops"],
                        "bytes": ent["bytes_in"] + ent["bytes_out"]}
                for label, ent in sorted(self._flows.items())}
        for row in by_flow.values():
            row["ops_share"] = round(row["ops"] / ops_attr, 4) \
                if ops_attr else 0.0
            row["bytes_share"] = round(row["bytes"] / bytes_attr, 4) \
                if bytes_attr else 0.0
        return {
            "ops_attributed": ops_attr,
            "ops_total": ops_total,
            "ops_pct": round(100.0 * ops_attr / ops_total, 2)
            if ops_total else 100.0,
            "bytes_attributed": bytes_attr,
            "bytes_total": bytes_total,
            "bytes_pct": round(100.0 * bytes_attr / bytes_total, 2)
            if bytes_total else 100.0,
            "by_flow": by_flow,
        }

    def tenant_series(self) -> list[tuple[str, str, dict]]:
        """Per-tenant exposition rows for the prometheus layer:
        (metric suffix, prom type, {tenant: value}). Labels are raw
        here; utils/prometheus escapes them per the exposition spec."""
        with self._lock:
            flows = {label: (ent["ops"], ent["bytes_in"],
                             ent["bytes_out"])
                     for label, ent in self._flows.items()}
        fair = self.fairness()["flows"]
        slo = self.slo_table()
        return [
            ("ops_total", "counter",
             {t: v[0] for t, v in flows.items()}),
            ("bytes_in_total", "counter",
             {t: v[1] for t, v in flows.items()}),
            ("bytes_out_total", "counter",
             {t: v[2] for t, v in flows.items()}),
            ("served_share", "gauge",
             {t: row["served_share"] for t, row in fair.items()}),
            ("demand_share", "gauge",
             {t: row["demand_share"] for t, row in fair.items()}),
            ("slo_burn_rate", "gauge",
             {t: row["burn_rate"] for t, row in slo.items()}),
        ]

    def snapshot(self) -> dict:
        """Full JSON-able view (the ``dump_flows`` payload)."""
        return {"glossary": dict(GLOSSARY),
                "counters": self.perf.dump(),
                **self.flow_table(),
                "fairness": self.fairness(),
                "starvation": self.starvation_report(),
                "slo": self.slo_table(),
                "attribution": self.attribution()}

    def snapshot_brief(self) -> dict:
        """The bench metric-line brief: zero counters dropped."""
        c = self.perf.dump()
        out = {}
        for key in ("ops", "unattributed_ops", "queue_credit",
                    "fsyncs", "starved_windows", "slo_breaches"):
            if c[key]:
                out[key] = c[key]
        if self._flows:
            out["jain_index"] = self.fairness()["jain_index"]
        return out

    def reset(self) -> None:
        """Test/report hook: drop the logger and side tables (a fresh
        telemetry() call re-creates both)."""
        collection().remove(self.name)
        global _telemetry
        with _module_lock:
            _telemetry = None


# -- enable/disable (the escape-hatch contract) -------------------------

_module_lock = threading.Lock()
_telemetry: FlowTelemetry | None = None
_enabled_cache: bool | None = None
_observing = False


def _resolve_enabled() -> bool:
    env = os.environ.get("CEPH_TPU_FLOWS")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "off", "")
    try:
        return bool(g_conf()["flows_enabled"])
    except Exception:
        return True


def enabled() -> bool:
    """Cached: the per-op fast path reads one bool. The config
    observer invalidates on flows_enabled writes; CEPH_TPU_FLOWS
    wins over the option (the bench/CI kill switch)."""
    global _enabled_cache, _observing
    if _enabled_cache is None:
        with _module_lock:
            if _enabled_cache is None:
                if not _observing:
                    try:
                        g_conf().add_observer("flows_enabled",
                                              _on_conf_change)
                        _observing = True
                    except Exception:
                        pass
                _enabled_cache = _resolve_enabled()
    return _enabled_cache


def _on_conf_change(name, value) -> None:
    global _enabled_cache
    _enabled_cache = None


def telemetry() -> FlowTelemetry:
    global _telemetry
    with _module_lock:
        if _telemetry is None:
            _telemetry = FlowTelemetry()
        return _telemetry


def telemetry_if_exists() -> FlowTelemetry | None:
    return _telemetry


def flows_if_active() -> FlowTelemetry | None:
    """The NOOP seam every attribution site goes through: None when
    flows are disabled — nothing materializes, nothing allocates."""
    if not enabled():
        return None
    tel = _telemetry
    if tel is not None:
        return tel
    return telemetry()


def reset_for_tests() -> None:
    global _telemetry, _enabled_cache
    with _module_lock:
        if _telemetry is not None:
            collection().remove(_telemetry.name)
            _telemetry = None
        _enabled_cache = None


# -- the thread-local flow context --------------------------------------

def set_current_flow(label: str | None) -> None:
    """Install the flow label on this thread (daemon admission /
    crimson inline continuation). NOOP when flows are disabled."""
    if not enabled():
        return
    _tls.flow = label or None


def current_flow() -> str | None:
    return getattr(_tls, "flow", None)


def clear_current_flow() -> None:
    if getattr(_tls, "flow", None) is not None:
        _tls.flow = None


class flow_scope:
    """``with flow_scope('tenant-a'):`` — scoped install+restore."""

    def __init__(self, label: str | None) -> None:
        self._label = label
        self._prev = None

    def __enter__(self):
        self._prev = current_flow()
        set_current_flow(self._label)
        return self

    def __exit__(self, *exc):
        set_current_flow(self._prev)
        if self._prev is None:
            clear_current_flow()
        return False


def capture_flow(qos: str = "client"):
    """Producer-side snapshot for a queued work item: the enqueue
    seam stores this on the item; the worker re-installs it via
    :func:`note_wq_grant`. None when flows are disabled (the NOOP
    contract: one attribute store of the None singleton, nothing
    else)."""
    if not enabled():
        return None
    return (current_flow() or "", qos)


def note_wq_grant(fctx) -> None:
    """Worker-side: the dequeued item consumed one seat grant of its
    qos class; re-install the producer's flow on this thread."""
    if fctx is None:
        return
    label, seat = fctx
    set_current_flow(label)
    try:
        telemetry().note_queue_credit(label, seat)
    except Exception:
        pass


def note_wq_done(fctx) -> None:
    if fctx is not None:
        clear_current_flow()


def txn_nbytes(txn) -> int:
    """Cheap payload-byte estimate of a store Transaction (or encoded
    bytes): sums the bytes/dict payloads in ``txn.ops`` without
    re-encoding — what note_store_txn charges a flow for."""
    if isinstance(txn, (bytes, bytearray, memoryview)):
        return len(txn)
    total = 0
    for op in getattr(txn, "ops", ()):
        for part in op:
            if isinstance(part, (bytes, bytearray, memoryview)):
                total += len(part)
            elif isinstance(part, dict):
                total += sum(len(k) + len(v)
                             for k, v in part.items())
    return total


def register_asok(asok) -> None:
    """``dump_flows`` on every daemon."""
    asok.register_command(
        "dump_flows", lambda a: telemetry().snapshot(),
        "tenant X-ray: per-flow cost attribution (ops/bytes, queue "
        "credit, stage waits, engine + store shares), fairness "
        "windows with Jain's index, starvation streaks, SLO burn "
        "rates")
