// Native host GF(2^8) + checksum kernels for ceph_tpu.
//
// Stands in for the reference's vendored native math (gf-complete, jerasure,
// isa-l, crc32c asm — all empty submodules or raw asm in the snapshot; see
// SURVEY.md §2.4). Roles:
//   * CPU fallback backend for every codec (ops/backend.py "native"),
//   * the honest single-socket baseline the TPU kernels are measured
//     against (BASELINE.md),
//   * host-side checksum pass (crc32c / xxhash64) for the stripe engine
//     (the role of src/common/Checksummer.h and crc32c_intel_fast_asm.s).
//
// GF(2^8) poly 0x11d (gf-complete w=8 / ISA-L field). The hot loop uses the
// same split-nibble table technique ISA-L implements in asm: y = T_lo[x&15]
// ^ T_hi[x>>4] with 16-entry tables in SIMD registers via PSHUFB (AVX2),
// scalar table fallback otherwise.
//
// Build: ops/native/Makefile (lazy, driven by ops/native_loader.py).

#include <cstdint>
#include <cstring>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Tables
// ---------------------------------------------------------------------------

static uint8_t MUL[256][256];
static uint8_t NIB_LO[256][16];  // NIB_LO[c][n] = c * n        (low nibble)
static uint8_t NIB_HI[256][16];  // NIB_HI[c][n] = c * (n << 4) (high nibble)
static int inited = 0;

static uint8_t gf_mul_slow(uint8_t a, uint8_t b) {
  uint16_t r = 0;
  uint16_t aa = a;
  while (b) {
    if (b & 1) r ^= aa;
    aa <<= 1;
    if (aa & 0x100) aa ^= 0x11d;
    b >>= 1;
  }
  return (uint8_t)r;
}

void gf256_init(void) {
  if (inited) return;
  for (int a = 0; a < 256; a++)
    for (int b = 0; b < 256; b++)
      MUL[a][b] = gf_mul_slow((uint8_t)a, (uint8_t)b);
  for (int c = 0; c < 256; c++) {
    for (int n = 0; n < 16; n++) {
      NIB_LO[c][n] = MUL[c][n];
      NIB_HI[c][n] = MUL[c][n << 4];
    }
  }
  inited = 1;
}

// ---------------------------------------------------------------------------
// Region ops
// ---------------------------------------------------------------------------

void gf256_region_xor(uint8_t *dst, const uint8_t *src, uint64_t len) {
  uint64_t i = 0;
#if defined(__AVX2__)
  for (; i + 32 <= len; i += 32) {
    __m256i d = _mm256_loadu_si256((const __m256i *)(dst + i));
    __m256i s = _mm256_loadu_si256((const __m256i *)(src + i));
    _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d, s));
  }
#endif
  for (; i < len; i++) dst[i] ^= src[i];
}

// dst ^= c * src  (the gf_vect_mad of ISA-L)
void gf256_region_mul_add(uint8_t *dst, const uint8_t *src, uint8_t c,
                          uint64_t len) {
  if (c == 0) return;
  if (c == 1) { gf256_region_xor(dst, src, len); return; }
  uint64_t i = 0;
#if defined(__AVX2__)
  __m128i lo128 = _mm_loadu_si128((const __m128i *)NIB_LO[c]);
  __m128i hi128 = _mm_loadu_si128((const __m128i *)NIB_HI[c]);
  __m256i lo = _mm256_broadcastsi128_si256(lo128);
  __m256i hi = _mm256_broadcastsi128_si256(hi128);
  __m256i maskf = _mm256_set1_epi8(0x0f);
  for (; i + 32 <= len; i += 32) {
    __m256i s = _mm256_loadu_si256((const __m256i *)(src + i));
    __m256i sl = _mm256_and_si256(s, maskf);
    __m256i sh = _mm256_and_si256(_mm256_srli_epi64(s, 4), maskf);
    __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lo, sl),
                                 _mm256_shuffle_epi8(hi, sh));
    __m256i d = _mm256_loadu_si256((const __m256i *)(dst + i));
    _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d, r));
  }
#endif
  const uint8_t *t = MUL[c];
  for (; i < len; i++) dst[i] ^= t[src[i]];
}

// out[m][len] = mat[m][k] (x) data[k][len]; rows are contiguous slabs.
// This is the ec_encode_data role (ISA-L) — the CPU hot kernel.
void gf256_matvec(const uint8_t *mat, int m, int k, const uint8_t *data,
                  uint8_t *out, uint64_t len) {
  for (int i = 0; i < m; i++) {
    uint8_t *dst = out + (uint64_t)i * len;
    std::memset(dst, 0, len);
    for (int j = 0; j < k; j++)
      gf256_region_mul_add(dst, data + (uint64_t)j * len, mat[i * k + j], len);
  }
}

// ---------------------------------------------------------------------------
// crc32c (Castagnoli) — the BlueStore/messenger checksum
// (role of src/common/crc32c_intel_fast_asm.s + sctp_crc32.c)
// ---------------------------------------------------------------------------

static uint32_t CRC_TBL[8][256];
static int crc_inited = 0;

static void crc32c_init_tbl(void) {
  if (crc_inited) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int j = 0; j < 8; j++) c = (c >> 1) ^ (0x82f63b78u & (~(c & 1) + 1));
    CRC_TBL[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = CRC_TBL[0][i];
    for (int t = 1; t < 8; t++) {
      c = (c >> 8) ^ CRC_TBL[0][c & 0xff];
      CRC_TBL[t][i] = c;
    }
  }
  crc_inited = 1;
}

uint32_t ceph_crc32c(uint32_t crc, const uint8_t *buf, uint64_t len) {
  crc32c_init_tbl();
  crc = ~crc;
  uint64_t i = 0;
#if defined(__SSE4_2__)
  for (; i + 8 <= len; i += 8) {
    uint64_t v;
    std::memcpy(&v, buf + i, 8);
    crc = (uint32_t)_mm_crc32_u64(crc, v);
  }
  for (; i < len; i++) crc = _mm_crc32_u8(crc, buf[i]);
#else
  for (; i + 8 <= len; i += 8) {
    crc ^= (uint32_t)(buf[i] | (buf[i + 1] << 8) | (buf[i + 2] << 16) |
                      ((uint32_t)buf[i + 3] << 24));
    uint32_t hi = (uint32_t)(buf[i + 4] | (buf[i + 5] << 8) |
                             (buf[i + 6] << 16) | ((uint32_t)buf[i + 7] << 24));
    uint32_t c = CRC_TBL[7][crc & 0xff] ^ CRC_TBL[6][(crc >> 8) & 0xff] ^
                 CRC_TBL[5][(crc >> 16) & 0xff] ^ CRC_TBL[4][crc >> 24] ^
                 CRC_TBL[3][hi & 0xff] ^ CRC_TBL[2][(hi >> 8) & 0xff] ^
                 CRC_TBL[1][(hi >> 16) & 0xff] ^ CRC_TBL[0][hi >> 24];
    crc = c;
  }
  for (; i < len; i++) crc = (crc >> 8) ^ CRC_TBL[0][(crc ^ buf[i]) & 0xff];
#endif
  return ~crc;
}

// ---------------------------------------------------------------------------
// xxhash64 (role of the xxHash submodule used by Checksummer.h)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t rd64(const uint8_t *p) {
  uint64_t v; std::memcpy(&v, p, 8); return v;
}
static inline uint32_t rd32(const uint8_t *p) {
  uint32_t v; std::memcpy(&v, p, 4); return v;
}
static inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2; acc = rotl64(acc, 31); acc *= P1; return acc;
}
static inline uint64_t merge(uint64_t acc, uint64_t val) {
  val = round1(0, val); acc ^= val; acc = acc * P1 + P4; return acc;
}

uint64_t ceph_xxhash64(uint64_t seed, const uint8_t *p, uint64_t len) {
  const uint8_t *end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const uint8_t *limit = end - 32;
    do {
      v1 = round1(v1, rd64(p)); p += 8;
      v2 = round1(v2, rd64(p)); p += 8;
      v3 = round1(v3, rd64(p)); p += 8;
      v4 = round1(v4, rd64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge(h, v1); h = merge(h, v2); h = merge(h, v3); h = merge(h, v4);
  } else {
    h = seed + P5;
  }
  h += len;
  while (p + 8 <= end) {
    h ^= round1(0, rd64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)rd32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33; h *= P2; h ^= h >> 29; h *= P3; h ^= h >> 32;
  return h;
}

uint32_t ceph_xxhash32(uint32_t seed, const uint8_t *p, uint64_t len) {
  const uint32_t Q1 = 0x9E3779B1u, Q2 = 0x85EBCA77u, Q3 = 0xC2B2AE3Du,
                 Q4 = 0x27D4EB2Fu, Q5 = 0x165667B1u;
  const uint8_t *end = p + len;
  uint32_t h;
  auto rotl32 = [](uint32_t x, int r) { return (x << r) | (x >> (32 - r)); };
  if (len >= 16) {
    uint32_t v1 = seed + Q1 + Q2, v2 = seed + Q2, v3 = seed, v4 = seed - Q1;
    const uint8_t *limit = end - 16;
    do {
      v1 = rotl32(v1 + rd32(p) * Q2, 13) * Q1; p += 4;
      v2 = rotl32(v2 + rd32(p) * Q2, 13) * Q1; p += 4;
      v3 = rotl32(v3 + rd32(p) * Q2, 13) * Q1; p += 4;
      v4 = rotl32(v4 + rd32(p) * Q2, 13) * Q1; p += 4;
    } while (p <= limit);
    h = rotl32(v1, 1) + rotl32(v2, 7) + rotl32(v3, 12) + rotl32(v4, 18);
  } else {
    h = seed + Q5;
  }
  h += (uint32_t)len;
  while (p + 4 <= end) { h = rotl32(h + rd32(p) * Q3, 17) * Q4; p += 4; }
  while (p < end) { h = rotl32(h + (*p) * Q5, 11) * Q1; p++; }
  h ^= h >> 15; h *= Q2; h ^= h >> 13; h *= Q3; h ^= h >> 16;
  return h;
}

}  // extern "C"
