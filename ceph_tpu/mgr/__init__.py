"""mgr — the metrics/orchestration plane (src/mgr/ + src/pybind/mgr/).

The reference's ceph-mgr hosts Python modules (balancer, progress,
telemetry, prometheus, ...) with a ``mgr_module.py`` API over aggregated
cluster state. Here the Mgr daemon (ceph_tpu/mgr/mgr.py) holds a
RadosClient session to the mon, ticks its modules, and exposes each
module's commands over its admin socket; per-daemon prometheus export
lives in ceph_tpu/utils/prometheus.py (the mgr prometheus-module role).
"""

from ceph_tpu.mgr.mgr import Mgr
from ceph_tpu.mgr.mgr_module import MgrModule

__all__ = ["Mgr", "MgrModule"]
