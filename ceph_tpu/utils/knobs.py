"""Typed actuator knobs — the registry the closed-loop tuner steps.

ISSUE 13: rounds 10-15 built every sensor the OSD hot path needs, but
the knobs those sensors argue about — engine launch-window depth,
flush thresholds, the dense->mesh crossover, sampling rates — were
hand-set constants. This module declares them as typed actuators: a
:class:`Knob` names the ``g_conf`` Option it steps, its safe bounds
(narrower than the Option's hard min/max — the tuner explores inside
an envelope an operator pre-approved), its step law (additive for
small integers like the window, geometric for byte thresholds and
rates), and its cool-down (how long a step must be observed before
the next actuation anywhere).

Pushes ride the existing config-observer seam: ``push`` writes the
``mon`` layer of the process ConfigProxy, so every daemon that
registered a cached observer (osd/device_engine, utils/tracing,
utils/profiler) picks the new value up without a hot-path config
read. Operator pins win by construction — the ``env`` and
``override`` layers outrank ``mon`` — and :meth:`KnobRegistry.push`
reports a pinned knob instead of pretending the step landed.

Safety invariant (the mid-adjustment-kill contract the scenario test
pins): every value that can ever reach a daemon passes
``clamp`` + the Option's own coercion, so ANY prefix of a tuner
run — including one that dies between step and revert — leaves every
knob inside its declared bounds.
"""

from __future__ import annotations

from dataclasses import dataclass

from ceph_tpu.utils.config import ConfigProxy, g_conf


@dataclass(frozen=True)
class Knob:
    """One tuner-managed actuator over a declared config Option."""

    name: str              # the g_conf Option this knob actuates
    lo: float              # tuner envelope (within the Option bounds)
    hi: float
    step: float            # step size: factor (mul) or delta (add)
    kind: str = "mul"      # "mul" | "add"
    cooldown_s: float = 3.0
    subsystem: str = ""
    desc: str = ""

    def __post_init__(self) -> None:
        assert self.kind in ("mul", "add"), self.kind
        assert self.lo <= self.hi, (self.name, self.lo, self.hi)
        assert self.step > (1.0 if self.kind == "mul" else 0.0)

    def _quantize(self, value: float, conf: ConfigProxy):
        opt = conf.schema.get(self.name)
        if opt.type is int:
            value = int(round(value))
        return opt.coerce(value)

    def clamp(self, value: float, conf: ConfigProxy | None = None):
        conf = conf or g_conf()
        return self._quantize(min(self.hi, max(self.lo, value)), conf)

    def up(self, value: float, conf: ConfigProxy | None = None):
        nxt = value * self.step if self.kind == "mul" \
            else value + self.step
        return self.clamp(nxt, conf)

    def down(self, value: float, conf: ConfigProxy | None = None):
        nxt = value / self.step if self.kind == "mul" \
            else value - self.step
        return self.clamp(nxt, conf)

    def stepped(self, value: float, direction: str,
                conf: ConfigProxy | None = None):
        assert direction in ("up", "down"), direction
        return self.up(value, conf) if direction == "up" \
            else self.down(value, conf)


class KnobRegistry:
    """Declared actuators, keyed by Option name (insertion-ordered:
    evaluation order is declaration order, part of determinism)."""

    def __init__(self, knobs: list[Knob] | None = None) -> None:
        self._knobs: dict[str, Knob] = {}
        for k in knobs or ():
            self.add(k)

    def add(self, knob: Knob) -> Knob:
        if knob.name in self._knobs:
            raise ValueError(f"duplicate knob {knob.name}")
        self._knobs[knob.name] = knob
        return knob

    def get(self, name: str) -> Knob:
        return self._knobs[name]

    def names(self) -> list[str]:
        return list(self._knobs)

    def __iter__(self):
        return iter(self._knobs.values())

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    # -- views ---------------------------------------------------------
    def vector(self, conf: ConfigProxy | None = None) -> dict:
        """{knob name: current effective value} — what gap_report
        prints next to its attribution table."""
        conf = conf or g_conf()
        return {name: conf.get(name) for name in self._knobs}

    def vector_detail(self, conf: ConfigProxy | None = None) -> dict:
        """Per-knob value + winning config source + whether a higher
        layer pins it against tuner ('mon'-layer) pushes."""
        conf = conf or g_conf()
        out = {}
        for name, knob in self._knobs.items():
            src = conf.source_of(name)
            out[name] = {"value": conf.get(name), "source": src,
                         "pinned": src in ("env", "override"),
                         "lo": knob.lo, "hi": knob.hi,
                         "subsystem": knob.subsystem}
        return out

    # -- actuation -----------------------------------------------------
    def push(self, name: str, value,
             conf: ConfigProxy | None = None) -> tuple[object, bool]:
        """Clamp + write one knob through the mon layer. Returns
        (applied value as clamped, landed) — ``landed`` False means a
        higher-precedence layer pins the knob and daemons will not
        see the write."""
        conf = conf or g_conf()
        knob = self._knobs[name]
        value = knob.clamp(value, conf)
        conf.set(name, value, source="mon")
        return value, conf.source_of(name) == "mon"


#: the ISSUE-13 actuator set: every knob the ROADMAP names as
#: hand-set today, each bounded inside its Option's hard range
TUNER_KNOBS = KnobRegistry([
    Knob("engine_window", lo=1, hi=16, step=1, kind="add",
         cooldown_s=3.0, subsystem="osd/device_engine",
         desc="launch-window depth: overlap vs HBM working set"),
    Knob("engine_flush_bytes", lo=1 << 20, hi=256 << 20, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="osd/device_engine",
         desc="flush threshold: batching amortization vs batching "
              "latency"),
    Knob("host_flush_bytes", lo=64 << 10, hi=4 << 20, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="osd/device_engine",
         desc="host-matvec crossover for small flushes"),
    Knob("mesh_flush_bytes", lo=128 << 10, hi=64 << 20, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="osd/device_engine",
         desc="dense->mesh crossover: single-chip vs sharded step"),
    Knob("crimson_smp", lo=1, hi=16, step=1, kind="add",
         cooldown_s=6.0, subsystem="crimson/osd",
         desc="shared-nothing reactor count (seastar --smp role); a "
              "step applies to crimson OSDs started afterwards — the "
              "observer caches it for the next boot, live reactors "
              "never reshard"),
    Knob("crimson_flush_bytes", lo=256 << 10, hi=64 << 20, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="crimson/osd",
         desc="crimson engine flush window: stripe-batch amortization "
              "vs run-to-completion commit latency (the only async "
              "boundary on the RTC path)"),
    Knob("objecter_stream_max_ops", lo=1, hi=256, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="client/objecter",
         desc="streaming-objecter batch window: writes coalesced "
              "per (pool, PG) frame — batching amortization vs "
              "head-of-line latency (ROADMAP 1b/5d)"),
    Knob("osd_read_set_spread", lo=1, hi=8, step=1, kind="add",
         cooldown_s=3.0, subsystem="osd/ec_backend",
         desc="any-k read-set rotation width: hot-object read "
              "balance vs decode-signature reuse (ROADMAP 3)"),
    Knob("client_cache_bytes", lo=8 << 20, hi=256 << 20, step=2.0,
         kind="mul", cooldown_s=3.0, subsystem="client/object_cacher",
         desc="librados cache-tier capacity: hit rate vs client "
              "memory (stepped on measured hit rate)"),
    Knob("trace_sample_every", lo=8, hi=1024, step=2.0, kind="mul",
         cooldown_s=6.0, subsystem="utils/tracing",
         desc="head-sample keep rate: observability vs overhead"),
    Knob("profiler_hz", lo=10.0, hi=200.0, step=2.0, kind="mul",
         cooldown_s=6.0, subsystem="utils/profiler",
         desc="stack-sampling rate while a profiler runs"),
])


def tuner_managed_names() -> list[str]:
    """The knob names the registry-drift lint holds to the
    cached-observer bar: a knob the tuner mutates at runtime must be
    consumed through ``add_observer``, never re-read per-op."""
    return TUNER_KNOBS.names()


def knob_vector(conf: ConfigProxy | None = None) -> dict:
    """Convenience for report surfaces (gap_report, bench lines)."""
    return TUNER_KNOBS.vector(conf)
