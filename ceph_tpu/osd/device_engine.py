"""DeviceEncodeEngine — the OSD's device-side stripe-batch pipeline.

This is the seam SURVEY.md §0 calls the north star: "ECBackend
accumulates sub-writes into device-side stripe batches". The reference
encodes synchronously inside try_reads_to_commit
(src/osd/ECBackend.cc:1986-2048, per-stripe loop ECUtil.cc:120-159);
a TPU cannot be fed per-4KiB-op without drowning in dispatch latency,
so the daemon's encode work is decoupled from the op path:

- ``stage_encode`` queues an op's padded payload; the engine folds
  every queued payload (across PGs — batching across placement groups
  is where the batch size comes from) into ONE device kernel launch
  via :class:`ceph_tpu.osd.ec_util.StripeBatcher`, then dispatches
  each op's continuation (hinfo + shard-txn build + fan-out) back
  onto the OSD's sharded op queue.
- ``stage_barrier`` queues a NON-encode mutation (remove, RMW
  partial write). A barrier flushes everything staged before it and
  is dispatched after those continuations — on the same per-PG FIFO
  wq shard — so per-PG commit order is exactly submission order (the
  check_ops pipeline-ordering invariant, ECBackend.cc:2107-2112).
- ``stage_decode`` queues a reconstruct (degraded read, recovery
  decode — the objects_read_and_reconstruct / continue_recovery_op
  consumers, src/osd/ECBackend.cc:2301,537,955). Decodes group by
  ERASURE SIGNATURE (present-set, want-set — the ISA decode-table
  cache key, src/erasure-code/isa/ErasureCodeIsa.cc:226-303) and
  each group flushes as ONE device matmul; concurrent degraded
  reads and parallel recovery builds coalesce. Unlike encode
  continuations, decode continuations run INLINE on the engine
  thread: callers block synchronously (decode_sync) on op-worker
  threads, so dispatching through the per-PG wq would deadlock
  behind the very thread that is waiting.

Batching policy ("batch while busy"): the engine thread drains
whatever is queued and encodes it in one launch; while the device
works, new ops accumulate for the next launch. An idle engine
therefore adds no latency (a lone op flushes immediately) and a busy
one amortizes dispatch over the whole backlog. A size cap
(``flush_bytes``) bounds the device working set.

Launch pipeline (the round-9 tentpole): encode flushes exploit JAX
async dispatch — a flush LAUNCHES its device program and parks the
``finalize`` (download) on a bounded in-flight deque instead of
blocking. Up to ``window`` (default 3, ``CEPH_TPU_ENGINE_WINDOW``)
batches stay in flight: while batch N computes on device, batch N+1
stages/uploads and batch N-1's parity downloads. Retirement is
strictly in deque order, so continuations still dispatch in
submission order and every ordering point — ``stage_barrier``,
``run_sync``, ``stop``, a launch failure — drains the whole window
first; the pre-pipeline per-PG commit-order invariant is preserved
exactly. ``window=1`` degenerates to the old serial engine (launch,
then immediately download), which is what the overlap tests compare
against.

Multi-chip routing: when a process default mesh is configured
(parallel/mesh.py), flushes whose batch size reaches
``mesh_flush_bytes`` (default 1 MiB, ``CEPH_TPU_MESH_FLUSH_BYTES``)
run the sharded encode step across all mesh devices
(parallel/sharded_codec.make_encode_step); smaller flushes stay on
the single-chip path, where one kernel launch beats paying the
collective/placement overhead (the dense-vs-sharded crossover,
BASELINE.md "Pipelined engine").

Failure containment: a device encode error fails over to the op
continuations with the error; ECBackend re-encodes those ops on its
host codec (the daemon must never wedge on an accelerator fault).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import numpy as np

from ceph_tpu.osd import ec_util
from ceph_tpu.utils import faults as _faults
from ceph_tpu.utils import profiler as _prof
from ceph_tpu.utils import stage_clock as _stage_clock
from ceph_tpu.utils.device_telemetry import telemetry as _telemetry
from ceph_tpu.utils.dout import Dout
from ceph_tpu.utils.tracing import NOOP

log = Dout("osd")

from ceph_tpu.utils import tracepoints as _tracepoints  # noqa: E402

_TP_FLUSH = _tracepoints.provider("osd").point(
    "device_flush", "ops", "bytes")
_TP_DECODE_FLUSH = _tracepoints.provider("osd").point(
    "device_decode_flush", "ops", "signature")


class DeviceEncodeEngine:
    """One per OSD; owns the device dispatch thread."""

    def __init__(self, dispatch: Callable[[object, Callable], None],
                 flush_bytes: int = 64 << 20,
                 counters=None, window: int | None = None,
                 mesh_flush_bytes: int | None = None) -> None:
        import os
        #: dispatch(key, fn): run fn on the per-key FIFO executor (the
        #: OSD passes op_wq.enqueue, keyed by pgid)
        self._dispatch = dispatch
        self._flush_bytes = flush_bytes
        self._counters = counters
        #: max launched-not-retired encode batches (the pipeline
        #: depth); 1 = the old serial engine
        if window is None:
            window = int(os.environ.get("CEPH_TPU_ENGINE_WINDOW", 3))
        self._window = max(1, window)
        #: batches at least this big route through the default mesh's
        #: sharded encode step (when one is configured); smaller ones
        #: stay single-chip
        if mesh_flush_bytes is None:
            mesh_flush_bytes = int(os.environ.get(
                "CEPH_TPU_MESH_FLUSH_BYTES", 1 << 20))
        self._mesh_flush_bytes = mesh_flush_bytes
        # warmup-kill: per-signature device programs persist across
        # processes (best-effort; a disabled/failed cache only costs
        # recompiles, never correctness)
        from ceph_tpu.utils import compile_cache
        compile_cache.enable()
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._running = True
        #: introspection (asok / tests): launches, ops, bytes, and the
        #: largest ops-per-launch seen — proof the batching engages
        self.stats = {"flushes": 0, "ops": 0, "bytes": 0,
                      "max_batch_ops": 0, "errors": 0,
                      "decode_flushes": 0, "decode_ops": 0,
                      "decode_bytes": 0, "max_decode_batch_ops": 0,
                      "decode_errors": 0, "device_fused_fallbacks": 0,
                      # launch-pipeline occupancy: the deepest the
                      # in-flight window ever got (>= 2 proves
                      # upload/compute/download overlapped) and how
                      # many flushes routed through the mesh
                      "max_inflight_depth": 0, "mesh_flushes": 0,
                      # auxiliary device work run via run_sync (deep
                      # scrub verify launches)
                      "aux_runs": 0,
                      # engine-thread seconds spent launching +
                      # finalizing device batches: busy_s/flushes is
                      # the MEASURED per-launch cost the amortization
                      # analysis divides out (BASELINE.md cluster
                      # table)
                      "busy_s": 0.0}
        _telemetry().note_engine_window(self._window)
        self._thread = threading.Thread(
            target=self._run, name="ec-device-engine", daemon=True)
        self._thread.start()

    # -- producer side (op-shard threads) -----------------------------
    def stage_encode(self, key, codec, sinfo: ec_util.StripeInfo,
                     data: np.ndarray,
                     cont: Callable[[dict | None, dict | None,
                                     Exception | None], None],
                     span=NOOP, clock=_stage_clock.NOOP) -> None:
        """Queue one op's stripe-aligned payload for batched device
        encode; ``cont(shards, crcs, err)`` is dispatched on ``key``
        (crcs = per-shard LINEAR crc parts computed on device from the
        same buffers, or None; err set and shards None on device
        failure — caller falls back). ``span``: the op's dataflow
        trace continues through the engine (flush launch, kernel
        dispatch, crc pass events); ``clock``: the op's StageClock —
        the engine marks engine_stage_wait / device_window_wait /
        device_finalize on it, so the per-op timeline survives the
        engine boundary. Both defaults are free no-ops."""
        import time as _time
        # HBM ledger: bytes enter the staged bucket here and leave it
        # at launch (-> in-window) or on a launch fault (-> retired)
        _telemetry().note_hbm(staged_delta=data.nbytes)
        self._q.put(("enc", key, codec, sinfo, data, cont, span,
                     clock, _time.monotonic()))

    def stage_barrier(self, key, fn: Callable[[], None]) -> None:
        """Queue an ordering barrier: ``fn`` dispatches on ``key``
        after every previously staged op's continuation."""
        self._q.put(("bar", key, fn))

    def stage_decode(self, key, codec, sinfo: ec_util.StripeInfo,
                     shards: dict[int, np.ndarray], want: list[int],
                     cont: Callable[[dict | None, Exception | None],
                                    None], span=NOOP,
                     clock=_stage_clock.NOOP) -> None:
        """Queue a reconstruct of ``want`` chunk streams from the
        surviving ``shards``; ``cont(decoded, err)`` runs INLINE on
        the engine thread (must be cheap and lock-free — the typical
        continuation publishes the result and sets an event for a
        blocked decode_sync caller)."""
        import time as _time
        _telemetry().note_hbm(staged_delta=_shards_nbytes(shards))
        self._q.put(("dec", key, codec, sinfo, shards, want, cont,
                     span, clock, _time.monotonic()))

    def decode_sync(self, key, codec, sinfo: ec_util.StripeInfo,
                    shards: dict[int, np.ndarray], want: list[int],
                    timeout: float = 60.0,
                    span=NOOP,
                    clock=_stage_clock.NOOP) -> dict[int, np.ndarray] | None:
        """Blocking decode through the batched engine; returns the
        decoded {chunk: bytes} map or None on device fault/timeout
        (the caller falls back to its host twin). Safe to call from
        op-worker threads: the continuation runs on the engine
        thread, not the caller's wq shard."""
        ev = threading.Event()
        box: list = [None, None]

        def cont(out, err):
            box[0], box[1] = out, err
            ev.set()

        self.stage_decode(key, codec, sinfo, shards, want, cont,
                          span=span, clock=clock)
        if not ev.wait(timeout):
            log(0, f"device decode timed out after {timeout}s; "
                "host fallback")
            self.stats["decode_errors"] += 1
            return None
        if box[1] is not None:
            return None
        return box[0]

    def run_sync(self, fn: Callable[[], object],
                 timeout: float = 120.0):
        """Run ``fn`` on the engine thread and return its result
        (deep scrub's verify launches ride here so background
        verification serializes with client encode/decode flushes on
        the one device instead of contending mid-download). Raises
        what ``fn`` raises; raises TimeoutError when the engine is
        stopped or wedged."""
        ev = threading.Event()
        box: list = [None, None]
        self._q.put(("run", fn, box, ev))
        if not ev.wait(timeout):
            raise TimeoutError("device engine run_sync timed out")
        if box[1] is not None:
            raise box[1]
        return box[0]

    def stop(self) -> None:
        self._running = False
        self._q.put(None)
        self._thread.join(timeout=10)

    # -- engine thread ------------------------------------------------
    def _run(self) -> None:
        import collections
        #: launch pipeline: deque of (items, finalize, kspans,
        #: launch_t) for batches whose device programs are queued but
        #: not yet downloaded — up to ``window`` deep. While batch N
        #: computes, batch N+1 concatenates/uploads and batch N-1
        #: downloads; retirement is strictly FIFO so continuation
        #: order equals submission order.
        self._inflight = collections.deque()
        while True:
            # profiler join: blocking on an empty queue is idle time,
            # not engine work — without the mark, every sample of the
            # parked engine thread would inflate engine_stage_wait
            _pidle = _prof.push_stage("idle")
            item = self._q.get()
            _prof.pop_stage(_pidle)
            if item is None:
                self._drain_inflight()
                return
            pending: dict[int, tuple] = {}   # id(codec) -> state
            # (id(codec), present, want) -> (codec, sinfo, items)
            dec_pending: dict[tuple, tuple] = {}
            nbytes = 0
            while True:
                if item is None:
                    self._flush(pending)
                    self._flush_decodes(dec_pending)
                    self._drain_inflight()
                    return
                if item[0] == "enc":
                    (_, key, codec, sinfo, data, cont, span, clock,
                     ts) = item
                    _, _, items = pending.setdefault(
                        id(codec), (codec, sinfo, []))
                    items.append((key, data, cont, span, clock, ts))
                    nbytes += data.nbytes
                    if nbytes >= self._flush_bytes:
                        # flush BOTH kinds: the byte counter is
                        # shared, and a staged decode left behind
                        # here would wait for the next barrier/idle
                        # while its decode_sync caller blocks
                        self._flush(pending)
                        self._flush_decodes(dec_pending)
                        pending, dec_pending, nbytes = {}, {}, 0
                elif item[0] == "dec":
                    (_, key, codec, sinfo, shards, want, cont, span,
                     clock, ts) = item
                    sig = (id(codec),
                           tuple(sorted(shards)), tuple(sorted(want)))
                    _, _, items = dec_pending.setdefault(
                        sig, (codec, sinfo, []))
                    items.append((key, shards, want, cont, span,
                                  clock, ts))
                    nbytes += sum(np.asarray(v).nbytes
                                  for v in shards.values())
                    if nbytes >= self._flush_bytes:
                        self._flush(pending)
                        self._flush_decodes(dec_pending)
                        pending, dec_pending, nbytes = {}, {}, 0
                elif item[0] == "run":
                    # auxiliary device work (deep-scrub verify): runs
                    # after the in-flight batch drains so it never
                    # contends with an encode download on the device
                    import time as _time
                    self._flush(pending)
                    self._flush_decodes(dec_pending)
                    self._drain_inflight()
                    pending, dec_pending, nbytes = {}, {}, 0
                    _, fn, box, ev = item
                    t0 = _time.perf_counter()
                    prev_stage = _prof.push_stage("scrub")
                    try:
                        box[0] = fn()
                    except Exception as exc:
                        box[1] = exc
                    finally:
                        _prof.pop_stage(prev_stage)
                    self.stats["aux_runs"] += 1
                    self.stats["busy_s"] += _time.perf_counter() - t0
                    ev.set()
                else:                        # barrier
                    self._flush(pending)
                    self._flush_decodes(dec_pending)
                    # the barrier fn must run AFTER every prior op's
                    # continuation: drain the launch pipeline first
                    self._drain_inflight()
                    pending, dec_pending, nbytes = {}, {}, 0
                    _, key, fn = item
                    self._dispatch(key, fn)
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    # nothing else queued: launch what we have now
                    # (an idle engine adds no batching latency) and
                    # drain — continuations must not wait for load
                    self._flush(pending)
                    self._flush_decodes(dec_pending)
                    self._drain_inflight()
                    pending, dec_pending, nbytes = {}, {}, 0
                    break
            # shutdown is the None sentinel, NOT self._running: ops
            # staged before stop() must still flush (checking the
            # flag here raced the idle drain and dropped them)

    def _flush(self, pending: dict) -> None:
        if not pending:
            return
        # profiler join: while the engine thread stages/launches, a
        # sample of it belongs to the op's engine_stage_wait interval
        prev_stage = _prof.push_stage("engine_stage_wait")
        try:
            self._flush_inner(pending)
        finally:
            _prof.pop_stage(prev_stage)

    def _flush_inner(self, pending: dict) -> None:
        import time as _time
        from ceph_tpu.parallel import mesh as mesh_mod
        t0 = _time.perf_counter()
        drained = 0.0                 # retirement self-accounts
        for codec, sinfo, items in pending.values():
            nbytes = sum(d.nbytes for _k, d, _c, _s, _cl, _t in items)
            # a configured default mesh takes the flush through the
            # multi-chip encode step (pod deployments; dryrun/tests)
            # — but only once the batch is big enough to amortize the
            # collective/placement overhead; small flushes stay on
            # the single-chip kernel (the dense-vs-sharded threshold,
            # BASELINE.md "Pipelined engine")
            mesh = mesh_mod.get_default_mesh()
            if mesh is not None and nbytes < self._mesh_flush_bytes:
                mesh = None
            batcher = ec_util.StripeBatcher(
                sinfo, codec, mesh=mesh,
                on_fallback=self._note_fused_fallback)
            for i, (_key, data, _cont, _span, _clock, _ts) in \
                    enumerate(items):
                batcher.append(i, data)
            if mesh is not None:
                self.stats["mesh_flushes"] += 1
            try:
                # chaos-harness seam (utils/faults engine_launch
                # rules): an injected launch failure rides the exact
                # failure-drain path a real device fault takes
                _faults.engine_fault("launch")
                finalize = batcher.flush_async(
                    with_crcs=ec_util.fuse_crc_policy(codec))
            except Exception as exc:
                # launch failed: older batches' continuations must
                # still run BEFORE these error continuations (per-PG
                # order), so drain first. The batch's bytes leave the
                # staged bucket here (fate decided: host fallback).
                _telemetry().note_hbm(staged_delta=-nbytes,
                                      retired=nbytes)
                drained += self._drain_inflight()
                log(0, f"device encode batch of {len(items)} ops "
                    f"failed: {exc!r}")
                self.stats["errors"] += 1
                for key, _data, cont, span, _clock, _ts in items:
                    span.event(f"device_error {exc!r}")
                    span.finish()
                    self._dispatch(key, _bind(cont, None, None, exc))
                continue
            # batch launched (async): park it on the in-flight deque
            # — its compute+download overlaps the NEXT batch's
            # staging/upload; only the window bound forces a harvest
            if _TP_FLUSH.enabled:
                _TP_FLUSH(len(items), nbytes)
            launched = _time.monotonic()
            tel = _telemetry()
            kspans = []
            for _key, _data, _cont, span, clock, ts in items:
                # queue wait = stage -> launch (the batching latency
                # an op paid for its amortization win)
                tel.note_queue_wait("encode", launched - ts)
                clock.mark("engine_stage_wait", t=launched)
                if span is not NOOP:   # no formatting when untraced
                    span.event(f"batch_flush ops={len(items)} "
                               f"bytes={nbytes}")
                kspans.append(span.child("kernel_dispatch"))
            # staged -> in-window (the batch byte count RIDES the
            # in-flight entry so retirement can reconcile it — the
            # pre-PR-7 engine dropped it here and the live gauges
            # could never return to zero)
            tel.note_hbm(staged_delta=-nbytes, inflight_delta=nbytes)
            self._inflight.append(
                (items, finalize, kspans, _time.perf_counter(),
                 nbytes))
            depth = len(self._inflight)
            self.stats["max_inflight_depth"] = max(
                self.stats["max_inflight_depth"], depth)
            tel.note_inflight_depth(depth)
            tel.note_engine_inflight(depth)
            while len(self._inflight) >= self._window:
                drained += self._retire_oldest()
        if pending:
            # retirement time self-accounts in _retire_oldest; only
            # the launch-side time is added here (no double count)
            self.stats["busy_s"] += \
                _time.perf_counter() - t0 - drained
        pending.clear()

    def _drain_inflight(self) -> float:
        """Retire EVERY in-flight batch in launch order (ordering
        points: barrier, run_sync, stop, launch failure); returns
        seconds spent (also accumulated into busy_s)."""
        dt = 0.0
        while self._inflight:
            dt += self._retire_oldest()
        return dt

    def _retire_oldest(self) -> float:
        """Harvest the OLDEST in-flight batch (download + dispatch its
        continuations); returns seconds spent (also accumulated into
        busy_s here)."""
        import time as _time
        if not self._inflight:
            return 0.0
        prev_stage = _prof.push_stage("device_finalize")
        t0 = _time.perf_counter()
        harvest_t = _time.monotonic()
        (items, finalize, kspans, launch_t,
         nbytes) = self._inflight.popleft()
        # per-op timeline: launch -> harvest begin is the pipeline-
        # window wait (overlapped with younger batches' staging)
        for _key, _data, _cont, _span, clock, _ts in items:
            clock.mark("device_window_wait", t=harvest_t)
        try:
            results = finalize()
        except Exception as exc:
            log(0, f"device encode batch of {len(items)} ops "
                f"failed: {exc!r}")
            self.stats["errors"] += 1
            for (key, _data, cont, span, _clock, _ts), kspan in \
                    zip(items, kspans):
                kspan.event(f"device_error {exc!r}")
                kspan.finish()
                span.finish()
                self._dispatch(key, _bind(cont, None, None, exc))
            results = None
        if results is not None:
            done_t = _time.monotonic()
            self.stats["flushes"] += 1
            self.stats["ops"] += len(items)
            self.stats["bytes"] += nbytes
            self.stats["max_batch_ops"] = max(
                self.stats["max_batch_ops"], len(items))
            if self._counters is not None:
                self._counters.inc("device_batches")
                self._counters.inc("device_batch_ops", len(items))
            for (key, _data, cont, span, clock, _ts), \
                    (_i, shards, crcs), kspan in zip(items, results,
                                                     kspans):
                if crcs is not None:
                    kspan.event("crc_pass")
                kspan.finish()
                span.finish()
                clock.mark("device_finalize", t=done_t)
                self._dispatch(key, _bind(cont, shards, crcs, None))
            _telemetry().note_encode_flush(
                len(items), nbytes, _time.perf_counter() - t0)
        dt = _time.perf_counter() - t0
        # overlap: launch->harvest-begin passed while the engine did
        # OTHER work (younger batches staged/launched); the remainder
        # of the lifetime is this harvest's blocking download
        tel = _telemetry()
        tel.note_overlap(t0 - launch_t,
                         _time.perf_counter() - launch_t)
        tel.note_engine_retired()
        tel.note_engine_inflight(len(self._inflight))
        # the batch's bytes leave the window on BOTH outcomes
        # (download or failover) — the gauges-to-zero invariant
        tel.note_hbm(inflight_delta=-nbytes, retired=nbytes)
        self.stats["busy_s"] += dt
        _prof.pop_stage(prev_stage)
        return dt


    def _note_fused_fallback(self, path: str, exc: Exception) -> None:
        """A mesh/fused flush path failed and the batch re-ran on the
        plain path: count it (asok 'status' surfaces the stats dict),
        so a persistent regression is visible instead of silently
        degrading every flush to host hashing (r2 verdict weak #3)."""
        self.stats["device_fused_fallbacks"] += 1
        _telemetry().note_fused_fallback()
        if self._counters is not None:
            self._counters.inc("device_fused_fallbacks")

    def _flush_decodes(self, dec_pending: dict) -> None:
        """One device matmul per erasure signature: every queued op of
        a signature shares the decode matrix (the LRU the codec keeps,
        keyed exactly like the ISA decode-table cache), so their shard
        streams concatenate along the byte axis into a single launch.
        Continuations run inline (see stage_decode)."""
        import time as _time
        if not dec_pending:
            return
        prev_stage = _prof.push_stage("device_finalize")
        try:
            self._flush_decodes_inner(dec_pending)
        finally:
            _prof.pop_stage(prev_stage)

    def _flush_decodes_inner(self, dec_pending: dict) -> None:
        import time as _time
        for (_cid, present, want), (codec, sinfo, items) in \
                dec_pending.items():
            launched = _time.monotonic()
            t0 = _time.perf_counter()
            tel = _telemetry()
            # staged bytes leave the ledger here: whatever happens
            # below (decode or fault), this group's buffers are done
            staged = sum(_shards_nbytes(shards)
                         for _k, shards, _w, _c, _s, _cl, _t in items)
            tel.note_hbm(staged_delta=-staged, retired=staged)
            for _key, _shards, _want, _cont, span, clock, ts in items:
                tel.note_queue_wait("decode", launched - ts)
                clock.mark("engine_stage_wait", t=launched)
                if span is not NOOP:   # no formatting when untraced
                    span.event(f"decode_flush ops={len(items)} "
                               f"sig={list(present)}->{list(want)}")
            try:
                # chaos-harness seam: injected decode-flush failure ->
                # every op in the group falls back to its host twin
                _faults.engine_fault("decode")
                merged = {
                    c: np.concatenate(
                        [np.asarray(shards[c], dtype=np.uint8)
                         for _k, shards, _w, _c, _s, _cl, _t in items])
                    for c in present}
                lens = [len(np.asarray(shards[present[0]]))
                        for _k, shards, _w, _c, _s, _cl, _t in items]
                out = ec_util.decode(sinfo, codec, merged, list(want))
            except Exception as exc:
                log(0, f"device decode batch of {len(items)} ops "
                    f"(sig {present}->{want}) failed: {exc!r}")
                self.stats["decode_errors"] += 1
                for (_key, _shards, _want, cont, span, _clock,
                     _ts) in items:
                    span.event(f"device_error {exc!r}")
                    span.finish()
                    cont(None, exc)
                continue
            if _TP_DECODE_FLUSH.enabled:
                _TP_DECODE_FLUSH(len(items), str(present))
            nbytes = sum(ln * len(present) for ln in lens)
            self.stats["decode_flushes"] += 1
            self.stats["decode_ops"] += len(items)
            self.stats["decode_bytes"] += nbytes
            self.stats["max_decode_batch_ops"] = max(
                self.stats["max_decode_batch_ops"], len(items))
            if self._counters is not None:
                self._counters.inc("device_decode_batches")
                self._counters.inc("device_decode_ops", len(items))
            tel.note_decode_flush(len(items), nbytes,
                                  _time.perf_counter() - t0)
            done_t = _time.monotonic()
            off = 0
            for (_key, _shards, _want, cont, span, clock, _ts), ln \
                    in zip(items, lens):
                span.event("decode_done")
                span.finish()
                clock.mark("device_finalize", t=done_t)
                cont({c: v[off:off + ln] for c, v in out.items()},
                     None)
                off += ln
        dec_pending.clear()


def _shards_nbytes(shards: dict) -> int:
    """Byte count of one staged decode's survivor map — the SAME
    expression on the staging and retiring side, so the HBM ledger
    reconciles exactly."""
    return sum(np.asarray(v).nbytes for v in shards.values())


def _bind(cont, shards, crcs, err):
    fn = lambda: cont(shards, crcs, err)   # noqa: E731
    # the continuation builds hinfo/shard txns and fans sub-writes out
    # — commit_wait work; the op-wq worker running it picks the tag up
    # for the profiler's stage join
    fn._profile_stage = "commit_wait"
    return fn
