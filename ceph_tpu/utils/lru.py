"""Tiny bounded LRU for codec table/plan caches.

The reference caches ISA decode tables per erasure signature in exactly
this shape (ErasureCodeIsaTableCache, src/erasure-code/isa/
ErasureCodeIsa.cc:226-303, LRU sizing notes isa/README:57-62); the matrix
codecs, SHEC plan search, and the Clay linearized transforms all share it
here instead of each hand-rolling the pattern.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, TypeVar

V = TypeVar("V")


class BoundedLRU(OrderedDict):
    """OrderedDict with a size bound and a get-or-build accessor.

    ``maxsize`` is a plain attribute so callers (and tests) can retune
    the bound after construction.

    Thread-safe for the put/get_or_build accessors: the decode-table
    caches are shared across the OSD's op-shard and reader threads
    (the reference locks its table cache the same way —
    ErasureCodeIsaTableCache, and tests the class of bug with
    TestErasureCodeShec_thread.cc). Without the lock, a get's
    move_to_end can race another thread's eviction of the same key
    into a KeyError, and two concurrent builds can double-evict.
    Plain dict operations remain unlocked — callers using them (the
    mon's dedup) hold their own locks.
    """

    def __init__(self, maxsize: int) -> None:
        super().__init__()
        self.maxsize = maxsize
        self._lock = threading.RLock()

    def put(self, key, value) -> None:
        """Bounded insert (plain ``self[key] =`` does NOT evict)."""
        with self._lock:
            self[key] = value
            self.move_to_end(key)
            if len(self) > self.maxsize:
                self.popitem(last=False)

    def get_or_build(self, key, build: Callable[[], V]) -> V:
        with self._lock:
            hit = self.get(key)
            if hit is None:
                # build under the lock: deterministic table builds are
                # cheap, and racing builders would double-insert/evict
                hit = self[key] = build()
                if len(self) > self.maxsize:
                    self.popitem(last=False)
            else:
                self.move_to_end(key)
            return hit
