"""ISSUE 6 tentpole coverage: the per-op stage timeline end to end.

One module-scoped MiniCluster (3 OSDs, k=2 m=1 EC pool on the jax
device backend) runs a warm write plus a pipelined burst of
concurrent writes with tracing OFF and Span.__init__ instrumented.
The tests then assert, against the same run:

- every EC write yields the complete canonical timeline, monotonic,
  durations >= 0, stage sums == end-to-end total;
- the timeline crosses the engine boundary under a window>1 burst;
- shard sub-op child timelines merge in (client+primary+shard span);
- per-message-type messenger counters advance;
- send/dispatch queue-depth gauges return to zero at idle;
- tracing off costs zero Span allocations while stage counters
  still record;
- dump_historic_ops carries the timeline; the dump_op_timeline and
  ``op age histogram`` asok commands serve the decomposition.
"""

import concurrent.futures
import time

import pytest

from ceph_tpu.parallel import messages as M
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils import stage_clock, tracing
from ceph_tpu.utils.admin_socket import asok_command
from ceph_tpu.utils.dataplane import dataplane
from ceph_tpu.utils.msgr_telemetry import telemetry as msgr_telemetry

N_BURST = 8
OBJ_BYTES = 20_000


@pytest.fixture(scope="module")
def dp_run():
    """The shared workload: warm write + pipelined concurrent burst,
    run with tracing FULLY disabled (trace_enabled=false restores the
    literal-NOOP mode of the pre-ISSUE-10 default) and Span
    allocations counted — stage counters must record regardless."""
    from ceph_tpu.utils.config import g_conf
    conf = g_conf()
    old_enabled = conf["trace_enabled"]
    conf.set("trace_enabled", False)
    dataplane().reset()
    made = []
    orig_init = tracing.Span.__init__

    def counting_init(self, *a, **kw):
        made.append(1)
        return orig_init(self, *a, **kw)

    tracing.Span.__init__ = counting_init
    try:
        with MiniCluster(n_osds=3) as cluster:
            rados = cluster.client()
            cluster.create_ec_pool("dp", k=2, m=1, pg_num=4,
                                   backend="jax")
            io = rados.open_ioctx("dp")
            io.op_timeout = 120.0     # CPU jit compiles on first write
            io.write_full("warm", b"w" * OBJ_BYTES)
            # window>1 pipelined burst: concurrent writes across PGs
            # keep multiple batches in flight through the engine
            with concurrent.futures.ThreadPoolExecutor(N_BURST) as p:
                list(p.map(lambda i: io.write_full(f"obj{i}",
                                                   b"d" * OBJ_BYTES),
                           range(N_BURST)))
            spans_during_io = len(made)
            timelines = rados.dump_op_timelines()
            yield {"cluster": cluster, "rados": rados, "io": io,
                   "timelines": timelines,
                   "spans": spans_during_io}
    finally:
        tracing.Span.__init__ = orig_init
        conf.set("trace_enabled", old_enabled)


def _write_timelines(timelines):
    """The timelines whose stage set is the full canonical EC write."""
    want = set(stage_clock.EC_WRITE_STAGES)
    return [t for t in timelines
            if {s["stage"] for s in t["stages"]} >= want]


def test_ec_write_timeline_complete_and_monotonic(dp_run):
    writes = _write_timelines(dp_run["timelines"])
    # warm + all burst ops came home with a full decomposition
    assert len(writes) >= N_BURST, \
        f"only {len(writes)} complete timelines of {N_BURST + 1} writes"
    for tl in writes:
        names = [s["stage"] for s in tl["stages"]]
        assert names == list(stage_clock.EC_WRITE_STAGES), names
        ts = [s["t_us"] for s in tl["stages"]]
        assert ts == sorted(ts), f"non-monotonic timeline: {tl}"
        assert all(s["dur_us"] >= 0 for s in tl["stages"]), tl
        # consecutive intervals partition the op: sums == total
        # (<= with rounding slack per the acceptance wording)
        total = sum(s["dur_us"] for s in tl["stages"])
        assert total <= tl["total_us"] + 1.0, tl
        assert total >= tl["total_us"] - 1.0, tl


def test_timeline_spans_shard_osds(dp_run):
    """Cross-daemon merge: at least one op carries shard children
    whose sub-op stages are monotonic with durations >= 0, and
    (ISSUE 14) the commit-wait envelope child rides next to them."""
    with_children = [t for t in dp_run["timelines"]
                     if t.get("children")]
    assert with_children, "no timeline merged a shard sub-op child"
    tl = with_children[-1]
    assert any(label.startswith("shard")
               for label in tl["children"]), tl["children"]
    for label, rows in tl["children"].items():
        names = [r["stage"] for r in rows]
        if label.startswith("shard"):
            assert names[0] == "subop_send", names
            assert "subop_commit" in names, names
        ts = [r["t_us"] for r in rows]
        assert ts == sorted(ts), rows
        assert all(r["dur_us"] >= 0 for r in rows), rows
    # the commit-wait envelope: anchored where commit_wait starts,
    # dispatch -> ship -> ack in order (the commit-path X-ray)
    commit = tl["children"].get("commit")
    assert commit is not None, tl["children"]
    names = [r["stage"] for r in commit]
    assert names[0] == "commit_start", names
    assert names[-1] == "commit_ack_wait", names
    assert "commit_dispatch" in names and \
        "commit_ship_wait" in names, names


def test_messenger_per_type_counters_advance(dp_run):
    snap = msgr_telemetry().snapshot()
    by_type = snap["by_type"]
    # under bulk ingest (the default) shard fan-out rides
    # MECSubWriteBatch/-Reply — ONE frame per (peer, flush) — instead
    # of per-(op, shard) MECSubWrite singletons (the ISSUE-9 fan-out
    # contract, asserted exactly in test_bulk_ingest)
    for mtype in (M.MOSDOp.MSG_TYPE, M.MOSDOpReply.MSG_TYPE,
                  M.MECSubWriteBatch.MSG_TYPE,
                  M.MECSubWriteBatchReply.MSG_TYPE):
        ent = by_type.get(str(mtype))
        assert ent is not None, f"type {mtype} missing: {by_type}"
        assert ent["sent"] > 0 and ent["sent_bytes"] > 0, ent
        assert ent["recv"] > 0 and ent["recv_bytes"] > 0, ent
        assert ent["serialize_s"] >= 0.0
    counters = snap["counters"]
    assert counters["send_msgs"] > 0
    assert counters["recv_msgs"] > 0
    assert counters["serialize_time"]["avgcount"] > 0
    assert counters["send_queue_wait"]["avgcount"] > 0


def test_queue_depth_gauges_return_to_zero(dp_run):
    """send-queue and dispatch-queue gauges drain to exactly zero at
    idle (heartbeats tick through, so poll for a quiescent read)."""
    perf = msgr_telemetry().perf
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        send_d = perf.get("send_queue_depth")
        disp_d = perf.get("dispatch_queue_depth")
        if send_d == 0 and disp_d == 0:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"gauges stuck: send={send_d} dispatch={disp_d}")


def test_tracing_off_zero_spans_but_counters_recorded(dp_run):
    """trace_enabled=false is the literal-NOOP escape hatch: zero
    Span allocations (the always-on default's zero-RETENTION contract
    is pinned separately in test_trace_sampling.py)."""
    assert dp_run["spans"] == 0, \
        f"{dp_run['spans']} Span objects allocated with tracing off"
    perf = dataplane().perf
    assert perf.get("ops_timed") >= N_BURST + 1
    assert perf.get("stage_engine_stage_wait")["avgcount"] >= N_BURST
    # the pow2 histogram twin recorded the same observations
    assert sum(perf.get("stage_engine_stage_wait_us")) >= N_BURST


def test_historic_ops_carry_stage_timeline(dp_run):
    """Satellite: dump_historic_ops entries include the timeline."""
    staged = []
    for osd in dp_run["cluster"].osds.values():
        for op in osd.op_tracker.dump_historic()["ops"]:
            if "stages" in op and "osd_op" in op["desc"]:
                staged.append(op)
    assert staged, "no historic op carries a stage timeline"
    names = {s["stage"] for op in staged
             for s in op["stages"]["stages"]}
    assert "engine_stage_wait" in names, names
    assert "commit_wait" in names, names


def test_dump_op_timeline_and_age_histogram_asok(dp_run):
    osd = next(iter(dp_run["cluster"].osds.values()))
    out = asok_command(osd.asok.path, "dump_op_timeline")
    assert out["glossary"]["engine_stage_wait"]
    bd = out["breakdown"]
    assert bd["ops"] >= N_BURST + 1
    assert bd["coverage_pct"] >= 90.0, bd
    assert "engine_stage_wait" in bd["stages"]
    assert out["recent"], "no recent timelines served"
    hist = asok_command(osd.asok.path, "op age histogram")
    assert hist["total_ops"] >= N_BURST + 1
    assert hist["p99_ms"] >= hist["p50_ms"] >= 0
    assert sum(b["count"] for b in hist["buckets"]) \
        == hist["total_ops"]


def test_degraded_read_timeline_rides_engine_decode(dp_run):
    """The decode seam: a degraded read's timeline crosses the engine
    too (engine_stage_wait + device_finalize from the decode flush)."""
    cluster, io = dp_run["cluster"], dp_run["io"]
    osdmap = cluster.mon.osdmap
    pool_id = osdmap.pool_by_name["dp"]
    ps = osdmap.object_to_pg(pool_id, "obj0")
    _, acting, primary = osdmap.pg_to_up_acting(pool_id, ps)
    # kill a non-primary shard holder so the read must reconstruct
    victim = next(o for o in acting if o != primary)
    cluster.kill_osd(victim)
    cluster.wait_for_osd_down(victim)
    before = dataplane().perf.get("stage_engine_stage_wait")["avgcount"]
    assert io.read("obj0") == b"d" * OBJ_BYTES
    after = dataplane().perf.get("stage_engine_stage_wait")["avgcount"]
    assert after > before, "degraded read never crossed the engine"
    cluster.revive_osd(victim)
    cluster.wait_for_clean(timeout=60)
