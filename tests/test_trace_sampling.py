"""ISSUE 10 tentpole coverage: always-on tail-sampled tracing.

Unit level (no cluster): the keep/drop policy — dropped traces retain
ZERO span objects (weakref-asserted), head sampling, the adaptive
slowness rule, error and fault-window keeps, the pending/keep-ring
memory bounds, wall-clock anchors on Span and StageClock dumps, and
the merged-tree builder.

Cluster level: the acceptance chain — a scripted slow op against a
device-backend EC pool yields (a) a kept trace whose merged tree
spans client, primary, shard OSDs and the engine via the mgr trace
module, (b) an autopsy with stage timeline + counter window + fault
events over ``dump_autopsies``, (c) a prometheus histogram exemplar
resolving to that trace_id, (d) a Perfetto-loadable Chrome-trace
export (mgr ``trace export`` AND the tools/trace_export.py CLI), and
(e) optracker slow-op entries embedding the kept trace_id. Plus the
loopback-vs-TCP fidelity pin: trace context, stage timelines and
sampling decisions are identical for the same ops across the PR-9
in-process loopback and the real-wire path.
"""

import gc
import json
import time
import weakref

import pytest

from ceph_tpu.utils import tracing
from ceph_tpu.utils.config import g_conf

_TRACE_KEYS = ("trace_enabled", "trace_all", "trace_sample_every",
               "trace_slow_factor", "trace_slow_min_ms",
               "trace_pending_traces", "trace_max_spans",
               "trace_keep_ring", "autopsy_ring_size")


@pytest.fixture
def trace_conf():
    """Save/restore every trace option; fresh tracer + autopsy state
    on both sides."""
    from ceph_tpu.utils import autopsy
    conf = g_conf()
    old = {k: conf[k] for k in _TRACE_KEYS}
    tracing.tracer().clear()
    autopsy.store().clear()
    yield conf
    for k, v in old.items():
        conf.set(k, v)
    tracing.tracer().clear()
    autopsy.store().clear()


def _no_cause_keeps(conf):
    """Disable every keep rule (drop-everything baseline)."""
    conf.set("trace_all", False)
    conf.set("trace_sample_every", 0)
    conf.set("trace_slow_min_ms", 1e12)
    conf.set("trace_slow_factor", 1e6)


# -- the zero-retention contract --------------------------------------

def test_dropped_traces_retain_zero_span_objects(trace_conf):
    """Acceptance bar: a dropped trace keeps NOTHING — no Span object
    survives, the pending buffer is empty, and only counters moved."""
    conf = trace_conf
    _no_cause_keeps(conf)
    t = tracing.tracer()
    before = t.perf.get("trace_dropped")
    refs = []
    for i in range(100):
        root = t.new_trace(f"osd_op(op=1 oid=o{i})", "client.zr",
                           op_type="zr")
        child = root.child("sub", "osd.0")
        grand = child.child("engine_flush")
        refs += [weakref.ref(root), weakref.ref(child),
                 weakref.ref(grand)]
        grand.finish()
        child.finish()
        root.finish()
        del root, child, grand
    gc.collect()
    alive = [r for r in refs if r() is not None]
    assert not alive, f"{len(alive)} Span objects retained after drop"
    st = t.stats()
    assert st["pending"] == 0 and st["kept"] == 0
    assert t.perf.get("trace_dropped") - before == 100


def test_disabled_mode_allocates_nothing(trace_conf):
    conf = trace_conf
    conf.set("trace_enabled", False)
    t = tracing.tracer()
    assert not t.enabled
    span = t.new_trace("x", "svc")
    assert span is tracing.NOOP
    assert t.from_wire("abc:7", "x", "svc") is tracing.NOOP


# -- keep rules --------------------------------------------------------

def test_head_sampling_keeps_every_nth(trace_conf):
    conf = trace_conf
    _no_cause_keeps(conf)
    conf.set("trace_sample_every", 10)
    t = tracing.tracer()
    t.clear()
    kept = [bool(t.new_trace("op", "c", op_type="hs").finish())
            for _ in range(30)]
    assert [i for i, k in enumerate(kept) if k] == [9, 19, 29]
    for rec in t.kept():
        assert rec["reason"] == "sample"


def test_slow_keep_is_adaptive_per_op_type(trace_conf):
    """EWMA-relative: a sleep op far above its type's history is
    kept (reason slow); same-speed ops are not."""
    conf = trace_conf
    _no_cause_keeps(conf)
    conf.set("trace_slow_min_ms", 10.0)
    conf.set("trace_slow_factor", 3.0)
    t = tracing.tracer()
    t.clear()
    for _ in range(10):           # warm the type's EWMA with fast ops
        assert not t.new_trace("op", "c", op_type="sl").finish()
    slow = t.new_trace("op", "c", op_type="sl")
    time.sleep(0.05)
    assert slow.finish() is True
    assert t.keep_reason(slow.trace_id) == "slow"
    # a different op type has its own baseline: its first op is never
    # slow-kept off this type's history
    other = t.new_trace("op", "c", op_type="other_type")
    assert not other.finish()


def test_error_keep_and_autopsy_contents(trace_conf):
    """An errored op is kept and autopsied: timeline, span tree,
    counter window (a forced flight-recorder sample), fault log."""
    from ceph_tpu.utils import autopsy
    from ceph_tpu.utils.stage_clock import StageClock
    conf = trace_conf
    _no_cause_keeps(conf)
    t = tracing.tracer()
    root = t.new_trace("osd_op(op=1 oid=boom)", "client.e",
                       op_type="er")
    child = root.child("sub", "osd.1")
    child.finish()
    clock = StageClock()
    clock.mark("objecter_encode")
    clock.mark("commit_reply")
    root.attach_clock(clock)
    root.set_error("code=-5")
    assert root.finish() is True
    assert t.keep_reason(root.trace_id) == "error"
    entry = autopsy.store().get(root.trace_id)
    assert entry is not None
    assert entry["reason"] == "error" and entry["error"] == "code=-5"
    assert len(entry["spans"]) == 2
    names = {s["name"] for s in entry["spans"]}
    assert names == {"osd_op(op=1 oid=boom)", "sub"}
    assert entry["timeline"]["stages"][1]["stage"] == "objecter_encode"
    assert entry["timeline"]["wall_epoch"] > 1e9
    assert entry["counter_window"], "forced sample missing"
    assert isinstance(entry["fault_events"], list)
    json.dumps(entry)             # asok-servable


def test_fault_window_keep(trace_conf):
    """A fault-registry fire inside the op's window keeps the trace
    (reason fault)."""
    from ceph_tpu.utils import faults
    conf = trace_conf
    _no_cause_keeps(conf)
    reg = faults.reset_for_tests(seed=3)
    t = tracing.tracer()
    quiet = t.new_trace("op", "c", op_type="fw")
    assert not quiet.finish()          # no fire in window: dropped
    rule = reg.add("store_eio", oid_prefix="fault_obj")
    victim = t.new_trace("op", "c", op_type="fw")
    assert faults.check_store_read("cid", "fault_obj_1") is True
    assert victim.finish() is True
    assert t.keep_reason(victim.trace_id) == "fault"
    reg.remove(rule)
    reg.reseed(0)


# -- memory bounds -----------------------------------------------------

def test_pending_buffer_bounded_and_evicts(trace_conf):
    conf = trace_conf
    _no_cause_keeps(conf)
    conf.set("trace_pending_traces", 8)
    t = tracing.tracer()
    t.clear()
    before = t.perf.get("trace_evicted")
    for i in range(20):
        # children finish, roots never do: the never-completed-trace
        # leak shape the pending bound exists for
        root = t.new_trace(f"op{i}", "c")
        root.child("sub").finish()
    assert t.stats()["pending"] <= 8
    assert t.perf.get("trace_evicted") - before >= 12


def test_keep_ring_bounded(trace_conf):
    conf = trace_conf
    conf.set("trace_all", True)
    conf.set("trace_keep_ring", 4)
    t = tracing.tracer()
    t.clear()
    tids = []
    for i in range(10):
        root = t.new_trace(f"op{i}", "c")
        tids.append(root.trace_id)
        root.finish()
    assert t.stats()["kept"] == 4
    assert all(t.is_kept(tid) for tid in tids[-4:])
    assert not any(t.is_kept(tid) for tid in tids[:6])


def test_span_cap_truncates_not_grows(trace_conf):
    conf = trace_conf
    conf.set("trace_all", True)
    t = tracing.tracer()
    t.clear()
    root = t.new_trace("op", "c")
    for i in range(conf["trace_max_spans"] + 50):
        root.child(f"s{i}").finish()
    root.finish()
    rec = [r for r in t.kept() if r["trace_id"] == root.trace_id][0]
    assert len(rec["spans"]) <= conf["trace_max_spans"] + 1
    assert t.perf.get("trace_spans_truncated") >= 50


# -- anchors + assembly ------------------------------------------------

def test_wall_anchor_on_span_and_stage_clock(trace_conf):
    from ceph_tpu.utils.stage_clock import StageClock
    conf = trace_conf
    conf.set("trace_all", True)
    now = time.time()
    span = tracing.tracer().new_trace("op", "c")
    span.finish()
    d = tracing.tracer().dump(span.trace_id)[0]
    assert abs(d["wall"] - now) < 5.0
    assert "t0" in d
    clock = StageClock()
    clock.mark("objecter_encode")
    assert abs(clock.dump()["wall_epoch"] - now) < 5.0
    # a from_wire continuation derives the SAME anchor (shared
    # monotonic clock): cross-daemon rows align on the epoch axis
    cont = StageClock.from_wire(clock.to_wire())
    assert abs(cont.dump()["wall_epoch"]
               - clock.dump()["wall_epoch"]) < 0.05


def test_build_tree_nests_by_parent(trace_conf):
    conf = trace_conf
    conf.set("trace_all", True)
    t = tracing.tracer()
    t.clear()
    root = t.new_trace("root_op", "client.x")
    c1 = root.child("sub1", "osd.0")
    c2 = root.child("sub2", "osd.1")
    gc1 = c1.child("engine_flush")
    for s in (gc1, c1, c2, root):
        s.finish()
    tree = t.tree(root.trace_id)
    assert tree["services"] == sorted({"client.x", "osd.0", "osd.1"})
    roots = tree["tree"]
    assert len(roots) == 1 and roots[0]["name"] == "root_op"
    kids = {c["name"]: c for c in roots[0]["children"]}
    assert set(kids) == {"sub1", "sub2"}
    assert kids["sub1"]["children"][0]["name"] == "engine_flush"


# -- export tool -------------------------------------------------------

def test_trace_export_cli_round_trip(trace_conf, tmp_path):
    """tools/trace_export.py on a kept-trace record: valid Chrome
    trace JSON with per-service process rows and engine async
    events."""
    from ceph_tpu.tools import trace_export
    conf = trace_conf
    conf.set("trace_all", True)
    t = tracing.tracer()
    t.clear()
    root = t.new_trace("osd_op(op=1 oid=x)", "client.ex")
    sub = root.child("ec_sub_write", "osd.0")
    eng = sub.child("engine_flush")
    eng.event("batch_flush ops=3")
    for s in (eng, sub, root):
        s.finish()
    rec = [r for r in t.kept() if r["trace_id"] == root.trace_id][0]
    src = tmp_path / "trace.json"
    dst = tmp_path / "out.json"
    src.write_text(json.dumps(rec))
    assert trace_export.main(["--input", str(src),
                              "--output", str(dst)]) == 0
    doc = json.loads(dst.read_text())
    events = doc["traceEvents"]
    procs = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"client.ex", "osd.0"}
    spans = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in spans} == \
        {"osd_op(op=1 oid=x)", "ec_sub_write", "engine_flush"}
    # engine flush window renders as an async bar too
    phases = {e["ph"] for e in events
              if e.get("cat") == "engine"}
    assert phases == {"b", "e"}
    # nesting encoded as tid depth
    by_name = {e["name"]: e for e in spans}
    assert by_name["engine_flush"]["tid"] == 2
    assert by_name["osd_op(op=1 oid=x)"]["tid"] == 0


# -- the cluster-level acceptance chain --------------------------------

def _find_op(cluster, oid):
    """(trace_id, optracker entry) for the op on ``oid`` from
    whichever OSD tracked it."""
    for osd in cluster.osds.values():
        for op in osd.op_tracker.dump_historic()["ops"]:
            if oid in op["desc"]:
                return op.get("trace_id"), op
    return None, None


def test_scripted_slow_op_full_artifact_chain(trace_conf):
    """The acceptance bar, end to end: scripted slow write -> kept
    trace -> mgr merged tree -> autopsy -> exemplar -> Perfetto
    export -> slow-op report link."""
    from ceph_tpu.qa.cluster import MiniCluster
    from ceph_tpu.tools.trace_export import export as export_doc
    from ceph_tpu.utils import autopsy, faults, prometheus
    from ceph_tpu.utils.admin_socket import asok_command

    conf = trace_conf
    _no_cause_keeps(conf)
    conf.set("trace_slow_min_ms", 60.0)
    conf.set("trace_slow_factor", 3.0)
    faults.reset_for_tests(seed=11)
    t = tracing.tracer()
    with MiniCluster(n_osds=3) as cluster:
        mgr = cluster.start_mgr()
        rados = cluster.client()
        cluster.create_ec_pool("slowpool", k=2, m=1, pg_num=1,
                               backend="jax")
        io = rados.open_ioctx("slowpool")
        io.op_timeout = 240.0     # CPU jit compiles on first write
        for i in range(6):
            io.write_full(f"warm{i}", b"w" * 20_000)
        # script the slow op: hold this write's shard sub-writes
        # 0.25 s before the wire — commit_wait stretches well past
        # the adaptive threshold AND the fault window marks the op
        reg = faults.registry()
        rule = reg.add("msgr_delay", msg_type=30, delay_s=0.25,
                       max_fires=2)
        io.write_full("slow_obj", b"s" * 20_000)
        reg.remove(rule)

        tid, entry = _find_op(cluster, "slow_obj")
        assert tid, "primary's optracker lost the op"
        assert t.is_kept(tid), t.stats()
        reason = t.keep_reason(tid)
        assert reason in ("slow", "fault"), reason

        # optracker satellite: the historic/slow-op report links to
        # the kept trace
        assert entry["trace_id"] == tid
        assert entry["trace_kept"] is True
        slowest = [
            op for osd in cluster.osds.values()
            for op in osd.op_tracker.dump_slowest()["ops"]
            if op.get("trace_id") == tid]
        assert slowest and slowest[0]["trace_kept"] is True

        # autopsy over the asok: timeline + counter window + faults
        osd0 = next(iter(cluster.osds.values()))
        out = asok_command(osd0.asok.path, "dump_autopsies")
        mine = [a for a in out["autopsies"] if a["trace_id"] == tid]
        assert mine, [a["trace_id"] for a in out["autopsies"]]
        aut = mine[-1]
        stages = {s["stage"] for s in aut["timeline"]["stages"]}
        assert "commit_wait" in stages and "commit_reply" in stages
        assert aut["timeline"]["wall_epoch"] > 1e9
        assert aut["counter_window"], "no flight-recorder window"
        assert aut["fault_events"], "msgr_delay fire not in autopsy"
        assert out["counters"]["autopsy_recorded"] >= 1

        # the autopsy also rides the health diagnostics bundle
        health_mod = mgr.modules["health"]
        bundle = health_mod.engine.dump_diagnostics("test")
        assert any(a["trace_id"] == tid
                   for a in bundle["autopsies"])

        # mgr cluster-wide assembly: ONE merged tree spanning client,
        # primary, shard OSDs and the engine
        out = asok_command(mgr.asok.path, "trace dump", trace_id=tid)
        assert out["code"] == 0, out
        tree = out["data"]
        services = set(tree["services"])
        assert any(s.startswith("client") for s in services)
        assert sum(1 for s in services if s.startswith("osd.")) >= 2

        def names(node, acc):
            acc.add(node["name"].split("(")[0])
            for c in node["children"]:
                names(c, acc)
            return acc

        got = set()
        for root in tree["tree"]:
            names(root, got)
        assert "osd_op" in got          # client root
        assert "handle_osd_op" in got   # primary
        assert "sub_write" in got       # shard OSDs
        assert "engine_flush" in got    # engine
        assert "kernel_dispatch" in got

        # prometheus exemplar: the op_total bucket links to the trace
        text = prometheus.render_text()
        ex_lines = [ln for ln in text.splitlines()
                    if "op_total_us_bucket" in ln and tid in ln]
        assert ex_lines, f"no op_total exemplar for {tid}"

        # Perfetto export, both surfaces: the mgr command and the
        # autopsy-entry CLI shape
        out = asok_command(mgr.asok.path, "trace export",
                           trace_id=tid)
        assert out["code"] == 0, out
        ct = out["data"]
        assert ct["traceEvents"], ct
        procs = {e["args"]["name"] for e in ct["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert any(p.startswith("client") for p in procs)
        assert any(p.startswith("osd.") for p in procs)
        assert any(e.get("cat") == "engine" and e["ph"] in "be"
                   for e in ct["traceEvents"])
        ct2 = export_doc(autopsy.store().get(tid))
        assert any(e["args"]["name"] == "timeline"
                   for e in ct2["traceEvents"]
                   if e["ph"] == "M" and e["name"] == "process_name")
        json.dumps(ct2)

        # the dashboard trace endpoint serves the same surface
        dash = mgr.modules.get("dashboard")
        if dash is not None:
            code, _, body = dash._api("/api/traces")
            assert code == 200
            payload = json.loads(body)
            assert any(r["trace_id"] == tid for r in payload["kept"])
            assert any(a["trace_id"] == tid
                       for a in payload["autopsies"])


def _fidelity_run(loopback: bool):
    """One 4-write run; returns (kept decisions, stage sequences,
    span shapes) keyed per object."""
    import os

    from ceph_tpu.qa.cluster import MiniCluster
    from ceph_tpu.utils.dataplane import dataplane

    os.environ["CEPH_TPU_MSGR_LOOPBACK"] = "1" if loopback else "0"
    t = tracing.tracer()
    t.clear()
    dataplane().reset()
    try:
        with MiniCluster(n_osds=3) as cluster:
            rados = cluster.client()
            cluster.create_ec_pool("fid", k=2, m=1, pg_num=1)
            io = rados.open_ioctx("fid")
            for i in range(4):
                io.write_full(f"fobj{i}", b"f" * 8_000)
            decisions, shapes = {}, {}
            for i in range(4):
                tid, entry = _find_op(cluster, f"fobj{i}")
                assert tid, f"fobj{i} not tracked"
                decisions[f"fobj{i}"] = t.is_kept(tid)
                spans = t.dump(tid)
                # client instance ids are random per connection:
                # normalize so the shape compares structure only
                shapes[f"fobj{i}"] = sorted(
                    (s["name"].split("(")[0],
                     "client" if s["service"].startswith("client")
                     else s["service"])
                    for s in spans)
            stage_seqs = [
                tuple(s["stage"] for s in tl["stages"])
                for tl in dataplane().recent()]
        return decisions, shapes, sorted(set(stage_seqs))
    finally:
        os.environ.pop("CEPH_TPU_MSGR_LOOPBACK", None)


def test_loopback_and_tcp_observability_identical(trace_conf):
    """Satellite: the PR-9 in-process loopback must be
    observability-transparent — same trace span shapes, same stage
    timeline structure, same sampling decisions as the real wire."""
    conf = trace_conf
    _no_cause_keeps(conf)
    conf.set("trace_sample_every", 2)
    loop = _fidelity_run(loopback=True)
    wire = _fidelity_run(loopback=False)
    assert loop[0] == wire[0], (loop[0], wire[0])   # decisions
    # span shape per object: kept traces carry identical
    # (name, service) trees on both paths; dropped ones are empty
    # on both
    assert loop[1] == wire[1]
    # stage-name sequences (structure, not values) match
    assert loop[2] == wire[2]
