"""Integration: failure handling — degraded reads, recovery, thrash.

The qa/standalone test-erasure-code.sh "kill osds and read back" role
plus thrash-lite (qa/tasks ceph_manager.Thrasher.kill_osd/revive_osd).
These tests use their own cluster instances (they mutate membership).
"""

import os
import time

import pytest

from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils.config import g_conf


@pytest.fixture
def fast_death():
    """Tighten failure-detection knobs so kill->down takes ~2s."""
    conf = g_conf()
    old_int = conf["osd_heartbeat_interval"]
    old_grace = conf["osd_heartbeat_grace"]
    conf.set("osd_heartbeat_interval", 0.25)
    conf.set("osd_heartbeat_grace", 1.0)
    yield
    conf.set("osd_heartbeat_interval", old_int)
    conf.set("osd_heartbeat_grace", old_grace)


def test_ec_degraded_read_and_recovery(fast_death):
    with MiniCluster(n_osds=4) as cluster:
        rados = cluster.client()
        cluster.create_ec_pool("ec", k=2, m=1, pg_num=4)
        io = rados.open_ioctx("ec")
        blobs = {f"obj{i}": os.urandom(20_000 + i) for i in range(8)}
        for oid, blob in blobs.items():
            io.write_full(oid, blob)

        victim = 1
        epoch = cluster.epoch()
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=30)
        rados.wait_for_epoch(epoch + 1, timeout=10)

        # degraded reads must still return every byte (decode path)
        for oid, blob in blobs.items():
            assert io.read(oid) == blob, f"degraded read of {oid}"

        # writes while degraded
        io.write_full("while_down", b"d" * 10_000)
        assert io.read("while_down") == b"d" * 10_000

        # revive: peering finds the stale shard, recovery pushes chunks
        cluster.revive_osd(victim)
        cluster.wait_for_osds_up(timeout=15)
        # touch every pg so primaries re-peer promptly
        for oid, blob in blobs.items():
            assert io.read(oid) == blob
        cluster.wait_for_clean(timeout=30)
        for oid, blob in blobs.items():
            assert io.read(oid) == blob


def test_replicated_failover_to_new_primary(fast_death):
    with MiniCluster(n_osds=3) as cluster:
        rados = cluster.client()
        cluster.create_pool("rep", pg_num=4, size=3)
        io = rados.open_ioctx("rep")
        for i in range(6):
            io.write_full(f"o{i}", f"payload-{i}".encode() * 100)

        # kill one osd; every PG it was primary for moves to a replica
        epoch = cluster.epoch()
        cluster.kill_osd(0)
        cluster.wait_for_osd_down(0, timeout=30)
        rados.wait_for_epoch(epoch + 1, timeout=10)
        for i in range(6):
            assert io.read(f"o{i}") == f"payload-{i}".encode() * 100
        # writes land on the new primaries
        io.write_full("post_fail", b"x" * 500)
        assert io.read("post_fail") == b"x" * 500

        # revive; stale shard catches up (including ops it missed)
        cluster.revive_osd(0)
        cluster.wait_for_osds_up(timeout=15)
        for i in range(6):
            assert io.read(f"o{i}") == f"payload-{i}".encode() * 100
        assert io.read("post_fail") == b"x" * 500
        cluster.wait_for_clean(timeout=30)


def test_removal_propagates_to_revived_osd(fast_death):
    with MiniCluster(n_osds=3) as cluster:
        rados = cluster.client()
        cluster.create_pool("rp", pg_num=2, size=3)
        io = rados.open_ioctx("rp")
        io.write_full("doomed", b"z" * 1000)
        io.write_full("keeper", b"k" * 1000)

        epoch = cluster.epoch()
        cluster.kill_osd(2)
        cluster.wait_for_osd_down(2, timeout=30)
        rados.wait_for_epoch(epoch + 1, timeout=10)
        io.remove("doomed")                 # osd.2 misses this

        cluster.revive_osd(2)
        cluster.wait_for_osds_up(timeout=15)
        # trigger peering on all pgs
        assert io.read("keeper") == b"k" * 1000
        cluster.wait_for_clean(timeout=30)
        # the revived osd must have dropped its stale copy
        time.sleep(0.5)
        store = cluster._stores[2]
        for cid in store.list_collections():
            if cid.startswith("pg_"):
                assert "doomed" not in store.list_objects(cid), cid
