"""Integration: full in-process cluster — mon + OSDs + client.

The qa/standalone/erasure-code/test-erasure-code.sh role: boot daemons,
create pools (replicated + every EC plugin), write/read/remove through
the real client stack, kill OSDs and verify degraded reads and
recovery (thrash-lite).
"""

import os

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.qa.cluster import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_osds=4) as c:
        yield c


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster.client()


def test_replicated_pool_io(cluster, rados):
    cluster.create_pool("rep", pg_num=4, size=3)
    io = rados.open_ioctx("rep")
    payload = os.urandom(100_000)
    v = io.write_full("obj1", payload)
    assert v >= 1
    assert io.read("obj1") == payload
    assert io.stat("obj1") == len(payload)
    # ranged read
    assert io.read("obj1", length=100, offset=50) == payload[50:150]
    # overwrite
    io.write_full("obj1", b"short")
    assert io.read("obj1") == b"short"
    io.remove("obj1")
    with pytest.raises(RadosError):
        io.read("obj1")


def test_replicated_many_objects(cluster, rados):
    cluster.create_pool("rep_many", pg_num=8, size=2)
    io = rados.open_ioctx("rep_many")
    blobs = {f"o{i}": os.urandom(1000 + i) for i in range(20)}
    for oid, blob in blobs.items():
        io.write_full(oid, blob)
    assert io.list_objects() == sorted(blobs)
    for oid, blob in blobs.items():
        assert io.read(oid) == blob


def test_ec_pool_io(cluster, rados):
    cluster.create_ec_pool("ecpool", k=2, m=1, plugin="jerasure",
                           pg_num=4)
    io = rados.open_ioctx("ecpool")
    payload = os.urandom(300_000)
    io.write_full("big", payload)
    assert io.read("big") == payload
    assert io.stat("big") == len(payload)
    # small object (sub-stripe, exercises padding)
    io.write_full("small", b"x")
    assert io.read("small") == b"x"
    # empty object
    io.write_full("empty", b"")
    assert io.read("empty") == b""
    io.remove("small")
    with pytest.raises(RadosError):
        io.stat("small")


def test_ec_rmw_write(cluster, rados):
    io = rados.open_ioctx("ecpool")
    io.write_full("rmw", b"A" * 10_000)
    io.write("rmw", b"B" * 100, offset=5000)
    data = io.read("rmw")
    assert data[:5000] == b"A" * 5000
    assert data[5000:5100] == b"B" * 100
    assert data[5100:] == b"A" * 4900
    io.append("rmw", b"C" * 50)
    assert io.read("rmw")[-50:] == b"C" * 50
    assert io.stat("rmw") == 10_050


def test_ec_isa_and_shec_pools(cluster, rados):
    for name, plugin, kw in (
            ("isa_pool", "isa", {}),
            ("shec_pool", "shec", {"c": 1}),
    ):
        cluster.create_ec_pool(name, k=2, m=1, plugin=plugin, pg_num=2,
                               **kw)
        io = rados.open_ioctx(name)
        payload = os.urandom(50_000)
        io.write_full("obj", payload)
        assert io.read("obj") == payload
