// lzcodecs — native LZ4-block and Snappy codecs (the reference vendors
// liblz4/libsnappy as submodules and wraps them via CompressionPlugin,
// src/compressor/{lz4,snappy}/; neither library ships in this image,
// so the block formats are implemented from their public specs:
//   LZ4 block:  https://github.com/lz4/lz4/blob/dev/doc/lz4_Block_format.md
//   Snappy:     https://github.com/google/snappy/blob/main/format_description.txt
// Compressors use greedy hash-chain matching (format-conformant; any
// spec decoder reads the output). Exposed through ctypes like the rest
// of this library.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t hash32(uint32_t v) { return (v * 2654435761u) >> 20; }
constexpr int HASH_SIZE = 1 << 12;

inline uint32_t load32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

}  // namespace

extern "C" {

// ---------------- LZ4 block format ----------------

// worst case: incompressible data + token overhead
int64_t lz4_max_compressed(int64_t n) { return n + n / 255 + 16; }

// returns compressed size, or -1 if dst too small
int64_t lz4_compress(const uint8_t *src, int64_t n, uint8_t *dst,
                     int64_t cap) {
  if (n == 0) return 0;
  int32_t table[HASH_SIZE];
  for (int i = 0; i < HASH_SIZE; i++) table[i] = -1;
  const int64_t MFLIMIT = 12;  // spec: last match must start 12B short
  int64_t ip = 0, anchor = 0, op = 0;

  auto emit = [&](int64_t lit_len, const uint8_t *lit, int64_t m_len,
                  int64_t m_off) -> bool {
    int64_t need = 1 + lit_len + lit_len / 255 + 1 + 2 + m_len / 255 + 1;
    if (op + need > cap) return false;
    uint8_t *tok = dst + op++;
    // literal length
    if (lit_len >= 15) {
      *tok = 15 << 4;
      int64_t rem = lit_len - 15;
      while (rem >= 255) { dst[op++] = 255; rem -= 255; }
      dst[op++] = (uint8_t)rem;
    } else {
      *tok = (uint8_t)(lit_len << 4);
    }
    std::memcpy(dst + op, lit, lit_len);
    op += lit_len;
    if (m_len == 0) return true;  // final literals-only sequence
    dst[op++] = (uint8_t)(m_off & 0xff);
    dst[op++] = (uint8_t)(m_off >> 8);
    int64_t ml = m_len - 4;       // spec: stored minus minmatch
    if (ml >= 15) {
      *tok |= 15;
      ml -= 15;
      while (ml >= 255) { dst[op++] = 255; ml -= 255; }
      dst[op++] = (uint8_t)ml;
    } else {
      *tok |= (uint8_t)ml;
    }
    return true;
  };

  while (ip + MFLIMIT < n) {
    uint32_t h = hash32(load32(src + ip)) & (HASH_SIZE - 1);
    int64_t cand = table[h];
    table[h] = (int32_t)ip;
    if (cand >= 0 && ip - cand <= 0xffff &&
        load32(src + cand) == load32(src + ip)) {
      int64_t m_len = 4;
      while (ip + m_len + 5 < n && src[cand + m_len] == src[ip + m_len])
        m_len++;
      if (!emit(ip - anchor, src + anchor, m_len, ip - cand)) return -1;
      ip += m_len;
      anchor = ip;
    } else {
      ip++;
    }
  }
  if (!emit(n - anchor, src + anchor, 0, 0)) return -1;
  return op;
}

// returns decompressed size, or -1 on corrupt input / overflow
int64_t lz4_decompress(const uint8_t *src, int64_t n, uint8_t *dst,
                       int64_t cap) {
  int64_t ip = 0, op = 0;
  while (ip < n) {
    uint8_t token = src[ip++];
    int64_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > n || op + lit > cap) return -1;
    std::memcpy(dst + op, src + ip, lit);
    ip += lit;
    op += lit;
    if (ip >= n) break;          // last sequence has no match
    if (ip + 2 > n) return -1;
    int64_t off = src[ip] | (src[ip + 1] << 8);
    ip += 2;
    if (off == 0 || off > op) return -1;
    int64_t ml = (token & 15);
    if (ml == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        ml += b;
      } while (b == 255);
    }
    ml += 4;
    if (op + ml > cap) return -1;
    for (int64_t i = 0; i < ml; i++) {  // overlap-safe byte copy
      dst[op] = dst[op - off];
      op++;
    }
  }
  return op;
}

// ---------------- Snappy format ----------------

int64_t snappy_max_compressed(int64_t n) { return 32 + n + n / 6; }

static int64_t put_varint(uint8_t *dst, uint64_t v) {
  int64_t i = 0;
  while (v >= 0x80) {
    dst[i++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  dst[i++] = (uint8_t)v;
  return i;
}

int64_t snappy_compress(const uint8_t *src, int64_t n, uint8_t *dst,
                        int64_t cap) {
  int64_t op = put_varint(dst, (uint64_t)n);
  int32_t table[HASH_SIZE];
  for (int i = 0; i < HASH_SIZE; i++) table[i] = -1;
  int64_t ip = 0, anchor = 0;

  auto emit_literal = [&](int64_t len, const uint8_t *lit) -> bool {
    while (len > 0) {                 // chunk: 2-byte length max
      int64_t piece = len > 65536 ? 65536 : len;
      if (op + piece + 8 > cap) return false;
      int64_t l = piece - 1;
      if (l < 60) {
        dst[op++] = (uint8_t)(l << 2);
      } else if (l < 256) {
        dst[op++] = (uint8_t)(60 << 2);
        dst[op++] = (uint8_t)l;
      } else {
        dst[op++] = (uint8_t)(61 << 2);
        dst[op++] = (uint8_t)(l & 0xff);
        dst[op++] = (uint8_t)(l >> 8);
      }
      std::memcpy(dst + op, lit, piece);
      op += piece;
      lit += piece;
      len -= piece;
    }
    return true;
  };
  auto emit_copy = [&](int64_t off, int64_t len) -> bool {
    while (len > 0) {
      if (op + 5 > cap) return false;
      if (len >= 4 && len < 12 && off < 2048) {
        dst[op++] = (uint8_t)(1 | ((len - 4) << 2) | ((off >> 8) << 5));
        dst[op++] = (uint8_t)(off & 0xff);
        len = 0;
      } else {
        int64_t l = len > 64 ? 64 : len;
        if (l < 4) return false;     // spec min copy is 4
        dst[op++] = (uint8_t)(2 | ((l - 1) << 2));
        dst[op++] = (uint8_t)(off & 0xff);
        dst[op++] = (uint8_t)(off >> 8);
        len -= l;
        if (len > 0 && len < 4) {    // avoid a tail shorter than 4
          len += l - 60;             // rebalance: emit 60, leave l-60+len
          op -= 3;
          dst[op++] = (uint8_t)(2 | ((60 - 1) << 2));
          dst[op++] = (uint8_t)(off & 0xff);
          dst[op++] = (uint8_t)(off >> 8);
        }
      }
    }
    return true;
  };

  while (ip + 8 < n) {
    uint32_t h = hash32(load32(src + ip)) & (HASH_SIZE - 1);
    int64_t cand = table[h];
    table[h] = (int32_t)ip;
    if (cand >= 0 && ip - cand <= 0xffff &&
        load32(src + cand) == load32(src + ip)) {
      int64_t m_len = 4;
      while (ip + m_len < n && src[cand + m_len] == src[ip + m_len])
        m_len++;
      if (!emit_literal(ip - anchor, src + anchor)) return -1;
      if (!emit_copy(ip - cand, m_len)) return -1;
      ip += m_len;
      anchor = ip;
    } else {
      ip++;
    }
  }
  if (!emit_literal(n - anchor, src + anchor)) return -1;
  return op;
}

int64_t snappy_uncompressed_length(const uint8_t *src, int64_t n) {
  uint64_t v = 0;
  int shift = 0;
  for (int64_t i = 0; i < n && i < 10; i++) {
    v |= (uint64_t)(src[i] & 0x7f) << shift;
    if (!(src[i] & 0x80)) return (int64_t)v;
    shift += 7;
  }
  return -1;
}

int64_t snappy_decompress(const uint8_t *src, int64_t n, uint8_t *dst,
                          int64_t cap) {
  uint64_t want = 0;
  int shift = 0;
  int64_t ip = 0;
  while (ip < n) {
    if (shift > 63) return -1;  // >10-byte varint: corrupt (a shift
                                // past 63 would be UB)
    uint8_t b = src[ip++];
    want |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  int64_t op = 0;
  while (ip < n) {
    uint8_t tag = src[ip++];
    int64_t len, off;
    switch (tag & 3) {
      case 0: {                      // literal
        len = (tag >> 2) + 1;
        if (len > 60) {
          int extra = (int)len - 60;
          if (ip + extra > n) return -1;
          len = 0;
          for (int i = 0; i < extra; i++) len |= (int64_t)src[ip++] << (8 * i);
          len += 1;
        }
        if (ip + len > n || op + len > cap) return -1;
        std::memcpy(dst + op, src + ip, len);
        ip += len;
        op += len;
        continue;
      }
      case 1:                        // copy, 1-byte offset
        if (ip >= n) return -1;
        len = ((tag >> 2) & 7) + 4;
        off = ((tag >> 5) << 8) | src[ip++];
        break;
      case 2:                        // copy, 2-byte offset
        if (ip + 2 > n) return -1;
        len = (tag >> 2) + 1;
        off = src[ip] | (src[ip + 1] << 8);
        ip += 2;
        break;
      default:                       // copy, 4-byte offset
        if (ip + 4 > n) return -1;
        len = (tag >> 2) + 1;
        off = (int64_t)load32(src + ip);
        ip += 4;
        break;
    }
    if (off == 0 || off > op || op + len > cap) return -1;
    for (int64_t i = 0; i < len; i++) {
      dst[op] = dst[op - off];
      op++;
    }
  }
  return op == (int64_t)want ? op : -1;
}

}  // extern "C"
