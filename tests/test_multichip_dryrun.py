"""Tier-1 smoke for the MULTICHIP dryrun (round 9).

``__graft_entry__.dryrun_multichip`` is the driver's multi-chip gate:
it builds the ('stripe' x 'shard') mesh, runs the distributed
encode/degraded-read/clay-repair collectives, AND (round 9) pushes one
real stripe batch through the DeviceEncodeEngine's mesh route. It must
run in a FRESH process (it steers JAX onto the virtual host-platform
mesh before the backend initializes), so this test execs it as a
subprocess on 8 host-platform devices — a mesh/engine regression fails
here in tier-1 instead of burning a TPU round.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_multichip_bench_rows_and_scaling_smoke():
    """ISSUE 12: ``bench.py --multichip-sub`` — the exact subprocess
    a single-chip driver spawns — lands BOTH multichip rows plus the
    scaling record on 8 host-platform devices. The near-linear bar
    (>= 6x at 8 devices) is asserted when the host has >= 8 real
    cores to express it; below that the weak-scaled mesh must still
    hold per-core efficiency (no partition overhead the cores can't
    hide — the axis-preserving global spelling measures ~1.0x on one
    core, vs ~0.14x for a resharding spelling)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["CEPH_TPU_MC_BUDGET"] = "25"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--multichip-sub"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    recs = {}
    for line in proc.stdout.splitlines():
        at = line.find('{"metric"')
        if at >= 0:
            rec = json.loads(line[at:])
            recs[rec["metric"]] = rec
    for row in ("multichip_encode_GBps", "multichip_decode_GBps"):
        assert row in recs, (sorted(recs), proc.stderr[-500:])
        assert recs[row].get("value", 0) > 0, recs[row]
        assert recs[row]["n_devices"] == 8
        assert "error" not in recs[row]
    sc = recs.get("multichip_scaling")
    assert sc and sc.get("value"), sc
    cores = sc["cores"]
    if cores >= 8:
        assert sc["value"] >= 6.0, \
            f"near-linear bar missed at {cores} cores: {sc}"
    else:
        floor = 0.5 * min(cores, 8)
        assert sc["value"] >= floor, \
            f"weak-scaling efficiency below {floor}: {sc}"


def test_dryrun_multichip_8_host_devices():
    env = dict(os.environ)
    # a fresh process: dryrun_multichip sets the host-platform device
    # count and jax_platforms itself; scrub the test session's values
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); "
         "print('DRYRUN_OK')"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=480)
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "DRYRUN_OK" in proc.stdout
