"""Cluster-level EC write bench — BASELINE.json config[4]: a vstart
cluster with a k=8,m=3 EC pool driving 4 MiB ``rados bench`` writes,
host encode vs the device stripe-batch engine.

    python -m ceph_tpu.bench.cluster_bench [--seconds N] [--osds N]
        [--backends native,pallas] [--obj-mb 4] [--threads N]

Prints one JSON line per backend with bandwidth, latency, and the
device engine's batching stats (launches / ops per launch) so the
record shows the TPU path actually carried the daemon's bytes
(reference seam: ObjBencher rados.cc:1030 + ECBackend.cc:1986-2048).
"""

from __future__ import annotations

import argparse
import json
import time


def _quiet(fut) -> bool:
    try:
        fut.result()
        return True
    except Exception:
        return False


def attach_stage_breakdown(out: dict) -> dict:
    """Fold the data-plane stage decomposition into a metric line
    (ISSUE 6): per-stage share of the summed end-to-end latency +
    the coverage the gap report asserts. Degrades to {} so a
    telemetry fault can never cost a metric line. Mutates and
    returns ``out``."""
    try:
        from ceph_tpu.utils.dataplane import dataplane
        out["stage_breakdown"] = dataplane().stage_breakdown()
    except Exception:
        out["stage_breakdown"] = {}
    # the commit-path brief (ISSUE 14): how many store txns/fsyncs
    # the run cost, so a metric line is one dump_store away from the
    # full X-ray; degrades to {} like the others
    try:
        from ceph_tpu.utils.store_telemetry import telemetry
        out["store"] = telemetry().snapshot_brief()
    except Exception:
        out["store"] = {}
    return attach_trace_brief(out)


def attach_trace_brief(out: dict) -> dict:
    """Tail-sampled tracing rides every bench run by default (ISSUE
    10): the metric line says how many traces the run kept/dropped so
    an outlier row is one ``trace ls`` away from its causes. Degrades
    to {} like the stage breakdown."""
    try:
        from ceph_tpu.utils.tracing import tracer
        c = tracer().perf.dump()
        out["trace"] = {"enabled": tracer().enabled,
                        "kept": c["trace_kept"],
                        "dropped": c["trace_dropped"],
                        "kept_slow": c["trace_kept_slow"],
                        "kept_error": c["trace_kept_error"],
                        "autopsies": c["autopsies_recorded"]}
    except Exception:
        out["trace"] = {}
    return out


def run_one(backend: str, seconds: float, n_osds: int, obj_size: int,
            threads: int, k: int = 8, m: int = 3) -> dict:
    from ceph_tpu.qa.cluster import MiniCluster
    from ceph_tpu.tools.rados_cli import _bench
    with MiniCluster(n_osds=n_osds) as cluster:
        cluster.create_ec_pool("bench", k=k, m=m, pg_num=16,
                               backend=backend)
        io = cluster.client().open_ioctx("bench")
        # warm the compile caches: the device backends jit one program
        # per pow2 bucket of (batch bytes, ops per batch), and over the
        # chip tunnel each compile costs ~30s — the timed run must not
        # pay that. Bursts of 1..threads ops walk the bucket ladder;
        # timeouts during warmup are retried (dup-op cache makes the
        # resend safe).
        import concurrent.futures
        # device-kernel compiles over the chip tunnel take ~30s per
        # shape bucket: give warm-up ops a long leash and keep
        # bursting until a FULL-concurrency burst completes fast
        # (every signature the timed run can produce is then compiled)
        io.op_timeout = 240.0
        warm_deadline = time.monotonic() + (
            420 if backend in ("jax", "pallas") else 30)
        payload = b"w" * obj_size
        bursts = [1, 2, max(threads // 2, 1), threads, threads]
        bi = 0
        while time.monotonic() < warm_deadline:
            burst = bursts[min(bi, len(bursts) - 1)]
            tb = time.monotonic()
            with concurrent.futures.ThreadPoolExecutor(burst) as pool:
                futs = [pool.submit(io.write_full, f"warm_{burst}_{i}",
                                    payload) for i in range(burst)]
                ok = all(_quiet(f) for f in futs)
            wall = time.monotonic() - tb
            if ok:
                bi += 1
                if bi >= len(bursts) and burst == threads and \
                        wall < 3.0:
                    break              # warm: full burst ran fast
        io.op_timeout = 60.0
        t0 = time.monotonic()
        out = _bench(io, seconds, "write", obj_size, threads)
        out["wall"] = round(time.monotonic() - t0, 2)
        out["backend"] = backend
        out["profile"] = f"k={k},m={m}"
        # dedupe by stats-dict identity: with the shared engine
        # service every OSD's handle reports the SAME engine — summing
        # per-OSD views would triple-count one pipeline
        stats = list({id(o._device_engine.stats):
                      dict(o._device_engine.stats)
                      for o in cluster.osds.values()
                      if o._device_engine is not None}.values())
        if stats:
            out["device_engine"] = {
                "launches": sum(s["flushes"] for s in stats),
                "ops": sum(s["ops"] for s in stats),
                "bytes": sum(s["bytes"] for s in stats),
                "max_batch_ops": max(s["max_batch_ops"]
                                     for s in stats),
                "errors": sum(s["errors"] for s in stats),
            }
        return attach_stage_breakdown(out)


def _engine_stats(cluster) -> dict:
    tot: dict = {}
    seen: set[int] = set()   # shared engine: one stats dict, N OSDs
    for o in cluster.osds.values():
        if o._device_engine is None or \
                id(o._device_engine.stats) in seen:
            continue
        seen.add(id(o._device_engine.stats))
        for name, v in o._device_engine.stats.items():
            tot[name] = tot.get(name, 0) + v
    return tot


def prewarm_fused(obj_size: int, max_ops: int = 16, k: int = 8,
                  m: int = 3, backend: str = "pallas") -> None:
    """Compile the fused-flush bucket LADDER deterministically before
    any daemon runs: each (nops_b, n_b) signature costs ~26 s over
    the tunnel, and the engine's batch composition is load-dependent
    — warming by traffic alone can converge while signatures remain
    uncompiled, which is exactly how a timed run ends up paying a
    compile mid-benchmark (measured: launch 26.4 s cold / 0.01 s
    warm, finalize 1.6 s). The jit cache is process-global, so one
    pass covers every in-process OSD."""
    import numpy as np

    from ceph_tpu.models import registry as ec_registry
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.ec_util import StripeInfo
    codec = ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": str(k), "m": str(m),
                     "backend": backend})
    stripe_unit = 4096
    sinfo = StripeInfo(stripe_width=k * stripe_unit,
                       chunk_size=stripe_unit)
    sw = sinfo.stripe_width
    padded = obj_size + (-obj_size % sw)
    nops = 1
    while True:
        bufs = [np.zeros(padded, dtype=np.uint8)] * nops
        t0 = time.monotonic()
        ec_util._flush_device_fused_async(
            sinfo, codec, tuple(range(nops)), tuple(bufs))()
        print(json.dumps({"prewarm": {"nops": nops,
                                      "s": round(time.monotonic()
                                                 - t0, 1)}}),
              flush=True)
        if nops >= max_ops:
            break
        nops = min(nops * 2, max_ops)


def run_curve(seconds: float, n_osds: int, obj_size: int,
              thread_steps: list[int], k: int = 8, m: int = 3) -> list:
    """The amortization curve (r2 verdict weak #1): ONE warm cluster,
    the same write workload at increasing concurrency — MB/s vs
    launches vs MB/launch — plus the locally-attached projection from
    the MEASURED per-launch engine cost. Through the axon tunnel each
    launch pays the ~0.1 s RTT; the curve shows throughput scaling
    with batch size at a fixed launch cost, and the projection
    replaces the tunnel RTT with a local dispatch (~0.2 ms) at the
    measured per-launch byte volume."""
    import concurrent.futures

    from ceph_tpu.qa.cluster import MiniCluster
    from ceph_tpu.tools.rados_cli import _bench
    # compile every fused-flush signature BEFORE the daemons exist
    # (the jit cache is process-global): timed runs then never pay a
    # 26 s mid-benchmark compile — the failure mode both curve
    # attempts hit when warming by traffic alone
    prewarm_fused(obj_size, max_ops=16, k=k, m=m)
    rows = []
    with MiniCluster(n_osds=n_osds) as cluster:
        cluster.create_ec_pool("bench", k=k, m=m, pg_num=16,
                               backend="pallas")
        io = cluster.client().open_ioctx("bench")
        io.op_timeout = 240.0   # tunnel-contention leash: a contended
        # window must slow a timed op, not fail the whole curve
        payload = b"w" * obj_size
        max_t = max(thread_steps)
        # short traffic warm (connections, stores, dup-op paths) —
        # the kernel signatures are already compiled
        for burst in (1, max_t):
            with concurrent.futures.ThreadPoolExecutor(burst) as pool:
                futs = [pool.submit(io.write_full,
                                    f"warm_{burst}_{i}", payload)
                        for i in range(burst)]
                [_quiet(f) for f in futs]
        for threads in thread_steps:
            before = _engine_stats(cluster)
            out = _bench(io, seconds, "write", obj_size, threads)
            after = _engine_stats(cluster)
            d = {name: after.get(name, 0) - before.get(name, 0)
                 for name in after}
            launches = max(d.get("flushes", 0), 1)
            row = {
                "threads": threads,
                "MBps": out.get("bandwidth_MBps"),
                "launches": launches,
                "ops": d.get("ops", 0),
                "MB_per_launch": round(
                    d.get("bytes", 0) / launches / 1e6, 2),
                "engine_busy_s": round(d.get("busy_s", 0.0), 2),
                "busy_ms_per_launch": round(
                    d.get("busy_s", 0.0) * 1000 / launches, 1),
            }
            attach_stage_breakdown(row)
            rows.append(row)
            print(json.dumps({"curve": row}, sort_keys=True),
                  flush=True)
        # locally-attached projection from the measured numbers: the
        # engine's per-launch cost through the tunnel is busy_s /
        # launches (dominated by the link RTT); locally the same
        # launch costs ~0.2 ms dispatch + bytes at the device-
        # resident fused rate (BASELINE.md: 537 GB/s encode+crc)
        best = max(rows, key=lambda r: r["MBps"] or 0)
        bytes_per_launch = best["MB_per_launch"] * 1e6
        local_launch_s = 0.0002 + bytes_per_launch / 537e9
        tunnel_launch_s = best["engine_busy_s"] / best["launches"]
        engine_local_MBps = bytes_per_launch / local_launch_s / 1e6
        proj = {
            "projection": {
                "measured_tunnel_s_per_launch": round(tunnel_launch_s,
                                                      4),
                "local_s_per_launch": round(local_launch_s, 6),
                "engine_capacity_local_MBps": round(engine_local_MBps,
                                                    0),
                "note": "locally-attached, the engine ceases to be "
                        "the bottleneck (capacity >> the host daemon "
                        "path's native ceiling); cluster MB/s then "
                        "tracks the native row",
            }
        }
        rows.append(proj)
        print(json.dumps(proj, sort_keys=True), flush=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cluster_bench")
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--osds", type=int, default=12)
    ap.add_argument("--obj-mb", type=float, default=4.0)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--backends", default="native,pallas")
    ap.add_argument("--curve", action="store_true",
                    help="amortization curve: one pallas cluster, "
                         "increasing concurrency")
    ap.add_argument("--curve-threads", default="4,8,16")
    args = ap.parse_args(argv)
    obj_size = int(args.obj_mb * (1 << 20))
    if args.curve:
        run_curve(args.seconds, args.osds, obj_size,
                  [int(x) for x in args.curve_threads.split(",")])
        return 0
    for backend in args.backends.split(","):
        out = run_one(backend.strip(), args.seconds, args.osds,
                      obj_size, args.threads)
        print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
