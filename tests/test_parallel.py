"""Sharded EC pipeline tests on the virtual 8-device CPU mesh."""

import time

import numpy as np
import pytest

from ceph_tpu.ops import gf256
from ceph_tpu.parallel import mesh as mesh_mod
from ceph_tpu.parallel import sharded_codec


@pytest.fixture(scope="module")
def mesh():
    import jax
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return mesh_mod.make_mesh(8)


def test_mesh_shape(mesh):
    assert mesh.shape["stripe"] * mesh.shape["shard"] == 8


def test_distributed_encode_matches_reference(mesh):
    k, m = 8, 3
    S, C = mesh.shape["stripe"] * 2, mesh.shape["shard"] * 64
    coding = gf256.rs_vandermonde_matrix(k, m)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(S, k, C), dtype=np.uint8)

    step = sharded_codec.make_encode_step(mesh, coding)
    chunks, csum = step(sharded_codec.shard_stripe_batch(mesh, data))
    chunks = np.asarray(chunks)

    n_shard = mesh.shape["shard"]
    c_l = C // n_shard
    for s in range(S):
        want_parity = gf256.gf_matvec_chunks(coding, data[s])
        got = chunks[s, k:]  # parity after the ppermute placement shift
        # undo the ring shift: local block b of output came from block b-1
        unshifted = np.concatenate(
            [got[:, ((b - 1) % n_shard) * c_l:((b - 1) % n_shard + 1) * c_l]
             for b in range(n_shard)], axis=1)
        # got block b holds parity computed on block b-1's bytes
        restored = np.zeros_like(got)
        for b in range(n_shard):
            src = (b - 1) % n_shard
            restored[:, src * c_l:(src + 1) * c_l] = \
                got[:, b * c_l:(b + 1) * c_l]
        assert np.array_equal(restored, want_parity), s
        assert np.array_equal(chunks[s, :k], data[s])
    del unshifted
    # checksum: byte sums per chunk position over whole batch
    want_csum = np.zeros(k + m, dtype=np.uint64)
    want_csum[:k] = data.astype(np.uint64).sum(axis=(0, 2))
    assert np.array_equal(np.asarray(csum)[:k].astype(np.uint64), want_csum[:k])


def test_distributed_degraded_read(mesh):
    k, m = 4, 2
    S, C = 2, mesh.shape["shard"] * 32
    coding = gf256.rs_vandermonde_matrix(k, m)
    gen = gf256.systematic_generator(coding)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(S, k, C), dtype=np.uint8)
    all_chunks = np.stack(
        [np.concatenate([d, gf256.gf_matvec_chunks(coding, d)]) for d in data])

    lost = [1, 4]
    present = [0, 2, 3, 5]
    surv = all_chunks[:, present]
    step = sharded_codec.make_degraded_read_step(mesh, gen, present, lost)
    rec, full = step(sharded_codec.shard_stripe_batch(mesh, surv))
    assert np.array_equal(np.asarray(rec), all_chunks[:, lost])
    assert np.array_equal(np.asarray(full), all_chunks[:, lost])


def test_batcher_flush_routes_through_mesh(mesh):
    """VERDICT #8: the daemon's StripeBatcher flushes through the
    multi-chip encode step when a mesh is present — bit-exact vs the
    host codec, per-op slices preserved."""
    from ceph_tpu.models import registry as ec_registry
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.ec_util import StripeBatcher, StripeInfo

    codec = ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": "4", "m": "2",
                     "backend": "jax"})
    cs = mesh.shape["shard"] * 64
    si = StripeInfo(stripe_width=4 * cs, chunk_size=cs)
    host = ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": "4", "m": "2",
                     "backend": "numpy"})
    rng = np.random.default_rng(7)
    b = StripeBatcher(si, codec, mesh=mesh)
    bufs = {}
    for op in range(3):
        data = rng.integers(0, 256, size=(op + 1) * si.stripe_width,
                            dtype=np.uint8)
        bufs[op] = data
        b.append(op, data)
    results = b.flush()
    assert len(results) == 3
    for op, shards, _crcs in results:
        want = ec_util.encode(si, host, bufs[op])
        for i in range(6):
            assert np.array_equal(shards[i], want[i]), (op, i)


def test_engine_uses_default_mesh(mesh):
    """The device engine picks up the process default mesh: flushes
    AT OR ABOVE the dense-vs-sharded threshold run the sharded encode
    step (multi-chip data plane engaged from the daemon seam), while
    smaller flushes stay on the single-chip path — both bit-exact."""
    from ceph_tpu.models import registry as ec_registry
    from ceph_tpu.osd import ec_util
    from ceph_tpu.osd.device_engine import DeviceEncodeEngine
    from ceph_tpu.osd.ec_util import StripeInfo
    from ceph_tpu.parallel import mesh as mesh_mod

    codec = ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": "4", "m": "2",
                     "backend": "jax"})
    cs = mesh.shape["shard"] * 64
    si = StripeInfo(stripe_width=4 * cs, chunk_size=cs)
    rng = np.random.default_rng(8)
    big = rng.integers(0, 256, size=2 * si.stripe_width,
                       dtype=np.uint8)
    small = rng.integers(0, 256, size=si.stripe_width,
                         dtype=np.uint8)
    got = {}
    # threshold between the two payloads: the big flush routes
    # through the mesh, the small one stays dense
    eng = DeviceEncodeEngine(lambda key, fn: fn(),
                             mesh_flush_bytes=len(big))
    mesh_mod.set_default_mesh(mesh)
    try:
        eng.stage_encode("pg", codec, si, big,
                         lambda s, c, e: got.setdefault("big",
                                                        (s, e)))
        deadline = time.monotonic() + 15
        while "big" not in got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.stats["mesh_flushes"] == 1, eng.stats
        eng.stage_encode("pg", codec, si, small,
                         lambda s, c, e: got.setdefault("small",
                                                        (s, e)))
        deadline = time.monotonic() + 15
        while "small" not in got and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        mesh_mod.set_default_mesh(None)
        eng.stop()
    assert eng.stats["mesh_flushes"] == 1, \
        (eng.stats, "sub-threshold flush must stay single-chip")
    host = ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": "4", "m": "2",
                     "backend": "numpy"})
    for name, payload in (("big", big), ("small", small)):
        assert name in got and got[name][1] is None, got
        want = ec_util.encode(si, host, payload)
        for i in range(6):
            assert np.array_equal(got[name][0][i], want[i]), (name, i)


def test_distributed_clay_repair(mesh):
    """Clay single-node repair as a mesh collective: helper sub-chunk
    fragments shard over the mesh, the linearized repair matrix
    (models/clay.py _repair_matrix) reconstructs the lost chunk, and
    an all_gather reassembles it — bit-exact vs the host repair."""
    from ceph_tpu.models import registry as ec_registry

    codec = ec_registry.instance().factory(
        "clay", {"plugin": "clay", "k": "4", "m": "2",
                 "backend": "numpy"})
    ssc = codec.get_sub_chunk_count()
    rss = ssc // codec.q
    sub = mesh.shape["shard"] * 16          # bytes per sub-chunk
    cs = ssc * sub
    rng = np.random.default_rng(9)
    data = {i: rng.integers(0, 256, cs, dtype=np.uint8)
            for i in range(4)}
    enc = codec.encode_chunks(list(range(6)), data)
    chunks = {**{i: np.asarray(data[i]) for i in range(4)},
              **{i: np.asarray(v) for i, v in enc.items()}}
    lost = 2
    helpers = tuple(i for i in range(6) if i != lost)
    # helper fragments: the repair sub-chunk ranges of each helper
    ranges = codec.get_repair_subchunks(lost)
    frag = {h: np.concatenate([
        chunks[h][off * sub:(off + cnt) * sub]
        for off, cnt in ranges]) for h in helpers}
    # host oracle
    want = codec.decode([lost], {h: f for h, f in frag.items()}, cs)
    mat = codec._repair_matrix(lost, helpers)
    # distribute: stack fragments as rows [S=1, H*rss, sub]
    x = np.stack([f.reshape(rss, sub) for h, f in
                  sorted(frag.items())]).reshape(1, len(helpers) * rss,
                                                 sub)
    # one logical stripe replicated across the stripe axis (the axis
    # must divide S; real batches carry many stripes)
    x = np.repeat(x, mesh.shape["stripe"], axis=0)
    step = sharded_codec.make_matrix_step(mesh, mat)
    rec, full = step(sharded_codec.shard_stripe_batch(mesh, x))
    got = np.asarray(full)[0].reshape(-1)
    assert np.array_equal(got, np.asarray(want[lost])), "clay mesh repair"
