"""Device-path telemetry — PerfCounters for the TPU EC pipeline.

The paper's metric is encode/decode GB/s, but a number that moves
needs an explanation: batching and data-movement effects dominate the
online-EC hot path (arXiv:1709.05365) and per-stage timing is what
makes a pipelined code debuggable (arXiv:1207.6744). Ceph's answer is
PerfCounters + ``perf dump``; this module is that answer for the
device path — one process-wide registry fed by:

- the Pallas/XLA compile entry points (``ops/gf_pallas``,
  ``ops/gf_block_sparse``, ``models/clay_device``,
  ``parallel/sharded_codec``): per-codec-signature compile counts and
  compile wall time. A signature that compiles MORE THAN ONCE is a
  bug-class signal (an unbucketed shape leaking into a jit cache —
  the recompile storm every device entry point is designed to
  prevent), surfaced as the ``recompiles`` counter;
- ``osd/device_engine.py``: batch-occupancy histograms for
  stage_encode/stage_decode flushes, flush sizes, the queue-wait vs
  device-time latency split, bytes encoded/decoded, fused-path
  fallbacks;
- ``models/clay_device.build_decode_matvec``: sparse-vs-dense
  calibration outcomes (winner + measured timings, per signature);
- ``models/clay.py``: linearized-transform LRU hits/misses.

Counters are ALWAYS ON and cheap (one lock, integer adds); the
per-signature side tables are bounded dicts. ``snapshot()`` is the
JSON-able view served by the ``device perf dump`` admin command, the
mgr dashboard's device panel, and the telemetry field bench.py
attaches to every metric line. The plain counters also live in the
process PerfCounters collection under the ``device`` logger, so
``perf dump`` and the prometheus exporter pick them up for free.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.utils.perf_counters import PerfCounters, collection

#: bound on the per-signature side tables (compiles / calibrations):
#: signatures are O(erasure signatures x shape buckets) in practice,
#: but a pathological caller must not grow the dump without bound
_MAX_SIGNATURES = 256


class DeviceTelemetry:
    """Process-wide device-path counters (one per process, like the
    reference's per-daemon PerfCounters — the device is per-process
    here, so the registry is too)."""

    def __init__(self, name: str = "device") -> None:
        self.name = name
        self._lock = threading.Lock()
        perf = collection().get(name)
        if perf is None:
            perf = collection().create(name)
            self._declare(perf)
        self.perf = perf
        #: signature -> {"compiles": n, "seconds": total}
        self._compiles: dict[str, dict] = {}
        #: "label|signature" -> calibration outcome dict
        self._calibrations: dict[str, dict] = {}
        #: signature -> compiled cost analysis (flops/bytes_accessed)
        self._costs: dict[str, dict] = {}
        #: exact live-byte mirrors of the hbm gauges (kept here so
        #: the peak update is race-free under one lock)
        self._hbm_staged = 0
        self._hbm_inflight = 0
        self._hbm_peak = 0
        #: placement slot -> live staged bytes (ISSUE 13: the tuner's
        #: chip-load signal for load-aware PG->slot weighting); bytes
        #: enter at stage time and leave at flush take, so idle reads
        #: all-zero like the hbm gauges
        self._slot_staged: dict[int, int] = {}

    @staticmethod
    def _declare(perf: PerfCounters) -> None:
        perf.add_u64_counter("compiles",
                             "device kernel/program compilations")
        perf.add_u64_counter("recompiles",
                             "signatures compiled more than once "
                             "(shape leaking into a jit cache)")
        perf.add_time_avg("compile_time",
                          "wall seconds per compilation")
        perf.add_u64_counter("compile_cache_hits",
                             "compiles of a signature the persistent "
                             "XLA cache already held (warm)")
        perf.add_u64_counter("compile_cache_misses",
                             "compiles of a first-ever signature "
                             "(cold; ledger seeded for next process)")
        perf.add_histogram("encode_batch_ops",
                           "ops per stage_encode flush (occupancy)")
        perf.add_histogram("decode_batch_ops",
                           "ops per stage_decode flush (occupancy)")
        perf.add_histogram("flush_bytes",
                           "payload bytes per encode flush")
        perf.add_time_avg("encode_queue_wait",
                          "stage_encode -> flush launch wait")
        perf.add_time_avg("decode_queue_wait",
                          "stage_decode -> flush launch wait")
        perf.add_time_avg("flush_device_time",
                          "engine-thread seconds per encode-flush "
                          "harvest (device wait + download + "
                          "continuation dispatch)")
        perf.add_time_avg("decode_flush_device_time",
                          "engine-thread seconds per decode flush")
        perf.add_u64_counter("bytes_encoded",
                             "payload bytes through device encode")
        perf.add_u64_counter("bytes_decoded",
                             "shard bytes through device decode")
        perf.add_u64_counter("fused_fallbacks",
                             "mesh/fused flush paths that fell back")
        perf.add_u64_counter("engine_decode_fallbacks",
                             "degraded-read/recovery decodes that fell "
                             "back from the batched engine route to "
                             "the host twin (ISSUE 8: silent before)")
        perf.add_u64_counter("calibrations",
                             "sparse-vs-dense on-device calibrations")
        perf.add_u64_counter("calibrations_sparse_won",
                             "calibrations the sparse kernel won")
        perf.add_u64_counter("lin_matvec_hits",
                             "clay linearized-transform LRU hits")
        perf.add_u64_counter("lin_matvec_misses",
                             "clay linearized-transform LRU builds")
        perf.add_u64_counter("mesh_dispatches",
                             "multi-chip sharded-codec step calls")
        # pod-scale sharded serving (ISSUE 12): how much of the data
        # path actually rode the mesh, and through which compile seam
        perf.add_u64_counter("mesh_flushes",
                             "engine encode flushes routed through "
                             "the sharded mesh step")
        perf.add_u64_counter("mesh_decode_flushes",
                             "signature-batched decode flushes "
                             "(degraded reads / recovery) routed "
                             "through the mesh twin")
        perf.add_u64_counter("mesh_scrub_batches",
                             "deep-scrub verify launches routed "
                             "through the mesh twin")
        perf.add_u64_counter("placement_flushes",
                             "flushes launched on a PG-placement "
                             "slot's submesh (disjoint chips per "
                             "slot; overlapped in the engine window)")
        perf.add_gauge("placement_slots",
                       "slots in the active PG->chip placement map "
                       "(0 = no map: single-chip or placement off)")
        perf.add_u64_counter("mesh_compile_pjit",
                             "mesh steps compiled through the "
                             "jit+in_shardings (pjit) seam")
        perf.add_u64_counter("mesh_compile_shard_map",
                             "mesh steps compiled through the "
                             "shard_map fallback shim")
        # pipelined engine (osd/device_engine.py): launch-window
        # accounting — depth proves batches overlap, overlap-pct is
        # the share of a batch's device lifetime hidden behind other
        # engine work (100% = the download wait fully overlapped)
        perf.add_histogram("engine_inflight_depth",
                           "launched-not-retired batches at each "
                           "flush launch (window occupancy)")
        perf.add_histogram("engine_overlap_pct",
                           "percent of a batch's launch->retire "
                           "lifetime spent overlapped with other "
                           "engine work")
        # stall detection inputs (mgr/health.py ENGINE_STALL): the
        # health engine reads the current window occupancy and checks
        # the retirement counter for progress over its window
        perf.add_gauge("engine_inflight",
                       "launched-not-retired batches right now")
        perf.add_gauge("engine_window",
                       "configured launch-window depth (0 = no "
                       "engine constructed yet)")
        perf.add_u64_counter("engine_retired",
                             "batches retired (downloaded + "
                             "continuations dispatched)")
        # deep-scrub engine (osd/scrub_engine.py): the background-
        # verification pipeline's own accounting
        perf.add_u64_counter("scrub_batches",
                             "deep-scrub device verify launches")
        perf.add_u64_counter("scrub_bytes_verified",
                             "shard bytes through the fused crc + "
                             "parity-re-encode verify pass")
        perf.add_u64_counter("scrub_mismatch_stripes",
                             "objects flagged by the device mismatch "
                             "bitmap / crc vector")
        perf.add_u64_counter("scrub_repaired_shards",
                             "shards rebuilt by deep-scrub sparse "
                             "decode + recovery push")
        perf.add_u64_counter("scrub_host_fallbacks",
                             "objects judged by the host shallow "
                             "oracle (device fault or ambiguous "
                             "conviction)")
        perf.add_histogram("scrub_batch_objs",
                           "objects per deep-scrub verify launch")
        perf.add_time_avg("scrub_device_time",
                          "wall seconds per deep-scrub verify launch")
        # live HBM accounting (osd/device_engine.py): every buffer
        # byte the engine holds is in exactly one of staged (queued,
        # pre-launch) or in-window (launched, not retired); both
        # gauges reconcile to 0 at idle — the shutdown-safety bar the
        # PR-6 queue-depth gauges set — and the peak gauges feed the
        # HBM_PRESSURE health check (mgr/health.py)
        perf.add_gauge("hbm_staged_bytes",
                       "payload bytes queued in the engine, not yet "
                       "launched")
        perf.add_gauge("hbm_inflight_bytes",
                       "payload bytes in launched-not-retired "
                       "batches (the pipeline window's working set)")
        perf.add_gauge("hbm_live_bytes",
                       "staged + in-window bytes (the HBM_PRESSURE "
                       "input)")
        perf.add_gauge("hbm_peak_live_bytes",
                       "high-water mark of hbm_live_bytes")
        perf.add_u64_counter("hbm_retired_bytes",
                             "bytes that left the launch window "
                             "(downloaded or failed over)")
        # bulk-ingest data plane (ISSUE 9)
        perf.add_u64_counter("staging_copies_avoided_bytes",
                             "flush bytes handed to the device as one "
                             "preconcatenated staging view (no flush-"
                             "time np.concatenate on the engine "
                             "thread)")
        perf.add_gauge("attached_osds",
                       "OSDs attached to the shared device engine "
                       "(0 = per-OSD engines / none attached)")

    # -- bulk-ingest accounting (ISSUE 9) -----------------------------
    def note_staging_copies_avoided(self, nbytes: int) -> None:
        self.perf.inc("staging_copies_avoided_bytes", nbytes)

    def note_attached_osds(self, n: int) -> None:
        self.perf.set_gauge("attached_osds", n)

    # -- compile accounting -------------------------------------------
    def note_compile(self, signature: str, seconds: float) -> None:
        """One compilation of ``signature`` took ``seconds`` wall.
        The second compile of the same signature counts a recompile —
        the bug-class every pow2-bucketed entry point exists to
        prevent. When the persistent compilation cache is enabled
        (utils/compile_cache), the signature is checked against the
        cross-process ledger: a signature a previous process already
        compiled counts a cache hit (the disk cache served it)."""
        self.perf.inc("compiles")
        self.perf.tinc("compile_time", seconds)
        try:
            from ceph_tpu.utils import compile_cache
            if compile_cache.enabled_dir() is not None:
                if compile_cache.note_compile(signature, seconds):
                    self.perf.inc("compile_cache_hits")
                else:
                    self.perf.inc("compile_cache_misses")
        except Exception:
            pass                   # ledger faults never cost the path
        with self._lock:
            ent = self._compiles.get(signature)
            if ent is None:
                if len(self._compiles) >= _MAX_SIGNATURES:
                    self._compiles.pop(next(iter(self._compiles)))
                ent = self._compiles[signature] = {"compiles": 0,
                                                   "seconds": 0.0}
            ent["compiles"] += 1
            ent["seconds"] += seconds
            recompiled = ent["compiles"] > 1
        if recompiled:
            self.perf.inc("recompiles")

    def compile_count(self, signature: str) -> int:
        with self._lock:
            ent = self._compiles.get(signature)
            return ent["compiles"] if ent else 0

    def timed_call(self, signature: str, fn, *args, **kwargs):
        """Call a jitted device entry point, accounting a compile when
        the jit cache grows underneath it (``_cache_size`` on jitted
        functions); falls back to first-call-per-signature counting on
        runtimes without that introspection. The non-compiling path
        costs two attribute loads and a perf_counter pair."""
        cache_size = getattr(fn, "_cache_size", None)
        before = None
        if cache_size is not None:
            try:
                before = cache_size()
            except Exception:
                cache_size = None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if cache_size is not None:
            try:
                if cache_size() > before:
                    self.note_compile(signature, dt)
            except Exception:
                pass
        else:
            with self._lock:
                seen = signature in self._compiles
            if not seen:
                self.note_compile(signature, dt)
        return out

    # -- engine flush accounting --------------------------------------
    def note_encode_flush(self, ops: int, nbytes: int,
                          device_s: float,
                          trace_id: str | None = None) -> None:
        """``trace_id`` (a traced op riding the flush) attaches as the
        histogram-bucket exemplar: a dashboard's outlier flush bucket
        links straight to a kept trace (ISSUE 10)."""
        self.perf.hinc("encode_batch_ops", ops, exemplar=trace_id)
        self.perf.hinc("flush_bytes", nbytes, exemplar=trace_id)
        self.perf.tinc("flush_device_time", device_s)
        self.perf.inc("bytes_encoded", nbytes)

    def note_decode_flush(self, ops: int, nbytes: int,
                          device_s: float,
                          trace_id: str | None = None) -> None:
        self.perf.hinc("decode_batch_ops", ops, exemplar=trace_id)
        self.perf.tinc("decode_flush_device_time", device_s)
        self.perf.inc("bytes_decoded", nbytes)

    def note_queue_wait(self, kind: str, seconds: float) -> None:
        self.perf.tinc(f"{kind}_queue_wait", seconds)

    def note_fused_fallback(self) -> None:
        self.perf.inc("fused_fallbacks")

    def note_decode_fallback(self) -> None:
        """A degraded read / recovery decode left the batched engine
        route for the host twin (device fault, timeout, or injected
        failure) — previously invisible; the degraded path's health
        depends on this staying near zero."""
        self.perf.inc("engine_decode_fallbacks")

    def note_inflight_depth(self, depth: int) -> None:
        """Launch-window occupancy at one flush launch (pipelined
        engine): depth >= 2 is the proof batches overlap."""
        self.perf.hinc("engine_inflight_depth", depth)

    def note_engine_window(self, window: int) -> None:
        """An engine came up with this launch-window depth."""
        self.perf.set_gauge("engine_window", window)

    def note_engine_inflight(self, depth: int) -> None:
        """Current launched-not-retired count (set on every launch
        AND retire, so the health engine sees saturation live)."""
        self.perf.set_gauge("engine_inflight", depth)

    def note_engine_retired(self) -> None:
        self.perf.inc("engine_retired")

    def note_overlap(self, overlapped_s: float,
                     lifetime_s: float) -> None:
        """One retired batch's overlap: ``overlapped_s`` of its
        ``lifetime_s`` launch->retire window passed while the engine
        did other work (staging/launching younger batches) instead of
        blocking on this one's download."""
        if lifetime_s <= 0:
            return
        pct = int(round(100.0 * max(0.0, min(overlapped_s,
                                             lifetime_s))
                        / lifetime_s))
        self.perf.hinc("engine_overlap_pct", pct)

    # -- codec-layer accounting ---------------------------------------
    def note_calibration(self, label: str, signature: str,
                         winner: str, measured: dict) -> None:
        """One build_decode_matvec outcome: which path won this
        signature on this chip and what both paths measured."""
        self.perf.inc("calibrations")
        if winner == "sparse":
            self.perf.inc("calibrations_sparse_won")
        with self._lock:
            if len(self._calibrations) >= _MAX_SIGNATURES:
                self._calibrations.pop(next(iter(self._calibrations)))
            self._calibrations[f"{label}|{signature}"] = {
                "winner": winner, **measured}

    def note_lin_matvec(self, hit: bool) -> None:
        self.perf.inc("lin_matvec_hits" if hit else "lin_matvec_misses")

    def note_mesh_dispatch(self) -> None:
        self.perf.inc("mesh_dispatches")

    # -- pod-scale sharded serving (ISSUE 12) -------------------------
    def note_mesh_flush(self, kind: str) -> None:
        """One engine flush routed through the mesh: ``kind`` is
        "encode" or "decode" (the two data-path twins)."""
        self.perf.inc("mesh_flushes" if kind == "encode"
                      else "mesh_decode_flushes")

    def note_mesh_scrub_batch(self) -> None:
        self.perf.inc("mesh_scrub_batches")

    def note_placement_flush(self) -> None:
        self.perf.inc("placement_flushes")

    def note_placement_slots(self, n: int) -> None:
        self.perf.set_gauge("placement_slots", n)

    def note_mesh_compile(self, path: str) -> None:
        """One mesh step built: which compile seam produced it."""
        self.perf.inc("mesh_compile_pjit" if path == "pjit"
                      else "mesh_compile_shard_map")

    def note_cost(self, signature: str, cost: dict) -> None:
        """One compiled cost analysis (ops/cost_model.analyze): the
        per-signature FLOPs/bytes table the dashboard and ``device
        perf dump`` serve next to the compile table."""
        with self._lock:
            if signature not in self._costs and \
                    len(self._costs) >= _MAX_SIGNATURES:
                self._costs.pop(next(iter(self._costs)))
            self._costs[signature] = dict(cost)

    # -- HBM accounting (osd/device_engine.py) ------------------------
    def note_hbm(self, staged_delta: int = 0,
                 inflight_delta: int = 0, retired: int = 0) -> None:
        """Move bytes between the engine's HBM buckets. Every staged
        byte is later either launched (staged->inflight) or abandoned
        (staged->out); every launched byte retires — so live bytes
        read 0 at idle (asserted across cluster lifecycles)."""
        with self._lock:
            self._hbm_staged = max(0, self._hbm_staged + staged_delta)
            self._hbm_inflight = max(
                0, self._hbm_inflight + inflight_delta)
            live = self._hbm_staged + self._hbm_inflight
            self._hbm_peak = max(self._hbm_peak, live)
            staged, inflight, peak = (self._hbm_staged,
                                      self._hbm_inflight,
                                      self._hbm_peak)
        self.perf.set_gauge("hbm_staged_bytes", staged)
        self.perf.set_gauge("hbm_inflight_bytes", inflight)
        self.perf.set_gauge("hbm_live_bytes", staged + inflight)
        self.perf.set_gauge("hbm_peak_live_bytes", peak)
        if retired > 0:
            self.perf.inc("hbm_retired_bytes", retired)

    def hbm_live_bytes(self) -> int:
        with self._lock:
            return self._hbm_staged + self._hbm_inflight

    def note_slot_staged(self, slot: int, delta: int) -> None:
        """Move live staged bytes on one placement slot's ledger
        (floored at zero per slot — the same self-healing the hbm
        gauges use, so an accounting slip decays instead of
        compounding)."""
        with self._lock:
            self._slot_staged[slot] = max(
                0, self._slot_staged.get(slot, 0) + delta)

    def slot_staged_bytes(self) -> dict[int, int]:
        """Per-slot live staged bytes — the queue-depth half of the
        tuner's chip-load signal (HBM pressure is the other half)."""
        with self._lock:
            return dict(self._slot_staged)

    # -- deep-scrub accounting ----------------------------------------
    def note_scrub_flush(self, objs: int, nbytes: int,
                         device_s: float) -> None:
        """One deep-scrub verify launch: ``objs`` objects, ``nbytes``
        shard bytes verified, in ``device_s`` wall seconds."""
        self.perf.inc("scrub_batches")
        self.perf.inc("scrub_bytes_verified", nbytes)
        self.perf.hinc("scrub_batch_objs", objs)
        self.perf.tinc("scrub_device_time", device_s)

    def note_scrub_mismatch(self) -> None:
        self.perf.inc("scrub_mismatch_stripes")

    def note_scrub_repair(self) -> None:
        self.perf.inc("scrub_repaired_shards")

    def note_scrub_host_fallback(self) -> None:
        self.perf.inc("scrub_host_fallbacks")

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """The full JSON-able view: counters + per-signature tables
        (the ``device perf dump`` payload)."""
        with self._lock:
            compiles = {s: dict(v) for s, v in self._compiles.items()}
            calibrations = {s: dict(v)
                            for s, v in self._calibrations.items()}
            costs = {s: dict(v) for s, v in self._costs.items()}
        with self._lock:
            slot_staged = dict(self._slot_staged)
        return {"counters": self.perf.dump(),
                "compiles_by_signature": compiles,
                "calibrations": calibrations,
                "costs_by_signature": costs,
                "slot_staged_bytes": slot_staged}

    def snapshot_brief(self) -> dict:
        """Compact view for bench metric lines: scalar counters plus
        calibration winners, no histograms (a metric line must stay
        one readable line)."""
        counters = self.perf.dump()
        brief = {}
        for key in ("compiles", "recompiles", "compile_cache_hits",
                    "compile_cache_misses", "bytes_encoded",
                    "bytes_decoded", "fused_fallbacks", "calibrations",
                    "calibrations_sparse_won", "lin_matvec_hits",
                    "lin_matvec_misses", "mesh_dispatches",
                    "mesh_flushes", "mesh_decode_flushes",
                    "mesh_scrub_batches", "placement_flushes",
                    "mesh_compile_pjit", "mesh_compile_shard_map",
                    "scrub_batches",
                    "scrub_bytes_verified", "scrub_mismatch_stripes",
                    "scrub_repaired_shards", "scrub_host_fallbacks"):
            val = counters.get(key)
            if val:
                brief[key] = val
        ct = counters.get("compile_time") or {}
        if ct.get("avgcount"):
            brief["compile_time_s"] = round(ct["sum"], 3)
        with self._lock:
            if self._calibrations:
                brief["calibration_winners"] = {
                    s: v["winner"]
                    for s, v in self._calibrations.items()}
        return brief

    def reset(self) -> None:
        """Test hook: drop the logger and side tables (a fresh
        telemetry() call re-creates both)."""
        collection().remove(self.name)
        global _telemetry
        with _module_lock:
            _telemetry = None


_module_lock = threading.Lock()
_telemetry: DeviceTelemetry | None = None


def telemetry() -> DeviceTelemetry:
    global _telemetry
    with _module_lock:
        if _telemetry is None:
            _telemetry = DeviceTelemetry()
        return _telemetry


def register_asok(asok) -> None:
    """The ``device perf dump`` admin command (the device-path
    counterpart of ``perf dump``)."""
    asok.register_command(
        "device perf dump", lambda a: telemetry().snapshot(),
        "device-path telemetry: compiles, flushes, occupancy, "
        "calibration outcomes")
