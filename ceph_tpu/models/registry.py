"""Plugin registry — semantic equivalent of ``ErasureCodePluginRegistry``.

Reference: src/erasure-code/ErasureCodePlugin.{h,cc}. The reference dlopens
``libec_<name>.so``, checks ``__erasure_code_version()`` against the build
version, then calls ``__erasure_code_init(name, dir)`` which must
self-register (ErasureCodePlugin.cc:126-184). Python has no dlopen, but the
failure surface is preserved: a plugin is a module that must

- expose ``__erasure_code_version__`` matching :data:`PLUGIN_VERSION`
  (version check at the reference's ErasureCodePlugin.cc:144),
- expose ``__erasure_code_init__(name, registry)`` (entry-point lookup at
  :151) which must call ``registry.add(name, plugin)``.

Built-in plugins resolve to ``ceph_tpu.models.<name>``; external plugin
directories (the ``erasure_code_dir`` of the reference) are searched for
``ec_<name>.py`` files loaded via importlib. All failure modes of the
reference's loader (missing library, missing entry point, version mismatch,
init failure, init-forgets-to-register) raise distinct errors and are
exercised by tests/test_plugin_registry.py, mirroring
src/test/erasure-code/TestErasureCodePlugin.cc and its purpose-built broken
plugins (ErasureCodePluginFailToInitialize.cc, …FailToRegister.cc,
…MissingEntryPoint.cc, …MissingVersion.cc).
"""

from __future__ import annotations

import importlib
import importlib.util
import threading
from abc import ABC, abstractmethod
from pathlib import Path

from ceph_tpu.models.interface import ErasureCodeError, ErasureCodeInterface

#: bumped when the plugin ABI changes (reference ties it to the git version)
PLUGIN_VERSION = "ceph-tpu-plugin-1"

#: built-in plugin name -> module
_BUILTIN_MODULES = {
    "example": "ceph_tpu.models.example_xor",
    "jerasure": "ceph_tpu.models.jerasure",
    "isa": "ceph_tpu.models.isa",
    "shec": "ceph_tpu.models.shec",
    "lrc": "ceph_tpu.models.lrc",
    "clay": "ceph_tpu.models.clay",
}


class PluginLoadError(ErasureCodeError):
    pass


class ErasureCodePlugin(ABC):
    """A factory for codec instances (reference: ErasureCodePlugin.h:31-43)."""

    @abstractmethod
    def factory(self, profile: dict) -> ErasureCodeInterface:
        """Instantiate and init() a codec for the profile."""


class ErasureCodePluginRegistry:
    """Singleton name -> plugin map with lazy loading
    (reference: ErasureCodePlugin.h:45-79)."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False  # parity knob; no-op in-process

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise PluginLoadError(f"plugin {name!r} already registered",
                                      errno_=17)
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def load(self, name: str, directory: str | None = None) -> ErasureCodePlugin:
        """Load plugin ``name``; mirrors ErasureCodePlugin.cc:126-184."""
        with self._lock:
            if name in self._plugins:
                return self._plugins[name]
            module = self._import_plugin_module(name, directory)
            version = getattr(module, "__erasure_code_version__", None)
            if version is None:
                raise PluginLoadError(
                    f"plugin {name!r} has no __erasure_code_version__ "
                    f"(reference: missing __erasure_code_version symbol)")
            if version != PLUGIN_VERSION:
                raise PluginLoadError(
                    f"plugin {name!r} version {version!r} != expected "
                    f"{PLUGIN_VERSION!r}", errno_=95)
            init = getattr(module, "__erasure_code_init__", None)
            if init is None:
                raise PluginLoadError(
                    f"plugin {name!r} has no __erasure_code_init__ entry point")
            try:
                init(name, self)
            except PluginLoadError:
                raise
            except Exception as exc:
                raise PluginLoadError(
                    f"plugin {name!r} init failed: {exc!r}") from exc
            if name not in self._plugins:
                raise PluginLoadError(
                    f"plugin {name!r} init() did not register itself "
                    f"(reference: load: {name} [init, registered]... missing)",
                    errno_=98)
            return self._plugins[name]

    def _import_plugin_module(self, name: str, directory: str | None):
        if directory:
            path = Path(directory) / f"ec_{name}.py"
            if not path.exists():
                raise PluginLoadError(
                    f"no plugin file {path} for {name!r}", errno_=2)
            spec = importlib.util.spec_from_file_location(
                f"ceph_tpu_ext_plugin_{name}", path)
            module = importlib.util.module_from_spec(spec)
            try:
                spec.loader.exec_module(module)
            except Exception as exc:
                raise PluginLoadError(
                    f"plugin file {path} failed to import: {exc!r}") from exc
            return module
        modname = _BUILTIN_MODULES.get(name)
        if modname is None:
            raise PluginLoadError(f"unknown plugin {name!r}", errno_=2)
        try:
            return importlib.import_module(modname)
        except ImportError as exc:
            raise PluginLoadError(
                f"plugin module {modname} failed to import: {exc!r}") from exc

    def factory(self, plugin_name: str, profile: dict,
                directory: str | None = None) -> ErasureCodeInterface:
        """Resolve plugin, instantiate codec, init with profile
        (reference: ErasureCodePluginRegistry::factory,
        ErasureCodePlugin.cc:92-120)."""
        plugin = self.load(plugin_name, directory)
        codec = plugin.factory(dict(profile))
        return codec

    def preload(self, names: list[str] | None = None,
                directory: str | None = None) -> None:
        """Preload plugins at daemon start (reference: config
        osd_erasure_code_plugins, ErasureCodePlugin.cc:186-202).
        ``names`` defaults to the ``osd_erasure_code_plugins``
        option, whitespace-separated as in the reference."""
        if names is None:
            from ceph_tpu.utils.config import g_conf
            names = g_conf()["osd_erasure_code_plugins"].split()
        for name in names:
            self.load(name, directory)


def instance() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()
