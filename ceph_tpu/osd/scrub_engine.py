"""Device-resident deep scrub — fused crc + parity-re-encode verify.

The host shallow scrub (``osd.py _do_scrub``) is an object-at-a-time
crc32c comparison against hinfo: one csum fan-out per object, hashes
computed on the serving OSD's CPU, and no parity consistency check at
all (a shard whose hinfo rotted alongside its data passes). This
module is the deep mode: a PG's objects stream through the SAME
device kernels the write path already owns —

1. **Gather**: every up shard of every object is read RAW (the
   hinfo crc gate on the serving OSD is bypassed — deep scrub wants
   the observation, and moves the hashing to the device), grouped by
   shape into pow2-bucketed batches (the compile-bounding discipline
   of ``ec_util._flush_device_fused_async``).
2. **Verify**: one fused device pass per batch — re-encode the data
   shards with the codec's GF matvec and XOR-compare against the
   stored parity, and take every shard's crc32c linear part from the
   same HBM-resident buffers (``ops/crc32c_device``). Only a
   [objects, m] mismatch bitmap and a [objects, shards] crc vector
   return to host: a clean batch costs ZERO per-object host verdict
   work (the shallow path's per-object csum fan-out + retry ladder).
3. **Repair**: convicted shards are reconstructed from the good
   shards ALREADY IN MEMORY through the codec's sparse-aware decode
   (``matrix_codec.decode_chunks`` column-occupancy skip; the device
   engine's signature-batched ``stage_decode`` when the pool runs a
   device backend) and pushed through the normal recovery write path
   (``MPGPush`` — the push guard still applies), rate-limited in
   bounded rounds. Shards that cannot be rebuilt from memory fall
   back to ``peer_missing`` + a QOS_SCRUB recovery kick.

Conviction logic (mirrors the shallow scrub's self-consistency rule):
a shard whose device-computed crc mismatches its OWN stored hinfo is
corrupt. A parity mismatch with no crc culprit (hinfo dropped by an
RMW, or the hinfo itself rotted) runs the EXCLUSION test: the one
position whose removal makes the remaining system self-consistent is
the rotten one — real bitrot *detection*, not just crc bookkeeping.
Anything still ambiguous goes to the host shallow oracle
(``_scrub_object``), which stays the cross-check for the device path.

Batches are bounded (``max_batch_objects``/``max_batch_bytes``) so
the HBM working set — and the crc bit-unpack's 8x amplification — is
capped per round; verify launches run on the device engine's thread
(``run_sync``) so scrub never contends with a client encode flush
mid-download.
"""

from __future__ import annotations

import json
import threading

from ceph_tpu.analysis.lock_witness import make_lock
import time

import numpy as np

from ceph_tpu.osd import ec_util
from ceph_tpu.osd.pg_backend import SUBOP_TIMEOUT, SubOpWait
from ceph_tpu.parallel import messages as M
from ceph_tpu.utils.device_telemetry import telemetry as _telemetry
from ceph_tpu.utils.dout import Dout

log = Dout("osd")

#: smallest shard-length bucket (pow2; a multiple of the crc kernel's
#: ROW_BYTES by construction — every pow2 >= 512 is)
_MIN_LEN_BUCKET = 1 << 12


def _pow2(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


#: (matrix bytes, k, l_b, nobj_b) -> jitted fused verify program;
#: pow2-bucketed dims keep this bounded no matter the object mix
_verify_cache: dict = {}
_VERIFY_CACHE_MAX = 64


def verify_fn(mat: np.ndarray, k: int, l_b: int, nobj_b: int):
    """The fused deep-scrub verify program for a [nobj_b, k+m, l_b]
    uint8 shard batch: re-encode data shards via the GF matvec,
    XOR-compare against stored parity (reduced to a [nobj_b, m] any-
    mismatch bitmap), and compute every shard's crc32c LINEAR part
    from the same device-resident buffers. Returns ``fn(batch) ->
    (mismatch [nobj_b, m] bool, crc_lin [nobj_b, k+m] uint32)``.
    Cached per (matrix, k, l_b, nobj_b) — bench and the engine share
    the exact compiled program."""
    import jax

    mat = np.asarray(mat, dtype=np.uint8)
    m = mat.shape[0]
    n = k + m
    key = (mat.tobytes(), k, l_b, nobj_b)
    fn = _verify_cache.get(key)
    if fn is not None:
        return fn
    if len(_verify_cache) >= _VERIFY_CACHE_MAX:
        _verify_cache.clear()

    def verify(batch):
        import jax.numpy as jnp
        from ceph_tpu.ops import crc32c_device as cd
        from ceph_tpu.ops import gf_jax
        # fold objects into the byte axis: GF matvec is position-wise
        data = batch[:, :k, :].transpose(1, 0, 2).reshape(
            k, nobj_b * l_b)
        par = gf_jax.matvec_device(mat, data)          # [m, nobj*l]
        par = par.reshape(m, nobj_b, l_b).transpose(1, 0, 2)
        mism = jnp.any(par != batch[:, k:, :], axis=2)  # [nobj, m]
        lin = cd.crc_linear_device(batch.reshape(nobj_b * n, l_b))
        return mism, lin.reshape(nobj_b, n)

    fn = _verify_cache[key] = jax.jit(verify)
    return fn


#: id(mesh) -> {(matrix bytes, k): verify step} — mesh twins of the
#: fused verify program (bounded like ec_util's step cache)
_mesh_verify_cache: dict = {}


def _mesh_verify_step(mesh, mat: np.ndarray, k: int):
    from ceph_tpu.parallel import sharded_codec
    if id(mesh) not in _mesh_verify_cache and \
            len(_mesh_verify_cache) >= _VERIFY_CACHE_MAX:
        _mesh_verify_cache.clear()
    per_mesh = _mesh_verify_cache.setdefault(id(mesh), {})
    key = (mat.tobytes(), k)
    step = per_mesh.get(key)
    if step is None:
        step = per_mesh[key] = sharded_codec.make_verify_step(
            mesh, mat, k)
    return step


def verify_batch(mat: np.ndarray, k: int, batch: np.ndarray,
                 mesh=None) -> tuple[np.ndarray, np.ndarray]:
    """Host entry: verify a [nobj, k+m, L] uint8 batch (L already a
    pow2 bucket, shards FRONT-padded — free under both GF and crc
    linearity). Pads the object axis to its pow2 bucket, runs the
    fused program through the telemetry compile accountant, and
    returns (mismatch [nobj, m] bool, crc_lin [nobj, k+m] uint32).

    With ``mesh`` (ISSUE 12), the batch spreads over every mesh chip
    through the sharded verify twin (parallel/sharded_codec.
    make_verify_step) — objects partition over the device axis, each
    chip re-encodes + crcs its objects locally, and only the verdict
    rows come home. Bit-exact vs the single-chip program (zero-padded
    objects verify clean on both). Raises on a mesh fault — callers
    fall back to the single-chip path."""
    mat = np.asarray(mat, dtype=np.uint8)
    nobj, n, l_b = batch.shape
    m = mat.shape[0]
    assert n == k + m, (n, k, m)
    nobj_b = _pow2(max(nobj, 1), 1)
    if mesh is not None:
        # the object axis shards over EVERY chip: round the pow2
        # bucket up to a device-count multiple
        n_dev = int(np.prod(list(mesh.shape.values())))
        if nobj_b % n_dev:
            nobj_b = -(-nobj_b // n_dev) * n_dev
    if nobj_b != nobj:
        # zero objects: zero parity re-encodes to zero (no mismatch)
        padded = np.zeros((nobj_b, n, l_b), dtype=np.uint8)
        padded[:nobj] = batch
        batch = padded
    if mesh is not None:
        from ceph_tpu.parallel import sharded_codec
        step = _mesh_verify_step(mesh, mat, k)
        mism, lin = step(sharded_codec.shard_object_batch(mesh, batch))
        _telemetry().note_mesh_scrub_batch()
        return (np.asarray(mism)[:nobj], np.asarray(lin)[:nobj])
    fn = verify_fn(mat, k, l_b, nobj_b)
    sig = f"scrub_verify[{m}x{k}]L{l_b}n{nobj_b}"
    mism, lin = _telemetry().timed_call(sig, fn, batch)
    return (np.asarray(mism)[:nobj], np.asarray(lin)[:nobj])


class DeepScrubEngine:
    """Per-OSD deep-scrub orchestrator (one instance, lazily built by
    ``OSD.scrub_engine()``); stateless across PGs except counters."""

    #: batch caps: objects per device launch and bytes per launch (the
    #: crc bit-unpack amplifies 8x in device memory, so the HBM bound
    #: is max_batch_bytes * 8 + the batch itself)
    max_batch_objects = 128
    max_batch_bytes = 32 << 20
    #: repair rate limiter: at most this many reconstructed bytes per
    #: round, then a breather — background repair must not crowd the
    #: client op path off the device or the wire
    repair_bytes_per_round = 16 << 20
    repair_round_delay = 0.05
    #: gather fan-out attempts before an object is skipped as
    #: unsettled (online scrub races in-flight writes, exactly like
    #: the shallow path's retry ladder)
    GATHER_ATTEMPTS = 3

    def __init__(self, osd) -> None:
        self.osd = osd
        self._lock = make_lock("scrub.state")
        self.stats = {
            "pgs": 0, "objects": 0, "batches": 0,
            "bytes_verified": 0, "mismatch_stripes": 0,
            "crc_convictions": 0, "exclusion_convictions": 0,
            "host_fallback_objects": 0, "skipped_unsettled": 0,
            "repaired_shards": 0, "repair_rounds": 0,
            "repair_bytes": 0, "device_errors": 0,
        }

    # -- public entry --------------------------------------------------
    def deep_scrub_pg(self, pg, repair: bool = True) -> dict | None:
        """Deep-scrub one ACTIVE primary PG. Returns the scrub result
        dict, or None when this pool cannot take the device path
        (replicated, or a layered/mapped codec) — the caller falls
        back to the host shallow scrub."""
        from ceph_tpu.osd.ec_backend import ECBackend
        be = pg.backend
        if not isinstance(be, ECBackend):
            return None
        from ceph_tpu.models.matrix_codec import MatrixErasureCode
        codec = be.codec
        if not isinstance(codec, MatrixErasureCode) or \
                codec.chunk_mapping:
            return None                 # layered codec: host scrub
        osd = self.osd
        with pg.lock:
            if pg.state != pg.ACTIVE:
                return {"error": "pg not active here"}
            if len(be.up_positions(pg)) < be.n:
                # a down shard can neither be verified nor repaired
                # into; judge it when the set is whole (recovery owns
                # the degraded case)
                return {"error": "acting set not whole", "deep": True}
            latest: dict[str, int] = {}
            for v in sorted(pg.log.entries):
                latest[pg.log.entries[v].oid] = pg.log.entries[v].op
        from ceph_tpu.osd.pg import LOG_REMOVE
        listing = [oid for oid in osd._scrub_listing(pg)
                   if latest.get(oid) != LOG_REMOVE]
        out = {"objects": len(listing), "inconsistent": {},
               "repaired": [], "deep": True, "batches": 0,
               "bytes_verified": 0}
        self.stats["pgs"] += 1

        gathered = self._gather(pg, listing)
        victims: dict[str, dict] = {}
        # bucket by shard-length bucket, chunk by the batch caps
        buckets: dict[int, list] = {}
        for oid, obs in gathered.items():
            if obs is None:
                self.stats["skipped_unsettled"] += 1
                continue
            if not obs["shards"] and not obs["bad"]:
                continue               # concurrently removed: clean
            if obs["bad"]:
                # read-layer conviction (EIO / ENOENT while peers
                # hold it): straight to repair, no device pass needed
                victims[oid] = obs
                continue
            l_b = _pow2(max(obs["shard_len"], 1), _MIN_LEN_BUCKET)
            buckets.setdefault(l_b, []).append((oid, obs))
        for l_b, items in sorted(buckets.items()):
            per_batch = max(1, min(self.max_batch_objects,
                                   self.max_batch_bytes //
                                   (be.n * l_b) or 1))
            for i in range(0, len(items), per_batch):
                chunk = items[i:i + per_batch]
                nb = self._verify_chunk(pg, be, l_b, chunk, victims)
                out["batches"] += 1
                out["bytes_verified"] += nb
        for oid, obs in victims.items():
            out["inconsistent"][oid] = sorted(obs["bad"])
        self.stats["objects"] += len(listing)
        if repair and victims:
            out["repaired"] = self._repair(pg, victims)
        return out

    # -- gather --------------------------------------------------------
    def _gather(self, pg, listing: list[str]) -> dict:
        """Raw full-shard reads of every object over every up
        position; per object returns {"shards": {pos: np}, "attrs":
        {pos: dict}, "versions", "shard_len", "bad": set()} or None
        when the observation never settled (in-flight write)."""
        from concurrent.futures import ThreadPoolExecutor
        if not listing:
            return {}
        with ThreadPoolExecutor(
                max_workers=min(8, len(listing)),
                thread_name_prefix="deep-scrub-gather") as pool:
            return dict(zip(listing,
                            pool.map(lambda o: self._gather_one(pg, o),
                                     listing)))

    def _gather_one(self, pg, oid: str) -> dict | None:
        osd = self.osd
        be = pg.backend
        for attempt in range(self.GATHER_ATTEMPTS):
            positions = be.up_positions(pg)
            tid = osd.new_tid()
            wait = SubOpWait(set(positions))
            osd.register_wait(tid, wait)
            for pos in positions:
                osd.send_osd(pg.acting[pos], M.MECSubRead(
                    tid=tid, pool=pg.pool, ps=pg.ps, shard=pos,
                    oid=oid, offset=0, length=0, want_attrs=True,
                    raw=True))
            replies = wait.wait(SUBOP_TIMEOUT)
            osd.unregister_wait(tid)
            shards: dict[int, np.ndarray] = {}
            attrs: dict[int, dict] = {}
            vers: dict[int, int] = {}
            bad: set[int] = set()
            enoent: set[int] = set()
            silent = False
            for pos in positions:
                rep = replies.get(pos)
                if rep is None:
                    silent = True
                    continue
                if rep.code == -2:
                    enoent.add(pos)
                    continue
                if rep.code != 0:
                    bad.add(pos)         # EIO: read-layer conviction
                    continue
                shards[pos] = np.frombuffer(rep.data, dtype=np.uint8)
                attrs[pos] = dict(rep.attrs)
                vers[pos] = rep.version
            lens = {len(v) for v in shards.values()}
            settled = (not silent and len(set(vers.values())) <= 1
                       and len(lens) <= 1
                       and not (shards and enoent))
            if settled:
                if not shards and not bad:
                    return {"shards": {}, "attrs": {}, "versions": {},
                            "shard_len": 0, "bad": set()}  # all-ENOENT
                bad |= enoent
                return {"shards": shards, "attrs": attrs,
                        "versions": vers,
                        "shard_len": lens.pop() if lens else 0,
                        "bad": bad}
            time.sleep(0.05 * (attempt + 1))
        return None

    # -- verify --------------------------------------------------------
    def _verify_chunk(self, pg, be, l_b: int, chunk: list,
                      victims: dict) -> int:
        """One device launch over ``chunk`` = [(oid, obs)] whose
        shards all bucket to ``l_b``. Convicts via the crc-vs-hinfo
        self-check, the exclusion test, or the host oracle; populates
        ``victims``. Returns bytes verified."""
        k, n = be.k, be.n
        mat = np.asarray(be.codec.coding_matrix, dtype=np.uint8)
        batch = np.zeros((len(chunk), n, l_b), dtype=np.uint8)
        for i, (_oid, obs) in enumerate(chunk):
            for pos, arr in obs["shards"].items():
                batch[i, pos, l_b - len(arr):] = arr  # FRONT pad
        nbytes = sum(len(a) for _o, obs in chunk
                     for a in obs["shards"].values())
        t0 = time.perf_counter()
        mism = lin = None
        engine = self.osd.device_engine()
        # multi-chip deep scrub (ISSUE 12): a big-enough batch
        # spreads over the PG's placement-slot submesh (or the whole
        # default mesh) through the sharded verify twin; a mesh fault
        # falls back to the single-chip program, never to a skipped
        # verification
        mesh = self._pick_mesh(pg, batch.nbytes)
        try:
            if mesh is not None:
                try:
                    mism, lin = engine.run_sync(
                        lambda: verify_batch(mat, k, batch,
                                             mesh=mesh))
                except Exception as exc:
                    log(1, f"{pg}: mesh scrub verify fell back to "
                        f"single-chip ({exc!r})")
                    _telemetry().note_fused_fallback()
            if mism is None:
                mism, lin = engine.run_sync(
                    lambda: verify_batch(mat, k, batch))
        except Exception as exc:
            log(0, f"{pg}: deep-scrub device verify failed ({exc!r});"
                " host oracle fallback for this batch")
            self.stats["device_errors"] += 1
        tel = _telemetry()
        if mism is None:
            # device fault: every object of the batch goes to the
            # host oracle (the daemon never wedges on the accelerator)
            for oid, obs in chunk:
                self._host_verdict(pg, oid, obs, victims)
            return nbytes
        self.stats["batches"] += 1
        self.stats["bytes_verified"] += nbytes
        tel.note_scrub_flush(len(chunk), nbytes,
                             time.perf_counter() - t0)
        from ceph_tpu.ops.crc32c_device import crc32c_from_linear
        for i, (oid, obs) in enumerate(chunk):
            parity_bad = bool(mism[i].any())
            crc_bad: set[int] = set()
            for pos in obs["shards"]:
                hraw = obs["attrs"].get(pos, {}).get("hinfo")
                if not hraw:
                    continue       # RMW dropped it: no self-check
                try:
                    hinfo = ec_util.HashInfo.from_dict(
                        json.loads(hraw))
                    want = hinfo.get_chunk_hash(pos)
                except (ValueError, KeyError, TypeError, IndexError):
                    crc_bad.add(pos)   # unparseable hinfo: corrupt
                    continue
                # full crc from the device linear part + the seed
                # correction for THIS object's true shard length (the
                # linear part is invariant under the bucket front pad)
                if crc32c_from_linear(int(lin[i, pos]),
                                      obs["shard_len"],
                                      ec_util.HINFO_SEED) != want:
                    crc_bad.add(pos)
            if not parity_bad and not crc_bad:
                continue               # clean: bitmap row only
            self.stats["mismatch_stripes"] += 1
            tel.note_scrub_mismatch()
            if crc_bad:
                self.stats["crc_convictions"] += len(crc_bad)
                victims[oid] = {**obs, "bad": set(crc_bad)}
                continue
            excl = self._exclusion_test(be, obs)
            if excl is not None:
                self.stats["exclusion_convictions"] += 1
                victims[oid] = {**obs, "bad": {excl}}
                continue
            self._host_verdict(pg, oid, obs, victims)
        return nbytes

    @staticmethod
    def _pick_mesh(pg, nbytes: int):
        """The mesh this PG's verify batch should ride: None below
        the dense->mesh crossover or with no default mesh; the PG's
        placement-slot submesh when a multi-slot map is active (scrub
        lands on the same chips that own the PG's encode/decode
        work); else the whole default mesh."""
        from ceph_tpu.osd import device_engine as de
        from ceph_tpu.parallel import mesh as mesh_mod
        from ceph_tpu.parallel import placement as _placement
        mesh = mesh_mod.get_default_mesh()
        if mesh is None or nbytes < de.mesh_flush_threshold():
            return None
        pmap = _placement.active_map()
        if pmap is not None and pmap.n_slots > 1:
            return pmap.submesh(pmap.slot(pg.pgid))
        return mesh

    def _exclusion_test(self, be, obs: dict) -> int | None:
        """Single-corruption localization with no crc evidence: the
        one position whose exclusion leaves a self-consistent system
        (decode it from any k of the others, re-encode, and every
        OTHER stored shard matches) is the rotten shard. Host-side
        numpy on one object's shards — runs only for the rare
        parity-mismatch-without-crc-culprit case."""
        k = be.k
        m = be.n - k
        codec = be.codec
        shards = obs["shards"]
        if len(shards) < k + 1:
            return None                # cannot cross-check
        consistent = []
        for p in sorted(shards):
            others = {c: v for c, v in shards.items() if c != p}
            try:
                dec = ec_util.decode(be.sinfo, codec, others,
                                     list(range(k)))
                data = np.stack([np.asarray(dec[c], dtype=np.uint8)
                                 for c in range(k)])
                parity = codec._matvec(codec.coding_matrix, data)
            except Exception:
                continue
            full = {c: data[c] for c in range(k)}
            full.update({k + j: parity[j] for j in range(m)})
            # decode returns present chunks verbatim, so re-derive the
            # WHOLE system from the decoded data and compare every
            # remaining stored shard against it
            if all(np.array_equal(full[c], np.asarray(shards[c]))
                   for c in others):
                consistent.append(p)
        return consistent[0] if len(consistent) == 1 else None

    def _host_verdict(self, pg, oid: str, obs: dict,
                      victims: dict) -> None:
        """Cross-check oracle: the shallow per-object judge."""
        self.stats["host_fallback_objects"] += 1
        _telemetry().note_scrub_host_fallback()
        bad, _auth = self.osd._scrub_object(pg, oid)
        if bad:
            victims[oid] = {**obs, "bad": set(bad)}

    # -- repair --------------------------------------------------------
    def _repair(self, pg, victims: dict) -> list[str]:
        """Reconstruct convicted shards from the gathered good shards
        (sparse-aware decode, signature-batched on the device path)
        and push them through the normal recovery write path, rate-
        limited per round. Unrebuildable objects fall back to
        peer_missing + a QOS_SCRUB recovery kick."""
        from ceph_tpu.osd.osd import QOS_SCRUB, _SelfConn
        osd = self.osd
        be = pg.backend
        repaired: list[str] = []
        fallback: dict[str, set] = {}
        round_bytes = 0
        self.stats["repair_rounds"] += 1
        for oid, obs in sorted(victims.items()):
            bad = sorted(obs["bad"])
            good = {pos: arr for pos, arr in obs["shards"].items()
                    if pos not in obs["bad"]}
            if len(good) < be.k or not obs.get("attrs"):
                fallback[oid] = set(bad)
                continue
            try:
                decoded = be._decode(pg, good, bad)
            except Exception as exc:
                log(1, f"{pg}: deep-scrub repair decode {oid} "
                    f"failed: {exc!r}")
                fallback[oid] = set(bad)
                continue
            ref_attrs = next(iter(
                obs["attrs"][p] for p in sorted(obs["attrs"])
                if p not in obs["bad"]), None)
            if ref_attrs is None:
                fallback[oid] = set(bad)
                continue
            ok = True
            for pos in bad:
                chunk = np.asarray(decoded[pos], dtype=np.uint8)
                tid = osd.new_tid()
                push = be._push_from_chunk(pg, oid, pos,
                                           obs["versions"].get(pos, 0)
                                           or int.from_bytes(
                                               ref_attrs.get("v", b""),
                                               "little"),
                                           chunk, ref_attrs, tid)
                if push is None:
                    ok = False
                    continue
                wait = SubOpWait({oid})
                osd.register_wait(tid, wait)
                target = pg.acting[pos]
                if target == osd.whoami:
                    osd._handle_pg_push(push, _SelfConn(osd))
                else:
                    osd.send_osd(target, push)
                replies = wait.wait(SUBOP_TIMEOUT)
                osd.unregister_wait(tid)
                rep = replies.get(oid)
                if rep is None or not getattr(rep, "committed",
                                              False):
                    ok = False
                    continue
                self.stats["repaired_shards"] += 1
                self.stats["repair_bytes"] += len(chunk)
                _telemetry().note_scrub_repair()
                round_bytes += len(chunk)
                if round_bytes >= self.repair_bytes_per_round:
                    # breather: background repair yields the device
                    # and the wire back to client traffic
                    self.stats["repair_rounds"] += 1
                    round_bytes = 0
                    time.sleep(self.repair_round_delay)
            if ok:
                repaired.append(oid)
                with pg.lock:
                    for pos in bad:
                        missing = pg.peer_missing.get(pos)
                        if missing:
                            missing.pop(oid, None)
            else:
                fallback[oid] = set(bad)
        if fallback:
            with pg.lock:
                for oid, bad in fallback.items():
                    ver = max(victims[oid]["versions"].values(),
                              default=0)
                    if ver <= 0:
                        continue       # nothing judgeable to push
                    for pos in bad:
                        pg.peer_missing.setdefault(pos, {})[oid] = ver
            osd.op_wq.enqueue(pg.pgid, lambda p=pg: osd._recover(p),
                              qos=QOS_SCRUB)
        return repaired
