"""Commit-path X-ray acceptance (ISSUE 14): the store txn lifecycle
decomposition, the timed-fsync seam, and the two batching what-if
ledgers.

- a scripted txn schedule under an injectable clock pins sub-stage
  sums == the txn's commit span (the decomposition is a partition,
  not a sample);
- every real store's fsyncs land counted/timed per call site through
  the named seam (blockstore: data fdatasync + kv WAL fsync; kstore
  on FileDB: WAL fsync; memstore: zero);
- the group-commit analyzer's projection matches a hand-computable
  arrival sequence, in both fsync-cost models;
- the objecter adjacency histogram under a scripted burst shows the
  coalescable batches a streaming seam would have formed.
"""

from __future__ import annotations

import os

import pytest

from ceph_tpu.store.object_store import Transaction, create_store
from ceph_tpu.utils import store_telemetry
from ceph_tpu.utils.store_telemetry import SUB_STAGES, telemetry


@pytest.fixture(autouse=True)
def _fresh_registry():
    tel = telemetry()
    tel.reset()
    yield
    telemetry().reset()


class FakeClock:
    """Injectable perf_counter: advances only when told to."""

    def __init__(self) -> None:
        self.t = 100.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- txn lifecycle decomposition --------------------------------------

def test_scripted_schedule_substage_sums_equal_commit_span():
    """Every instant of a scripted commit is attributed to exactly
    one sub-stage: the sums equal the span, to the clock tick."""
    tel = telemetry()
    clock = FakeClock()
    tmr = tel.txn_timer("synth", 7, now=clock)
    tmr.n_ops = 3
    span0 = clock.t
    with tmr:
        with tmr.stage("queue_wait"):
            clock.advance(0.002)
        with tmr.stage("apply"):
            clock.advance(0.003)
        with tmr.stage("kv_build"):
            clock.advance(0.0015)
        tmr.add("wal_append", 0.001)
        clock.advance(0.001)                    # the wal time itself
        tmr.add_fsync("synth.wal", 0.004, nbytes=4096)
        clock.advance(0.004)                    # the fsync time
        tmr.run_on_commit(lambda: clock.advance(0.0005))
    span = clock.t - span0
    assert tmr.total() == pytest.approx(span, abs=1e-12)
    assert tmr.durations == pytest.approx({
        "queue_wait": 0.002, "apply": 0.003, "kv_build": 0.0015,
        "wal_append": 0.001, "fsync": 0.004, "on_commit": 0.0005})
    # the registry saw exactly one txn with those sums
    snap = tel.perf.dump()
    assert snap["txns"] == 1
    for stage, want in tmr.durations.items():
        assert snap[f"txn_{stage}"]["sum"] == pytest.approx(want)
    bd = tel.txn_breakdown()
    assert bd["txns"] == 1
    assert bd["span_s"] == pytest.approx(span, abs=1e-9)
    shares = sum(e["share_pct"] for e in bd["stages"].values())
    assert shares == pytest.approx(100.0, abs=1.0)
    # the seam's per-site table recorded the barrier
    sites = tel.fsync_sites()
    assert sites["synth.wal"]["count"] == 1
    assert sites["synth.wal"]["bytes"] == 4096


def test_every_substage_key_is_registered():
    keys = set(telemetry().perf.dump())
    for stage in SUB_STAGES:
        assert f"txn_{stage}" in keys
        assert f"txn_{stage}_us" in keys


# -- fsync accounting per store ---------------------------------------

def _commit_one_write(store) -> None:
    txn = Transaction()
    txn.create_collection("c")
    txn.write("c", "o", 0, b"payload" * 64)
    fired = []
    store.queue_transaction(txn, lambda: fired.append(1))
    assert fired == [1]


def test_memstore_commits_with_zero_fsyncs():
    store = create_store("memstore")
    store.mount()
    _commit_one_write(store)
    snap = telemetry().perf.dump()
    assert snap["txns"] >= 1
    assert snap["fsyncs"] == 0
    assert snap["txn_apply"]["avgcount"] >= 1
    assert snap["txn_on_commit"]["avgcount"] >= 1


def test_blockstore_fsyncs_timed_per_site(tmp_path):
    store = create_store("blockstore", str(tmp_path / "bs"))
    store.mount()
    _commit_one_write(store)
    tel = telemetry()
    snap = tel.perf.dump()
    # one data-file barrier + one WAL fsync, both through the seam
    sites = tel.fsync_sites()
    assert sites["blockstore.data"]["count"] >= 1
    assert sites["kv.wal"]["count"] >= 1
    assert sites["kv.wal"]["bytes"] > 0
    assert snap["fsyncs"] >= 2
    assert snap["fsync_time"]["avgcount"] == snap["fsyncs"]
    # the txn's own decomposition carried the barrier + wal time
    assert snap["txn_fsync"]["sum"] > 0
    assert snap["txn_wal_append"]["sum"] > 0
    assert snap["txn_apply"]["avgcount"] >= 1
    assert snap["txn_kv_build"]["avgcount"] >= 1
    store.umount()


def test_kstore_filedb_fsyncs_land_on_txn(tmp_path):
    store = create_store("kstore", str(tmp_path / "ks"))
    store.mount()
    _commit_one_write(store)
    tel = telemetry()
    snap = tel.perf.dump()
    assert tel.fsync_sites()["kv.wal"]["count"] >= 1
    assert snap["txn_fsync"]["sum"] > 0
    assert snap["txn_queue_wait"]["avgcount"] >= 1
    assert snap["txn_kv_build"]["avgcount"] >= 1
    store.umount()


def test_timed_fsync_outside_txn_still_counts(tmp_path):
    """The seam records straight into the registry when no txn timer
    is active (mon-store compactions, bit-flip injection)."""
    path = tmp_path / "f"
    with open(path, "wb") as f:
        f.write(b"x")
        store_telemetry.timed_fsync(f.fileno(), site="synth.loose",
                                    nbytes=1)
    tel = telemetry()
    assert tel.fsync_sites()["synth.loose"]["count"] == 1
    assert tel.perf.dump()["fsyncs"] == 1


# -- group-commit what-if ledger --------------------------------------

def test_group_commit_projection_hand_computed():
    """Arrivals [0, 0.4ms, 0.8ms, 10ms] in one store: under a 1 ms
    window the first three share a leader -> 2 groups, 2 barriers
    saved; fsync cost model is MEASURED (2 fsyncs x 1 ms each per
    txn)."""
    tel = telemetry()
    for t in (0.0, 0.0004, 0.0008, 0.010):
        tel.note_txn("synth", 1, t, 2, {"apply": 0.0001},
                     fsyncs=2, fsync_s=0.002)
    out = tel.group_commit_projection(windows_s=(0.001,))
    assert len(out) == 1
    row = out[0]
    assert row["window_ms"] == 1.0
    assert row["txns"] == 4
    assert row["groups"] == 2
    assert row["max_group"] == 3
    # 4 txns - 2 groups = 2 txn-barriers saved x 2 fsyncs/txn
    assert row["fsyncs_saved"] == pytest.approx(4.0)
    # measured cost: 8 fsyncs took 8 ms -> 1 ms each
    assert row["wall_saved_s"] == pytest.approx(0.004)
    assert row["fsync_model"] == "measured"


def test_group_commit_projection_profile_model_when_no_fsyncs():
    """A memstore run records zero fsyncs; the projection prices
    barriers with the durable-store profile and SAYS so."""
    tel = telemetry()
    for t in (0.0, 0.0001, 0.0002):
        tel.note_txn("memstore", 1, t, 1, {"apply": 0.0001},
                     fsyncs=0, fsync_s=0.0)
    row = tel.group_commit_projection(windows_s=(0.001,))[0]
    assert row["fsync_model"] == "durable_profile"
    assert row["groups"] == 1
    assert row["fsyncs_saved"] > 0


def test_group_commit_adjacency_is_per_store():
    """Two stores' interleaved arrivals never group together —
    adjacency only means anything within one store's commit queue."""
    tel = telemetry()
    tel.note_txn("synth", 1, 0.0, 1, {}, 0, 0.0)
    tel.note_txn("synth", 2, 0.0001, 1, {}, 0, 0.0)
    row = tel.group_commit_projection(windows_s=(0.001,))[0]
    assert row["txns"] == 2
    assert row["groups"] == 2            # one per store: no sharing
    assert row["fsyncs_saved"] == 0.0


# -- objecter submission-stream ledger --------------------------------

def test_objecter_adjacency_under_scripted_burst():
    """A burst of 4 submits inside the window on pg (1, 3) + a
    straggler + an unrelated pg: the analyzer forms the batches a
    streaming objecter would have framed."""
    tel = telemetry()
    for t in (0.0, 0.001, 0.002, 0.003):
        tel.note_objecter_submit(1, 3, t=t)
    tel.note_objecter_submit(1, 3, t=5.0)      # outside any window
    tel.note_objecter_submit(1, 4, t=0.0)      # different PG
    out = tel.objecter_adjacency(window_s=0.010)
    assert out["pgs"] == 2
    assert out["ops"] == 6
    assert out["batches"] == 3                 # [4-burst], [1], [1]
    assert out["max_batch"] == 4
    assert out["coalescable_ops"] == 3
    assert out["mean_batch"] == pytest.approx(2.0)
    # the size histogram recorded each batch
    hist = telemetry().perf.get("objecter_batch_ops")
    assert sum(hist) == 3


def test_objecter_inflight_depth_histogram():
    tel = telemetry()
    tel.note_objecter_submit(2, 0, t=0.0)
    tel.note_objecter_submit(2, 0, t=0.001)    # depth 2 while first
    tel.note_objecter_done(2, 0)
    tel.note_objecter_done(2, 0)
    tel.note_objecter_submit(2, 0, t=0.002)    # back to depth 1
    hist = tel.perf.get("objecter_pg_inflight")
    # pow2 buckets: depth 1 -> bucket 1, depth 2 -> bucket 2
    assert hist[1] == 2 and hist[2] == 1
    assert tel.perf.dump()["objecter_ops"] == 3


# -- export surfaces ---------------------------------------------------

def test_snapshot_and_brief_shapes():
    tel = telemetry()
    tel.note_txn("synth", 1, 0.0, 2, {"apply": 0.001}, 1, 0.0005)
    tel.note_fsync("synth.site", 0.0005, 64)
    snap = tel.snapshot()
    assert {"glossary", "counters", "txn_breakdown", "fsync_sites",
            "group_commit", "objecter_stream"} <= set(snap)
    brief = tel.snapshot_brief()
    assert brief["txns"] == 1
    assert brief["fsyncs"] == 1
    assert brief["fsyncs_per_txn"] == 1.0


def test_windows_env_override(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_WHATIF_WINDOWS_MS", "1,4")
    assert store_telemetry.whatif_windows_s() == (0.001, 0.004)
    monkeypatch.setenv("CEPH_TPU_WHATIF_WINDOWS_MS", "garbage")
    assert store_telemetry.whatif_windows_s() == \
        store_telemetry._DEFAULT_WINDOWS_S


def test_native_and_python_data_engines_share_the_seam(tmp_path):
    """Both blockstore data engines route their barrier through
    site blockstore.data (the format-compatibility twin of the
    engines themselves)."""
    from ceph_tpu.store.blockstore import _PyDataFile
    from ceph_tpu.store.native_io import NativeDataFile
    py = _PyDataFile(str(tmp_path / "py"))
    py.append(b"blob")
    py.sync()
    py.close()
    tel = telemetry()
    count = tel.fsync_sites()["blockstore.data"]["count"]
    assert count >= 1
    native = NativeDataFile.open(str(tmp_path / "nat"))
    if native is not None:
        native.append(b"blob")
        native.sync()
        native.close()
        assert tel.fsync_sites()["blockstore.data"]["count"] \
            == count + 1
