"""Acceptance gate for tools/gap_report.py (ISSUE 6 + ISSUE 7): on a
CPU-only MiniCluster run the profiler prints a stage-attribution
table whose stage sums account for >= 90% of the measured end-to-end
client-op latency, plus one machine-parseable JSON line, and the
cluster_bench metric machinery it reuses carries stage_breakdown +
p50/p99. With ``--profile`` the run is sampled at 50 Hz and the
table bottoms out in function names: per-stage top-10 hot frames,
>= 80% of sampled wall time attributed to named stages."""

import json

from ceph_tpu.utils import profiler as prof_mod


def test_gap_report_quick_run_attributes_latency(capsys):
    from ceph_tpu.tools import gap_report

    prof_mod.reset_for_tests()
    rc = gap_report.main([
        "--seconds", "0.5", "--osds", "3", "--obj-kb", "32",
        "--threads", "2", "--backend", "jax", "--profile"])
    assert rc == 0
    out = capsys.readouterr().out
    # the human table landed
    assert "data-plane gap report" in out
    assert "stage sum coverage" in out
    assert "engine staging queue" in out
    # the JSON line parses and carries the attribution
    line = [ln for ln in out.splitlines()
            if ln.startswith('{"gap_report"')][-1]
    rep = json.loads(line)["gap_report"]
    assert rep["coverage_pct"] >= 90.0, rep
    assert rep["ops"] > 0
    assert rep["cluster_MBps"] > 0
    assert rep["engine_GBps"] > 0
    assert rep["engine_source"] in ("baseline", "engine_loop", "cli")
    assert rep["gap_x"] > 1
    # every attributed stage has a share and a mean
    for stage, ent in rep["stages"].items():
        assert ent["share_pct"] >= 0.0
        assert ent["mean_ms"] >= 0.0
    # the canonical decomposition stages all landed
    for stage in ("wire", "dispatch_queue_wait", "engine_stage_wait",
                  "commit_wait"):
        assert stage in rep["stages"], rep["stages"]
    # the cluster_bench line it wraps carried the tail latencies
    assert rep["cluster_p50_ms"] > 0
    assert rep["cluster_p99_ms"] >= rep["cluster_p50_ms"]

    # -- ISSUE 14: the commit-path X-ray on the same quick run --
    # (a) the commit-wait envelope decomposes commit_wait: sub-stage
    # sums cover >= 90% of the measured commit_wait
    commit = rep["commit_path"]
    assert commit["coverage_pct"] >= 90.0, commit
    for stage in ("commit_dispatch", "commit_ship_wait",
                  "commit_ack_wait"):
        assert stage in commit["stages"], commit
        assert commit["stages"][stage]["mean_ms"] >= 0.0
    # (b) the what_if object parses and projects fsyncs-saved > 0
    # under the bulk-ingest burst (memstore run: durable profile)
    wi = rep["what_if"]
    assert wi["fsyncs_saved"] > 0, wi
    assert wi["fsync_model"] in ("measured", "durable_profile")
    assert wi["projected_MBps"] >= rep["cluster_MBps"], wi
    for row in wi["group_commit"]:
        assert row["txns"] >= row["groups"] > 0
    # (c) the objecter adjacency ledger shows coalescable ops > 1
    # per (pool, PG) window under the concurrent burst
    obj = wi["objecter_stream"]
    assert obj["max_batch"] > 1, obj
    assert obj["coalescable_ops"] > 0, obj
    # (d) wire framing accounted: batch frames counted with their
    # serialized sizes and a loopback/TCP split
    framing = wi["wire_framing"]
    assert framing["batch_frames"] > 0, framing
    assert framing["loopback_msgs"] + framing["tcp_msgs"] > 0
    assert framing["mean_batch_frame_bytes"] > 0
    # (e) the store table rode the report: txn decomposition + brief
    store = rep["store"]
    assert store["txn_breakdown"]["txns"] > 0
    assert store["brief"]["txns"] > 0
    # the human table printed the commit-path block + what-if line
    assert "commit path (under commit_wait" in out
    assert "what-if @" in out

    # -- ISSUE 7: --profile joins hot frames under the stage rows --
    prof = rep["profiler"]
    assert prof["hz"] == 50.0
    assert prof["samples"] > 0
    # >= 80% of sampled wall time attributed to named stages
    assert prof["attributed_pct"] >= 80.0, prof["by_stage"]
    hot = prof["hot_frames"]
    assert hot, "no hot frames sampled"
    for stage, frames in hot.items():
        assert len(frames) <= 10
        for f in frames:
            assert f["frame"] and f["samples"] > 0
            assert 0.0 <= f["pct"] <= 100.0
    # frames landed under stages the attribution table knows
    assert set(hot) & (set(rep["stages"]) | {"idle", "client_wait"}), \
        set(hot)
    # the table view prints frames indented under stage rows
    assert "↳" in out
    # the sampler's own cost is visible and small
    assert prof["sampler_overhead_pct"] < 25.0
    # sampler stopped with the run
    assert not [t for t in __import__("threading").enumerate()
                if t.name == "py-profiler"]
    prof_mod.reset_for_tests()


def test_gap_report_without_profile_has_no_profiler_field(capsys):
    """--profile stays opt-in: the plain run neither starts a sampler
    nor carries the profiler JSON field."""
    from ceph_tpu.tools import gap_report

    prof_mod.reset_for_tests()
    rc = gap_report.main([
        "--seconds", "0.2", "--osds", "2", "--obj-kb", "16",
        "--threads", "1", "--backend", "native"])
    assert rc == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith('{"gap_report"')][-1]
    rep = json.loads(line)["gap_report"]
    assert "profiler" not in rep
    assert prof_mod.profiler_if_exists() is None, \
        "a plain gap_report run must not allocate a profiler"


def _report(monkeypatch, capsys, bulk: str) -> dict:
    from ceph_tpu.tools import gap_report
    monkeypatch.setenv("CEPH_TPU_BULK_INGEST", bulk)
    rc = gap_report.main([
        "--seconds", "1.0", "--osds", "3", "--obj-kb", "64",
        "--threads", "4", "--backend", "jax"])
    assert rc == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith('{"gap_report"')][-1]
    return json.loads(line)["gap_report"]


def _combined_share(rep: dict) -> float:
    return sum(rep["stages"].get(s, {}).get("share_pct", 0.0)
               for s in ("commit_wait", "engine_stage_wait"))


def _combined_mean_ms(rep: dict) -> float:
    return sum(rep["stages"].get(s, {}).get("mean_ms", 0.0)
               for s in ("commit_wait", "engine_stage_wait"))


def test_bulk_ingest_before_after_regression_gate(monkeypatch,
                                                  capsys):
    """ISSUE 9's permanent regression gate: the SAME gap-report quick
    run under CEPH_TPU_BULK_INGEST=0 then =1 must show the combined
    commit_wait + engine_stage_wait attack surface SHRINK (those two
    stages are what the batched fan-out + zero-copy staging + shared
    engine attack), with timeline coverage still >= 90% in both
    modes — the decomposition stays complete while the path gets
    faster. Shares move less than per-op stage times (EVERY stage
    gets faster, so ratios nearly cancel, and a run where the REST
    of the pipeline speeds up most can push commit's share UP while
    per-op time halves — BASELINE.md "Bulk ingest"): the hard bar is
    the absolute per-op commit+stage time collapsing; the share
    check passes on the pre-PR 66% absolute bar OR same-pair
    shrinkage, and fresh measurement pairs absorb scheduler noise
    (the quick runs are 1 s samples inside a full-suite process)."""
    last = None
    for attempt in range(3):
        before = _report(monkeypatch, capsys, "0")
        after = _report(monkeypatch, capsys, "1")
        assert before["coverage_pct"] >= 90.0, before
        assert after["coverage_pct"] >= 90.0, after
        # per-op commit+stage wall time collapses (measured ~3x on
        # the CPU quick run; >= 25% holds under full-suite load)
        m_before = _combined_mean_ms(before)
        m_after = _combined_mean_ms(after)
        assert m_after < 0.75 * m_before, \
            (f"combined commit/stage per-op time did not drop: "
             f"{m_before:.2f}ms -> {m_after:.2f}ms")
        # the throughput direction must agree (the hard 2x bar lives
        # in test_bulk_ingest with a longer, retried measurement)
        assert after["cluster_MBps"] > before["cluster_MBps"], \
            (before["cluster_MBps"], after["cluster_MBps"])
        s_before = _combined_share(before)
        s_after = _combined_share(after)
        if s_after < 66.0 or s_after < s_before:
            return
        last = (s_before, s_after)
    # exhausted: a loaded suite process shifts the =1 share up a few
    # points SYSTEMATICALLY (GIL pressure inflates commit_wait while
    # the other stages stay collapsed — the documented clean quick
    # run measures 61.3%, BASELINE.md). The per-op-time bar above
    # already failed hard if batching actually broke (=1 would read
    # like =0); here only reject a real share REGRESSION, beyond
    # measured in-suite jitter.
    assert last[1] < last[0] + 4.0, (
        f"combined commit/stage share grew past noise: "
        f"{last[0]:.1f}% -> {last[1]:.1f}%")


def test_knob_section_rides_report_and_table(capsys):
    """ISSUE 13: gap_report carries the active knob vector (value +
    winning source + pin marker) next to its attribution table, and
    the table prints it — an attribution is never read without
    knowing which knob vector produced it."""
    from ceph_tpu.tools.gap_report import _knob_section, print_table
    from ceph_tpu.utils.knobs import TUNER_KNOBS

    section = _knob_section()
    assert set(section["vector"]) == set(TUNER_KNOBS.names())
    for name, ent in section["vector"].items():
        assert {"value", "source", "pinned"} <= set(ent), name
    assert section["tuner_active"] is False
    report = {"cluster_MBps": 1.0, "cluster_p50_ms": 1,
              "cluster_p99_ms": 2, "engine_GBps": 80.0,
              "engine_source": "baseline", "gap_x": 10.0,
              "backend": "jax", "profile": "k2m1",
              "stages": {}, "subops": {}, "coverage_pct": 0.0,
              "knobs": section}
    print_table(report)
    out = capsys.readouterr().out
    assert "knobs (tuner off" in out
    assert "engine_window=" in out
