"""flame — render the profiler's folded stacks in a terminal.

The continuous profiler (utils/profiler.py) exports flamegraph
"folded" lines — ``stage;frame;frame;frame count`` — via ``profile
flame`` on any daemon's admin socket, ``/api/profile``, and
``gap_report --profile``. This tool turns that text into something a
terminal can read without external flamegraph software:

    python -m ceph_tpu.tools.flame dump.folded            # tree view
    python -m ceph_tpu.tools.flame --top 20 dump.folded   # hot frames
    ... | python -m ceph_tpu.tools.flame -                # from stdin
    python -m ceph_tpu.tools.flame --stage commit_wait f  # one stage

The folded text itself is bit-compatible with Brendan Gregg's
``flamegraph.pl`` (the stage rides as the root frame), so a real SVG
is one pipe away where that tool exists.
"""

from __future__ import annotations

import argparse
import json
import sys

#: tree nodes below this share of total samples are pruned (noise)
_MIN_PCT = 0.5


def parse_folded(text: str) -> dict[tuple[str, ...], int]:
    """``stage;f1;f2 count`` lines -> {(stage, f1, f2): count}.
    Accepts the asok JSON payload (``{"folded": "..."}``) too."""
    text = text.strip()
    if text.startswith("{"):
        try:
            text = json.loads(text).get("folded", "")
        except ValueError:
            pass
    stacks: dict[tuple[str, ...], int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        body, _, count = line.rpartition(" ")
        if not body:
            continue
        try:
            n = int(count)
        except ValueError:
            continue
        key = tuple(body.split(";"))
        stacks[key] = stacks.get(key, 0) + n
    return stacks


def filter_stage(stacks: dict, stage: str) -> dict:
    return {k: v for k, v in stacks.items() if k and k[0] == stage}


def top_frames(stacks: dict, n: int = 20) -> list[tuple[str, int]]:
    """Self-sample (leaf frame) ranking — "where does the time
    actually burn"."""
    agg: dict[str, int] = {}
    for key, count in stacks.items():
        leaf = key[-1]
        agg[leaf] = agg.get(leaf, 0) + count
    return sorted(agg.items(), key=lambda kv: -kv[1])[:n]


class _Node:
    __slots__ = ("count", "children")

    def __init__(self) -> None:
        self.count = 0
        self.children: dict[str, _Node] = {}


def build_tree(stacks: dict) -> _Node:
    root = _Node()
    for key, count in stacks.items():
        root.count += count
        node = root
        for frame in key:
            node = node.children.setdefault(frame, _Node())
            node.count += count
    return root


def render_tree(root: _Node, min_pct: float = _MIN_PCT,
                width: int = 100) -> str:
    """Indented inclusive-sample tree, heaviest child first — the
    flamegraph, rotated 90 degrees for a terminal."""
    total = max(root.count, 1)
    lines: list[str] = []

    def walk(node: _Node, depth: int) -> None:
        for frame, child in sorted(node.children.items(),
                                   key=lambda kv: -kv[1].count):
            pct = 100.0 * child.count / total
            if pct < min_pct:
                continue
            bar = "#" * max(1, int(pct / 2))
            label = f"{'  ' * depth}{frame}"
            lines.append(f"{label[:width - 22]:<{width - 22}}"
                         f"{child.count:>7} {pct:>5.1f}% {bar}")
            walk(child, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def render_top(stacks: dict, n: int) -> str:
    total = max(sum(stacks.values()), 1)
    lines = [f"{'self':>7} {'share':>6}  frame"]
    for frame, count in top_frames(stacks, n):
        lines.append(f"{count:>7} {100.0 * count / total:>5.1f}%  "
                     f"{frame}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flame")
    ap.add_argument("path", help="folded-stacks file, a 'profile "
                                 "flame' JSON payload, or - for stdin")
    ap.add_argument("--top", type=int, default=0, metavar="N",
                    help="print the top-N hot frames (self samples) "
                         "instead of the tree")
    ap.add_argument("--stage", default="",
                    help="restrict to one stage root (e.g. "
                         "commit_wait)")
    ap.add_argument("--min-pct", type=float, default=_MIN_PCT,
                    help="prune tree nodes under this share")
    args = ap.parse_args(argv)
    text = sys.stdin.read() if args.path == "-" else \
        open(args.path).read()
    stacks = parse_folded(text)
    if args.stage:
        stacks = filter_stage(stacks, args.stage)
    if not stacks:
        print("no samples", file=sys.stderr)
        return 1
    if args.top:
        print(render_top(stacks, args.top))
    else:
        print(render_tree(build_tree(stacks), min_pct=args.min_pct))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
