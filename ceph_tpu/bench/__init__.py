"""Benchmark harnesses (the reference's src/test/erasure-code benchmark suite)."""
