"""dencoder — encode/decode/dump wire types (ceph-dencoder role).

Reference: src/tools/ceph-dencoder (+ src/test/encoding/readable.sh):
lists every encodable type, round-trips instances through the versioned
wire encoding, and dumps them as JSON — the tool behind the
ceph-object-corpus cross-version compatibility gate.

    python -m ceph_tpu.tools.dencoder list
    python -m ceph_tpu.tools.dencoder type MOSDOp dump_json < payload.bin
    python -m ceph_tpu.tools.dencoder type OSDMap encode > map.bin
    python -m ceph_tpu.tools.dencoder test          # roundtrip all types

Message types use their declarative FIELDS schema; structural types
(OSDMap, Transaction, HashInfo) register explicit codecs.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys


def _message_types() -> dict[str, type]:
    from ceph_tpu.parallel import messages as M
    return {name: cls for name, cls in vars(M).items()
            if isinstance(cls, type) and issubclass(cls, M.Message)
            and cls is not M.Message and cls.MSG_TYPE}


def _jsonable(v):
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode()}
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def _dump_message(msg) -> dict:
    return {"type": type(msg).__name__,
            "fields": {n: _jsonable(getattr(msg, n))
                       for n, _ in msg.FIELDS}}


# -- structural types --------------------------------------------------

def _osdmap_sample():
    from ceph_tpu.parallel import crush
    from ceph_tpu.parallel.osdmap import OSDMap
    m = OSDMap()
    m.epoch = 42
    m.crush.add_bucket("default", "root")
    m.crush.add_bucket("host0", "host", parent="default")
    m.crush.add_device(0, "host0")
    m.add_osd(0)
    m.mark_up(0, "127.0.0.1:6800")
    m.crush.add_rule(crush.Rule("data", "default", "osd", "firstn"))
    m.create_pool("p", 8, "data", size=1, min_size=1)
    m.pg_upmap_items[(1, 0)] = [(0, 0)]
    return m


def _txn_sample():
    from ceph_tpu.store.object_store import Transaction
    t = Transaction()
    t.create_collection("c")
    t.touch("c", "o")
    t.write("c", "o", 0, b"data")
    t.setattr("c", "o", "v", b"\x01")
    t.omap_set("c", "o", {"k": b"v"})
    return t


def _hashinfo_sample():
    import numpy as np
    from ceph_tpu.osd.ec_util import HashInfo
    h = HashInfo(3)
    h.append(0, {i: np.full(16, i, dtype=np.uint8) for i in range(3)})
    return h


STRUCTS = {
    "OSDMap": {
        "sample": _osdmap_sample,
        "encode": lambda m: m.encode(),
        "decode": lambda b: __import__(
            "ceph_tpu.parallel.osdmap", fromlist=["OSDMap"]
        ).OSDMap.decode(b),
        "dump": lambda m: {"epoch": m.epoch,
                           "osds": sorted(m.osds),
                           "pools": sorted(m.pool_by_name),
                           "pg_upmap_items": {
                               f"{k[0]}.{k[1]}": v for k, v in
                               m.pg_upmap_items.items()}},
        "eq": lambda a, b: a.encode() == b.encode(),
    },
    "Transaction": {
        "sample": _txn_sample,
        "encode": lambda t: t.encode(),
        "decode": lambda b: __import__(
            "ceph_tpu.store.object_store", fromlist=["Transaction"]
        ).Transaction.decode(b),
        "dump": lambda t: {"ops": [_jsonable(list(op)) for op in t.ops]},
        "eq": lambda a, b: a.encode() == b.encode(),
    },
    "HashInfo": {
        "sample": _hashinfo_sample,
        "encode": lambda h: json.dumps(h.to_dict()).encode(),
        "decode": lambda b: __import__(
            "ceph_tpu.osd.ec_util", fromlist=["HashInfo"]
        ).HashInfo.from_dict(json.loads(b)),
        "dump": lambda h: h.to_dict(),
        "eq": lambda a, b: a.to_dict() == b.to_dict(),
    },
}


def op_list() -> int:
    names = sorted(_message_types()) + sorted(STRUCTS)
    print("\n".join(names))
    return 0


def op_type(name: str, action: str) -> int:
    msgs = _message_types()
    if name in msgs:
        cls = msgs[name]
        if action == "encode":
            sys.stdout.buffer.write(cls().encode_payload())
            return 0
        payload = sys.stdin.buffer.read()
        msg = cls.decode_payload(payload)
        if action == "decode":
            print("ok")
        else:
            print(json.dumps(_dump_message(msg), indent=2))
        return 0
    if name in STRUCTS:
        spec = STRUCTS[name]
        if action == "encode":
            sys.stdout.buffer.write(spec["encode"](spec["sample"]()))
            return 0
        obj = spec["decode"](sys.stdin.buffer.read())
        if action == "decode":
            print("ok")
        else:
            print(json.dumps(_jsonable(spec["dump"](obj)), indent=2))
        return 0
    print(f"unknown type {name!r} (see 'list')", file=sys.stderr)
    return 22


def op_test() -> int:
    """Roundtrip every type: encode(default) -> decode -> re-encode
    must be byte-identical (the readable.sh non-regression role)."""
    failures = []
    count = 0
    for name, cls in sorted(_message_types().items()):
        count += 1
        try:
            msg = cls()
            raw = msg.encode_payload()
            back = cls.decode_payload(raw)
            if back.encode_payload() != raw:
                failures.append(f"{name}: re-encode mismatch")
        except Exception as exc:
            failures.append(f"{name}: {exc!r}")
    for name, spec in sorted(STRUCTS.items()):
        count += 1
        try:
            obj = spec["sample"]()
            raw = spec["encode"](obj)
            back = spec["decode"](raw)
            if not spec["eq"](obj, back):
                failures.append(f"{name}: roundtrip mismatch")
            if spec["encode"](back) != raw:
                failures.append(f"{name}: re-encode mismatch")
        except Exception as exc:
            failures.append(f"{name}: {exc!r}")
    print(json.dumps({"types": count, "failures": failures}, indent=2))
    return 1 if failures else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="dencoder")
    sub = ap.add_subparsers(dest="op", required=True)
    sub.add_parser("list")
    tp = sub.add_parser("type")
    tp.add_argument("name")
    tp.add_argument("action",
                    choices=("encode", "decode", "dump_json"))
    sub.add_parser("test")
    args = ap.parse_args(argv)
    if args.op == "list":
        return op_list()
    if args.op == "test":
        return op_test()
    return op_type(args.name, args.action)


if __name__ == "__main__":
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)   # behave under | head
    raise SystemExit(main())
