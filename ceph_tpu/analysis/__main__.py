"""``python -m ceph_tpu.analysis`` — the static-analysis gate CLI."""

from ceph_tpu.tools.analyze import main

if __name__ == "__main__":
    raise SystemExit(main())
