"""Offline admin tools: objectstore_tool + dencoder.

Roles of src/tools/ceph-objectstore-tool (offline store surgery,
PG export/import) and src/tools/ceph-dencoder (wire-type roundtrip
gate, src/test/encoding/readable.sh)."""

import json
import subprocess
import sys

import pytest

from ceph_tpu.store.object_store import Transaction, create_store
from ceph_tpu.tools import dencoder, objectstore_tool


@pytest.fixture
def store_dir(tmp_path):
    path = str(tmp_path / "osd.0")
    store = create_store("blockstore", path)
    store.mount()
    txn = Transaction()
    txn.create_collection("pg_1_0")
    txn.touch("pg_1_0", "obj_a")
    txn.write("pg_1_0", "obj_a", 0, b"hello world")
    txn.setattr("pg_1_0", "obj_a", "v", (7).to_bytes(8, "little"))
    txn.omap_set("pg_1_0", "obj_a", {"k1": b"v1"})
    txn.touch("pg_1_0", "obj_b")
    txn.write("pg_1_0", "obj_b", 0, b"x" * 5000)
    done = []
    store.queue_transaction(txn, on_commit=lambda: done.append(1))
    assert done
    store.umount()
    return path


def run_tool(path, *argv):
    return objectstore_tool.main(["--data-path", path, *argv])


def test_objectstore_list_info_fsck(store_dir, capsys):
    assert run_tool(store_dir, "list") == 0
    assert "pg_1_0" in json.loads(capsys.readouterr().out)
    assert run_tool(store_dir, "list", "--cid", "pg_1_0") == 0
    assert json.loads(capsys.readouterr().out) == ["obj_a", "obj_b"]
    assert run_tool(store_dir, "info", "--cid", "pg_1_0",
                    "--oid", "obj_a") == 0
    info = json.loads(capsys.readouterr().out)
    assert info["size"] == 11
    assert "v" in info["attrs"] and "k1" in info["omap"]
    assert run_tool(store_dir, "fsck") == 0
    out = json.loads(capsys.readouterr().out)
    assert out["objects"] == 2 and not out["errors"]


def test_objectstore_export_import_roundtrip(store_dir, tmp_path,
                                             capsys):
    dump = str(tmp_path / "pg.export")
    assert run_tool(store_dir, "export", "--cid", "pg_1_0",
                    "--file", dump) == 0
    # import into a fresh store (disaster-recovery move)
    path2 = str(tmp_path / "osd.1")
    store2 = create_store("blockstore", path2)
    store2.mount()
    store2.umount()
    assert run_tool(path2, "import", "--file", dump) == 0
    capsys.readouterr()
    assert run_tool(path2, "info", "--cid", "pg_1_0",
                    "--oid", "obj_a") == 0
    info = json.loads(capsys.readouterr().out)
    assert info["size"] == 11 and "k1" in info["omap"]
    # importing over an existing collection is refused
    assert run_tool(path2, "import", "--file", dump) == 17


def test_objectstore_set_bytes_rm(store_dir, tmp_path, capsys):
    blob = tmp_path / "blob"
    blob.write_bytes(b"rewritten")
    assert run_tool(store_dir, "set-bytes", "--cid", "pg_1_0",
                    "--oid", "obj_a", "--file", str(blob)) == 0
    assert run_tool(store_dir, "get-bytes", "--cid", "pg_1_0",
                    "--oid", "obj_a", "--file", "-") == 0
    assert capsys.readouterr().out.encode() == b"rewritten"
    assert run_tool(store_dir, "rm", "--cid", "pg_1_0",
                    "--oid", "obj_b") == 0
    assert run_tool(store_dir, "list", "--cid", "pg_1_0") == 0
    assert json.loads(capsys.readouterr().out) == ["obj_a"]


def test_dencoder_roundtrips_every_type(capsys):
    assert dencoder.main(["test"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["types"] >= 28 and not out["failures"]


def test_dencoder_cli_pipeline():
    """encode | dump_json through the real CLI (subprocess, like the
    readable.sh harness drives the binary)."""
    enc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.tools.dencoder",
         "type", "OSDMap", "encode"],
        capture_output=True, timeout=120)
    assert enc.returncode == 0 and enc.stdout
    dump = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.tools.dencoder",
         "type", "OSDMap", "dump_json"],
        input=enc.stdout, capture_output=True, timeout=120)
    assert dump.returncode == 0
    assert json.loads(dump.stdout)["epoch"] == 42
