"""rgw multisite-lite — zone replication (src/rgw/rgw_sync.cc +
rgw_data_sync.cc roles, reduced).

The reference replicates between zones with a two-phase protocol:
FULL SYNC (enumerate and copy everything once) then INCREMENTAL
(tail the source zone's per-bucket log and apply deltas). This lite
keeps exactly that shape over two :class:`RGWGateway` instances:

- the SOURCE gateway runs with ``zone_log=True``: every object
  mutation appends a SEQUENCED entry to ``.rgwlog.<bucket>`` (atomic
  cls-counter seq + omap key — O(1) appends, paged tailing);
- :class:`RGWSyncAgent` is the pull-based sync worker (the radosgw
  sync-thread role): per bucket it keeps a durable SEQ MARKER in the
  DESTINATION zone (``.rgwsync.<bucket>`` — restart-safe; applying
  is idempotent, so a crash between apply and marker save merely
  re-applies), tails the log in bounded pages, and carries the
  SOURCE etag (multipart 'md5-N' etags survive replication);
- ``trim_applied()`` drops log entries at or below the destination
  marker — safe because markers are seqs, not positions (with
  multiple destination zones, run it at the minimum marker).

BIDIRECTIONAL (active-active) multisite: run one agent per direction.
Log entries carry their ORIGIN zone (echo suppression: an agent skips
entries that originated at its destination) and, for unversioned
objects, a per-object (epoch, zone) version PAIR — a Lamport pair
whose lexicographic comparison makes conflict resolution symmetric:
both zones deterministically keep the same winner for concurrent
writes, and tombstone pairs stop a stale remote put from resurrecting
a deleted key. Versioned buckets converge on the generation SET
(version ids are globally unique), with per-zone current-pointer
arrival order as the documented reduction.

Deliberate cuts vs the 130 kLoC reference sync machinery: no shard
fan-out of the data log, no metadata sync beyond bucket existence +
versioning state.
"""

from __future__ import annotations

import json

from ceph_tpu.services.rgw import RGWError, RGWGateway

#: log entries tailed per page (bounded wire transfer per pass)
PAGE = 1000


class RGWSyncAgent:
    def __init__(self, src: RGWGateway, dst: RGWGateway) -> None:
        self.src = src
        self.dst = dst

    # -- durable per-bucket seq marker (in the DESTINATION zone) ------
    def _marker(self, bucket: str) -> int | None:
        """Last applied seq, or None when this bucket has never been
        synced. Only a definitive ENOENT means 'never synced' — a
        transient read error must surface, not trigger a wholesale
        full-sync re-copy."""
        from ceph_tpu.client.rados import RadosError
        try:
            return json.loads(
                self.dst.io.read(f".rgwsync.{bucket}"))["applied"]
        except RadosError as exc:
            if exc.code == -2:
                return None
            raise
        except (KeyError, ValueError):
            return None            # corrupt marker: re-bootstrap

    def _save_marker(self, bucket: str, applied_seq: int) -> None:
        self.dst.io.write_full(
            f".rgwsync.{bucket}",
            json.dumps({"applied": applied_seq}).encode())

    def _log_page(self, bucket: str, after_seq: int) -> list[tuple]:
        """[(seq, entry), ...] after ``after_seq``, one bounded page,
        ascending."""
        from ceph_tpu.client.rados import RadosError
        try:
            page = self.src.io.omap_get(
                f".rgwlog.{bucket}", start_after=f"{after_seq:016d}",
                max_return=PAGE)
        except RadosError as exc:
            if exc.code == -2:
                return []          # no log yet
            raise
        return sorted((int(k), json.loads(v))
                      for k, v in page.items())

    def _log_head_seq(self, bucket: str) -> int:
        """Highest assigned seq (the cls counter), 0 when no log."""
        from ceph_tpu.client.rados import RadosError
        try:
            raw = self.src.io.read(f".rgwlog.{bucket}")
            return int(json.loads(raw).get("seq", 0))
        except (RadosError, ValueError):
            return 0

    # -- sync ---------------------------------------------------------
    def _apply(self, bucket: str, ent: dict) -> bool:
        """Returns True when the destination was actually mutated
        (echo-skips and conflict losses return False, so callers can
        detect quiescence)."""
        if ent.get("zone") and ent["zone"] == self.dst.zone and \
                self.dst.zone != self.src.zone:
            # echo suppression (the reference's zone short-id check
            # in rgw_data_sync): this entry ORIGINATED at the
            # destination and came back around a bidirectional (or
            # ring) topology — applying it would loop forever. Only
            # meaningful when the deployment actually names distinct
            # zones (legacy one-way setups leave both at "default").
            return False
        vid = ent.get("vid")
        pair = ent.get("pair")
        origin = ent.get("zone")
        if ent["op"] == "put":
            try:
                data, meta = self.src.get_object(
                    bucket, ent["key"], version_id=vid)
            except RGWError:
                return False    # superseded by a later delete: the
                # delete entry follows in the log and converges
            # version ids REPLICATE (the reference carries the source
            # instance id through data sync): dst mints nothing.
            # put_object returns None when the entry LOST a
            # bidirectional conflict (destination holds a newer pair)
            return self.dst.put_object(
                bucket, ent["key"], data,
                etag=meta.get("etag") or None,
                version_id=vid, pair=pair,
                origin=origin,
                oseq=ent.get("oseq") or meta.get("oseq")) is not None
        elif ent["op"] == "del":
            try:
                self.dst.delete_object(bucket, ent["key"],
                                       pair=pair, origin=origin)
            except RGWError:
                return False    # absent (idempotent) or conflict
                # loss (RemoteStale) — either way nothing mutated
        elif ent["op"] == "dm":
            try:
                self.dst.delete_object(bucket, ent["key"],
                                       _marker_vid=vid,
                                       origin=origin,
                                       oseq=ent.get("oseq"))
            except RGWError:
                return False
        elif ent["op"] == "delver":
            try:
                self.dst.delete_object(bucket, ent["key"],
                                       version_id=vid,
                                       origin=origin)
            except RGWError:
                return False    # that generation never made it here
        return True

    def _full_sync(self, bucket: str) -> None:
        """Bootstrap: copy the source bucket wholesale (the FULL SYNC
        phase), carrying each object's source etag. Versioned buckets
        copy every generation oldest-first so the destination's
        current-version resolution (arrival order) lands on the same
        generation the source shows."""
        if self.src.get_versioning(bucket) is not None:
            gens = sorted(self.src.list_versions(bucket),
                          key=lambda e: e["seq"])
            for ent in gens:
                if ent.get("dm"):
                    self.dst.delete_object(bucket, ent["key"],
                                           _marker_vid=ent["vid"],
                                           _log=False,
                                           oseq=ent.get("oseq"))
                    continue
                try:
                    data, meta = self.src.get_object(
                        bucket, ent["key"], version_id=ent["vid"])
                except RGWError:
                    continue    # reaped mid-enumeration
                self.dst.put_object(bucket, ent["key"], data,
                                    etag=meta.get("etag") or None,
                                    version_id=ent["vid"],
                                    oseq=ent.get("oseq"))
            return
        marker = ""
        while True:
            page = self.src.list_objects(bucket, max_keys=1000,
                                         marker=marker)
            if not page:
                return
            for key in sorted(page):
                try:
                    data, meta = self.src.get_object(bucket, key)
                except RGWError:
                    continue    # deleted mid-enumeration
                # bootstrap carries the source's CURRENT pair so a
                # bidirectional peer resolves conflicts against it
                pair = self.src._get_pair(bucket, key) \
                    if self.src.zone_log else [0, ""]
                self.dst.put_object(
                    bucket, key, data,
                    etag=meta.get("etag") or None,
                    pair=pair if pair[0] else None,
                    origin=self.src.zone if pair[0] else None)
            marker = max(page)

    def sync_once(self) -> dict:
        """One sync pass; returns per-bucket applied-entry counts."""
        report: dict[str, int] = {}
        dst_buckets = set(self.dst.list_buckets())
        for bucket in self.src.list_buckets():
            if bucket not in dst_buckets:
                self.dst.create_bucket(bucket)
                dst_buckets.add(bucket)
            # metadata sync: mirror the versioning state (a versioned
            # source must replicate into a versioned destination or
            # generation ids are lost)
            sv = self.src.get_versioning(bucket)
            if sv is not None and self.dst.get_versioning(bucket) != sv:
                try:
                    self.dst.set_versioning(bucket, sv)
                except RGWError:
                    # destination bucket rides a cls (EC-pool) index:
                    # no versions omap there. Degrade to replicating
                    # current data only rather than wedging the whole
                    # zone's sync pass.
                    pass
            marker = self._marker(bucket)
            if marker is None:
                # FULL SYNC: snapshot the head seq FIRST — entries
                # logged during the copy re-apply incrementally
                # (idempotent), never skip
                head = self._log_head_seq(bucket)
                self._full_sync(bucket)
                self._save_marker(bucket, head)
                report[bucket] = 0
                continue
            applied = 0
            while True:
                page = self._log_page(bucket, marker)
                if not page:
                    break
                for seq, ent in page:
                    if self._apply(bucket, ent):
                        applied += 1
                    marker = seq
                    self._save_marker(bucket, marker)
            report[bucket] = applied
        return report

    def trim_applied(self) -> int:
        """Drop source-log entries at or below the destination marker
        (the log-trim role; with several destination zones run at the
        min marker). Returns entries removed."""
        removed = 0
        for bucket in self.src.list_buckets():
            marker = self._marker(bucket)
            if not marker:
                continue
            while True:
                page = self._log_page(bucket, 0)
                stale = [f"{seq:016d}" for seq, _ in page
                         if seq <= marker]
                if not stale:
                    break
                self.src.io.omap_rm_keys(f".rgwlog.{bucket}", stale)
                removed += len(stale)
                if len(page) < PAGE:
                    break
        return removed
