"""The codec contract — semantic equivalent of ``ceph::ErasureCodeInterface``.

Reference: src/erasure-code/ErasureCodeInterface.h:155-464. The chunk/stripe
model (documented there at :39-78) is preserved exactly:

- an object is striped into stripes of ``k * chunk_size`` bytes;
- each stripe is split into k data chunks, and m coding chunks are computed;
- chunk i of every stripe goes to the same shard/OSD;
- array codes (Clay) further divide chunks into ``sub_chunk_count``
  sub-chunks, and ``minimum_to_decode`` can request sub-chunk ranges
  (reference: ErasureCodeInterface.h:251-300).

Differences from the reference, deliberate and TPU-first:

- chunks are numpy ``uint8`` arrays (zero-copy handoff to JAX device
  buffers) instead of ``bufferlist``;
- profiles are ``dict[str, str]`` (the reference's ErasureCodeProfile is a
  ``map<string,string>``);
- errors raise :class:`ErasureCodeError` instead of returning -errno.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Mapping, Sequence

import numpy as np

ErasureCodeProfile = dict  # profile: str -> str, like the reference's map

#: chunk -> list of (offset, count) sub-chunk ranges to read, in units of
#: chunk_size / sub_chunk_count (reference: ErasureCodeInterface.h:280-300).
SubChunkPlan = dict


class ErasureCodeError(Exception):
    """Codec failure (invalid profile, unrecoverable erasure pattern, ...)."""

    def __init__(self, message: str, errno_: int = 22):
        super().__init__(message)
        self.errno = errno_


class ErasureCodeInterface(ABC):
    """Abstract codec contract (reference: ErasureCodeInterface.h:170-462)."""

    @abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from a profile; raises ErasureCodeError on bad params."""

    @abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        """The profile as completed by init() (defaults filled in)."""

    @abstractmethod
    def get_chunk_count(self) -> int:
        """k + m: total chunks per stripe."""

    @abstractmethod
    def get_data_chunk_count(self) -> int:
        """k: chunks that hold object data."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Array codes (Clay) divide each chunk into sub-chunks; scalar
        codes return 1 (reference: ErasureCodeInterface.h:251-259)."""
        return 1

    @abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunk size for an object/stripe of ``stripe_width`` bytes,
        including padding/alignment (reference: ErasureCodeInterface.h:222-245)."""

    @abstractmethod
    def minimum_to_decode(
        self, want_to_read: Sequence[int], available: Sequence[int]
    ) -> SubChunkPlan:
        """Smallest chunk set (with sub-chunk ranges) sufficient to decode
        ``want_to_read`` from ``available``.  Raises if impossible.
        Reference: ErasureCodeInterface.h:280-300."""

    def minimum_to_decode_with_cost(
        self, want_to_read: Sequence[int], available: Mapping[int, int]
    ) -> list[int]:
        """Like minimum_to_decode but pick cheapest chunks given a cost map
        (reference: ErasureCodeInterface.h:302-315). Default: sort by cost
        and take the cheapest feasible set."""
        ordered = sorted(available, key=lambda c: (available[c], c))
        plan = self.minimum_to_decode(want_to_read, ordered)
        return sorted(plan)

    @abstractmethod
    def encode(
        self, want_to_encode: Sequence[int], data: bytes | np.ndarray
    ) -> dict[int, np.ndarray]:
        """Split+pad ``data`` into k chunks, compute m coding chunks, return
        the requested subset (reference: ErasureCodeInterface.h:317-349)."""

    @abstractmethod
    def encode_chunks(
        self, want_to_encode: Sequence[int], chunks: dict[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        """Low-level: chunks already split/aligned; compute coding chunks."""

    @abstractmethod
    def decode(
        self,
        want_to_read: Sequence[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        """Reconstruct the wanted chunks from the available ones
        (reference: ErasureCodeInterface.h:351-387)."""

    @abstractmethod
    def decode_chunks(
        self,
        want_to_read: Sequence[int],
        chunks: Mapping[int, np.ndarray],
    ) -> dict[int, np.ndarray]:
        """Low-level decode: all chunks same size, no padding logic."""

    def get_chunk_mapping(self) -> list[int]:
        """Optional remap: chunk i of the encoder is stored at position
        mapping[i] (reference: ErasureCodeInterface.h:389-401).  Empty list
        means identity."""
        return []

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Decode and concatenate the data chunks in order — used by the
        read path (reference: ErasureCodeInterface.h:403-416)."""
        k = self.get_data_chunk_count()
        want = list(range(k))
        some = next(iter(chunks.values()))
        decoded = self.decode(want, chunks, len(some))
        return np.concatenate([decoded[i] for i in want])

    def create_rule(self, name: str, crush_map) -> int:
        """Create a placement rule for this codec in the given CRUSH map
        (reference: ErasureCodeInterface.h:205-220; base impl
        ErasureCode.cc:53-72 uses 'indep' mode).  Implemented by the base
        class once the parallel/crush layer is present."""
        raise NotImplementedError
