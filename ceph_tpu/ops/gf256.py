"""GF(2^8) arithmetic core — the numpy reference implementation.

This replaces the reference's vendored gf-complete / jerasure / ISA-L math
(all empty submodules in the snapshot; the reference C++ only orchestrates —
see src/erasure-code/jerasure/ErasureCodeJerasure.cc and
src/erasure-code/isa/ErasureCodeIsa.cc for the call sites this feeds).

Field: GF(2^8) with the primitive polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11d), the polynomial used by both gf-complete (w=8 default) and ISA-L, so
Reed-Solomon coefficients here match the reference plugins' field semantics.

Everything is vectorized numpy over uint8 arrays. This module is the
bit-exactness oracle for the TPU path (ops/gf_jax.py): the corpus gate
(reference: src/test/erasure-code/ceph_erasure_code_non_regression.cc:39-57)
requires encode output to be byte-identical across backends.
"""

from __future__ import annotations

import numpy as np

# Primitive polynomial for GF(2^8): x^8+x^4+x^3+x^2+1 (0x11d) with generator 2.
POLY = 0x11D
FIELD = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Build exp/log tables, the full 256x256 multiplication table and inverses."""
    gf_exp = np.zeros(512, dtype=np.uint8)
    gf_log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        gf_exp[i] = x
        gf_log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    # replicate so exp[log a + log b] never needs a mod
    gf_exp[255:510] = gf_exp[0:255]

    # Full multiplication table: MUL[a, b] = a * b in GF(2^8).
    la = gf_log[:, None]  # [256,1]
    lb = gf_log[None, :]  # [1,256]
    mul = gf_exp[(la + lb) % 255].astype(np.uint8)
    mul[0, :] = 0
    mul[:, 0] = 0

    inv = np.zeros(256, dtype=np.uint8)
    inv[1:] = gf_exp[(255 - gf_log[1:]) % 255]
    return gf_exp, gf_log, mul, inv


GF_EXP, GF_LOG, MUL_TABLE, INV_TABLE = _build_tables()


def gf_mul(a, b):
    """Element-wise GF(2^8) multiply of arrays/scalars (uint8)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    return MUL_TABLE[a, b]


def gf_inv(a):
    """Element-wise multiplicative inverse. inv(0) = 0 by convention."""
    return INV_TABLE[np.asarray(a, dtype=np.uint8)]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_pow(a: int, n: int) -> int:
    """a**n in GF(2^8) (scalar)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): C[i,j] = XOR_k a[i,k] * b[k,j].

    Works for any a:[M,K], b:[K,N] uint8. For the codec hot path with large N
    (chunk bytes) use :func:`gf_matvec_chunks` which loops over K to bound
    temporary memory.
    """
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    prod = MUL_TABLE[a[:, :, None], b[None, :, :]]  # [M,K,N]
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_matvec_chunks(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Apply an [M,K] GF matrix to K data chunks of N bytes each -> [M,N].

    This is the reference hot kernel: ``ec_encode_data`` in ISA-L /
    ``jerasure_matrix_encode`` (called from
    src/erasure-code/isa/ErasureCodeIsa.cc:118-130 and
    src/erasure-code/jerasure/ErasureCodeJerasure.cc), done position-wise:
    out[i][x] = XOR_k mat[i,k] * data[k][x].
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    m, k = mat.shape
    assert data.shape[0] == k, (mat.shape, data.shape)
    out = np.zeros((m, data.shape[1]), dtype=np.uint8)
    for j in range(k):
        out ^= MUL_TABLE[mat[:, j][:, None], data[j][None, :]]
    return out


def invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix via Gauss-Jordan elimination.

    The decode path builds a k×k submatrix of surviving rows and inverts it
    (reference: src/erasure-code/isa/ErasureCodeIsa.cc:274
    ``gf_invert_matrix``; jerasure ``jerasure_invert_matrix``).
    Raises ValueError if singular.
    """
    mat = np.array(mat, dtype=np.uint8)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    aug = np.concatenate([mat, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise ValueError("matrix is singular over GF(2^8)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = INV_TABLE[aug[col, col]]
        aug[col] = MUL_TABLE[inv_p, aug[col]]
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= MUL_TABLE[aug[row, col], aug[col]]
    return aug[:, n:].copy()


# ---------------------------------------------------------------------------
# Generator-matrix constructions
# ---------------------------------------------------------------------------

def rs_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """Systematic Reed-Solomon coding matrix, jerasure ``reed_sol_van`` style.

    Semantics of jerasure's ``reed_sol_vandermonde_coding_matrix`` (reference
    call site: src/erasure-code/jerasure/ErasureCodeJerasure.h:82-120,
    technique ``reed_sol_van``): build the (k+m)×k Vandermonde matrix
    V[i,j] = i^j over GF(2^8), then apply elementary *column* operations to
    turn the top k×k block into the identity; the bottom m rows are the
    coding matrix. Any k rows of the result are invertible (each k×k
    submatrix of a Vandermonde on distinct points is nonsingular, and column
    ops preserve that), so this is MDS for k+m <= 256.

    Returns the m×k coding matrix (the systematic identity is implicit).
    """
    n = k + m
    if n > FIELD:
        raise ValueError(f"k+m={n} exceeds field size {FIELD}")
    vdm = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        for j in range(k):
            vdm[i, j] = gf_pow(i, j)  # 0^0 == 1
    # Column-eliminate the top square block to identity.
    for i in range(k):
        if vdm[i, i] == 0:
            swap = next(j for j in range(i + 1, k) if vdm[i, j] != 0)
            vdm[:, [i, swap]] = vdm[:, [swap, i]]
        if vdm[i, i] != 1:
            vdm[:, i] = MUL_TABLE[INV_TABLE[vdm[i, i]], vdm[:, i]]
        for j in range(k):
            if j != i and vdm[i, j] != 0:
                vdm[:, j] ^= MUL_TABLE[vdm[i, j], vdm[:, i]]
    assert np.array_equal(vdm[:k], np.eye(k, dtype=np.uint8))
    return vdm[k:].copy()


def rs_matrix_isa(k: int, m: int) -> np.ndarray:
    """ISA-L ``gf_gen_rs_matrix`` coding rows (non-systematized Vandermonde).

    Coding row r has entries (2^r)^j for j in 0..k-1 — i.e. row 0 is all
    ones, row 1 is 1,2,4,8,..., row 2 is 1,4,16,... This is only guaranteed
    MDS inside the envelope k<=32, m<=4 (m==4 => k<=21), which the reference
    clamps at src/erasure-code/isa/ErasureCodeIsa.cc:330-360; callers must
    enforce the same envelope.
    """
    mat = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for i in range(m):
        p = 1
        for j in range(k):
            mat[i, j] = p
            p = int(MUL_TABLE[p, gen])
        gen = int(MUL_TABLE[gen, 2])
    return mat


def cauchy_matrix_isa(k: int, m: int) -> np.ndarray:
    """ISA-L ``gf_gen_cauchy1_matrix``: coding row i, col j = inv((i+k) ^ j).

    Cauchy matrices are MDS for any k+m <= 256 (used by the reference when
    the Vandermonde envelope is exceeded, ErasureCodeIsa.cc:344-358).
    """
    if k + m > FIELD:
        raise ValueError(f"k+m={k + m} exceeds field size {FIELD}")
    rows = np.arange(k, k + m, dtype=np.int32)[:, None]
    cols = np.arange(k, dtype=np.int32)[None, :]
    return INV_TABLE[(rows ^ cols).astype(np.uint8)]


def cauchy_original_matrix(k: int, m: int) -> np.ndarray:
    """jerasure ``cauchy_original_coding_matrix``: row i, col j = 1/(i ^ (m+j)).

    Technique ``cauchy_orig`` (reference:
    src/erasure-code/jerasure/ErasureCodeJerasure.h:135-155). Points i in
    0..m-1 and m+j in m..m+k-1 are disjoint, so all entries are defined.
    """
    if k + m > FIELD:
        raise ValueError(f"k+m={k + m} exceeds field size {FIELD}")
    rows = np.arange(m, dtype=np.int32)[:, None]
    cols = np.arange(m, m + k, dtype=np.int32)[None, :]
    return INV_TABLE[(rows ^ cols).astype(np.uint8)]


def systematic_generator(coding: np.ndarray) -> np.ndarray:
    """Stack identity over the m×k coding matrix -> full (k+m)×k generator."""
    m, k = coding.shape
    return np.concatenate([np.eye(k, dtype=np.uint8), coding], axis=0)


def decode_matrix(generator: np.ndarray, present_rows: list[int],
                  want_rows: list[int]) -> np.ndarray:
    """Build the decode matrix mapping k surviving chunks -> wanted chunks.

    ``generator`` is the full (k+m)×k systematic generator. ``present_rows``
    lists k surviving chunk indices (sorted); ``want_rows`` the chunk indices
    to reconstruct. Mirrors the reference decode: select the k surviving
    generator rows, invert, then re-multiply by the wanted rows
    (src/erasure-code/isa/ErasureCodeIsa.cc:150-310).
    """
    k = generator.shape[1]
    assert len(present_rows) == k, (present_rows, k)
    sub = generator[np.asarray(present_rows, dtype=np.int64)]
    inv = invert_matrix(sub)  # maps surviving chunks -> data chunks
    out_rows = []
    for r in want_rows:
        if r < k:
            out_rows.append(inv[r])
        else:
            # parity chunk: generator row r applied to recovered data
            out_rows.append(gf_matmul(generator[r][None, :], inv)[0])
    return np.stack(out_rows).astype(np.uint8)
