"""BlockStore — durable log-structured object store (BlueStore role).

Reference: src/os/bluestore/. Same commit discipline, simplified
geometry: object payloads append to a single data blob file, metadata
(attrs/omap/size/extent map) lives in the WAL-backed kv (store/kv.py —
the RocksDB seat). Commit order per transaction, as in BlueStore's txc
state machine (BlueStore.cc:9037):

  1. append write payloads to the data file, fdatasync;
  2. commit one kv batch with all metadata updates (kv WAL fsync);
  3. fire on_commit.

A crash between 1 and 2 leaks dead bytes at the data-file tail but
never exposes a partial transaction — the kv batch is the atomicity
point. Checksums are at blob granularity exactly like BlueStore's
csum_type=crc32c default (BlueStore.h:1925): each written blob carries
its crc32c; any read of any slice re-reads the whole blob and verifies
(_verify_csum role, BlueStore.cc:8061) raising EIOError on mismatch —
the trigger for EC repair upstream.
"""

from __future__ import annotations

import os
import threading

from ceph_tpu.analysis.lock_witness import make_lock
from typing import Callable

from ceph_tpu.store import object_store as osr
from ceph_tpu.store.kv import FileDB, WriteBatch
from ceph_tpu.store.object_store import (
    EIOError,
    NoSuchCollection,
    NoSuchObject,
    ObjectStore,
    Transaction,
)
from ceph_tpu.utils import checksum
from ceph_tpu.utils.encoding import Decoder, Encoder


from ceph_tpu.utils import tracepoints as _tracepoints

_TP_QUEUE_TXN = _tracepoints.provider("objectstore").point(
    "queue_transaction", "ops")

#: on-disk compressor ids (bluestore_compression_algorithm role); the
#: id is stored per blob so config changes never orphan old blobs
COMP_NONE = 0
_COMP_ALGS = {1: "zlib", 2: "zstd", 3: "bz2", 4: "lzma", 5: "lz4",
              6: "snappy", 7: "lz4block"}
_COMP_IDS = {v: k for k, v in _COMP_ALGS.items()}

#: blob checksum algorithms (Checksummer.h:11-19 role); id rides the
#: extent so csum_type config changes never orphan old blobs. id 0 =
#: crc32c (the pre-existing default encoding).
_CSUM_FNS = {
    0: lambda d: checksum.crc32c(d),
    1: lambda d: checksum.xxhash32(d),
    2: lambda d: checksum.xxhash64(d) & 0xFFFFFFFF,
    3: lambda d: 0,                    # "none"
}
_CSUM_IDS = {"crc32c": 0, "xxhash32": 1, "xxhash64": 2, "none": 3,
             "crc32c_16": 0, "crc32c_8": 0}


class _Extent:
    """A logical range backed by a slice of a crc-protected blob in the
    data file (BlueStore's lextent -> blob indirection). ``blob_len``
    is the blob's UNcompressed length (slice space); ``disk_len`` the
    stored bytes; ``comp`` the compressor id (0 = stored raw)."""

    __slots__ = ("logical_off", "length", "blob_off", "blob_len",
                 "blob_crc", "slice_off", "disk_len", "comp", "csum")

    def __init__(self, logical_off: int, length: int, blob_off: int,
                 blob_len: int, blob_crc: int, slice_off: int,
                 disk_len: int | None = None,
                 comp: int = COMP_NONE, csum: int = 0) -> None:
        self.logical_off = logical_off
        self.length = length
        self.blob_off = blob_off      # file offset of the whole blob
        self.blob_len = blob_len
        self.blob_crc = blob_crc      # checksum of the STORED bytes
        self.slice_off = slice_off    # this extent's start within the blob
        self.disk_len = blob_len if disk_len is None else disk_len
        self.comp = comp
        self.csum = csum              # _CSUM_FNS id used for blob_crc

    @property
    def end(self) -> int:
        return self.logical_off + self.length


class _Meta:
    __slots__ = ("size", "attrs", "omap", "extents")

    def __init__(self) -> None:
        self.size = 0
        self.attrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}
        self.extents: list[_Extent] = []   # sorted, non-overlapping

    def encode(self) -> bytes:
        e = Encoder()
        e.u64(self.size)
        e.map(self.attrs, Encoder.str, Encoder.bytes)
        e.map(self.omap, Encoder.str, Encoder.bytes)
        e.list(self.extents, lambda en, x: (
            en.u64(x.logical_off), en.u64(x.length), en.u64(x.blob_off),
            en.u64(x.blob_len), en.u32(x.blob_crc), en.u64(x.slice_off),
            en.u64(x.disk_len), en.u8(x.comp), en.u8(x.csum)))
        return e.getvalue()

    @classmethod
    def decode(cls, buf: bytes) -> "_Meta":
        d = Decoder(buf)
        m = cls()
        m.size = d.u64()
        m.attrs = d.map(Decoder.str, Decoder.bytes)
        m.omap = d.map(Decoder.str, Decoder.bytes)
        m.extents = d.list(lambda dd: _Extent(
            dd.u64(), dd.u64(), dd.u64(), dd.u64(), dd.u32(), dd.u64(),
            dd.u64(), dd.u8(), dd.u8()))
        return m


def _clip(extents: list[_Extent], a: int, b: int) -> list[_Extent]:
    """Remove logical range [a, b) from the extent list, splitting
    extents that straddle the boundary (slices keep pointing into their
    original crc'd blob)."""
    out: list[_Extent] = []
    for x in extents:
        if x.end <= a or x.logical_off >= b:
            out.append(x)
            continue
        if x.logical_off < a:
            out.append(_Extent(x.logical_off, a - x.logical_off,
                               x.blob_off, x.blob_len, x.blob_crc,
                               x.slice_off, x.disk_len, x.comp,
                               x.csum))
        if x.end > b:
            cut = b - x.logical_off
            out.append(_Extent(b, x.end - b, x.blob_off, x.blob_len,
                               x.blob_crc, x.slice_off + cut,
                               x.disk_len, x.comp, x.csum))
    return out


class _PyDataFile:
    """Pure-python twin of store/native_io.NativeDataFile (same raw
    concatenated-blob format; returns None for crc so callers hash
    via the configured csum fn)."""

    def __init__(self, path: str) -> None:
        # unbuffered: appends hit the fd directly, so concurrent preads
        # never observe a python-level buffer, and there is no shared
        # seek position between readers (os.pread is positionless)
        self._f = open(path, "a+b", buffering=0)

    def size(self) -> int:
        return os.fstat(self._f.fileno()).st_size

    def append(self, data: bytes):
        # O_APPEND ("a" mode) writes at EOF atomically; the returned
        # offset is only meaningful under the store's append lock,
        # which serializes the size probe with the write. Unbuffered
        # FileIO.write can return short (e.g. ENOSPC mid-blob) —
        # loop to completion or raise, mirroring ioeng_append
        off = os.fstat(self._f.fileno()).st_size
        view = memoryview(data)
        while view:
            n = self._f.write(view)
            if not n:
                raise OSError("short write appending blob")
            view = view[n:]
        return off, None

    def read(self, off: int, length: int):
        return os.pread(self._f.fileno(), length, off), None

    def sync(self) -> None:
        from ceph_tpu.utils import store_telemetry
        store_telemetry.timed_fdatasync(self._f.fileno(),
                                        site="blockstore.data")

    def close(self) -> None:
        self._f.close()


class BlockStore(ObjectStore):
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._db: FileDB | None = None
        self._data = None
        self._eio: set[tuple[str, str]] = set()
        # serializes the append stage: the data engines derive each
        # blob's offset from the current file size, so two concurrent
        # queue_transaction calls (different PGs on different op-shard
        # threads) must not interleave size-probe and write — they
        # would record the same offset for different blobs
        self._append_lock = make_lock("blockstore.append")
        self._parked = osr._ParkedCompletions("blockstore.parked")
        # leader-follower barrier coalescing (ROADMAP 1a): concurrent
        # commits share fsync rounds instead of each paying its own;
        # the hot-leader dwell window is cached at mount
        self._shared = osr._SharedBarrier("blockstore.barrier")
        self._barrier_window_s = 0.0

    # -- lifecycle ----------------------------------------------------
    def mount(self) -> None:
        from ceph_tpu.utils.config import g_conf
        self._barrier_window_s = \
            g_conf()["store_barrier_window_ms"] / 1e3
        self._db = FileDB(os.path.join(self.path, "db"))
        data_path = os.path.join(self.path, "data")
        # native data-plane engine (KernelDevice/aio role: one-pass
        # append+crc32c, lock-free pread) with a pure-python fallback;
        # both write the same raw-blob format
        from ceph_tpu.store.native_io import NativeDataFile
        data = NativeDataFile.open(data_path) or _PyDataFile(data_path)
        with self._append_lock:
            self._data = data

    def umount(self) -> None:
        if self._db:
            self._db.close()
            self._db = None
        # serialize against in-flight appends (the engine-shutdown
        # race class): an appender either finishes before the close
        # or sees _data already gone
        with self._append_lock:
            data, self._data = self._data, None
        if data:
            data.close()

    # -- metadata helpers ---------------------------------------------
    @staticmethod
    def _okey(cid: str, oid: str) -> str:
        return f"o/{cid}/{oid}"

    @staticmethod
    def _ckey(cid: str) -> str:
        return f"c/{cid}"

    def _require_coll(self, cid: str) -> None:
        if self._db.get(self._ckey(cid)) is None:
            raise NoSuchCollection(cid)

    def _meta(self, cid: str, oid: str) -> _Meta:
        raw = self._db.get(self._okey(cid, oid))
        if raw is None:
            self._require_coll(cid)
            raise NoSuchObject(f"{cid}/{oid}")
        return _Meta.decode(raw)

    # -- transactions -------------------------------------------------
    def queue_transaction(self, txn: Transaction,
                          on_commit: Callable[[], None] | None = None) -> None:
        assert self._db is not None, "not mounted"
        from ceph_tpu.utils import store_telemetry
        tmr = store_telemetry.telemetry().txn_timer(
            "blockstore", id(self))
        tmr.n_ops = len(txn)
        with tmr:
            if osr.group_commit_enabled():
                # barriers ride the shared leader-follower rounds:
                # an idle store syncs immediately; concurrent commits
                # coalesce onto one fsync set (the page-cache WAL
                # write precedes the data barrier inside a round —
                # the same OS-crash-only ordering note as the
                # deferred group path)
                self._queue_transaction_timed(txn, tmr, sync=False)
                self._shared.sync(self._sync_all,
                                  self._barrier_window_s)
            else:
                self._queue_transaction_timed(txn, tmr)
            tmr.run_on_commit(on_commit)

    def queue_transaction_group(self, pairs: list,
                                defer: bool = False) -> None:
        """Group commit (ROADMAP 1a): the flush group's writes append
        in one pass under one append-lock hold, pay ONE data-file
        fdatasync, build ONE metadata kv batch = ONE WAL append + ONE
        kv.wal fsync — instead of a barrier set per txn. ``defer``
        parks both barriers and the completion sweep for
        :meth:`barrier` (the cross-thread leg: the deferred WAL
        record is page-cache-written before the data barrier, so the
        data-before-wal *barrier* order still holds at the shared
        :meth:`barrier`; the exposure window narrows the crash
        contract to OS-crash page reordering, same class as the
        reference's deferred writes)."""
        assert self._db is not None, "not mounted"
        if not pairs:
            return
        from ceph_tpu.utils import store_telemetry
        tmr = store_telemetry.telemetry().txn_timer(
            "blockstore", id(self))
        merged = Transaction()
        for txn, _ in pairs:
            merged.ops.extend(txn.ops)
        tmr.n_ops = len(merged)
        tmr.n_txns = len(pairs)
        with tmr:
            data_dirty = self._queue_transaction_timed(
                merged, tmr, sync=False)
            if defer:
                self._parked.park([cb for _, cb in pairs],
                                  dirty=data_dirty)
            else:
                self._shared.sync(self._sync_all,
                                  self._barrier_window_s)
                tmr.run_on_commit_sweep([cb for _, cb in pairs])

    def _sync_all(self) -> None:
        """One barrier round: the data-file fdatasync then the WAL
        fsync — the same data-before-wal barrier order as the inline
        path, paid once per leader-follower round."""
        data = self._data
        if data is not None:
            data.sync()
        if self._db is not None:
            self._db.sync()

    def barrier(self) -> None:
        """The shared deferred barrier: one barrier round covering
        every ``defer=True`` group parked so far, then the completion
        sweep in submission order. Runs lock-free (the fsyncs must
        never sit under the append lock or a PG lock)."""
        from ceph_tpu.utils import store_telemetry
        cbs, dirty = self._parked.take()
        if not cbs and not dirty:
            return
        self._shared.sync(self._sync_all, self._barrier_window_s)
        store_telemetry.sweep_completions(cbs)

    def barrier_pending(self) -> bool:
        return bool(self._parked)

    def _queue_transaction_timed(self, txn: Transaction, tmr,
                                 sync: bool = True) -> bool:
        _TP_QUEUE_TXN(len(txn))
        # stage 1: data-file appends for every WRITE op; blobs compress
        # when the configured algorithm saves enough
        # (bluestore_compression_* semantics)
        comp_alg, comp_min, comp_ratio = self._comp_config()
        from ceph_tpu.utils.config import g_conf
        csum_id = _CSUM_IDS.get(g_conf()["bluestore_csum_type"], 0)
        csum_fn = _CSUM_FNS[csum_id]
        data_dirty = False
        # op idx -> (file_off, raw_len, disk_len, csum, comp_id, csum_id)
        blob_at: dict[int, tuple[int, int, int, int, int, int]] = {}
        # compress and hash outside the lock (CPU-bound), append inside
        # it: the engines derive blob offsets from file size, so
        # interleaved appends from two op-shard threads would alias
        # offsets. The native engine still computes crc32c in its own
        # single pass over the hot buffer (inside the lock, but that
        # pass IS the write path); only non-crc32c types / the python
        # engine need the explicit hash, done here.
        native = not isinstance(self._data, _PyDataFile)
        staged: list[tuple[int, bytes, bytes, int, int | None]] = []
        with tmr.stage("apply"):
            for i, op in enumerate(txn.ops):
                if op[0] == osr.OP_WRITE:
                    payload = op[4]
                    stored, comp_id = payload, COMP_NONE
                    if comp_alg is not None and \
                            len(payload) >= comp_min:
                        packed = comp_alg.compress(payload)
                        if len(packed) <= len(payload) * comp_ratio:
                            stored = packed
                            comp_id = _COMP_IDS[comp_alg.name]
                    pre = None if (csum_id == 0 and native) \
                        else csum_fn(stored)
                    staged.append((i, payload, bytes(stored), comp_id,
                                   pre))
        if staged:
            t0 = tmr.now()
            with self._append_lock:
                tmr.mark_wait("queue_wait", t0)
                with tmr.stage("apply"):
                    for i, payload, stored, comp_id, pre in staged:
                        file_off, ncrc = self._data.append(stored)
                        csum = pre if pre is not None else ncrc
                        blob_at[i] = (file_off, len(payload),
                                      len(stored), csum, comp_id,
                                      csum_id)
            data_dirty = True
        if data_dirty and sync:
            # the data-file barrier: both engines route their
            # fdatasync through the timed seam (site blockstore.data)
            self._data.sync()

        # stage 2: one kv batch for all metadata effects
        batch = WriteBatch()
        metas: dict[tuple[str, str], _Meta | None] = {}

        def load(cid: str, oid: str, create: bool) -> _Meta:
            key = (cid, oid)
            if key in metas and metas[key] is None:
                # removed earlier in this txn: recreate fresh or fail
                if not create:
                    raise NoSuchObject(f"{cid}/{oid}")
                metas[key] = _Meta()
            if key not in metas:
                raw = self._db.get(self._okey(cid, oid))
                if raw is not None:
                    metas[key] = _Meta.decode(raw)
                elif create:
                    # collection must exist (created earlier in this txn
                    # or already present)
                    if self._db.get(self._ckey(cid)) is None and \
                            not any(o[0] == osr.OP_MKCOLL and o[1] == cid
                                    for o in txn.ops):
                        raise NoSuchCollection(cid)
                    metas[key] = _Meta()
                else:
                    raise NoSuchObject(f"{cid}/{oid}")
            return metas[key]

        t_kv = tmr.now()
        for i, op in enumerate(txn.ops):
            code = op[0]
            if code == osr.OP_MKCOLL:
                batch.put(self._ckey(op[1]), b"")
            elif code == osr.OP_RMCOLL:
                batch.delete(self._ckey(op[1]))
                for k, _ in list(self._db.iterate(f"o/{op[1]}/")):
                    batch.delete(k)
                # objects staged earlier in this txn must not be re-put
                # by the final metas flush after this delete
                for key in list(metas):
                    if key[0] == op[1]:
                        metas[key] = None
            elif code == osr.OP_TOUCH:
                load(op[1], op[2], create=True)
            elif code == osr.OP_WRITE:
                m = load(op[1], op[2], create=True)
                off, payload = op[3], op[4]
                foff, raw_len, disk_len, fcrc, comp_id, cs_id = \
                    blob_at[i]
                m.extents = _clip(m.extents, off, off + raw_len)
                m.extents.append(_Extent(off, raw_len, foff, raw_len,
                                         fcrc, 0, disk_len, comp_id,
                                         cs_id))
                m.extents.sort(key=lambda x: x.logical_off)
                m.size = max(m.size, off + raw_len)
            elif code == osr.OP_ZERO:
                m = load(op[1], op[2], create=True)
                off, ln = op[3], op[4]
                m.extents = _clip(m.extents, off, off + ln)
                m.size = max(m.size, off + ln)
            elif code == osr.OP_TRUNCATE:
                m = load(op[1], op[2], create=True)
                size = op[3]
                m.extents = _clip(m.extents, size, 1 << 62)
                m.size = size
            elif code == osr.OP_REMOVE:
                metas[(op[1], op[2])] = None
                batch.delete(self._okey(op[1], op[2]))
                # a rewrite replaces the data; injected/latent read
                # errors do not survive it
                self._eio.discard((op[1], op[2]))
            elif code == osr.OP_SETATTR:
                load(op[1], op[2], create=True).attrs[op[3]] = op[4]
            elif code == osr.OP_RMATTR:
                load(op[1], op[2], create=False).attrs.pop(op[3], None)
            elif code == osr.OP_OMAP_SET:
                load(op[1], op[2], create=True).omap.update(op[3])
            elif code == osr.OP_OMAP_RM:
                m = load(op[1], op[2], create=False)
                for k in op[3]:
                    m.omap.pop(k, None)
            elif code == osr.OP_OMAP_RMRANGE:
                m = load(op[1], op[2], create=True)
                for k in [k for k in m.omap if k.startswith(op[3])]:
                    del m.omap[k]
        for (cid, oid), m in metas.items():
            if m is not None:
                batch.put(self._okey(cid, oid), m.encode())
        tmr.add("kv_build", tmr.now() - t_kv)
        # FileDB.submit lands wal_append + the kv.wal fsync on this
        # txn's timer — the atomicity point's own decomposition
        # (sync=False defers the fsync to the group's shared barrier)
        self._db.submit(batch, sync=sync)
        return data_dirty

    # -- reads --------------------------------------------------------
    @staticmethod
    def _comp_config():
        """(Compressor|None, min_blob_size, required_ratio) from config."""
        from ceph_tpu.utils.config import g_conf
        name = g_conf()["bluestore_compression_algorithm"]
        if name == "none":
            return None, 0, 1.0
        from ceph_tpu.compressor import CompressionError, Compressor
        try:
            comp = Compressor.create(name)
        except CompressionError:
            return None, 0, 1.0
        return (comp, g_conf()["bluestore_compression_min_blob_size"],
                g_conf()["bluestore_compression_required_ratio"])

    def _read_blob(self, x: _Extent) -> bytes:
        blob, ncrc = self._data.read(x.blob_off, x.disk_len)
        got = ncrc if (x.csum == 0 and ncrc is not None) \
            else _CSUM_FNS[x.csum](blob)
        if len(blob) != x.disk_len or got != x.blob_crc:
            raise EIOError(
                f"checksum mismatch reading blob at {x.blob_off}")
        if x.comp != COMP_NONE:
            from ceph_tpu.compressor import Compressor
            try:
                blob = Compressor.create(
                    _COMP_ALGS[x.comp]).decompress(blob)
            except Exception as exc:
                # legacy id-5 blobs: before 'lz4block' got its own id,
                # environments without python-lz4 wrote the native
                # BLOCK framing under id 5. The frame format opens
                # with magic 0x184D2204, so a block blob reliably
                # fails frame decode (or 'lz4' is unregistered) —
                # retry it as lz4block instead of going EIO.
                if x.comp != _COMP_IDS.get("lz4"):
                    raise
                try:
                    blob = Compressor.create("lz4block").decompress(
                        blob)
                except Exception:
                    raise exc
            if len(blob) != x.blob_len:
                raise EIOError(
                    f"decompressed blob at {x.blob_off} has wrong size")
        return blob

    def read(self, cid: str, oid: str, off: int = 0,
             length: int | None = None) -> bytes:
        from ceph_tpu.utils import faults as _faults
        if _faults.check_store_read(cid, oid):
            raise EIOError(f"injected fault EIO on {cid}/{oid}")
        if (cid, oid) in self._eio:
            raise EIOError(f"injected EIO on {cid}/{oid}")
        m = self._meta(cid, oid)
        end = m.size if length is None else min(off + length, m.size)
        if end <= off:
            return b""
        buf = bytearray(end - off)  # holes read as zeros
        for x in m.extents:
            lo, hi = max(x.logical_off, off), min(x.end, end)
            if lo >= hi:
                continue
            blob = self._read_blob(x)
            s = x.slice_off + (lo - x.logical_off)
            buf[lo - off:hi - off] = blob[s:s + (hi - lo)]
        return bytes(buf)

    def stat(self, cid: str, oid: str) -> int:
        return self._meta(cid, oid).size

    def getattr(self, cid: str, oid: str, name: str) -> bytes:
        attrs = self._meta(cid, oid).attrs
        if name not in attrs:
            raise NoSuchObject(f"attr {name} on {cid}/{oid}")
        return attrs[name]

    def getattrs(self, cid: str, oid: str) -> dict[str, bytes]:
        return dict(self._meta(cid, oid).attrs)

    def omap_get(self, cid: str, oid: str) -> dict[str, bytes]:
        return dict(self._meta(cid, oid).omap)

    def list_collections(self) -> list[str]:
        return [k[2:] for k, _ in self._db.iterate("c/")]

    def list_objects(self, cid: str) -> list[str]:
        self._require_coll(cid)
        prefix = f"o/{cid}/"
        return [k[len(prefix):] for k, _ in self._db.iterate(prefix)]

    # -- fault injection ----------------------------------------------
    def inject_data_error(self, cid: str, oid: str) -> None:
        self._eio.add((cid, oid))

    def clear_data_error(self, cid: str, oid: str) -> None:
        self._eio.discard((cid, oid))

    def inject_bit_flip(self, cid: str, oid: str, offset: int = 0,
                        length: int = 4) -> None:
        """Silent corruption: flip stored bytes of the blob backing
        logical ``offset`` and repoint the extent at a blob whose
        checksum MATCHES the flipped bytes — the store's blob csum
        cannot see it (the csum-collision / below-the-checksum rot
        class), so reads return rot with no EIO. That is exactly the
        corruption only the deep-scrub parity/crc pass catches."""
        m = self._meta(cid, oid)
        changed = False
        for x in m.extents:
            lo = max(x.logical_off, offset)
            hi = min(x.end, offset + length)
            if lo >= hi:
                continue
            if x.comp != COMP_NONE:
                # flipping compressed bytes would fail decompression
                # loudly, not silently; decompress, flip, restore raw
                blob = bytearray(self._read_blob(x))
                comp = COMP_NONE
            else:
                raw, _ = self._data.read(x.blob_off, x.disk_len)
                blob = bytearray(raw)
                comp = x.comp
            s = x.slice_off + (lo - x.logical_off)
            blob[s:s + (hi - lo)] = bytes(b ^ 0xFF
                                          for b in blob[s:s + (hi - lo)])
            with self._append_lock:
                file_off, ncrc = self._data.append(bytes(blob))
            self._data.sync()
            x.blob_off = file_off
            x.blob_len = len(blob)
            x.disk_len = len(blob)
            x.comp = comp
            x.blob_crc = ncrc if (x.csum == 0 and ncrc is not None) \
                else _CSUM_FNS[x.csum](bytes(blob))
            changed = True
        if changed:
            batch = WriteBatch()
            batch.put(self._okey(cid, oid), m.encode())
            self._db.submit(batch, sync=True)
