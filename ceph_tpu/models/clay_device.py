"""Clay layered codec as a staged TPU pipeline.

The linearized flat matrix (models/clay.py) is bit-exact but dense:
for k=8,m=4 it spends ~20x the necessary FLOPs (density ~5%). The
layered algorithm itself is MXU/VPU-friendly when expressed over whole
planes instead of per-sub-chunk host loops:

  - the pairwise coupling transforms (C<->U) are 2x2 GF-constant maps
    applied elementwise across lanes — VPU work (8 masked XORs per GF
    constant multiply, fused by XLA);
  - each plane's MDS solve is ONE small GF matrix multiply batched
    over (planes-in-level x lanes) — the same bit-sliced MXU matmul
    every other codec uses;
  - the score-level ordering of ErasureCodeClay.cc:644-709 becomes a
    short static chain (<= m+1 stages) inside one jit.

``trace_layered`` symbolically executes the host algorithm's control
flow (which depends only on (q, t, erased)) and records vectorizable
op groups; ``build_transform`` compiles them into a jitted function
``C[q*t, ssc, L] -> C'`` with recovered nodes filled in. Signatures
are cached, so encode (erased = parity nodes) compiles once per
profile. Bit-exactness vs the host plane machinery is asserted in
tests/test_clay_device.py.

Measured (v5e, k=8,m=4,d=11 encode, 64 MiB batches): 4.7 GB/s — the
score-level chain inherently sweeps the full [q*t, ssc, L] working
set ~6x per level (permuted gathers + masked selects), so the DENSE
linearized signature matrix (models/clay.py, one [m*ssc, k*ssc]
matmul, ~9 GB/s despite 20x FLOP waste) remains the production device
path; this module is the faithful staged expression of the algorithm,
kept as the validated alternative and the basis for a future
plane-blocked kernel.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.ops import bitmatrix, gf256


# -- static trace ------------------------------------------------------

@dataclass
class LevelOps:
    """Vectorizable op groups for one score level (all index arrays)."""
    # phase 1: U for intact nodes
    ident: list = field(default_factory=list)      # (node, z)
    pair_a: dict = field(default_factory=dict)     # variant -> [(nxy, z, nsw, zsw)]
    # per-plane MDS decode of erased U
    planes: list = field(default_factory=list)     # [z, ...]
    # phase 2: C for erased nodes
    ident2: list = field(default_factory=list)     # (node, z)
    type_c: dict = field(default_factory=dict)     # variant -> [(nxy, z, nsw, zsw)]
    pair_b: list = field(default_factory=list)     # (nxy, z, nsw, zsw)


def trace_layered(codec, erased: frozenset[int]) -> list[LevelOps]:
    """Replay _decode_layered's control flow (ErasureCodeClay.cc:
    644-709) recording ops instead of computing bytes. ``erased`` is
    the PADDED node-id set (virtual/parity fill to m, as the host path
    builds it)."""
    q, t = codec.q, codec.t
    ssc = codec.sub_chunk_no
    zvecs = [codec.get_plane_vector(z) for z in range(ssc)]
    order = [sum(1 for i in erased if i % q == zvecs[z][i // q])
             for z in range(ssc)]
    max_score = max(order) if erased else 0
    levels = []
    for score in range(max_score + 1):
        ops = LevelOps()
        planes = [z for z in range(ssc) if order[z] == score]
        for z in planes:
            zv = zvecs[z]
            for y in range(t):
                for x in range(q):
                    node_xy = q * y + x
                    if node_xy in erased:
                        continue
                    node_sw = q * y + zv[y]
                    if zv[y] == x:
                        ops.ident.append((node_xy, z))
                    elif zv[y] < x or node_sw in erased:
                        z_sw = codec._z_sw(z, x, zv[y], y)
                        variant = 1 if zv[y] > x else 0
                        ops.pair_a.setdefault(variant, []).append(
                            (node_xy, z, node_sw, z_sw))
        ops.planes = planes
        for z in planes:
            zv = zvecs[z]
            for node_xy in sorted(erased):
                x, y = node_xy % q, node_xy // q
                node_sw = q * y + zv[y]
                if zv[y] == x:
                    ops.ident2.append((node_xy, z))
                elif node_sw not in erased:
                    z_sw = codec._z_sw(z, x, zv[y], y)
                    variant = 1 if zv[y] > x else 0
                    ops.type_c.setdefault(variant, []).append(
                        (node_xy, z, node_sw, z_sw))
                elif zv[y] < x:
                    z_sw = codec._z_sw(z, x, zv[y], y)
                    ops.pair_b.append((node_xy, z, node_sw, z_sw))
        levels.append(ops)
    return levels


# -- pft coefficient extraction ----------------------------------------

def _pft_matrix(codec, want: list[int], known_slots: list[int]
                ) -> np.ndarray:
    """2x2 (or 1x2) GF matrix of one pairwise-transform solve, probed
    from the pft codec (GF-linear)."""
    rows = []
    for basis in range(len(known_slots)):
        known = {s: np.array([1 if i == basis else 0], dtype=np.uint8)
                 for i, s in enumerate(known_slots)}
        out = codec.pft.decode_chunks(want, known)
        rows.append([int(np.asarray(out[w])[0]) for w in want])
    return np.array(rows, dtype=np.uint8).T   # [len(want), len(known)]


def pft_coefficients(codec) -> dict:
    """All coefficient matrices the trace can reference, per slot
    variant (slot order (i0,i1,i2,i3) = (1,0,3,2) when zy > x)."""
    coeffs = {}
    for variant, slots in ((0, (0, 1, 2, 3)), (1, (1, 0, 3, 2))):
        i0, i1, i2, i3 = slots
        # pair_a: (U_xy, U_sw) from (C_xy, C_sw)
        m = _pft_matrix(codec, [i2, i3], [i0, i1])
        coeffs[("a", variant)] = m                      # [2, 2]
        # type_c: C_xy from (C_sw, U_xy)
        m = _pft_matrix(codec, [i0], [i1, i2])
        coeffs[("c", variant)] = m                      # [1, 2]
    # pair_b: (C_xy, C_sw) from (U_xy, U_sw); called with zv[y] < x
    # only, so slot order is fixed at variant 0
    coeffs[("b", 0)] = _pft_matrix(codec, [0, 1], [2, 3])
    return coeffs


# -- device execution ---------------------------------------------------

def _gf_scale(x, c: int):
    """x (*) c over GF(2^8), elementwise, for a static constant c:
    XOR of up-to-8 masked constant selects (VPU work XLA fuses)."""
    import jax.numpy as jnp
    if c == 0:
        return jnp.zeros_like(x)
    if c == 1:
        return x
    y = None
    for b in range(8):
        t = int(gf256.gf_mul(c, 1 << b))
        if t == 0:
            continue
        term = jnp.where((x >> b) & 1 == 1,
                         jnp.uint8(t), jnp.uint8(0))
        y = term if y is None else y ^ term
    return y


def _combine2(m: np.ndarray, a, b):
    """[out0, out1] = m @ [a, b] over GF, m a small host matrix."""
    outs = []
    for row in m:
        acc = _gf_scale(a, int(row[0])) ^ _gf_scale(b, int(row[1]))
        outs.append(acc)
    return outs


def _varmul_tables(coef: np.ndarray) -> list[tuple[int, np.ndarray]]:
    """Bit tables for an elementwise multiply by VARYING constants:
    y[e] = coef[e] (*) x[e] = XOR_b ((x>>b)&1) * gf_mul(coef, 2^b)[e].
    Returns only the bit planes with a nonzero table."""
    out = []
    for b in range(8):
        tab = gf256.gf_mul(coef, 1 << b)
        if tab.any():
            out.append((b, tab))
    return out


def _varmul(x, tables, jnp):
    """Apply _varmul_tables to x [qt, ssc, L] (tables broadcast over
    lanes). One fused XOR chain — no scatters, no per-pair gathers."""
    y = None
    for b, tab in tables:
        t = jnp.asarray(tab[:, :, None])
        term = jnp.where((x >> b) & 1 == 1, t, jnp.uint8(0))
        y = term if y is None else y ^ term
    if y is None:
        return jnp.zeros_like(x)
    return y


def build_transform(codec, erased: frozenset[int]):
    """Jitted ``C[q*t, ssc, L] uint8 -> C'`` filling erased nodes.
    ``erased``: padded node-id set, |erased| <= m.

    Executor shape: per level, phase 1 is ONE whole-array masked pass
    ``U' = sel(mask, a1(*)C + a2(*)C[perm], U)`` (a1/a2/perm are
    static [qt, ssc] tables), the MDS solve is one bit-sliced matmul
    over (planes-in-level x lanes), and phase 2 is one more masked
    pass over C — a handful of fused HBM passes per level instead of
    per-op-group scatters."""
    import jax
    import jax.numpy as jnp

    levels = trace_layered(codec, erased)
    coeffs = pft_coefficients(codec)
    qt = codec.q * codec.t
    ssc = codec.sub_chunk_no
    intact = [i for i in range(qt) if i not in erased]
    er = sorted(erased)
    probe = {i: np.zeros(len(intact), dtype=np.uint8) for i in intact}
    for idx, i in enumerate(intact):
        probe[i][idx] = 1
    sol = codec.mds.decode_chunks(er, probe)
    dmat = np.stack([np.asarray(sol[i], dtype=np.uint8) for i in er])
    dbmat = bitmatrix.expand_bitmatrix(dmat).astype(np.int8)

    from ceph_tpu.ops.gf_jax import _bitsliced_matvec_device

    static = []
    for ops in levels:
        # phase 1 tables: U[n,z] = a1[n,z](*)C[n,z] ^ a2[n,z](*)C[perm]
        a1 = np.zeros((qt, ssc), dtype=np.uint8)
        a2 = np.zeros((qt, ssc), dtype=np.uint8)
        pn = np.tile(np.arange(qt, dtype=np.int32)[:, None], (1, ssc))
        pz = np.tile(np.arange(ssc, dtype=np.int32)[None, :], (qt, 1))
        mask_u = np.zeros((qt, ssc), dtype=bool)
        for n, z in ops.ident:
            a1[n, z] = 1
            mask_u[n, z] = True
        for v, lst in ops.pair_a.items():
            m = coeffs[("a", v)]
            for nxy, z, nsw, zsw in lst:
                # target (nxy, z): self C + partner C
                a1[nxy, z], a2[nxy, z] = int(m[0][0]), int(m[0][1])
                pn[nxy, z], pz[nxy, z] = nsw, zsw
                mask_u[nxy, z] = True
                # target (nsw, zsw): its self is C[nsw, zsw]
                a1[nsw, zsw], a2[nsw, zsw] = int(m[1][1]), int(m[1][0])
                pn[nsw, zsw], pz[nsw, zsw] = nxy, z
                mask_u[nsw, zsw] = True
        # phase 2 tables:
        #   C[n,z] = b1(*)C[perm2] ^ b2(*)U[n,z] ^ b3(*)U[perm2]
        b1 = np.zeros((qt, ssc), dtype=np.uint8)
        b2 = np.zeros((qt, ssc), dtype=np.uint8)
        b3 = np.zeros((qt, ssc), dtype=np.uint8)
        p2n = np.tile(np.arange(qt, dtype=np.int32)[:, None],
                      (1, ssc))
        p2z = np.tile(np.arange(ssc, dtype=np.int32)[None, :],
                      (qt, 1))
        mask_c = np.zeros((qt, ssc), dtype=bool)
        for n, z in ops.ident2:
            b2[n, z] = 1
            mask_c[n, z] = True
        for v, lst in ops.type_c.items():
            m = coeffs[("c", v)]
            for nxy, z, nsw, zsw in lst:
                b1[nxy, z] = int(m[0][0])
                b2[nxy, z] = int(m[0][1])
                p2n[nxy, z], p2z[nxy, z] = nsw, zsw
                mask_c[nxy, z] = True
        mb = coeffs[("b", 0)]
        for nxy, z, nsw, zsw in ops.pair_b:
            b2[nxy, z], b3[nxy, z] = int(mb[0][0]), int(mb[0][1])
            p2n[nxy, z], p2z[nxy, z] = nsw, zsw
            mask_c[nxy, z] = True
            b2[nsw, zsw], b3[nsw, zsw] = int(mb[1][1]), int(mb[1][0])
            p2n[nsw, zsw], p2z[nsw, zsw] = nxy, z
            mask_c[nsw, zsw] = True
        static.append({
            "planes": np.asarray(ops.planes, dtype=np.int32),
            "t_a1": _varmul_tables(a1), "t_a2": _varmul_tables(a2),
            "perm": (pn, pz), "mask_u": mask_u,
            "t_b1": _varmul_tables(b1), "t_b2": _varmul_tables(b2),
            "t_b3": _varmul_tables(b3),
            "perm2": (p2n, p2z), "mask_c": mask_c,
        })

    intact_idx = jnp.asarray(np.asarray(intact, dtype=np.int32))
    er_idx = jnp.asarray(np.asarray(er, dtype=np.int32))

    @jax.jit
    def transform(c_in):
        C = c_in
        U = jnp.zeros_like(C)
        L = C.shape[-1]
        for entry in static:
            # phase 1: one masked whole-array pass
            pn, pz = entry["perm"]
            cp = C[jnp.asarray(pn), jnp.asarray(pz)]
            cand = _varmul(C, entry["t_a1"], jnp) ^ \
                _varmul(cp, entry["t_a2"], jnp)
            U = jnp.where(jnp.asarray(entry["mask_u"])[:, :, None],
                          cand, U)
            # MDS decode of erased U on this level's planes
            if len(entry["planes"]):
                planes = jnp.asarray(entry["planes"])
                x = U[intact_idx][:, planes, :].reshape(
                    len(intact), -1)
                y = _bitsliced_matvec_device(jnp.asarray(dbmat), x)
                y = y.reshape(len(er), len(entry["planes"]), L)
                U = U.at[er_idx[:, None], planes[None, :]].set(y)
            # phase 2: one masked whole-array pass
            p2n, p2z = entry["perm2"]
            cp2 = C[jnp.asarray(p2n), jnp.asarray(p2z)]
            up2 = U[jnp.asarray(p2n), jnp.asarray(p2z)]
            cand = _varmul(cp2, entry["t_b1"], jnp) ^ \
                _varmul(U, entry["t_b2"], jnp) ^ \
                _varmul(up2, entry["t_b3"], jnp)
            C = jnp.where(jnp.asarray(entry["mask_c"])[:, :, None],
                          cand, C)
        return C

    return transform


class ClayDeviceCodec:
    """Per-codec cache of compiled layered transforms, keyed by the
    padded erased-node signature (bounded: C(k+m, m) signatures exist
    and each holds a compiled executable)."""

    def __init__(self, codec) -> None:
        from ceph_tpu.utils.lru import BoundedLRU
        self.codec = codec
        self._fns: BoundedLRU = BoundedLRU(64)

    def transform(self, erased: frozenset[int], c_in: np.ndarray):
        """c_in: [q*t, ssc, L] uint8 (numpy or device array); returns
        the completed node array (device)."""
        import jax.numpy as jnp
        fn = self._fns.get_or_build(
            erased, lambda: build_transform(self.codec, erased))
        return fn(jnp.asarray(c_in))
