"""Clay (coupled-layer MSR) codec tests.

Mirrors src/test/erasure-code/TestErasureCodeClay.cc coverage: round trips
across erasure patterns, sub-chunk geometry, and the repair-bandwidth
property (single failure reads sub_chunk_no/q sub-chunks from d helpers).
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.models import ErasureCodeError, instance


def make(**profile):
    prof = {str(k): str(v) for k, v in profile.items()}
    prof["backend"] = "numpy"
    return instance().factory("clay", prof)


def test_defaults_and_geometry():
    codec = make()  # k=4, m=2, d=5
    assert codec.get_chunk_count() == 6
    assert codec.get_data_chunk_count() == 4
    assert (codec.q, codec.t, codec.nu) == (2, 3, 0)
    assert codec.get_sub_chunk_count() == 8


def test_geometry_with_virtual_nodes():
    codec = make(k=4, m=3, d=6)  # q=3, k+m=7 -> nu=2, t=3
    assert (codec.q, codec.nu, codec.t) == (3, 2, 3)
    assert codec.get_sub_chunk_count() == 27


@pytest.mark.parametrize("profile", [
    dict(k=4, m=2),                      # d = k+m-1 = 5, q=2
    dict(k=3, m=3, d=4),                 # q=2, t=3
    dict(k=4, m=3, d=6),                 # nu=2 virtual nodes
    dict(k=4, m=2, scalar_mds="isa"),
])
def test_roundtrip_all_erasures(profile):
    codec = make(**profile)
    k, m = codec.get_data_chunk_count(), codec.get_coding_chunk_count()
    n = k + m
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, size=k * 1024, dtype=np.uint8).tobytes()
    enc = codec.encode(list(range(n)), data)
    cs = codec.get_chunk_size(len(data))
    assert cs % codec.get_sub_chunk_count() == 0
    # systematic
    concat = np.concatenate([enc[i] for i in range(k)]).tobytes()
    assert concat[: len(data)] == data
    for r in (1, m):
        for lost in itertools.combinations(range(n), r):
            avail = {i: enc[i] for i in range(n) if i not in lost}
            dec = codec.decode(list(lost), avail, cs)
            for c in lost:
                assert np.array_equal(dec[c], enc[c]), (lost, c)


def test_repair_subchunk_plan():
    codec = make(k=8, m=4, d=11)  # BASELINE.md clay config: q=4, t=3, sub=64
    assert codec.get_sub_chunk_count() == 64
    n = 12
    avail = [i for i in range(n) if i != 3]
    plan = codec.minimum_to_decode([3], avail)
    assert len(plan) == 11  # d helpers
    for chunk, ranges in plan.items():
        assert sum(cnt for _, cnt in ranges) == 64 // 4  # sub/q per helper


def test_repair_path_bit_exact():
    """Single-failure repair from sub-chunk helper reads must reproduce the
    chunk exactly (the repair-bandwidth-optimal path)."""
    codec = make(k=4, m=2, d=5)
    n, sub = 6, codec.get_sub_chunk_count()
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=4 * 2048, dtype=np.uint8).tobytes()
    enc = codec.encode(list(range(n)), data)
    cs = codec.get_chunk_size(len(data))
    sc = cs // sub
    for lost in range(n):
        avail = [i for i in range(n) if i != lost]
        plan = codec.minimum_to_decode([lost], avail)
        assert len(plan) == 5  # d helpers
        helpers = {}
        for chunk, ranges in plan.items():
            parts = [enc[chunk][off * sc:(off + cnt) * sc]
                     for off, cnt in ranges]
            helpers[chunk] = np.concatenate(parts)
            assert len(helpers[chunk]) == cs // codec.q  # bandwidth saving
        dec = codec.decode([lost], helpers, cs)
        assert np.array_equal(dec[lost], enc[lost]), lost


def test_bad_profiles():
    with pytest.raises(ErasureCodeError):
        make(k=4, m=2, d=6)  # d > k+m-1
    with pytest.raises(ErasureCodeError):
        make(k=4, m=2, d=3)  # d < k
    with pytest.raises(ErasureCodeError):
        make(k=1, m=2)
    with pytest.raises(ErasureCodeError):
        make(k=4, m=2, scalar_mds="bogus")


def test_too_many_erasures():
    codec = make(k=4, m=2)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=4096, dtype=np.uint8).tobytes()
    enc = codec.encode(list(range(6)), data)
    cs = codec.get_chunk_size(len(data))
    avail = {i: enc[i] for i in range(3)}
    with pytest.raises(ErasureCodeError):
        codec.decode([3, 4, 5], avail, cs)


# -- linearized device path vs host plane machinery ------------------------
# The hot path collapses the layered codec into one flat GF matrix per
# erasure signature (probed from the host path, LRU-cached like the ISA
# decode tables). These tests pin bit-exactness of every linearized path
# against the plane-by-plane oracle.

@pytest.mark.parametrize("profile", [
    dict(k=4, m=2),                 # q=2, ssc=8
    dict(k=3, m=3, d=4),            # q=2, t=3
    dict(k=4, m=3, d=6),            # nu=2 virtual nodes, q=3, ssc=27
])
def test_linearized_encode_decode_matches_host(profile):
    lin = make(**profile)
    host = make(**profile, linearize="false")
    assert lin.linearize and not host.linearize
    k, m = lin.get_data_chunk_count(), lin.get_coding_chunk_count()
    ssc = lin.get_sub_chunk_count()
    size = ssc * 13
    rng = np.random.default_rng(7)
    data = {i: rng.integers(0, 256, size, dtype=np.uint8) for i in range(k)}
    want = list(range(k, k + m))
    enc = lin.encode_chunks(want, data)
    enc_h = host.encode_chunks(want, data)
    for i in want:
        assert np.array_equal(enc[i], enc_h[i])
    full = dict(data)
    full.update(enc)
    for erased in itertools.combinations(range(k + m), m):
        sub = {i: v for i, v in full.items() if i not in erased}
        dec = lin.decode_chunks(list(erased), sub)
        dec_h = host.decode_chunks(list(erased), sub)
        for i in erased:
            assert np.array_equal(dec[i], full[i])
            assert np.array_equal(dec[i], dec_h[i])


def test_linearized_repair_matches_host():
    lin = make(k=4, m=2)
    host = make(k=4, m=2, linearize="false")
    k, m = 4, 2
    ssc, q = lin.get_sub_chunk_count(), lin.q
    size = ssc * 19
    sc = size // ssc
    rng = np.random.default_rng(11)
    data = {i: rng.integers(0, 256, size, dtype=np.uint8) for i in range(k)}
    full = dict(data)
    full.update(lin.encode_chunks(list(range(k, k + m)), data))
    for lost in range(k + m):
        avail = [i for i in range(k + m) if i != lost]
        minimum = lin.minimum_to_decode([lost], avail)
        helpers = {}
        for cid, ranges in minimum.items():
            parts = [full[cid][z * sc:(z + 1) * sc]
                     for off, cnt in ranges for z in range(off, off + cnt)]
            helpers[cid] = np.concatenate(parts)
        got = lin.decode([lost], helpers, size)
        got_h = host.decode([lost], helpers, size)
        assert np.array_equal(got[lost], full[lost])
        assert np.array_equal(got[lost], got_h[lost])


def test_linearized_cache_is_bounded_lru():
    codec = make(k=4, m=2)
    codec._lin_cache.maxsize = 4
    ssc = codec.get_sub_chunk_count()
    size = ssc * 3
    rng = np.random.default_rng(3)
    data = {i: rng.integers(0, 256, size, dtype=np.uint8) for i in range(4)}
    full = dict(data)
    full.update(codec.encode_chunks([4, 5], data))
    for erased in itertools.combinations(range(6), 2):
        sub = {i: v for i, v in full.items() if i not in erased}
        out = codec.decode_chunks(list(erased), sub)
        for i in erased:
            assert np.array_equal(out[i], full[i])
    assert len(codec._lin_cache) <= 4
