"""rbd-mirror-lite — journal-based image replication.

Reference: src/tools/rbd_mirror (ImageReplayer, PoolReplayer) over
librbd journaling: the daemon bootstraps each mirror-enabled image
(initial full sync), then tails the source journal from its per-client
commit position, replays events onto the target image, advances the
commit position, and trims. Promote/demote flips which side accepts
writes (the target stays non-primary and rejects client mutations).

Pool-level enablement lives in a ``rbd_mirroring`` object on the
source pool (the reference's mirroring pool metadata).
"""

from __future__ import annotations

import json
import threading
import time

from ceph_tpu.services.journal import JournalError, Journaler
from ceph_tpu.services.rbd import RBD, Image, RBDError
from ceph_tpu.utils.dout import Dout

log = Dout("rbd-mirror")

MIRRORING_OID = "rbd_mirroring"


def mirror_image_enable(ioctx, name: str) -> None:
    """Mark a journaled image for mirroring (``rbd mirror image
    enable`` role)."""
    img = Image(ioctx, name)
    if img.journal is None:
        raise RBDError(f"image {name!r} has no journaling feature")
    try:
        d = json.loads(ioctx.read(MIRRORING_OID))
    except Exception:
        d = {"images": []}
    if name not in d["images"]:
        d["images"].append(name)
        ioctx.write_full(MIRRORING_OID,
                         json.dumps(d, sort_keys=True).encode())


def mirror_image_disable(ioctx, name: str) -> None:
    try:
        d = json.loads(ioctx.read(MIRRORING_OID))
    except Exception:
        return
    if name in d["images"]:
        d["images"].remove(name)
        ioctx.write_full(MIRRORING_OID,
                         json.dumps(d, sort_keys=True).encode())


def mirror_images(ioctx) -> list[str]:
    try:
        return list(json.loads(ioctx.read(MIRRORING_OID))["images"])
    except Exception:
        return []


class ImageReplayer:
    """Tail one image's journal and replay onto the peer pool
    (rbd_mirror ImageReplayer role)."""

    def __init__(self, src_io, dst_io, name: str,
                 client_id: str = "mirror") -> None:
        self.src_io = src_io
        self.dst_io = dst_io
        self.name = name
        self.client_id = client_id
        self.journal = Journaler(src_io, f"rbd.{name}")

    def bootstrap(self) -> None:
        """Initial sync: record the journal end, copy current content,
        commit at the recorded position. Events from before the copy
        may replay again — every event is idempotent against content
        that already includes it (writes/resizes rewrite the same
        bytes, snap events check existence)."""
        # pos0 FIRST: any mutation after this position replays; the
        # header/content copied below may already include some of
        # those events (replay is idempotent), but an event between a
        # header load and a later pos0 would be lost on both sides
        pos0 = self.journal.end_position()
        src = Image(self.src_io, self.name)
        rbd_dst = RBD(self.dst_io)
        if self.name not in rbd_dst.list():
            rbd_dst.create(self.name, src.size(),
                           layout=src._data.layout,
                           journaling=False, primary=False)
        dst = Image(self.dst_io, self.name)
        content = src._data.read()
        if content:
            dst._data.write(content)
        dst._header["size"] = src.size()
        dst._header["primary"] = False
        # copy the SOURCE snapshots' point-in-time content (resolved
        # through the COW chain), not a re-snapshot of current dst
        # data: a later replayed snap_rollback must restore the same
        # bytes on both sides. Chain order is preserved.
        order = list(src._header.get("snap_order", []))
        order += [s for s in sorted(src._header["snaps"])
                  if s not in order]
        for snap in order:
            meta = src._header["snaps"][snap]
            dst._snap_ingest(snap, src.snap_read(snap), meta["size"])
        dst._save_header()
        self.journal.commit(self.client_id, pos0)
        log(1, f"rbd-mirror: bootstrapped {self.name} at pos {pos0}")

    def replay_once(self) -> int:
        """Apply everything past the commit position; returns the
        number of events applied."""
        if not self.journal.exists():
            return 0
        start = self.journal.committed(self.client_id)
        dst = Image(self.dst_io, self.name)
        applied = 0
        last = start - 1
        try:
            for pos, payload in self.journal.read_from(start):
                kind, offset, data, arg = Image.decode_event(payload)
                dst._apply_event(kind, offset, data, arg)
                last = pos
                applied += 1
        except JournalError as exc:
            # commit only the applied prefix; the rest replays next
            # pass (advancing past unread events would skip them on
            # the target forever)
            log(1, f"rbd-mirror: replay of {self.name} stopped "
                f"early: {exc}")
        if applied:
            self.journal.commit(self.client_id, last + 1)
            self.journal.trim()
        return applied

    def sync(self) -> int:
        """Bootstrap if needed, then replay to the journal tip."""
        rbd_dst = RBD(self.dst_io)
        if self.name not in rbd_dst.list():
            self.bootstrap()
        return self.replay_once()


class MirrorDaemon:
    """PoolReplayer role: replicate every mirror-enabled image of a
    source pool onto a destination pool, continuously or one-shot."""

    def __init__(self, src_io, dst_io,
                 client_id: str = "mirror",
                 interval: float = 0.5) -> None:
        self.src_io = src_io
        self.dst_io = dst_io
        self.client_id = client_id
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sync_once(self) -> dict[str, int]:
        out = {}
        for name in mirror_images(self.src_io):
            try:
                out[name] = ImageReplayer(
                    self.src_io, self.dst_io, name,
                    self.client_id).sync()
            except (RBDError, JournalError) as exc:
                if "no such image" in str(exc) or \
                        "no journal" in str(exc):
                    # source image removed while still registered:
                    # prune, or every pass fails for it forever
                    log(1, f"rbd-mirror: pruning removed {name!r}")
                    mirror_image_disable(self.src_io, name)
                    out[name] = -1
                    continue
                log(1, f"rbd-mirror: {name}: {exc!r}")
                out[name] = -1
            except Exception as exc:
                log(1, f"rbd-mirror: {name}: {exc!r}")
                out[name] = -1
        return out

    def start(self) -> "MirrorDaemon":
        self._thread = threading.Thread(
            target=self._run, name="rbd-mirror", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.sync_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def promote(ioctx, name: str) -> None:
    """Make the local side primary (failover: ``rbd mirror image
    promote``)."""
    Image(ioctx, name).promote()


def demote(ioctx, name: str) -> None:
    Image(ioctx, name).demote()
