"""Cache tiering (PrimaryLogPG.cc:2754 maybe_handle_cache_detail +
:13842 agent_work, reduced to a writeback tier): overlay redirect,
promote on miss, whiteout deletes, flush/evict agent."""

import time

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.qa.cluster import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_osds=3) as c:
        c.create_pool("base", pg_num=4, size=2)
        c.create_pool("hot", pg_num=4, size=2)
        rados = c.client()
        for cmd in (
            {"prefix": "osd tier add", "pool": "base",
             "tierpool": "hot"},
            {"prefix": "osd tier cache-mode", "pool": "hot",
             "mode": "writeback"},
            {"prefix": "osd tier set-overlay", "pool": "base",
             "overlaypool": "hot"},
        ):
            code, outs, _ = rados.mon_command(cmd)
            assert code == 0, outs
        # wait until the overlay is visible in the CLIENT's cached
        # osdmap AND every OSD's: mon commits propagate by async
        # push, and a write_full racing the push goes straight to
        # base instead of redirecting — either because the client
        # targeted base directly or because the serving OSD's map
        # predates the overlay (the second, rarer window showed up
        # once per ~5 full-tier runs after round-12's scheduling
        # shifts)
        base_id = c.mon.osdmap.pool_by_name["base"]

        def overlay_everywhere() -> bool:
            maps = [rados.monc.osdmap]
            maps += [o.get_osdmap() for o in c.osds.values()]
            for m in maps:
                pool = m.pools.get(base_id) if m else None
                if pool is None or pool.read_tier < 0:
                    return False
            return True

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if overlay_everywhere():
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("overlay never reached every map")
        yield c


@pytest.fixture(scope="module")
def rados(cluster):
    return cluster._clients[0]


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.3)


def _tier_counter(cluster, name) -> int:
    total = 0
    for osd in cluster.osds.values():
        try:
            total += osd.logger.get(name)
        except Exception:
            pass
    return total


def test_write_lands_in_cache_and_agent_flushes(cluster, rados):
    """Write through the overlay -> object lives in the cache pool;
    the agent writes it back to base."""
    base_io = rados.open_ioctx("base")
    hot_io = rados.open_ioctx("hot")
    # hold the heartbeat-driven agent off while asserting the
    # PRE-flush state: under suite load a tick could flush obj1 to
    # base between the write and the first listing (the other
    # direction of the seed's ~5% PGLS flake), which is legitimate
    # agent behavior but not what this phase asserts
    for osd in cluster.osds.values():
        with osd.tier._agent_lock:
            osd.tier._agent_running = True
    try:
        base_io.write_full("obj1", b"tiered-payload")   # redirected
        # the object materialized in the CACHE pool, not base (PGLS
        # is not redirected, so the two listings tell them apart);
        # the hot listing is polled briefly — PGLS fans per-PG ops
        # that can transiently race the map churn right after
        # pool/tier creation
        assert "obj1" not in base_io.list_objects()
        _wait(lambda: "obj1" in hot_io.list_objects(), timeout=10,
              msg="write visible in cache-pool listing")
        # reads through the overlay serve from cache
        assert base_io.read("obj1") == b"tiered-payload"
    finally:
        for osd in cluster.osds.values():
            with osd.tier._agent_lock:
                osd.tier._agent_running = False
    # agent flush propagates to base
    _wait(lambda: "obj1" in base_io.list_objects(),
          msg="agent flush to base")
    # base copy is bit-identical (read the BASE pool object directly
    # via a non-overlay path: list caught it; compare through the
    # cache which is authoritative)
    assert base_io.read("obj1") == b"tiered-payload"


def test_read_miss_promotes_from_base(cluster, rados):
    base_io = rados.open_ioctx("base")
    hot_io = rados.open_ioctx("hot")
    base_io.write_full("obj2", b"x" * 4096)
    base_io.setxattr("obj2", "color", b"blue")
    _wait(lambda: "obj2" in base_io.list_objects(),
          msg="flush of obj2")
    # evict it from the cache by hand (simulates agent eviction)
    before = _tier_counter(cluster, "tier_promote")
    for osd in cluster.osds.values():
        for pg in list(osd.pgs.values()):
            if pg.pool == hot_io.pool_id:
                with pg.lock:
                    try:
                        v = pg.alloc_version()
                        pg.backend.submit_remove(pg, "obj2", v,
                                                 lambda c: None)
                    except Exception:
                        pass
    time.sleep(0.3)
    assert "obj2" not in hot_io.list_objects()
    # read through the overlay: MISS -> promote -> serve
    assert base_io.read("obj2") == b"x" * 4096
    assert base_io.getxattr("obj2", "color") == b"blue"
    assert "obj2" in hot_io.list_objects()
    assert _tier_counter(cluster, "tier_promote") > before


def test_partial_write_miss_promotes_first(cluster, rados):
    base_io = rados.open_ioctx("base")
    hot_io = rados.open_ioctx("hot")
    base_io.write_full("obj3", b"A" * 100)
    _wait(lambda: "obj3" in base_io.list_objects(),
          msg="flush of obj3")
    for osd in cluster.osds.values():
        for pg in list(osd.pgs.values()):
            if pg.pool == hot_io.pool_id:
                with pg.lock:
                    try:
                        v = pg.alloc_version()
                        pg.backend.submit_remove(pg, "obj3", v,
                                                 lambda c: None)
                    except Exception:
                        pass
    time.sleep(0.3)
    # offset write on a cache miss must splice into the BASE content
    base_io.write("obj3", b"B" * 10, offset=50)
    got = base_io.read("obj3")
    assert got == b"A" * 50 + b"B" * 10 + b"A" * 40


def test_delete_is_whiteout_and_propagates(cluster, rados):
    base_io = rados.open_ioctx("base")
    base_io.write_full("doomed", b"bye")
    _wait(lambda: "doomed" in base_io.list_objects(),
          msg="flush of doomed")
    base_io.remove("doomed")
    # immediately deleted from the client's view — no promote-through
    with pytest.raises(RadosError) as ei:
        base_io.read("doomed")
    assert ei.value.code == -2
    # the agent propagates the delete to the base pool
    _wait(lambda: "doomed" not in base_io.list_objects(),
          msg="whiteout propagation")
    with pytest.raises(RadosError):
        base_io.read("doomed")    # still gone (no resurrection)


def test_eviction_respects_target_and_keeps_dirty(cluster, rados):
    code, outs, _ = rados.mon_command(
        {"prefix": "osd pool set", "pool": "hot",
         "var": "target_max_objects", "val": "1"})
    assert code == 0, outs
    base_io = rados.open_ioctx("base")
    hot_io = rados.open_ioctx("hot")
    for i in range(8):
        base_io.write_full(f"ev{i}", bytes([i]) * 512)
    # all 8 flush to base, then eviction drains the cache toward the
    # (tiny) target; nothing is lost — reads promote back
    _wait(lambda: all(f"ev{i}" in base_io.list_objects()
                      for i in range(8)),
          msg="flush of ev*")
    _wait(lambda: len([o for o in hot_io.list_objects()
                       if o.startswith("ev")]) <= 4,
          msg="eviction under target")
    assert _tier_counter(cluster, "tier_evict") > 0
    for i in range(8):
        assert base_io.read(f"ev{i}") == bytes([i]) * 512


def test_deleted_xattr_stays_deleted_across_flush_cycles(cluster,
                                                         rados):
    """The flush rebuilds the base object from scratch: an xattr
    removed in the cache must not resurrect after evict + promote."""
    base_io = rados.open_ioctx("base")
    hot_io = rados.open_ioctx("hot")
    base_io.write_full("meta", b"m")
    base_io.setxattr("meta", "keep", b"1")
    base_io.setxattr("meta", "drop", b"1")
    _wait(lambda: "meta" in base_io.list_objects(),
          msg="first flush of meta")
    base_io.rmxattr("meta", "drop")          # marks dirty again
    # wait until re-flushed clean, then force-evict and re-promote
    def reflushed():
        for osd in cluster.osds.values():
            for pg in osd.pgs.values():
                if pg.pool != hot_io.pool_id:
                    continue
                with pg.lock:
                    try:
                        a = pg.backend.get_xattrs(pg, "meta")
                    except Exception:
                        continue
                    return "t/c" in a and "t/d" not in a
        return False
    _wait(reflushed, msg="re-flush after rmxattr")
    for osd in cluster.osds.values():
        for pg in list(osd.pgs.values()):
            if pg.pool == hot_io.pool_id:
                with pg.lock:
                    try:
                        v = pg.alloc_version()
                        pg.backend.submit_remove(pg, "meta", v,
                                                 lambda c: None)
                    except Exception:
                        pass
    time.sleep(0.3)
    # promote pulls from base: 'drop' must NOT come back
    assert base_io.getxattr("meta", "keep") == b"1"
    with pytest.raises(RadosError):
        base_io.getxattr("meta", "drop")


def test_tier_commands_validate(cluster, rados):
    code, outs, _ = rados.mon_command(
        {"prefix": "osd tier remove", "pool": "base",
         "tierpool": "hot"})
    assert code == -16 and "overlay" in outs   # overlay still set
    code, _, _ = rados.mon_command(
        {"prefix": "osd tier cache-mode", "pool": "base",
         "mode": "writeback"})
    assert code == -22                         # base is not a tier
    code, outs, _ = rados.mon_command(
        {"prefix": "osd tier cache-mode", "pool": "hot",
         "mode": "none"})
    assert code == -16 and "overlay" in outs   # clients still redirect


def test_proxy_read_preserves_pool_snapshot(cluster, rados):
    """Regression (_proxy_read dropped the op's snap context): a
    pool-snapshot read proxied through a hit-set-gated cache tier
    must return the SNAPSHOT clone's bytes from the base pool, not
    HEAD data. Seeds + snapshots the base pool BEFORE the overlay
    lands, so the reads are genuine cold misses served by proxy
    (min_read_recency_for_promote=2 keeps both touches proxied)."""
    cluster.create_pool("base3", pg_num=4, size=2)
    cluster.create_pool("hot3", pg_num=4, size=2)
    base_io = rados.open_ioctx("base3")
    base_io.write_full("snapobj", b"version-one")
    snapid = base_io.snap_create("s1")
    base_io.write_full("snapobj", b"version-two!")   # COWs v1
    assert base_io.read("snapobj", snap=snapid) == b"version-one"
    for cmd in (
        {"prefix": "osd tier add", "pool": "base3",
         "tierpool": "hot3", "force_nonempty": "1"},
        {"prefix": "osd tier cache-mode", "pool": "hot3",
         "mode": "writeback"},
        {"prefix": "osd tier set-overlay", "pool": "base3",
         "overlaypool": "hot3"},
        {"prefix": "osd pool set", "pool": "hot3",
         "var": "hit_set_period", "val": "60"},
        {"prefix": "osd pool set", "pool": "hot3",
         "var": "min_read_recency_for_promote", "val": "2"},
    ):
        code, outs, _ = rados.mon_command(cmd)
        assert code == 0, outs
    hot_id = rados.monc.osdmap.pool_by_name["hot3"]
    rados.wait_for_epoch(cluster.mon.osdmap.epoch)
    _wait(lambda: rados.monc.osdmap.pools[hot_id].hit_set_period
          == 60.0, msg="hit_set knobs in client map")
    proxies0 = _tier_counter(cluster, "tier_proxy_read")
    # HEAD through the overlay: proxied, current bytes
    assert base_io.read("snapobj") == b"version-two!"
    # SNAPSHOT through the overlay: proxied, must serve the clone
    assert base_io.read("snapobj", snap=snapid) == b"version-one"
    assert _tier_counter(cluster, "tier_proxy_read") >= proxies0 + 2
    # nothing promoted: the tier stayed clean (reads were proxied)
    hot_io = rados.open_ioctx("hot3")
    assert "snapobj" not in hot_io.list_objects()


def test_hit_sets_gate_promotion_scan_vs_hot(cluster, rados):
    """r5 (src/osd/HitSet.h:33 + PrimaryLogPG.cc:2445): with hit sets
    on, a SCAN (one touch per object) is served by proxy reads —
    nothing promotes — while a HOT object (touched repeatedly inside
    the window) does promote. Uses a fresh pool pair seeded BEFORE
    the overlay lands, so every read is a genuine cache miss."""
    cluster.create_pool("base2", pg_num=4, size=2)
    cluster.create_pool("hot2", pg_num=4, size=2)
    base_io = rados.open_ioctx("base2")
    scan_oids = [f"scan-{i}" for i in range(6)]
    for oid in scan_oids + ["hotobj"]:
        base_io.write_full(oid, f"payload-{oid}".encode())
    for cmd in (
        {"prefix": "osd tier add", "pool": "base2",
         "tierpool": "hot2", "force_nonempty": "1"},
        {"prefix": "osd tier cache-mode", "pool": "hot2",
         "mode": "writeback"},
        {"prefix": "osd tier set-overlay", "pool": "base2",
         "overlaypool": "hot2"},
        {"prefix": "osd pool set", "pool": "hot2",
         "var": "hit_set_period", "val": "60"},
        {"prefix": "osd pool set", "pool": "hot2",
         "var": "min_read_recency_for_promote", "val": "1"},
    ):
        code, outs, _ = rados.mon_command(cmd)
        assert code == 0, outs
    hot_id = rados.monc.osdmap.pool_by_name["hot2"]
    rados.wait_for_epoch(cluster.mon.osdmap.epoch)
    _wait(lambda: rados.monc.osdmap.pools[hot_id].hit_set_period
          == 60.0, msg="hit_set knobs in client map")
    promotes0 = _tier_counter(cluster, "tier_promote")
    proxies0 = _tier_counter(cluster, "tier_proxy_read")
    # SCAN: one touch each -> every read is a miss, all proxied
    for oid in scan_oids:
        assert base_io.read(oid) == f"payload-{oid}".encode()
    assert _tier_counter(cluster, "tier_promote") == promotes0, \
        "scan reads must not promote"
    assert _tier_counter(cluster, "tier_proxy_read") >= \
        proxies0 + len(scan_oids)
    hot_io = rados.open_ioctx("hot2")
    assert hot_io.list_objects() == [], "scan polluted the tier"
    # HOT: first touch proxied, second touch within the window
    # promotes
    assert base_io.read("hotobj") == b"payload-hotobj"
    assert base_io.read("hotobj") == b"payload-hotobj"
    _wait(lambda:
          _tier_counter(cluster, "tier_promote") > promotes0,
          msg="hot object promoted on re-touch")
    _wait(lambda: "hotobj" in hot_io.list_objects(),
          msg="hot object resident in the tier")
    # and the promoted object serves from the cache
    assert base_io.read("hotobj") == b"payload-hotobj"
