"""CRUSH — deterministic pseudo-random placement (the reference's crush/).

Role of src/crush/mapper.c (crush_do_rule :900, straw2 bucket choose),
src/crush/hash.c (rjenkins1), and the CrushWrapper map-building surface
(src/crush/CrushWrapper.h). Placement is computed, not looked up: any
client with the map derives object -> PG -> OSD set with no directory
service, and a weight change moves only the proportional share of PGs
(straw2's independence property).

This is a from-scratch implementation of the published algorithms
(Jenkins 96-bit integer mix; straw2 = max over items of ln(u)/w with u
drawn per (input, item, trial)). It is deterministic within this
framework; it does not aim for bit-compatibility with Ceph's maps.

Two selection modes mirror the reference's (mapper.c firstn vs indep):
  - ``firstn``  — replication: result list shrinks past failures.
  - ``indep``   — erasure coding: positions are significant; a slot that
    cannot be filled stays ``NONE`` so shard k keeps meaning shard k
    (doc/dev/osd_internals/erasure_coding/ecbackend.rst:49-76).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from math import log

NONE = -1  # CRUSH_ITEM_NONE: an unfillable indep slot

_U32 = 0xFFFFFFFF
_HASH_SEED = 1315423911  # golden-ratio-ish seed, as in crush/hash.c


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    """Robert Jenkins' public-domain 96-bit integer mix (crush/hash.c)."""
    a = (a - b - c) & _U32; a ^= c >> 13
    b = (b - c - a) & _U32; b ^= (a << 8) & _U32
    c = (c - a - b) & _U32; c ^= b >> 13
    a = (a - b - c) & _U32; a ^= c >> 12
    b = (b - c - a) & _U32; b ^= (a << 16) & _U32
    c = (c - a - b) & _U32; c ^= b >> 5
    a = (a - b - c) & _U32; a ^= c >> 3
    b = (b - c - a) & _U32; b ^= (a << 10) & _U32
    c = (c - a - b) & _U32; c ^= b >> 15
    return a, b, c


def hash2(x: int, y: int) -> int:
    a, b, c = _HASH_SEED ^ 2, x & _U32, y & _U32
    b, c, a = _mix(b, c, a)
    return _mix(x & _U32, a, c)[2]


def hash3(x: int, y: int, z: int) -> int:
    a, b, c = _HASH_SEED ^ 3, x & _U32, y & _U32
    b, c, a = _mix(b, c, a)
    a, b, c = (z & _U32), a, c
    return _mix(a, b, c)[2]


def hash_name(name: str) -> int:
    """Object-name hash (rjenkins role of ceph_str_hash_rjenkins)."""
    h = _HASH_SEED
    for byte in name.encode():
        h = hash2(h, byte)
    return h


def stable_mod(x: int, b: int, bmask: int) -> int:
    """ceph_stable_mod (include/types.h): pg count growth splits PGs
    stably — a pg maps to itself or its direct split child."""
    return x & bmask if (x & bmask) < b else x & (bmask >> 1)


@dataclass
class Bucket:
    """A straw2 internal node: children are item ids (>=0 devices,
    <0 nested buckets), each with a weight."""

    id: int                      # negative
    name: str
    type: str                    # e.g. "root", "host", "osd-domain"
    items: list[int] = field(default_factory=list)
    weights: list[float] = field(default_factory=list)

    def choose(self, x: int, r: int) -> int:
        """straw2: draw u ~ (0,1] per item from hash(x, item, r); the
        item maximizing ln(u)/weight wins (mapper.c bucket_straw2_choose).
        Weight-0 items never win unless nothing has weight."""
        best, best_draw = NONE, -float("inf")
        for item, w in zip(self.items, self.weights):
            if w <= 0.0:
                continue
            u = (hash3(x, item & _U32, r) & 0xFFFF) + 1  # (0, 65536]
            draw = log(u / 65536.0) / w
            if draw > best_draw:
                best, best_draw = item, draw
        return best


@dataclass
class Rule:
    """A placement rule: take <root>, choose(leaf) across a failure
    domain, emit. Mirrors the shape CrushWrapper::add_simple_rule
    builds (CrushWrapper.cc:1800; EC uses indep, ErasureCode.cc:53-72)."""

    name: str
    root: str
    failure_domain: str          # bucket type to spread across ("osd" = leaf)
    mode: str = "indep"          # "firstn" | "indep"


class CrushMap:
    """Bucket hierarchy + devices + rules; the CrushWrapper role."""

    TOTAL_TRIES = 51             # choose_total_tries default (mapper.c)

    def __init__(self) -> None:
        self.buckets: dict[int, Bucket] = {}
        self.by_name: dict[str, int] = {}
        self.device_weights: dict[int, float] = {}   # reweight, 0..1
        self.rules: dict[str, Rule] = {}
        self._next_bucket_id = -1

    # -- map building -------------------------------------------------
    def add_bucket(self, name: str, type: str, parent: str | None = None,
                   weight: float = 1.0) -> int:
        bid = self._next_bucket_id
        self._next_bucket_id -= 1
        self.buckets[bid] = Bucket(bid, name, type)
        self.by_name[name] = bid
        if parent is not None:
            pb = self.buckets[self.by_name[parent]]
            pb.items.append(bid)
            pb.weights.append(weight)
        return bid

    def add_device(self, osd_id: int, host: str, weight: float = 1.0) -> None:
        hb = self.buckets[self.by_name[host]]
        hb.items.append(osd_id)
        hb.weights.append(weight)
        self.device_weights[osd_id] = 1.0

    def add_rule(self, rule: Rule) -> None:
        self.rules[rule.name] = rule

    def reweight(self, osd_id: int, w: float) -> None:
        """Post-selection acceptance weight (the osdmap reweight knob):
        1.0 = always accept, 0.0 = always reject (device drained)."""
        self.device_weights[osd_id] = w

    def set_crush_weight(self, osd_id: int, w: float) -> None:
        """Adjust a device's CRUSH weight in its parent bucket AND
        propagate the delta up every ancestor's subtree weight — the
        straw2 draw weight (CrushWrapper::adjust_item_weight role,
        which updates ancestor weight sums the same way), distinct
        from reweight()'s post-selection acceptance knob. Without the
        propagation, upweighting the sole device of a one-device host
        bucket (the mon's boot-time topology) would be a placement
        no-op: the root-level draw over hosts would never see it.
        straw2 then moves only the proportional share of placements
        (tests/test_crush_quality.py quantifies it)."""
        item, delta = osd_id, None
        while True:
            holder = None
            for b in self.buckets.values():
                for i, it in enumerate(b.items):
                    if it == item:
                        holder, idx = b, i
                        break
                if holder is not None:
                    break
            if holder is None:
                if delta is None:
                    raise KeyError(f"no device {osd_id} in any bucket")
                return                  # reached an un-parented root
            if delta is None:
                delta = w - holder.weights[idx]
                holder.weights[idx] = w
            else:
                holder.weights[idx] += delta
            item = holder.id            # continue up from this bucket

    def bucket_of(self, name: str) -> Bucket:
        return self.buckets[self.by_name[name]]

    # -- selection ----------------------------------------------------
    def _leaf_accepted(self, osd: int, x: int, out: set[int]) -> bool:
        if osd in out:
            return False
        w = self.device_weights.get(osd, 0.0)
        if w >= 1.0:
            return True
        if w <= 0.0:
            return False
        return (hash2(x, osd) & 0xFFFF) < int(w * 0x10000)

    def _descend(self, bucket: Bucket, x: int, r: int, domain: str,
                 out: set[int], taken: set[int]) -> int:
        """Walk down from ``bucket`` to one device, re-drawing on
        rejection; returns NONE if tries exhaust. ``taken`` holds
        failure-domain bucket ids already used by other slots, so no two
        slots land in the same domain (rack/host separation)."""
        for attempt in range(self.TOTAL_TRIES):
            rr = r + attempt * 17
            node: Bucket | None = bucket
            dom: Bucket | None = None
            while node is not None:
                if node.type == domain:
                    dom = node
                    break
                child = node.choose(x, rr)
                if child == NONE:
                    node = None
                elif child >= 0:
                    # reached a device: only valid if devices themselves
                    # are the failure domain
                    if domain == "osd" and self._leaf_accepted(child, x, out):
                        return child
                    node = None
                else:
                    node = self.buckets[child]
            if dom is None or dom.id in taken:
                continue
            leaf = self._choose_leaf_in(dom, x, rr, out)
            if leaf != NONE:
                taken.add(dom.id)
                return leaf
        return NONE

    def _choose_leaf_in(self, bucket: Bucket, x: int, r: int,
                        out: set[int]) -> int:
        for attempt in range(self.TOTAL_TRIES):
            node = bucket
            rr = r + attempt * 131
            while node.id < 0:
                child = node.choose(x, rr)
                if child == NONE:
                    node = None
                    break
                if child >= 0:
                    if self._leaf_accepted(child, x, out):
                        return child
                    node = None
                    break
                node = self.buckets[child]
            if node is None:
                continue
        return NONE

    def _parent_index(self) -> dict[int, int]:
        """child (device or bucket id) -> parent bucket id."""
        parent: dict[int, int] = {}
        for b in self.buckets.values():
            for item in b.items:
                parent[item] = b.id
        return parent

    def _domain_of(self, osd: int, domain: str,
                   parent: dict[int, int]) -> int:
        """Ancestor bucket id of ``osd`` with type ``domain`` (NONE if
        no such ancestor)."""
        node = parent.get(osd)
        while node is not None:
            bucket = self.buckets[node]
            if bucket.type == domain:
                return bucket.id
            node = parent.get(node)
        return NONE

    def do_rule(self, rule_name: str, x: int, size: int,
                down: set[int] | None = None) -> list[int]:
        """crush_do_rule: map input x to ``size`` devices under rule.

        firstn (replication): ``down`` devices are rejected inline, so
        the result fills past failures (later slots shift up).

        indep (EC): position stability is the contract
        (crush_choose_indep semantics, mapper.c) — pass 1 computes the
        layout as if nothing were down, so healthy slots NEVER move
        when a peer fails; pass 2 redraws only the failed slots,
        excluding every kept device (and its failure domain). A slot
        that cannot be refilled stays NONE so shard k keeps meaning
        shard k."""
        rule = self.rules[rule_name]
        root = self.bucket_of(rule.root)
        down = set(down or ())
        if rule.mode != "indep":
            out: set[int] = set(down)
            result: list[int] = []
            taken: set[int] = set()
            for slot in range(size):
                osd = self._descend(root, x, slot, rule.failure_domain,
                                    out, taken)
                if osd != NONE:
                    out.add(osd)
                    result.append(osd)
            return result

        # pass 1: stable layout, failures ignored
        out = set()
        taken = set()
        result = []
        for slot in range(size):
            osd = self._descend(root, x, slot, rule.failure_domain,
                                out, taken)
            result.append(osd)
            if osd != NONE:
                out.add(osd)
        if not down.intersection(result):
            return result
        # pass 2: redraw only the failed slots
        kept = {o for o in result if o != NONE and o not in down}
        taken2: set[int] = set()
        if rule.failure_domain != "osd":
            parent = self._parent_index()
            for o in kept:
                dom = self._domain_of(o, rule.failure_domain, parent)
                if dom != NONE:
                    taken2.add(dom)
        out2 = set(kept) | down
        for slot, osd in enumerate(result):
            if osd == NONE or osd not in down:
                continue
            repl = self._descend(root, x, slot, rule.failure_domain,
                                 out2, taken2)
            result[slot] = repl
            if repl != NONE:
                out2.add(repl)
        return result


def build_flat_map(n_osds: int, osds_per_host: int = 4,
                   rule_mode: str = "indep",
                   failure_domain: str | None = None) -> CrushMap:
    """Convenience: root -> hosts -> osds, one rule ("data").

    The vstart-style default topology. failure_domain defaults to
    "osd" (single-host dev clusters can't separate by host for typical
    k+m widths); pass "host" explicitly for host separation."""
    m = CrushMap()
    m.add_bucket("default", "root")
    n_hosts = max(1, (n_osds + osds_per_host - 1) // osds_per_host)
    if failure_domain is None:
        failure_domain = "osd"
    for h in range(n_hosts):
        m.add_bucket(f"host{h}", "host", parent="default",
                     weight=float(min(osds_per_host, n_osds - h * osds_per_host)))
        for o in range(h * osds_per_host, min((h + 1) * osds_per_host, n_osds)):
            m.add_device(o, f"host{h}")
    m.add_rule(Rule("data", root="default", failure_domain=failure_domain,
                    mode=rule_mode))
    return m
