"""Coroutine-native EC reads for the crimson data path.

The threaded ``ECBackend._read_shards`` parks an op worker in
``SubOpWait.wait`` (a condition variable) while MECSubRead replies
trickle in — fine when workers are threads, fatal when the "worker"
is the PG's owning reactor: blocking it stalls every PG on the shard.
This module is the same read protocol — minimum_to_decode planning
over the up set, retry ladder with jittered backoff around
unreachable/EIO shards, version-agreement before combining chunks
(mixing a mid-commit shard into a decode is silent garbage), ENOENT
only when EVERY shard says so — rebuilt on awaitable futures the
messenger resolves via the owning reactor, so a degraded read costs
the reactor nothing but the suspended coroutine frame.

Degraded decode runs the HOST codec twin (``ec_util.decode``)
deliberately: ``decode_sync`` blocks its caller on an engine
continuation, and on a reactor that continuation would be queued
behind the very frame that is blocking — a self-deadlock. The host
twin is exact (the device path is an optimization, not a semantic),
and crimson's read fan-out concurrency comes from the event loop
instead of the engine's signature batching.
"""

from __future__ import annotations

import asyncio
import random

import numpy as np

from ceph_tpu.osd import ec_util
from ceph_tpu.osd.ec_backend import ECReadError
from ceph_tpu.osd.pg import pg_cid
from ceph_tpu.osd.pg_backend import SUBOP_TIMEOUT, user_xattrs
from ceph_tpu.parallel import messages as M
from ceph_tpu.store.object_store import (
    NoSuchCollection,
    NoSuchObject,
    StoreError,
)
from ceph_tpu.utils.config import g_conf
from ceph_tpu.utils.dout import Dout

log = Dout("crimson")

__all__ = ["read_shards", "read_object", "object_attrs"]


async def _backoff(attempt: int) -> None:
    conf = g_conf()
    base = conf["osd_ec_read_backoff_base"]
    cap = conf["osd_ec_read_backoff_max"]
    await asyncio.sleep(min(cap, base * (1 << attempt))
                        * (0.5 + random.random() * 0.5))


async def _fan_out_round(svc, be, pg, oid: str, need: list[int]):
    """One fan-out attempt over the planned positions: local shard
    read inline, remote shards as MECSubRead with one awaited future
    per (tid, shard) that the messenger resolves THROUGH the owning
    reactor. Returns (results, vers, attrs, failed, saw_data)."""
    reactor = svc.reactor
    mypos = be.my_position(pg)
    results: dict[int, np.ndarray] = {}
    vers: dict[int, int] = {}
    attrs: dict[str, bytes] = {}
    failed: set[int] = set()
    saw_data = False
    remote = [p for p in need if p != mypos]
    tid = svc.new_tid()
    futs: dict[int, asyncio.Future] = {}
    for pos in remote:
        futs[pos] = reactor.loop.create_future()
        reactor.read_waits[(tid, pos)] = futs[pos]
    try:
        for pos in remote:
            svc.send_osd(pg.acting[pos], M.MECSubRead(
                tid=tid, pool=pg.pool, ps=pg.ps, shard=pos,
                oid=oid, want_attrs=True))
        if mypos in need:
            cid = pg_cid(pg.pool, pg.ps, mypos)
            try:
                results[mypos] = np.frombuffer(
                    svc.store.read(cid, oid), dtype=np.uint8)
                local_attrs = svc.store.getattrs(cid, oid)
                vers[mypos] = int.from_bytes(
                    local_attrs.get("v", b""), "little")
                attrs = attrs or local_attrs
                saw_data = True
            except (NoSuchObject, NoSuchCollection):
                failed.add(mypos)
            except StoreError:
                failed.add(mypos)
                saw_data = True
        for pos in remote:
            try:
                rep = await asyncio.wait_for(futs[pos], SUBOP_TIMEOUT)
            except asyncio.TimeoutError:
                failed.add(pos)
                continue
            if rep.code != 0:
                failed.add(pos)
                if rep.code != -2:        # anything but ENOENT
                    saw_data = True
                continue
            saw_data = True
            results[pos] = np.frombuffer(rep.data, dtype=np.uint8)
            vers[pos] = rep.version
            if rep.attrs:
                attrs = dict(rep.attrs)
    finally:
        for pos in remote:
            reactor.read_waits.pop((tid, pos), None)
    return results, vers, attrs, failed, saw_data


async def read_shards(svc, be, pg, oid: str, want_chunks: list[int]
                      ) -> tuple[dict[int, np.ndarray],
                                 dict[str, bytes]]:
    """Awaitable ``_read_shards``: same ladder, same version
    discipline, no blocked thread. ``svc`` is the owning reactor's
    :class:`~ceph_tpu.crimson.reactor.ReactorServices`, ``be`` its
    mainline :class:`ECBackend`."""
    base_avoid: set[int] = set()
    ver_avoid: set[int] = set()
    known_vers: dict[int, int] = {}
    enoent_everywhere = True
    disagreements = 0
    for attempt in range(be.MAX_READ_ATTEMPTS):
        avoid = set(base_avoid) | ver_avoid
        available = [p for p in be.up_positions(pg) if p not in avoid]
        try:
            plan = be.codec.minimum_to_decode(want_chunks, available)
        except Exception:
            if enoent_everywhere and attempt > 0:
                raise NoSuchObject(oid)
            if attempt < be.MAX_READ_ATTEMPTS - 1:
                await _backoff(attempt)
                continue
            raise ECReadError(
                f"{oid}: cannot reconstruct chunks {want_chunks} "
                f"from positions {available} after {attempt + 1} "
                f"attempts (unreachable shards->osds "
                f"{be._shard_osd_map(pg, avoid)})")
        need = sorted(plan)
        results, vers, attrs, failed, saw = await _fan_out_round(
            svc, be, pg, oid, need)
        if saw:
            enoent_everywhere = False
        missing_reads = set(need) - set(results)
        if missing_reads:
            base_avoid |= failed | missing_reads
            if attempt < be.MAX_READ_ATTEMPTS - 1:
                await _backoff(attempt)
            continue
        known_vers.update(vers)
        if len(set(vers.values())) > 1:
            if attempt >= be.MAX_READ_ATTEMPTS - 1:
                break
            disagreements += 1
            if disagreements <= 2:
                log(10, f"{oid}: shard versions disagree {vers}, "
                    f"retrying")
            else:
                ver_avoid = be._version_split_avoid(
                    pg, want_chunks, base_avoid, known_vers)
                log(1, f"{oid}: persistent shard version split "
                    f"{known_vers}; re-reading around positions "
                    f"{sorted(ver_avoid)}")
            await _backoff(attempt)
            continue
        return results, attrs
    if enoent_everywhere:
        raise NoSuchObject(oid)
    raise ECReadError(
        f"{oid}: no consistent readable shard set after "
        f"{be.MAX_READ_ATTEMPTS} attempts (want {want_chunks}; "
        f"unreachable shards->osds "
        f"{be._shard_osd_map(pg, base_avoid)}; "
        f"observed shard versions {known_vers})")


async def read_object(svc, be, pg, oid: str) -> tuple[bytes, int]:
    """Full-object EC read -> (logical bytes, version). Fast path
    concatenates the k data chunks; degraded reconstructs on the host
    codec (see module docstring for why never ``decode_sync``)."""
    want = list(range(be.k))
    chunks, attrs = await read_shards(svc, be, pg, oid, want)
    size = be._attr_size(attrs)
    version = int.from_bytes(attrs.get("v", b""), "little")
    if not all(i in chunks for i in want):
        chunks = dict(chunks)
        chunks.update(ec_util.decode(
            be.sinfo, be.codec, chunks,
            [i for i in want if i not in chunks]))
    return be._chunks_to_logical(
        {i: chunks[i] for i in want}, size), version


async def object_attrs(svc, be, pg, oid: str) -> dict[str, bytes]:
    """Attrs (size/version/user xattrs travel on every shard): local
    shard fast path, else one remote sub-read round."""
    mypos = be.my_position(pg)
    if mypos >= 0:
        try:
            return svc.store.getattrs(
                pg_cid(pg.pool, pg.ps, mypos), oid)
        except StoreError:
            pass
    _, attrs = await read_shards(svc, be, pg, oid, [0])
    if not attrs:
        raise NoSuchObject(oid)
    return attrs


def user_visible_xattrs(attrs: dict[str, bytes]) -> dict[str, bytes]:
    return user_xattrs(attrs)
