"""SHEC — Shingled Erasure Code (locally-repairable layered parity).

Reference: src/erasure-code/shec/ErasureCodeShec.{h,cc} (Fujitsu). Profile
k, m, c with defaults 4,3,2 (ErasureCodeShec.h:50-57). Semantics
reproduced (construction and search re-written, not translated):

- The coding matrix starts from the systematic Vandermonde RS matrix and
  each parity row keeps only a circular "shingle" window of data columns:
  row rr of a layer with (m_l, c_l) covers columns
  [rr*k/m_l, (rr+c_l)*k/m_l) mod k (zeroing loop at
  ErasureCodeShec.cc:505-521). c == m degenerates to plain RS.
- ``technique=multiple`` (default) splits parity into two layers (m1,c1) +
  (m2,c2) chosen by exhaustive search minimizing the recovery-efficiency
  metric (ErasureCodeShec.cc:418-456, 470-500); ``single`` uses one layer.
- Decode searches all parity subsets (2^m, pruned) for the smallest square
  invertible system covering the erased data columns — the combinatorial
  search of shec_make_decoding_matrix (ErasureCodeShec.cc:560-686). SHEC
  is *not* MDS: patterns with no recoverable system raise.
- Decode plans are cached per (want, avail) signature like the reference's
  ErasureCodeShecTableCache.

Local repair property: a single lost chunk is recovered from ~c*k/m data
chunks + 1 parity instead of k chunks — the storage analog of sparse
mixture routing, and the reason SHEC shines for single-failure recovery
bandwidth.
"""

from __future__ import annotations

import itertools

import numpy as np

from ceph_tpu.models.interface import ErasureCodeError
from ceph_tpu.utils.lru import BoundedLRU
from ceph_tpu.models.matrix_codec import MatrixErasureCode
from ceph_tpu.models.registry import ErasureCodePlugin
from ceph_tpu.ops import gf256

__erasure_code_version__ = "ceph-tpu-plugin-1"


def _window_cols(rr: int, k: int, m_l: int, c_l: int) -> set[int]:
    """Columns kept for parity row rr of a layer with m_l rows, overlap c_l:
    circular [rr*k/m_l, (rr+c_l)*k/m_l)."""
    start = (rr * k) // m_l
    end = ((rr + c_l) * k) // m_l
    return {cc % k for cc in range(start, end)}


def _recovery_efficiency(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """The r_e1 metric of shec_calc_recovery_efficiency1: average chunks
    read to recover, over parity rows and best-covering window per data
    chunk. Lower is better."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    best_cover = [10 ** 8] * k
    total = 0.0
    for m_l, c_l in ((m1, c1), (m2, c2)):
        for rr in range(m_l):
            width = ((rr + c_l) * k) // m_l - (rr * k) // m_l
            for cc in _window_cols(rr, k, m_l, c_l):
                best_cover[cc] = min(best_cover[cc], width)
            total += width
    total += sum(best_cover)
    return total / (k + m1 + m2)


class ErasureCodeShec(MatrixErasureCode):
    DEFAULT_K, DEFAULT_M, DEFAULT_C = 4, 3, 2

    def __init__(self) -> None:
        super().__init__()
        self.c = 0
        self._plan_cache: BoundedLRU = BoundedLRU(1024)

    def init(self, profile):
        profile = dict(profile)
        k = self.to_int("k", profile, self.DEFAULT_K)
        m = self.to_int("m", profile, self.DEFAULT_M)
        c = self.to_int("c", profile, self.DEFAULT_C)
        technique = profile.get("technique", "multiple")
        if technique not in ("single", "multiple"):
            raise ErasureCodeError(
                f"shec technique={technique!r} must be single|multiple")
        w = self.to_int("w", profile, 8)
        if w != 8:
            raise ErasureCodeError("shec: only w=8 is implemented")
        # parameter envelope (reference parse + TestErasureCodeShec_arguments)
        if not (0 < c <= m <= k):
            raise ErasureCodeError(
                f"shec requires 0 < c <= m <= k, got k={k} m={m} c={c}")
        if k + m > 256:
            raise ErasureCodeError(f"k+m={k + m} > 256 for w=8")
        self.c = c
        coding = self._build_matrix(k, m, c, technique)
        profile.setdefault("plugin", "shec")
        profile["technique"] = technique
        profile["c"] = str(c)
        self._setup(k, m, coding, profile)

    # -- construction ------------------------------------------------------

    @staticmethod
    def _layer_split(k: int, m: int, c: int, technique: str):
        """Choose (m1,c1,m2,c2): exhaustive search for 'multiple'
        (ErasureCodeShec.cc:470-500), trivial for 'single'."""
        if technique == "single":
            return 0, 0, m, c
        best, best_r = None, 100.0
        for c1 in range(0, c // 2 + 1):
            for m1 in range(0, m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0) != (c1 == 0) or (m2 == 0) != (c2 == 0):
                    continue
                r = _recovery_efficiency(k, m1, m2, c1, c2)
                if r >= 0 and r < best_r - 1e-12:
                    best_r, best = r, (m1, c1, m2, c2)
        if best is None:
            raise ErasureCodeError(
                f"shec: no valid layer split for k={k} m={m} c={c}")
        m1, c1, m2, c2 = best
        return m1, c1, m2, c2

    @classmethod
    def _build_matrix(cls, k: int, m: int, c: int, technique: str) -> np.ndarray:
        m1, c1, m2, c2 = cls._layer_split(k, m, c, technique)
        mat = gf256.rs_vandermonde_matrix(k, m)
        for rr in range(m1):
            keep = _window_cols(rr, k, m1, c1)
            for cc in range(k):
                if cc not in keep:
                    mat[rr, cc] = 0
        for rr in range(m2):
            keep = _window_cols(rr, k, m2, c2)
            for cc in range(k):
                if cc not in keep:
                    mat[m1 + rr, cc] = 0
        return mat

    # -- decode plan search (shec_make_decoding_matrix) --------------------

    def _decode_plan(self, want: frozenset, avail: frozenset):
        return self._plan_cache.get_or_build(
            (want, avail), lambda: self._search_plan(want, avail))

    def _search_plan(self, want: frozenset, avail: frozenset):
        k, m = self._k, self._m
        mat = self.coding_matrix
        # erased wanted parity pulls in its data columns (.cc:531-539)
        want_data = set(i for i in want if i < k)
        for i in range(m):
            if (k + i) in want and (k + i) not in avail:
                want_data |= set(int(j) for j in np.flatnonzero(mat[i]))
        best = None  # (dup, rows, cols, parity_sel)
        min_dup, min_p = k + 1, k + 1
        for pp in range(1 << m):
            parity_sel = [i for i in range(m) if pp >> i & 1]
            if len(parity_sel) > min_p:
                continue
            if any((k + i) not in avail for i in parity_sel):
                continue
            cols = {j for j in want_data if j not in avail}
            rows: set[int] = set()
            for i in parity_sel:
                rows.add(k + i)
                nz = set(int(j) for j in np.flatnonzero(mat[i]))
                cols |= nz
                rows |= {j for j in nz if j in avail}
            if len(rows) != len(cols):
                continue
            dup = len(rows)
            if dup == 0:
                best = (0, [], [], parity_sel)
                min_dup, min_p = 0, len(parity_sel)
                break
            if dup >= min_dup:
                continue
            rlist, clist = sorted(rows), sorted(cols)
            sub = self._submatrix(rlist, clist)
            try:
                gf256.invert_matrix(sub)
            except ValueError:
                continue
            best = (dup, rlist, clist, parity_sel)
            min_dup, min_p = dup, len(parity_sel)
        if best is None:
            raise ErasureCodeError(
                f"shec: cannot recover want={sorted(want)} from "
                f"avail={sorted(avail)}", errno_=5)
        dup, rlist, clist, parity_sel = best
        # minimum chunk set: system rows + wanted available chunks (.cc:695-718)
        minimum = set(rlist)
        minimum |= {i for i in want if i in avail}
        return dup, rlist, clist, parity_sel, minimum, want_data

    def _submatrix(self, rows: list[int], cols: list[int]) -> np.ndarray:
        k = self._k
        sub = np.zeros((len(rows), len(cols)), dtype=np.uint8)
        for ri, r in enumerate(rows):
            for ci, c_ in enumerate(cols):
                if r < k:
                    sub[ri, ci] = 1 if r == c_ else 0
                else:
                    sub[ri, ci] = self.coding_matrix[r - k, c_]
        return sub

    # -- interface overrides ----------------------------------------------

    def minimum_to_decode(self, want_to_read, available):
        want = frozenset(want_to_read)
        avail = frozenset(available)
        if want <= avail:
            return {c: [(0, 1)] for c in sorted(want)}
        *_, minimum, _wd = self._decode_plan(want, avail)
        return {c: [(0, 1)] for c in sorted(minimum)}

    def decode_chunks(self, want_to_read, chunks):
        k = self._k
        want = frozenset(want_to_read)
        avail = frozenset(chunks)
        missing = [c for c in want if c not in chunks]
        if not missing:
            return {c: np.asarray(chunks[c], dtype=np.uint8) for c in want}
        dup, rows, cols, parity_sel, _min, want_data = \
            self._decode_plan(want, avail)
        out = {c: np.asarray(chunks[c], dtype=np.uint8)
               for c in want if c in chunks}
        recovered: dict[int, np.ndarray] = {
            i: np.asarray(chunks[i], dtype=np.uint8)
            for i in range(k) if i in chunks
        }
        if dup > 0:
            sub = self._submatrix(rows, cols)
            inv = gf256.invert_matrix(sub)
            b = np.stack([np.asarray(chunks[r if r < k else r], dtype=np.uint8)
                          for r in rows])
            solved = self._matvec(inv, b)  # solves for cols
            for ci, c_ in enumerate(cols):
                recovered[c_] = solved[ci]
        for c_ in missing:
            if c_ < k:
                out[c_] = recovered[c_]
            else:
                # re-encode erased wanted parity from recovered data
                row = self.coding_matrix[c_ - k][None, :]
                nz = [int(j) for j in np.flatnonzero(row[0])]
                data = np.stack([recovered[j] for j in nz])
                out[c_] = self._matvec(row[:, nz], data)[0]
        return out


class ShecPlugin(ErasureCodePlugin):
    def factory(self, profile):
        codec = ErasureCodeShec()
        codec.init(profile)
        return codec


def __erasure_code_init__(name, registry):
    registry.add(name, ShecPlugin())
