"""rgw-lite — object gateway over RADOS (src/rgw role, reduced).

Reference: radosgw serves S3/Swift over HTTP; every bucket has an
index object whose entries are maintained ATOMICALLY by in-OSD
``cls_rgw`` methods, and object data lives in RADOS (striped when
large). This lite gateway keeps exactly that object model:

- ``.buckets``            — bucket directory (json)
- ``.bucket.<name>``      — per-bucket index, mutated ONLY via the
                            ``rgw`` object class (cls/__init__.py), so
                            concurrent gateways never race the index
- ``<bucket>/<key>``      — object data through the striper

The HTTP front end is S3-path-shaped (PUT/GET/DELETE /bucket and
/bucket/key, GET /bucket lists with ?prefix=), answering JSON rather
than S3's XML and with no request signing — documented reductions.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ceph_tpu.client.striper import FileLayout, StripedObject

BUCKETS_OID = ".buckets"


class RGWError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status


class RGWGateway:
    """Gateway core (the librados-facing half of radosgw)."""

    def __init__(self, ioctx) -> None:
        self.io = ioctx
        self._layout = FileLayout(stripe_unit=1 << 20, stripe_count=1,
                                  object_size=1 << 20)

    # -- buckets -------------------------------------------------------
    def _buckets(self) -> dict:
        try:
            return json.loads(self.io.read(BUCKETS_OID))
        except Exception:
            return {}

    def list_buckets(self) -> list[str]:
        return sorted(self._buckets())

    def create_bucket(self, name: str) -> None:
        if not name or "/" in name or name.startswith("."):
            raise RGWError(400, f"invalid bucket name {name!r}")
        b = self._buckets()
        if name in b:
            return                     # S3 PUT bucket is idempotent
        b[name] = {}
        self.io.write_full(BUCKETS_OID, json.dumps(b).encode())
        self.io.write_full(f".bucket.{name}", b"{}")

    def delete_bucket(self, name: str) -> None:
        b = self._buckets()
        if name not in b:
            raise RGWError(404, "NoSuchBucket")
        if self.list_objects(name):
            raise RGWError(409, "BucketNotEmpty")
        del b[name]
        self.io.write_full(BUCKETS_OID, json.dumps(b).encode())
        try:
            self.io.remove(f".bucket.{name}")
        except Exception:
            pass

    def _check_bucket(self, bucket: str) -> None:
        if bucket not in self._buckets():
            raise RGWError(404, "NoSuchBucket")

    # -- objects -------------------------------------------------------
    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        self._check_bucket(bucket)
        so = StripedObject(self.io, f"{bucket}/{key}", self._layout)
        so.remove()                    # replace semantics
        so = StripedObject(self.io, f"{bucket}/{key}", self._layout)
        if data:
            so.write(data)
        etag = hashlib.md5(data).hexdigest()
        self.io.execute(f".bucket.{bucket}", "rgw", "bucket_add",
                        json.dumps({"key": key, "size": len(data),
                                    "etag": etag}).encode())
        return etag

    def get_object(self, bucket: str, key: str) -> tuple[bytes, dict]:
        self._check_bucket(bucket)
        idx = self.list_objects(bucket, prefix=key)
        meta = idx.get(key)
        if meta is None:
            raise RGWError(404, "NoSuchKey")
        so = StripedObject(self.io, f"{bucket}/{key}")
        return so.read(), meta

    def delete_object(self, bucket: str, key: str) -> None:
        self._check_bucket(bucket)
        from ceph_tpu.client.rados import RadosError
        try:
            self.io.execute(f".bucket.{bucket}", "rgw", "bucket_rm",
                            json.dumps({"key": key}).encode())
        except RadosError as exc:
            if exc.code == -2:
                raise RGWError(404, "NoSuchKey")
            raise
        StripedObject(self.io, f"{bucket}/{key}").remove()

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000) -> dict:
        self._check_bucket(bucket)
        out = self.io.execute(
            f".bucket.{bucket}", "rgw", "bucket_list",
            json.dumps({"prefix": prefix, "max_keys": max_keys}).encode())
        return json.loads(out or b"{}")


class _Handler(BaseHTTPRequestHandler):
    gw: RGWGateway = None  # set by server factory

    def _split(self) -> tuple[str, str, dict]:
        parsed = urllib.parse.urlparse(self.path)
        parts = parsed.path.lstrip("/").split("/", 1)
        bucket = urllib.parse.unquote(parts[0])
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        q = dict(urllib.parse.parse_qsl(parsed.query))
        return bucket, key, q

    def _reply(self, status: int, body: bytes = b"",
               ctype: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _run(self, fn) -> None:
        try:
            fn()
        except RGWError as exc:
            self._reply(exc.status, json.dumps(
                {"error": str(exc)}).encode())
        except Exception as exc:  # pragma: no cover
            self._reply(500, json.dumps({"error": repr(exc)}).encode())

    def do_GET(self) -> None:  # noqa: N802
        bucket, key, q = self._split()

        def run() -> None:
            if not bucket:
                self._reply(200, json.dumps(
                    {"buckets": self.gw.list_buckets()}).encode())
            elif not key:
                idx = self.gw.list_objects(
                    bucket, prefix=q.get("prefix", ""),
                    max_keys=int(q.get("max-keys", 1000)))
                self._reply(200, json.dumps(
                    {"bucket": bucket, "objects": idx}).encode())
            else:
                data, meta = self.gw.get_object(bucket, key)
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("ETag", f'"{meta["etag"]}"')
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.end_headers()
                self.wfile.write(data)
        self._run(run)

    def do_PUT(self) -> None:  # noqa: N802
        bucket, key, _ = self._split()
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n) if n else b""

        def run() -> None:
            if not key:
                self.gw.create_bucket(bucket)
                self._reply(200)
            else:
                etag = self.gw.put_object(bucket, key, body)
                self.send_response(200)
                self.send_header("ETag", f'"{etag}"')
                self.send_header("Content-Length", "0")
                self.end_headers()
        self._run(run)

    def do_DELETE(self) -> None:  # noqa: N802
        bucket, key, _ = self._split()

        def run() -> None:
            if not key:
                self.gw.delete_bucket(bucket)
            else:
                self.gw.delete_object(bucket, key)
            self._reply(204)
        self._run(run)

    def do_HEAD(self) -> None:  # noqa: N802
        bucket, key, _ = self._split()

        def run() -> None:
            _, meta = self.gw.get_object(bucket, key)
            self.send_response(200)
            self.send_header("Content-Length", str(meta["size"]))
            self.send_header("ETag", f'"{meta["etag"]}"')
            self.end_headers()
        self._run(run)

    def log_message(self, *args) -> None:
        pass


class RGWServer:
    """Threaded HTTP front end (radosgw + civetweb role)."""

    def __init__(self, ioctx, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        gw = RGWGateway(ioctx)
        handler = type("BoundHandler", (_Handler,), {"gw": gw})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self.port = self._srv.server_address[1]
        self.gateway = gw
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="rgw", daemon=True)

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=2)
