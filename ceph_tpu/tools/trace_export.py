"""trace_export — render traces and autopsies as Chrome-trace JSON.

Perfetto (ui.perfetto.dev) and chrome://tracing both load the Chrome
Trace Event format: ``{"traceEvents": [...]}`` with microsecond
timestamps. This tool maps the tail sampler's artifacts onto it:

- every **service** (client.x, osd.N, mgr) becomes a process row
  (``pid`` + a ``process_name`` metadata event), so one export shows
  the op crossing daemons;
- every **span** is a complete event (``ph: "X"``) whose ``tid`` is
  its depth in the span tree — nested spans stack like a flame;
- span **events** become instant events (``ph: "i"``) at their offset;
- **engine flush windows** (spans named ``engine_flush`` /
  ``kernel_dispatch``) additionally emit async begin/end pairs
  (``ph: "b"/"e"``, cat ``engine``) so the batching window reads as
  one horizontal bar across the ops that shared it;
- an **autopsy**'s stage timeline renders as a ``timeline`` process
  row: one X event per stage interval, wall-anchored with the
  ``wall_epoch`` satellite of ISSUE 10.

Timestamps use each span's wall anchor (``wall``) so rows from
different daemons align on the epoch axis.

CLI (also via the repo-root shim ``tools/trace_export.py``)::

    python -m ceph_tpu.tools.trace_export --input trace.json \
        [--output out.json]

``--input`` accepts any of: a kept-trace record (``{"spans": [...]}``,
the mgr ``trace dump``/archive shape), a bare span list (the asok
``dump_traces`` shape), an autopsy entry (``{"spans", "timeline",
...}`` from ``dump_autopsies``), or a dispatch snapshot
(``{"recent_chains": [...]}`` from ``dump_dispatch`` — ISSUE 17: one
track per logical thread of the data path, one slice per queue wait,
and a flow arrow per cross-thread hop, so an op's causal chain
``admission -> N hops -> commit reply`` reads as connected arrows in
Perfetto). ``-`` reads stdin.
"""

from __future__ import annotations

import argparse
import json
import sys

#: span names that also render as async engine-window bars
_ENGINE_SPANS = ("engine_flush", "kernel_dispatch")


def _pid_map(spans: list[dict]) -> dict[str, int]:
    """Stable service -> pid assignment (sorted, 1-based)."""
    return {svc: i + 1
            for i, svc in enumerate(
                sorted({s.get("service", "?") for s in spans}))}


def _depths(spans: list[dict]) -> dict[int, int]:
    """span_id -> depth via parent links (orphans are depth 0)."""
    parents = {s["span_id"]: s["parent_id"] for s in spans}
    depths: dict[int, int] = {}

    def depth(sid: int, hop: int = 0) -> int:
        if sid in depths:
            return depths[sid]
        parent = parents.get(sid, 0)
        if parent == 0 or parent not in parents or hop > 64:
            depths[sid] = 0
        else:
            depths[sid] = depth(parent, hop + 1) + 1
        return depths[sid]

    for sid in parents:
        depth(sid)
    return depths


def to_chrome_trace(spans: list[dict], title: str = "",
                    timeline: dict | None = None) -> dict:
    """Span dicts (tracing.Span.dump shape) -> Chrome-trace JSON.
    ``timeline`` (a StageClock dump) adds the stage rows."""
    pids = _pid_map(spans)
    depths = _depths(spans)
    events: list[dict] = []
    for svc, pid in pids.items():
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": svc}})
    for s in spans:
        pid = pids.get(s.get("service", "?"), 0)
        tid = depths.get(s["span_id"], 0)
        ts = s.get("wall", 0.0) * 1e6
        dur = max(s.get("duration", 0.0), 0.0) * 1e6
        args = {"trace_id": s.get("trace_id", ""),
                "span_id": s["span_id"],
                "parent_id": s["parent_id"]}
        if s.get("error"):
            args["error"] = s["error"]
        events.append({"ph": "X", "name": s.get("name", "?"),
                       "cat": "span", "pid": pid, "tid": tid,
                       "ts": ts, "dur": dur, "args": args})
        for ev in s.get("events", ()):
            events.append({"ph": "i", "s": "t",
                           "name": ev.get("event", "?"),
                           "cat": "span", "pid": pid, "tid": tid,
                           "ts": ts + ev.get("t", 0.0) * 1e6})
        if any(s.get("name", "").startswith(n)
               for n in _ENGINE_SPANS):
            # the flush window as one async bar: ops sharing a flush
            # produce overlapping bars on the engine track
            ident = str(s["span_id"])
            base = {"cat": "engine", "name": s["name"], "pid": pid,
                    "id": ident,
                    "args": {"trace_id": s.get("trace_id", "")}}
            events.append(dict(base, ph="b", ts=ts))
            events.append(dict(base, ph="e", ts=ts + dur))
    if timeline:
        events.extend(_timeline_events(timeline,
                                       pid=len(pids) + 1))
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if title:
        out["otherData"] = {"title": title}
    return out


def _timeline_events(timeline: dict, pid: int) -> list[dict]:
    """A StageClock dump as one 'timeline' process row: each stage
    interval is an X event ending at its mark (the stage-names-the-
    interval-ending-at-it semantics of utils/stage_clock)."""
    wall0 = timeline.get("wall_epoch", 0.0) * 1e6
    events: list[dict] = [{"ph": "M", "pid": pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": "timeline"}}]

    def rows(stages, tid):
        for st in stages:
            dur = st.get("dur_us", 0.0)
            if dur <= 0:
                continue
            events.append({"ph": "X", "name": st["stage"],
                           "cat": "stage", "pid": pid, "tid": tid,
                           "ts": wall0 + st["t_us"] - dur,
                           "dur": dur})

    rows(timeline.get("stages", ()), 0)
    for i, (label, stages) in enumerate(
            sorted(timeline.get("children", {}).items())):
        events.append({"ph": "M", "pid": pid, "tid": i + 1,
                       "name": "thread_name",
                       "args": {"name": label}})
        rows(stages, i + 1)
    return events


def to_dispatch_trace(chains: list[dict]) -> dict:
    """Per-op causal handoff chains (the ``dump_dispatch``
    ``recent_chains`` ring) -> Chrome-trace JSON: one ``dispatch``
    process, one thread row per logical track, each hop an X slice of
    its queue wait on the DESTINATION track, plus a flow-event pair
    (``ph: "s"``/``"f"``) from the source track to the slice end so
    the cross-thread arrow renders in Perfetto."""
    events: list[dict] = [{"ph": "M", "pid": 1, "tid": 0,
                           "name": "process_name",
                           "args": {"name": "dispatch"}}]
    tids: dict[str, int] = {}

    def tid(track: str) -> int:
        if track not in tids:
            tids[track] = len(tids) + 1
            events.append({"ph": "M", "pid": 1, "tid": tids[track],
                           "name": "thread_name",
                           "args": {"name": track}})
        return tids[track]

    flow = 0
    for ci, chain in enumerate(chains):
        wall0 = chain.get("wall_epoch", 0.0) * 1e6
        for hop in chain.get("hops", ()):
            flow += 1
            src = tid(hop.get("src", "?"))
            dst = tid(hop.get("dst", "?"))
            wait = max(hop.get("wait_us", 0.0), 0.0)
            end = wall0 + hop.get("t_us", 0.0)
            start = end - wait
            name = hop.get("seam") or hop.get("stage") or "hop"
            base = {"name": name, "cat": "handoff", "pid": 1}
            events.append(dict(base, ph="X", tid=dst, ts=start,
                               dur=wait,
                               args={"stage": hop.get("stage", ""),
                                     "chain": ci}))
            events.append(dict(base, ph="s", tid=src, ts=start,
                               id=flow))
            events.append(dict(base, ph="f", bp="e", tid=dst, ts=end,
                               id=flow))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export(doc) -> dict:
    """Accept any supported input shape (see module docstring)."""
    if isinstance(doc, list):
        if doc and isinstance(doc[0], dict) and "hops" in doc[0]:
            return to_dispatch_trace(doc)    # bare chain ring
        return to_chrome_trace(doc)
    if isinstance(doc, dict) and "recent_chains" in doc:
        return to_dispatch_trace(doc["recent_chains"])
    if isinstance(doc, dict) and "spans" in doc:
        return to_chrome_trace(
            doc["spans"], title=doc.get("root", ""),
            timeline=doc.get("timeline"))
    if isinstance(doc, dict) and "traceEvents" in doc:
        return doc        # already exported
    raise ValueError(
        "unrecognized input: expected a span list, a kept-trace "
        "record, an autopsy entry, or a dispatch snapshot")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a kept trace or autopsy as Chrome-trace/"
                    "Perfetto JSON")
    ap.add_argument("--input", "-i", required=True,
                    help="JSON file (or '-' for stdin): span list, "
                         "kept-trace record, or autopsy entry")
    ap.add_argument("--output", "-o", default="-",
                    help="output file (default stdout)")
    args = ap.parse_args(argv)
    if args.input == "-":
        doc = json.load(sys.stdin)
    else:
        with open(args.input) as f:
            doc = json.load(f)
    out = export(doc)
    text = json.dumps(out, indent=1)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {len(out['traceEvents'])} events to "
              f"{args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
