"""cephfs-lite (src/mds + src/client roles, reduced): namespace ops,
file I/O through the striper, dirop atomicity via object classes."""

import errno
import os

import pytest

from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.services.cephfs import CephFS, FSError


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_osds=3) as c:
        rados = c.client()
        c.create_pool("fspool", pg_num=4, size=2)
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return CephFS(cluster._clients[0].open_ioctx("fspool"))


def test_tree_and_readdir(fs):
    fs.mkdir("/a")
    fs.mkdir("/a/b")
    fs.mkdir("/a/b/c")
    fs.mkdir("/d")
    assert fs.readdir("/") == ["a", "d"]
    assert fs.readdir("/a/b") == ["c"]
    assert fs.stat("/a")["type"] == "dir"
    with pytest.raises(FSError) as ei:
        fs.mkdir("/a")                 # exists
    assert ei.value.errno == errno.EEXIST
    with pytest.raises(FSError):
        fs.readdir("/nope")


def test_file_io_and_unlink(fs):
    f = fs.create("/a/hello.txt")
    f.write(b"hello fs")
    assert fs.stat("/a/hello.txt")["size"] == 8
    f2 = fs.open("/a/hello.txt")
    assert f2.read() == b"hello fs"
    # big striped file with offset I/O
    blob = os.urandom(3 << 20)
    big = fs.open("/a/big.bin", create=True)
    big.write(blob)
    assert big.read(4096, 1 << 20) == blob[1 << 20:(1 << 20) + 4096]
    big.write(b"patch", offset=100)
    assert big.read(5, 100) == b"patch"
    # sparse tail reads as zeros after truncate-grow
    big.truncate(len(blob) + 1000)
    assert big.read(1000, len(blob)) == b"\x00" * 1000
    fs.unlink("/a/hello.txt")
    with pytest.raises(FSError):
        fs.open("/a/hello.txt")
    assert "hello.txt" not in fs.readdir("/a")


def test_rename(fs):
    f = fs.open("/d/old.txt", create=True)
    f.write(b"payload")
    fs.rename("/d/old.txt", "/a/new.txt")
    assert "old.txt" not in fs.readdir("/d")
    assert fs.open("/a/new.txt").read() == b"payload"
    fs.unlink("/a/new.txt")


def test_rmdir_semantics(fs):
    fs.mkdir("/victim")
    fs.open("/victim/f", create=True).write(b"x")
    with pytest.raises(FSError) as ei:
        fs.rmdir("/victim")
    assert ei.value.errno == errno.ENOTEMPTY
    fs.unlink("/victim/f")
    fs.rmdir("/victim")
    assert "victim" not in fs.readdir("/")
    with pytest.raises(FSError):
        fs.rmdir("/a")                 # still has entries


def test_remount_persistence(cluster, fs):
    f = fs.open("/a/persist.bin", create=True)
    payload = os.urandom(50_000)
    f.write(payload)
    # a second mount (fresh client) sees the same tree and data
    rados2 = cluster.client()
    fs2 = CephFS(rados2.open_ioctx("fspool"))
    assert "persist.bin" in fs2.readdir("/a")
    assert fs2.open("/a/persist.bin").read() == payload


def test_concurrent_dirops_atomic(fs):
    """Two clients racing dir_link on one directory never lose an
    entry (the cls-method atomicity the MDS journal provides)."""
    import concurrent.futures
    fs.mkdir("/race")

    def worker(i):
        fs.open(f"/race/f{i}", create=True).write(b"x")
        return i

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        list(pool.map(worker, range(24)))
    assert fs.readdir("/race") == sorted(
        (f"f{i}" for i in range(24)))

def test_mds_journal_replays_half_done_rename(cluster):
    """MDS failover story (osdc/Journaler + MDLog roles): a crash
    between rename's link and unlink steps leaves both names; the
    next mount (the standby taking over) replays the journal intent
    and finishes the op — exactly one name survives."""
    from ceph_tpu.services.cephfs import CephFS
    io = cluster._clients[0].open_ioctx("fspool")
    fs = CephFS(io)
    f = fs.open("/crashy", create=True)
    f.write(b"payload")
    f.release()
    # simulate the crash: journal the intent, apply only the LINK
    ino, _ = fs._resolve("/crashy")
    fs._mds_event("rename", ino=ino, new_parent=1, new_name="moved",
                  old_parent=1, old_name="crashy")
    fs._dir_link(1, "moved", ino)
    # both names visible — the torn state
    assert {"crashy", "moved"} <= set(fs.readdir("/"))
    fs2 = CephFS(io)          # failover mount: replays the tail
    names = set(fs2.readdir("/"))
    assert "moved" in names and "crashy" not in names
    assert fs2.open("/moved").read() == b"payload"
    assert fs2.journal.committed(fs2.client_id) == \
        fs2.journal.end_position()
    fs2.unlink("/moved")


def test_mds_journal_replays_half_done_unlink(cluster):
    from ceph_tpu.services.cephfs import CephFS
    io = cluster._clients[0].open_ioctx("fspool")
    fs = CephFS(io)
    f = fs.open("/doomed2", create=True)
    f.write(b"bye")
    ino, _ = fs._resolve("/doomed2")
    # crash after the dir unlink, before the inode/data removal
    fs._mds_event("unlink", parent=1, name="doomed2", ino=ino)
    fs._dir_unlink(1, "doomed2")
    fs2 = CephFS(io)
    assert "doomed2" not in fs2.readdir("/")
    import pytest
    from ceph_tpu.client.rados import RadosError
    with pytest.raises(RadosError):
        io.read(f"inode.{ino}")      # replay removed the orphan


def test_mds_journal_replays_half_done_mksnap(cluster):
    """Regression (cephfs.py _apply_mds_event): replaying a mksnap
    intent used to rewrite the parent inode with NO SnapContext. The
    pool-context fallback still COWs, but tags the clone with only
    the LATEST pool seq — not the governing realm — so once the new
    snapid is retired, the trimmer reclaims a clone an ANCESTOR
    snapshot still needs, and the ancestor's frozen view silently
    picks up later mutations. Replay now rebuilds the parent's realm
    and passes the live path's snapc (realm + new snapid)."""
    from ceph_tpu.services.cephfs import CephFS
    io = cluster._clients[0].open_ioctx("fspool")
    fs = CephFS(io)
    fs.mkdir("/p")
    fs.mkdir("/p/d")
    fs.create("/p/d/A").write(b"pre-snapshot")
    ino_d, _ = fs._resolve("/p/d")
    sp = fs.mksnap("/p", "sp")        # ancestor realm over /p/d
    # the crash: /p/d's own snapshot s1 — snapid allocated + intent
    # journaled, nothing applied
    s1 = io.selfmanaged_snap_create()
    fs._mds_event("mksnap", parent=ino_d, name="s1", ino=s1)
    fs2 = CephFS(io)              # failover mount replays the intent
    assert fs2.lssnap("/p/d") == {"s1": s1}
    # pre-snapshot dir state is readable through BOTH governing snaps
    assert fs2.readdir("/p/.snap/sp/d") == ["A"]
    assert fs2.readdir("/p/d/.snap/s1") == ["A"]
    # mutate after replay (no new clone: the inode's snapset seq is
    # already s1, so the replay-time clone is the only copy of {A})
    fs2.create("/p/d/B").write(b"post")
    # retire s1; the replayed clone must be tagged with the WHOLE
    # realm [sp, s1] — tagged [s1] alone (the no-snapc fallback), the
    # trimmer reclaims it here and sp's view leaks B
    fs2.rmsnap("/p/d", "s1")
    for osd in cluster.osds.values():
        for pg in list(osd.pgs.values()):
            osd._snap_trim(pg)
    assert fs2.readdir("/p/.snap/sp/d") == ["A"], \
        "trim reclaimed the replayed mksnap clone the ancestor " \
        "snapshot still needed"
    assert fs2.open("/p/.snap/sp/d/A").read() == b"pre-snapshot"


def test_two_client_caps_coherence(cluster):
    """Two concurrent mounts (Capability.h role): exclusive-write /
    shared-read caps serialize file access cluster-wide; a reader
    admitted after the writer releases sees the committed bytes
    (write-then-read visibility), and concurrent shared readers
    coexist."""
    import time as _t

    from ceph_tpu.services.cephfs import CephFS
    io1 = cluster._clients[0].open_ioctx("fspool")
    io2 = cluster._clients[0].open_ioctx("fspool")
    a = CephFS(io1, client_id="mount-a")
    b = CephFS(io2, client_id="mount-b")

    fa = a.open("/shared-file", create=True)
    fa.write(b"from-a " * 100)
    # writer holds the exclusive cap: B's write must block, then
    # EAGAIN inside its timeout window
    fb = b.open("/shared-file")
    fb.cap_timeout = 0.3
    t0 = _t.monotonic()
    try:
        fb.write(b"clobber")
        raise AssertionError("conflicting write was admitted while "
                             "the exclusive cap was held")
    except Exception as exc:
        assert getattr(exc, "errno", None) == 11, exc   # EAGAIN
    assert _t.monotonic() - t0 >= 0.25       # it actually waited
    # the MDS-side cap table shows the holder
    holders = a.cap_holders("/shared-file")
    assert any("mount-a" in k and v["type"] == "exclusive"
               for k, v in holders.items()), holders

    # writer releases -> reader admitted, sees the committed bytes
    fa.release()
    fb.cap_timeout = 10.0
    assert fb.read() == b"from-a " * 100     # write-then-read visible
    # two SHARED readers coexist
    fa2 = a.open("/shared-file")
    assert fa2.read() == b"from-a " * 100
    holders = a.cap_holders("/shared-file")
    assert all(v["type"] == "shared" for v in holders.values())
    # a writer now must wait for BOTH readers (upgrade denied while
    # another shared holder exists)
    fw = b.open("/shared-file")
    fw.cap_timeout = 0.3
    try:
        fw.write(b"early")
        raise AssertionError("exclusive granted over live readers")
    except Exception as exc:
        assert getattr(exc, "errno", None) == 11, exc
    fa.release(); fa2.release(); fb.release()
    fw.cap_timeout = 10.0
    fw.write(b"now-b")
    fw.release()
    assert a.open("/shared-file").read(5) == b"now-b"


def test_two_client_caps_lease_expiry(cluster):
    """A dead mount's exclusive cap expires (lease TTL): the blocked
    conflicting writer proceeds instead of hanging forever — the
    revoke-on-conflict story without an MDS to recall through."""
    from ceph_tpu.services.cephfs import CAP_TTL, CephFS
    io1 = cluster._clients[0].open_ioctx("fspool")
    io2 = cluster._clients[0].open_ioctx("fspool")
    a = CephFS(io1, client_id="mount-dead")
    b = CephFS(io2, client_id="mount-live")
    fa = a.open("/orphaned", create=True)
    fa.write(b"last words")
    # mount-a "dies" (no release): B's writer waits out the lease
    fb = b.open("/orphaned")
    fb.cap_timeout = CAP_TTL + 5
    fb.write(b"taken over")
    assert fb.read(10) == b"taken over"
    fb.release()


def test_two_client_rename_under_contention(cluster):
    """Concurrent dirops from two mounts (rename storm in one
    directory): the multi-writer journal + atomic dir cls methods
    keep the tree consistent — every file survives under exactly one
    name, nothing lost, nothing duplicated."""
    import concurrent.futures

    from ceph_tpu.services.cephfs import CephFS
    io1 = cluster._clients[0].open_ioctx("fspool")
    io2 = cluster._clients[0].open_ioctx("fspool")
    a = CephFS(io1, client_id="ren-a")
    b = CephFS(io2, client_id="ren-b")
    a.mkdir("/storm")
    for i in range(12):
        f = a.open(f"/storm/f{i}", create=True)
        f.write(b"payload%d" % i)
        f.release()

    def mover(args):
        fs, i = args
        fs.rename(f"/storm/f{i}", f"/storm/g{i}")
        return i

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        jobs = [(a if i % 2 == 0 else b, i) for i in range(12)]
        list(pool.map(mover, jobs))
    names = a.readdir("/storm")
    assert names == sorted(f"g{i}" for i in range(12)), names
    for i in range(12):
        f = b.open(f"/storm/g{i}")
        assert f.read() == b"payload%d" % i
        f.release()
    # both mounts journaled; a fresh mount replays cleanly and agrees
    c = CephFS(cluster._clients[0].open_ioctx("fspool"),
               client_id="ren-c")
    assert c.readdir("/storm") == names
    a.umount(); b.umount(); c.umount()


def test_journal_single_to_multi_writer_upgrade(cluster):
    """A journal written in single-writer mode (pre-round-3 mdslog)
    opened multi-writer: legacy entries stay replayable (end_position
    falls back to the header count) and new allocations seed PAST the
    legacy positions — never colliding with existing records."""
    from ceph_tpu.services.journal import Journaler
    io = cluster._clients[0].open_ioctx("fspool")
    old = Journaler(io, "upg")
    old.create()
    for i in range(5):
        old.append(b"legacy-%d" % i)
    old.commit("mds", 3)                 # positions 3,4 uncommitted

    mw = Journaler(io, "upg", multi_writer=True)
    assert mw.end_position() == 5        # legacy header count honored
    got = dict(mw.read_from(3))
    assert got == {3: b"legacy-3", 4: b"legacy-4"}
    # new allocations never collide with legacy positions
    p1 = mw.append(b"new-a")
    p2 = mw.append(b"new-b")
    assert p1 >= 5 and p2 > p1, (p1, p2)
    tail = dict(mw.read_from(3))
    assert tail[3] == b"legacy-3" and tail[p1] == b"new-a" \
        and tail[p2] == b"new-b"
    mw.remove()
