"""cephfs-lite — a POSIX-ish filesystem on RADOS (src/mds + src/client
roles, massively reduced).

Reference: CephFS keeps a metadata tree in the MDS (journaled to RADOS
via osdc/Journaler) and file data striped over RADOS objects by
file_layout_t. This lite version drops the separate MDS daemon and
stores metadata DIRECTLY in RADOS, with the dirop atomicity the MDS
journal provides coming from in-OSD object-class methods instead:

- ``.fs_super``     — inode allocator (cls fs.alloc_ino)
- ``inode.<ino>``   — json inode: dirs carry {name: ino} entries
                      (mutated only via cls fs.dir_link/dir_unlink,
                      so concurrent clients cannot corrupt a dir),
                      files carry size/mtime
- ``fsdata.<ino>``  — file content through the striper

API mirrors libcephfs: mkdir/rmdir/readdir, open/read/write, unlink,
rename, stat. Reductions (documented): no hard links across dirs; no
permissions/uids; one flat namespace per pool.

Concurrent mounts are first-class (round 3): the mdslog journal runs
in multi-writer mode (atomic position allocator + OSD-atomic chunk
appends; each mount tracks its own commit position), dir mutations
were already atomic in-OSD cls methods, and per-file CAPABILITIES
(src/mds/Capability.h role) coordinate data access — shared-read /
exclusive-write leases taken through the cls lock family on a
``caps.<ino>`` object. A cap is a TTL lease: its holder may cache the
inode while it holds the cap; a conflicting opener blocks until
release or lease expiry (the reference's cap revoke collapsed to
lease expiry — there is no MDS daemon to recall through). A mount
that dies without ``umount()`` pins the journal trim floor at its
last commit until a later mount re-commits past it (the reference
evicts such sessions; space-only, never correctness).

Metadata journaling (the osdc/Journaler + MDLog role): every
MULTI-STEP namespace op (mkdir/create/unlink/rmdir/rename) appends an
intent record to the ``mdslog`` journal before executing its steps;
mount replays the un-committed tail, re-executing steps idempotently.
That closes the crash windows the reference closes with the MDS
journal — most importantly rename's link-then-unlink window (a crash
between the two no longer leaves both names) — and is the MDS
FAILOVER story: the next mount (the standby taking over) recovers the
half-done op from the journal, exactly as a standby MDS replays the
failed rank's journal.
"""

from __future__ import annotations

import errno
import json
import time

from ceph_tpu.client.striper import FileLayout, StripedObject
from ceph_tpu.services.journal import Journaler, JournalError

ROOT_INO = 1
SUPER_OID = ".fs_super"

#: legacy journal-client id (pre-multi-writer mounts); still honored
#: in the replay floor so an old journal replays correctly
MDS_CLIENT = "mds"

#: capability lease (Capability.h role): seconds a shared/exclusive
#: file cap stays valid without renewal; a dead holder's cap expires
#: and a blocked conflicting opener proceeds
CAP_TTL = 2.0
CAP_NAME = "fscap"


class FSError(Exception):
    def __init__(self, err: int, message: str = "") -> None:
        super().__init__(message or errno.errorcode.get(err, str(err)))
        self.errno = err


class CephFS:
    """A mounted filesystem (libcephfs ceph_mount role)."""

    def __init__(self, ioctx, layout: FileLayout | None = None,
                 journaling: bool = True, caps: bool = True,
                 client_id: str | None = None) -> None:
        self.io = ioctx
        self.layout = layout or FileLayout(stripe_unit=1 << 20,
                                           stripe_count=1,
                                           object_size=1 << 20)
        if client_id is None:
            import uuid
            client_id = f"mds-{uuid.uuid4().hex[:8]}"
        self.client_id = client_id
        self.caps_enabled = caps
        self.journal = Journaler(self.io, "mdslog",
                                 multi_writer=True) \
            if journaling else None
        import threading
        self._mds_lock = threading.Lock()
        self._mds_pos = 0            # own commit floor
        #: positions THIS mount allocated and has not yet completed
        self._mds_pending: set[int] = set()
        #: MOUNT-level cap table (Capability.h: caps belong to the
        #: CLIENT session, not the fd): ino -> (type, expires). All
        #: File handles of this mount share one cls-lock cookie, so
        #: acquisition must go through here — a handle re-locking the
        #: shared cookie with a weaker type would silently downgrade
        #: a sibling handle's exclusive cap on the server.
        self._caps: dict[int, tuple[str, float]] = {}
        self._caps_lock = threading.Lock()
        #: MOUNT-level inode cache, valid only while the mount's cap
        #: on that ino is valid. Shared across handles: a sibling
        #: handle's write must be visible to every reader of this
        #: mount, cap or no cap (same-client coherence).
        self._ino_cache: dict[int, dict] = {}
        #: (client, tid) -> journal record, for every journaled intent
        #: seen during replay that carried a request id. The MDS daemon
        #: seeds its completed-request dedup from this (the reference
        #: journals completed_requests in the MDLog for the same
        #: reason: a client retrying across MDS failover must get the
        #: completed reply, not a re-execution — SessionMap.h role).
        self.replayed_requests: dict[tuple[str, int], dict] = {}
        if self.journal is not None:
            if not self.journal.exists():
                self.journal.create()
            self._replay_mds_tail()
        # bootstrap the root directory (idempotent)
        try:
            self._read_inode(ROOT_INO)
        except FSError:
            self._write_inode(ROOT_INO, {
                "type": "dir", "entries": {}, "mtime": time.time()})

    def umount(self, drain_timeout: float = 5.0) -> None:
        """Clean unmount: release every held cap (a waiting opener on
        another mount proceeds immediately), drain in-flight dirops,
        and retire this mount's journal client (its commit position
        stops pinning the trim floor for good — the session-eviction
        role). If dirops fail to drain within ``drain_timeout`` the
        retirement is skipped LOUDLY — the client id stays pinned so
        the un-finished intents remain replayable."""
        for ino in list(self._caps):
            self._cap_release(ino)
        if self.journal is None:
            return
        deadline = time.time() + drain_timeout
        while time.time() < deadline:
            with self._mds_lock:
                if not self._mds_pending:
                    self.journal.retire(self.client_id)
                    return
            time.sleep(0.05)
        import sys
        print(f"cephfs umount: {len(self._mds_pending)} dirops still "
              f"pending after {drain_timeout}s; journal client "
              f"{self.client_id} NOT retired (its intents stay "
              "replayable)", file=sys.stderr)

    # -- MDS journal (osdc/Journaler + MDLog roles) -------------------
    def _replay_mds_tail(self) -> None:
        """Mount-time recovery (the standby-MDS replay): re-execute
        journaled intents from the lowest committed position of ANY
        registered mount — a crashed mount's half-done op is finished
        here. Steps are idempotent-tolerant, so replaying an op that
        partially (or fully) applied — even one a LIVE mount is
        executing concurrently — converges."""
        try:
            end = self.journal.end_position()
        except JournalError:
            return
        clients = self.journal.clients()
        floor = min(clients.values()) if clients \
            else self.journal.trim_floor()
        applied = max(min(floor, end), self.journal.trim_floor())
        clean = True
        try:
            for epos, payload in self.journal.read_from(applied):
                self._apply_mds_event(json.loads(payload))
                applied = epos + 1
        except JournalError:
            clean = False   # commit only the prefix that applied: a
            # transient chunk-read failure must NOT advance the floor
            # past un-replayed intents (a later mount re-attempts)
        if clean:
            # trailing hole positions (alloc'd, never appended) have
            # nothing to replay: the floor may cover them
            applied = max(applied, end)
        with self._mds_lock:
            self._mds_pos = applied
        self.journal.commit(self.client_id, applied)

    def _mds_event(self, op: str, req: tuple[str, int] | None = None,
                   **args) -> int | None:
        if self.journal is None:
            return None
        rec = {"op": op, **args}
        if req is not None:
            rec["req"] = list(req)
        payload = json.dumps(rec).encode()
        with self._mds_lock:
            pos = self.journal.append(payload)
            self._mds_pending.add(pos)
        return pos

    def _mds_committed(self, pos: int | None) -> None:
        """Mark an op's intent completed — including DELIBERATE
        failures (EEXIST etc.): only a crash mid-steps may leave an
        intent for replay. This mount's commit position advances to
        just below its OLDEST still-pending op (positions interleave
        across mounts; other mounts' positions never hold ours back —
        each mount's pointer promises only 'none of MY incomplete ops
        are below this')."""
        if self.journal is None or pos is None:
            return
        with self._mds_lock:
            self._mds_pending.discard(pos)
            old_pos = self._mds_pos
            new_pos = min(self._mds_pending) if self._mds_pending \
                else pos + 1
            if new_pos > old_pos:
                self._mds_pos = new_pos
                self.journal.commit(self.client_id, new_pos)
                # boundary-crossing check: out-of-order completion can
                # advance PAST a multiple of 128 in one step
                if old_pos // 128 != new_pos // 128:
                    # reclaim consumed journal chunks (the reference
                    # trims MDLog segments the same way); without this
                    # the journal grows one entry per dirop forever
                    self.journal.trim()

    @staticmethod
    def _step(fn) -> None:
        """Run one replay step, tolerating already-applied state
        (EEXIST/ENOENT from a step that landed before the crash):
        tolerance must be PER STEP — an op's later steps are exactly
        what the replay exists to finish."""
        try:
            fn()
        except Exception:
            pass

    def _apply_mds_event(self, rec: dict) -> None:
        op = rec["op"]
        if "req" in rec:
            client, tid = rec["req"]
            self.replayed_requests[(client, int(tid))] = rec
        if op in ("mkdir", "create"):
            kind = "dir" if op == "mkdir" else "file"
            inode = {"type": kind, "mtime": time.time()}
            inode.update({"entries": {}} if kind == "dir"
                         else {"size": 0})

            def mk():
                try:
                    self._read_inode(rec["ino"])
                except FSError:
                    self._write_inode(rec["ino"], inode)
            self._step(mk)
            self._step(lambda: self._dir_link(rec["parent"],
                                              rec["name"],
                                              rec["ino"]))
        elif op == "unlink":
            self._step(lambda: self._dir_unlink(rec["parent"],
                                                rec["name"]))
            self._step(lambda: StripedObject(
                self.io, f"fsdata.{rec['ino']}").remove())
            self._step(lambda: self.io.remove(f"inode.{rec['ino']}"))
        elif op == "rmdir":
            self._step(lambda: self._dir_unlink(rec["parent"],
                                                rec["name"]))
            self._step(lambda: self.io.remove(f"inode.{rec['ino']}"))
        elif op == "mksnap":
            def addsnap():
                inode = dict(self._read_inode(rec["parent"]))
                snaps = dict(inode.get("snaps", {}))
                if snaps.get(rec["name"]) != rec["ino"]:
                    # the live path writes with the realm INCLUDING
                    # the new snapid, so the pre-snapshot dir state is
                    # COW-preserved under it; replay must match, or a
                    # crash mid-mksnap loses that clone (and the COW
                    # owed to other governing realm snaps)
                    realm = self._realm_for_ino(rec["parent"]) or []
                    snaps[rec["name"]] = rec["ino"]
                    inode["snaps"] = snaps
                    self._write_inode(
                        rec["parent"], inode,
                        snapc=self._realm_snapc(
                            sorted(set(realm) | {rec["ino"]})))
            self._step(addsnap)
        elif op == "rmsnap":
            def dropsnap():
                inode = dict(self._read_inode(rec["parent"]))
                snaps = dict(inode.get("snaps", {}))
                if rec["name"] in snaps:
                    # live rmsnap writes under the REMAINING realm so
                    # older snapshots keep their COW; replay matches
                    realm = self._realm_for_ino(rec["parent"]) or []
                    del snaps[rec["name"]]
                    inode["snaps"] = snaps
                    self._write_inode(
                        rec["parent"], inode,
                        snapc=self._realm_snapc(
                            sorted(set(realm) - {rec["ino"]})))
            self._step(dropsnap)
            self._step(lambda: self.io.selfmanaged_snap_remove(
                rec["ino"]))
        elif op == "rename":
            self._step(lambda: self._dir_link(rec["new_parent"],
                                              rec["new_name"],
                                              rec["ino"]))
            self._step(lambda: self._dir_unlink(rec["old_parent"],
                                                rec["old_name"]))

    def _realm_for_ino(self, target: int) -> list[int] | None:
        """Rebuild the governing realm for ``target`` by walking the
        tree from the root (journal replay records inos, not paths):
        the union of every directory's snapids on the root->target
        path, INCLUDING the target's own — exactly what _resolve2
        collects during a live descent. Returns None when the ino is
        unreachable (caller degrades to no SnapContext, the pre-fix
        behavior)."""
        def walk(ino: int, realm: frozenset,
                 seen: set) -> frozenset | None:
            try:
                inode = self._read_inode(ino)
            except FSError:
                return None
            realm = realm | frozenset(
                inode.get("snaps", {}).values())
            if ino == target:
                return realm
            if inode.get("type") != "dir":
                return None
            for child in inode.get("entries", {}).values():
                if child in seen:
                    continue
                seen.add(child)
                got = walk(child, realm, seen)
                if got is not None:
                    return got
            return None

        got = walk(ROOT_INO, frozenset(), {ROOT_INO})
        return sorted(got) if got is not None else None

    # -- inode plumbing ------------------------------------------------
    def _read_inode(self, ino: int, snap: int = 0) -> dict:
        try:
            return json.loads(self.io.read(f"inode.{ino}", snap=snap))
        except Exception:
            raise FSError(errno.ENOENT, f"no inode {ino}")

    def _write_inode(self, ino: int, inode: dict,
                     snapc: dict | None = None) -> None:
        self.io.write_full(f"inode.{ino}", json.dumps(inode).encode(),
                           snapc=snapc)

    def _alloc_ino(self) -> int:
        out = self.io.execute(SUPER_OID, "fs", "alloc_ino")
        return json.loads(out)["ino"]

    def _resolve(self, path: str) -> tuple[int, dict]:
        """path -> (ino, inode); raises ENOENT/ENOTDIR."""
        ino, inode, _realm = self._resolve2(path)
        return ino, inode

    def _resolve2(self, path: str) -> tuple[int, dict, list[int]]:
        """path -> (ino, inode, realm snapids). The realm is the
        union of every traversed directory's snapshots — SnapRealm
        resolution (src/mds/SnapRealm.h:27 get_snaps walks ancestors
        the same way); collected during the descent the resolver
        already performs, so realms cost no extra reads."""
        ino, inode = ROOT_INO, self._read_inode(ROOT_INO)
        realm: set[int] = set(inode.get("snaps", {}).values())
        for part in [p for p in path.split("/") if p]:
            if inode["type"] != "dir":
                raise FSError(errno.ENOTDIR, path)
            child = inode["entries"].get(part)
            if child is None:
                raise FSError(errno.ENOENT, path)
            ino, inode = child, self._read_inode(child)
            realm.update(inode.get("snaps", {}).values())
        return ino, inode, sorted(realm)

    @staticmethod
    def _realm_snapc(realm: list[int]) -> dict | None:
        """SnapContext for a write governed by ``realm`` (librados
        SnapContext: seq + snapids newest-first), or None when no
        snapshot governs the path."""
        if not realm:
            return None
        return {"snap_seq": max(realm),
                "snaps": sorted(realm, reverse=True)}

    def _resolve_parent(self, path: str) -> tuple[int, str]:
        ino, name, _ = self._resolve_parent3(path)
        return ino, name

    def _resolve_parent3(self, path: str) -> tuple[int, str, dict]:
        ino, name, inode, _realm = self._resolve_parent4(path)
        return ino, name, inode

    def _resolve_parent4(self, path: str
                         ) -> tuple[int, str, dict, list[int]]:
        """Like _resolve_parent but also hands back the parent inode
        and the governing realm snapids already collected during
        resolution (saves callers a second walk)."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FSError(errno.EINVAL, "root has no parent")
        parent = "/".join(parts[:-1])
        ino, inode, realm = self._resolve2(parent)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, parent)
        return ino, parts[-1], inode, realm

    def _dir_link(self, dir_ino: int, name: str, ino: int,
                  snapc: dict | None = None) -> None:
        from ceph_tpu.client.rados import RadosError
        try:
            self.io.execute(f"inode.{dir_ino}", "fs", "dir_link",
                            json.dumps({"name": name,
                                        "ino": ino}).encode(),
                            snapc=snapc)
        except RadosError as exc:
            raise FSError(-exc.code) from None

    def _dir_unlink(self, dir_ino: int, name: str,
                    snapc: dict | None = None) -> int:
        from ceph_tpu.client.rados import RadosError
        try:
            out = self.io.execute(f"inode.{dir_ino}", "fs",
                                  "dir_unlink",
                                  json.dumps({"name": name}).encode(),
                                  snapc=snapc)
        except RadosError as exc:
            raise FSError(-exc.code) from None
        return json.loads(out)["ino"]

    # -- namespace ops (libcephfs surface) ----------------------------
    def mkdir(self, path: str,
              req: tuple[str, int] | None = None) -> None:
        parent, name, pinode, realm = self._resolve_parent4(path)
        if name in pinode.get("entries", {}):
            raise FSError(errno.EEXIST, path)
        snapc = self._realm_snapc(realm)
        ino = self._alloc_ino()
        pos = self._mds_event("mkdir", parent=parent, name=name,
                              ino=ino, req=req)
        try:
            self._write_inode(ino, {"type": "dir", "entries": {},
                                    "mtime": time.time()},
                              snapc=snapc)
            self._dir_link(parent, name, ino, snapc=snapc)
        finally:
            self._mds_committed(pos)

    def readdir(self, path: str) -> list[str]:
        snap = self._snap_split(path)
        if snap is not None:
            dirpath, snapname, rest = snap
            if snapname is None:      # ".../<dir>/.snap" itself
                _, dinode = self._resolve(dirpath)
                if dinode["type"] != "dir":
                    raise FSError(errno.ENOTDIR, path)
                return sorted(dinode.get("snaps", {}))
            _, inode, _sid = self._resolve_snap(dirpath, snapname,
                                                rest)
            if inode["type"] != "dir":
                raise FSError(errno.ENOTDIR, path)
            return sorted(inode["entries"])
        _, inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        return sorted(inode["entries"])

    def stat(self, path: str) -> dict:
        snap = self._snap_split(path)
        if snap is not None and snap[1] is not None:
            ino, inode, snapid = self._resolve_snap(*snap)
            out = {"ino": ino, "type": inode["type"],
                   "mtime": inode["mtime"], "snapid": snapid}
            if inode["type"] == "file":
                out["size"] = inode.get("size", 0)
            else:
                out["nentries"] = len(inode["entries"])
            return out
        ino, inode = self._resolve(path)
        out = {"ino": ino, "type": inode["type"],
               "mtime": inode["mtime"]}
        if inode["type"] == "file":
            out["size"] = inode.get("size", 0)
        else:
            out["nentries"] = len(inode["entries"])
        return out

    def rmdir(self, path: str,
              req: tuple[str, int] | None = None) -> None:
        ino, inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        if inode["entries"]:
            raise FSError(errno.ENOTEMPTY, path)
        parent, name, _pinode, realm = self._resolve_parent4(path)
        snapc = self._realm_snapc(realm)
        pos = self._mds_event("rmdir", parent=parent, name=name,
                              ino=ino, req=req)
        try:
            self._dir_unlink(parent, name, snapc=snapc)
            self.io.remove(f"inode.{ino}", snapc=snapc)
        finally:
            self._mds_committed(pos)

    def create(self, path: str,
               req: tuple[str, int] | None = None) -> "File":
        parent, name, pinode, realm = self._resolve_parent4(path)
        if name in pinode.get("entries", {}):
            raise FSError(errno.EEXIST, path)
        snapc = self._realm_snapc(realm)
        ino = self._alloc_ino()
        pos = self._mds_event("create", parent=parent, name=name,
                              ino=ino, req=req)
        try:
            self._write_inode(ino, {"type": "file", "size": 0,
                                    "mtime": time.time()},
                              snapc=snapc)
            self._dir_link(parent, name, ino, snapc=snapc)
        finally:
            self._mds_committed(pos)
        return File(self, ino, snapc=snapc)

    def open(self, path: str, create: bool = False) -> "File":
        snap = self._snap_split(path)
        if snap is not None:
            ino, inode, snapid = self._resolve_snap(*snap)
            if inode["type"] != "file":
                raise FSError(errno.EISDIR, path)
            return File(self, ino, snapid=snapid)
        try:
            ino, inode, realm = self._resolve2(path)
        except FSError as exc:
            if create and exc.errno == errno.ENOENT:
                return self.create(path)
            raise
        if inode["type"] != "file":
            raise FSError(errno.EISDIR, path)
        return File(self, ino, snapc=self._realm_snapc(realm))

    # -- capabilities (Capability.h role, per-mount session) ----------
    def cap_holders(self, path: str) -> dict:
        """Live cap lockers of a file: {"name/cookie": {"type", ...}}
        (the MDS's cap tracking, surfaced for tests/tools)."""
        ino, _ = self._resolve(path)
        out = self.io.execute(f"caps.{ino}", "lock", "info")
        return json.loads(out).get("lockers", {})

    def _cap_acquire(self, ino: int, want: str,
                     timeout: float) -> None:
        """Take/renew this MOUNT's cap on ``ino`` — never weaker than
        what the mount already holds (an exclusive cap covers shared
        requests; re-locking the shared cookie with 'shared' would
        downgrade a sibling handle's exclusive on the server). The
        lease deadline is stamped from BEFORE the lock RPC, so the
        client-side expiry is always <= the server-side one. The
        table lock guards only table reads/writes — the RPC runs
        OUTSIDE it, so a contended file never stalls cap checks of
        other files in this mount."""
        if not self.caps_enabled:
            return
        from ceph_tpu.client.rados import RadosError
        deadline = time.time() + timeout
        while True:
            with self._caps_lock:
                cur = self._caps.get(ino)
                now = time.time()
                if cur is not None and now < cur[1] - CAP_TTL / 2 \
                        and (cur[0] == want or cur[0] == "exclusive"):
                    return              # held, fresh, and sufficient
                eff = "exclusive" if want == "exclusive" or (
                    cur is not None and cur[0] == "exclusive"
                    and now < cur[1]) else want
            t_req = time.time()
            try:
                self.io.execute(
                    f"caps.{ino}", "lock", "lock",
                    json.dumps({"name": CAP_NAME,
                                "cookie": self.client_id,
                                "type": eff,
                                "duration": CAP_TTL}).encode())
                with self._caps_lock:
                    # keep the strongest view: a concurrent acquirer
                    # may have upgraded while our RPC was in flight
                    cur = self._caps.get(ino)
                    if cur is None or cur[0] != "exclusive" or \
                            eff == "exclusive":
                        self._caps[ino] = (eff, t_req + CAP_TTL)
                return
            except RadosError as exc:
                if exc.code != -16:      # not EBUSY
                    raise FSError(-exc.code) from None
                with self._caps_lock:
                    self._caps.pop(ino, None)
                    self._ino_cache.pop(ino, None)
            if time.time() >= deadline:
                raise FSError(errno.EAGAIN,
                              "file cap held by another client")
            time.sleep(0.05)

    def _cap_release(self, ino: int) -> None:
        """Drop the mount's cap on ``ino`` (all handles lose it; the
        next op re-acquires)."""
        with self._caps_lock:
            held = self._caps.pop(ino, None)
            self._ino_cache.pop(ino, None)
        if held is None or not self.caps_enabled:
            return
        from ceph_tpu.client.rados import RadosError
        try:
            self.io.execute(
                f"caps.{ino}", "lock", "unlock",
                json.dumps({"name": CAP_NAME,
                            "cookie": self.client_id}).encode())
        except RadosError:
            pass                        # already expired/stolen

    def _cap_valid(self, ino: int) -> bool:
        with self._caps_lock:
            cur = self._caps.get(ino)
            return cur is not None and time.time() < cur[1]

    def _cached_inode(self, ino: int) -> "dict | None":
        """Mount-level cached inode, valid only under a live cap."""
        with self._caps_lock:
            cur = self._caps.get(ino)
            if cur is None or time.time() >= cur[1]:
                self._ino_cache.pop(ino, None)
                return None
            return self._ino_cache.get(ino)

    def _cache_inode(self, ino: int, inode: dict) -> None:
        with self._caps_lock:
            cur = self._caps.get(ino)
            if cur is not None and time.time() < cur[1]:
                self._ino_cache[ino] = inode

    def unlink(self, path: str,
               req: tuple[str, int] | None = None) -> None:
        ino, inode = self._resolve(path)
        if inode["type"] == "dir":
            raise FSError(errno.EISDIR, path)
        parent, name, _pinode, realm = self._resolve_parent4(path)
        snapc = self._realm_snapc(realm)
        pos = self._mds_event("unlink", parent=parent, name=name,
                              ino=ino, req=req)
        try:
            self._dir_unlink(parent, name, snapc=snapc)
            # carried snapc: removal COW-preserves the file's data
            # and inode for governing snapshots (snapshotted files
            # survive their deletion — the point of the snapshot)
            StripedObject(self.io, f"fsdata.{ino}",
                          snapc=snapc).remove()
            self.io.remove(f"inode.{ino}", snapc=snapc)
        finally:
            self._mds_committed(pos)

    def rename(self, old: str, new: str,
               req: tuple[str, int] | None = None) -> None:
        """Link under the new name, then unlink the old. The journaled
        intent makes the pair crash-atomic: a mount after a crash
        between the steps replays the intent and finishes the unlink
        (the MDS journal's dirop atomicity, MDLog/EUpdate role)."""
        ino, _ = self._resolve(old)
        new_parent, new_name, _pi, new_realm = \
            self._resolve_parent4(new)
        old_parent, old_name, _pi2, old_realm = \
            self._resolve_parent4(old)
        pos = self._mds_event(
            "rename", ino=ino, new_parent=new_parent,
            new_name=new_name, old_parent=old_parent,
            old_name=old_name, req=req)
        try:
            self._dir_link(new_parent, new_name, ino,
                           snapc=self._realm_snapc(new_realm))
            self._dir_unlink(old_parent, old_name,
                             snapc=self._realm_snapc(old_realm))
        finally:
            self._mds_committed(pos)


    # -- snapshots (SnapRealm-lite: src/mds/SnapRealm.h:27,
    # SnapServer.h roles) ---------------------------------------------
    # A snapshot lives on a DIRECTORY: its snapid is allocated from
    # the pool's self-managed snap sequence (the SnapServer table
    # role, delegated to the pool like librados selfmanaged snaps),
    # recorded in the directory inode, and every write under the
    # directory carries a SnapContext including it — the OSD's
    # make_writeable COW preserves both metadata objects (inodes,
    # journaled dir entries) and striped data, so reading any inode
    # or data object at the snapid reconstructs the subtree as of the
    # snapshot. Surfaced through the ".snap" pseudo-directory
    # convention: readdir("/d/.snap") lists snapshots and
    # "/d/.snap/<name>/..." resolves inside one, as in the reference.

    def mksnap(self, path: str, name: str,
               req: tuple[str, int] | None = None) -> int:
        """Snapshot directory ``path`` as ``name``; returns the
        snapid. Journaled (mksnap intent carries the allocated
        snapid, so a crash mid-op replays to completion)."""
        ino, inode, realm = self._resolve2(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        if name in inode.get("snaps", {}):
            raise FSError(errno.EEXIST, f"{path}@{name}")
        snapid = self.io.selfmanaged_snap_create()
        pos = self._mds_event("mksnap", parent=ino, name=name,
                              ino=snapid, req=req)
        try:
            inode = dict(self._read_inode(ino))
            snaps = dict(inode.get("snaps", {}))
            snaps[name] = snapid
            inode["snaps"] = snaps
            # the inode write carries the NEW snap too: COW preserves
            # the pre-snapshot dir state under the new snapid
            self._write_inode(
                ino, inode,
                snapc=self._realm_snapc(sorted(set(realm)
                                               | {snapid})))
        finally:
            self._mds_committed(pos)
        return snapid

    def rmsnap(self, path: str, name: str,
               req: tuple[str, int] | None = None) -> None:
        ino, inode, realm = self._resolve2(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        snapid = inode.get("snaps", {}).get(name)
        if snapid is None:
            raise FSError(errno.ENOENT, f"{path}@{name}")
        pos = self._mds_event("rmsnap", parent=ino, name=name,
                              ino=snapid, req=req)
        try:
            inode = dict(self._read_inode(ino))
            snaps = dict(inode.get("snaps", {}))
            snaps.pop(name, None)
            inode["snaps"] = snaps
            self._write_inode(
                ino, inode,
                snapc=self._realm_snapc(
                    sorted(set(realm) - {snapid})))
            # retire the snapid: OSD trimmers reclaim its clones
            self.io.selfmanaged_snap_remove(snapid)
        finally:
            self._mds_committed(pos)

    def lssnap(self, path: str) -> dict:
        """{name: snapid} of the directory's snapshots."""
        _, inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        return dict(inode.get("snaps", {}))

    @staticmethod
    def _snap_split(path: str):
        """Detect the ".snap" pseudo-directory: returns
        (dirpath, snapname | None, rest) or None for ordinary
        paths."""
        parts = [p for p in path.split("/") if p]
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        dirpath = "/".join(parts[:i])
        tail = parts[i + 1:]
        if not tail:
            return dirpath, None, []
        return dirpath, tail[0], tail[1:]

    def _resolve_snap(self, dirpath: str, snapname: str,
                      rest: list[str]) -> tuple[int, dict, int]:
        """Resolve a path inside a snapshot: the snapshotted dir is
        read at HEAD to find the snapid, then every descent below it
        reads inodes AT the snapid (the realm's frozen namespace)."""
        dino, dinode = self._resolve(dirpath)
        if dinode["type"] != "dir":
            raise FSError(errno.ENOTDIR, dirpath)
        snapid = dinode.get("snaps", {}).get(snapname)
        if snapid is None:
            raise FSError(errno.ENOENT, f"{dirpath}@{snapname}")
        # the dir itself as of the snapshot
        ino = dino
        inode = self._read_inode(dino, snap=snapid)
        for part in rest:
            if inode["type"] != "dir":
                raise FSError(errno.ENOTDIR, part)
            child = inode["entries"].get(part)
            if child is None:
                raise FSError(errno.ENOENT, part)
            ino = child
            inode = self._read_inode(child, snap=snapid)
        return ino, inode, snapid


class File:
    """An open file handle (libcephfs Fh role) with per-file
    CAPABILITIES (src/mds/Capability.h role, reduced to leases):

    - reads take a SHARED cap, writes an EXCLUSIVE cap, on the file's
      ``caps.<ino>`` object via the cls lock family — any number of
      readers, one writer, cluster-wide;
    - a cap is a CAP_TTL lease renewed lazily by use; while held, the
      inode may be cached (cache validity == cap validity — the
      coherence contract caps exist for);
    - a conflicting opener blocks until release or lease expiry
      (the reference's revoke recall, collapsed to lease expiry), then
      raises EAGAIN past ``cap_timeout``.
    """

    def __init__(self, fs: CephFS, ino: int,
                 snapc: dict | None = None, snapid: int = 0) -> None:
        self.fs = fs
        self.ino = ino
        #: realm SnapContext (writes) / pinned snapid (snapshot
        #: handles are read-only). The realm is captured at open; a
        #: snapshot created while a writer holds the handle applies
        #: from its next open (documented reduction of the
        #: reference's cap-recall realm push).
        self.snapc = snapc
        self.snapid = snapid
        self._data = StripedObject(fs.io, f"fsdata.{ino}", fs.layout,
                                   snapc=snapc, snapid=snapid)
        self.cap_timeout = 10.0

    # -- caps (delegated to the MOUNT's session table) ----------------
    def _acquire_cap(self, want: str) -> None:
        self.fs._cap_acquire(self.ino, want, self.cap_timeout)

    def release(self) -> None:
        """Drop the mount's cap on this file (libcephfs close role): a
        waiting conflicting opener proceeds immediately instead of at
        lease expiry. Sibling handles of the same mount simply
        re-acquire on their next op."""
        self.fs._cap_release(self.ino)

    close = release

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _inode(self) -> dict:
        """Inode view — cached at the MOUNT level while the mount's
        cap on this ino is unexpired (sibling handles of one mount
        share the cache, so one handle's write is visible to the
        others immediately); re-read otherwise."""
        if self.snapid:
            return self.fs._read_inode(self.ino, snap=self.snapid)
        if self.fs.caps_enabled:
            cached = self.fs._cached_inode(self.ino)
            if cached is not None:
                return cached
        inode = self.fs._read_inode(self.ino)
        if self.fs.caps_enabled:
            self.fs._cache_inode(self.ino, inode)
        return inode

    def _put_inode(self, inode: dict) -> None:
        self.fs._write_inode(self.ino, inode, snapc=self.snapc)
        if self.fs.caps_enabled:
            self.fs._cache_inode(self.ino, inode)

    # -- I/O ----------------------------------------------------------
    def write(self, data: bytes, offset: int = 0) -> int:
        if self.snapid:
            raise FSError(errno.EROFS, "snapshot handles are "
                          "read-only")
        self._acquire_cap("exclusive")
        self._data.write(data, offset=offset)
        inode = self._inode()
        inode = dict(inode)
        inode["size"] = max(inode.get("size", 0), offset + len(data))
        inode["mtime"] = time.time()
        self._put_inode(inode)
        return len(data)

    def read(self, length: int | None = None, offset: int = 0) -> bytes:
        if not self.snapid:
            self._acquire_cap("shared")
        inode = self._inode()
        size = inode.get("size", 0)
        # inode size is authoritative: sync the striper handle's
        # cached stream size, or a handle opened before another
        # client grew the file clamps its reads short
        self._data.size = size
        if length is None:
            length = max(size - offset, 0)
        length = min(length, max(size - offset, 0))
        if length <= 0:
            return b""
        out = self._data.read(length, offset)
        return out + b"\x00" * (length - len(out))

    def truncate(self, size: int) -> None:
        if self.snapid:
            raise FSError(errno.EROFS, "snapshot handles are "
                          "read-only")
        self._acquire_cap("exclusive")
        inode = dict(self._inode())
        inode["size"] = size
        self._put_inode(inode)
        self._data.size = min(self._data.size, size)
        self._data._write_meta()

    def size(self) -> int:
        self._acquire_cap("shared")
        return self._inode().get("size", 0)
