"""Acceptance gate for tools/gap_report.py (ISSUE 6): on a CPU-only
MiniCluster run the profiler prints a stage-attribution table whose
stage sums account for >= 90% of the measured end-to-end client-op
latency, plus one machine-parseable JSON line, and the cluster_bench
metric machinery it reuses carries stage_breakdown + p50/p99."""

import json


def test_gap_report_quick_run_attributes_latency(capsys):
    from ceph_tpu.tools import gap_report

    rc = gap_report.main([
        "--seconds", "0.5", "--osds", "3", "--obj-kb", "32",
        "--threads", "2", "--backend", "jax"])
    assert rc == 0
    out = capsys.readouterr().out
    # the human table landed
    assert "data-plane gap report" in out
    assert "stage sum coverage" in out
    assert "engine staging queue" in out
    # the JSON line parses and carries the attribution
    line = [ln for ln in out.splitlines()
            if ln.startswith('{"gap_report"')][-1]
    rep = json.loads(line)["gap_report"]
    assert rep["coverage_pct"] >= 90.0, rep
    assert rep["ops"] > 0
    assert rep["cluster_MBps"] > 0
    assert rep["engine_GBps"] > 0
    assert rep["engine_source"] in ("baseline", "engine_loop", "cli")
    assert rep["gap_x"] > 1
    # every attributed stage has a share and a mean
    for stage, ent in rep["stages"].items():
        assert ent["share_pct"] >= 0.0
        assert ent["mean_ms"] >= 0.0
    # the canonical decomposition stages all landed
    for stage in ("wire", "dispatch_queue_wait", "engine_stage_wait",
                  "commit_wait"):
        assert stage in rep["stages"], rep["stages"]
    # the cluster_bench line it wraps carried the tail latencies
    assert rep["cluster_p50_ms"] > 0
    assert rep["cluster_p99_ms"] >= rep["cluster_p50_ms"]
