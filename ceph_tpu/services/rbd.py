"""rbd-lite — block images on RADOS (src/librbd role, reduced).

Reference: librbd stores an image as a header object + striped data
objects (``rbd_data.<id>.<objectno>``), with an ``rbd_directory``
listing images per pool. This lite version keeps that object model —
directory object, per-image header (size + layout), striped data via
ceph_tpu.client.striper — and the core API: create/open/list/remove,
byte-addressed read/write, resize, and snapshots.

Snapshots here are full object-range copies into a snap namespace
(``rbd_snap.<image>@<snap>...``), not the reference's COW clones —
correct semantics (point-in-time, rollback, independent of later
writes) at lite cost; COW is future work.

Journaling (librbd journaling feature, src/journal/ role): an image
created with ``journaling=True`` appends an event record to its
journal (services/journal.py) BEFORE applying each mutation — the
write-ahead ordering rbd-mirror replay depends on. Non-primary images
(mirror targets, ``primary=False``) refuse client mutations; the
replayer applies through the internal ``_apply_event`` path
(services/rbd_mirror.py).
"""

from __future__ import annotations

import json

from ceph_tpu.client.striper import FileLayout, StripedObject
from ceph_tpu.services.journal import Journaler
from ceph_tpu.utils.encoding import Decoder, Encoder

DIRECTORY_OID = "rbd_directory"


class RBDError(Exception):
    pass


def _load_dir(io) -> dict:
    try:
        return json.loads(io.read(DIRECTORY_OID))
    except Exception:
        return {}


def _save_dir(io, d: dict) -> None:
    io.write_full(DIRECTORY_OID, json.dumps(d, sort_keys=True).encode())


class RBD:
    """Pool-level image management (librbd::RBD role)."""

    def __init__(self, ioctx) -> None:
        self.io = ioctx

    def create(self, name: str, size: int,
               layout: FileLayout | None = None,
               journaling: bool = False,
               primary: bool = True) -> "Image":
        d = _load_dir(self.io)
        if name in d:
            raise RBDError(f"image {name!r} exists")
        layout = layout or FileLayout(stripe_unit=1 << 20,
                                      stripe_count=1,
                                      object_size=1 << 20)
        header = {"size": size, "su": layout.stripe_unit,
                  "sc": layout.stripe_count, "os": layout.object_size,
                  "snaps": {}, "journaling": journaling,
                  "primary": primary}
        if journaling:
            Journaler(self.io, f"rbd.{name}").create()
        self.io.write_full(f"rbd_header.{name}",
                           json.dumps(header).encode())
        d[name] = {"size": size}
        _save_dir(self.io, d)
        return Image(self.io, name)

    def list(self) -> list[str]:
        return sorted(_load_dir(self.io))

    def remove(self, name: str) -> None:
        img = Image(self.io, name)
        for snap in list(img.snap_list()):
            # direct apply: removing a NON-PRIMARY (mirror-target)
            # image must not trip the writability check or journal
            img._snap_remove_apply(snap)
        if img.journal is not None:
            img.journal.remove()
        img._data.remove()
        try:
            self.io.remove(f"rbd_header.{name}")
        except Exception:
            pass
        d = _load_dir(self.io)
        d.pop(name, None)
        _save_dir(self.io, d)

    def open(self, name: str) -> "Image":
        return Image(self.io, name)


class Image:
    """One open image (librbd::Image role)."""

    def __init__(self, ioctx, name: str) -> None:
        self.io = ioctx
        self.name = name
        try:
            self._header = json.loads(self.io.read(f"rbd_header.{name}"))
        except Exception:
            raise RBDError(f"no such image {name!r}")
        layout = FileLayout(self._header["su"], self._header["sc"],
                            self._header["os"])
        self._data = StripedObject(self.io, f"rbd_data.{name}", layout)
        self.journal = Journaler(self.io, f"rbd.{name}") \
            if self._header.get("journaling") else None

    # -- header --------------------------------------------------------
    def _save_header(self) -> None:
        self.io.write_full(f"rbd_header.{self.name}",
                           json.dumps(self._header).encode())
        d = _load_dir(self.io)
        if self.name in d:
            d[self.name]["size"] = self._header["size"]
            _save_dir(self.io, d)

    def size(self) -> int:
        return self._header["size"]

    def stat(self) -> dict:
        return {"name": self.name, "size": self._header["size"],
                "stripe_unit": self._header["su"],
                "stripe_count": self._header["sc"],
                "object_size": self._header["os"],
                "snaps": sorted(self._header["snaps"])}

    # -- journaling / mirroring roles ----------------------------------
    def is_primary(self) -> bool:
        return self._header.get("primary", True)

    def promote(self) -> None:
        self._header["primary"] = True
        self._save_header()

    def demote(self) -> None:
        self._header["primary"] = False
        self._save_header()

    def _journal_event(self, kind: str, offset: int = 0,
                       data: bytes = b"", arg: str = "") -> None:
        if self.journal is None:
            return
        e = Encoder()
        e.str(kind)
        e.u64(offset)
        e.bytes(data)
        e.str(arg)
        self.journal.append(e.getvalue())

    @staticmethod
    def decode_event(payload: bytes) -> tuple[str, int, bytes, str]:
        d = Decoder(payload)
        return d.str(), d.u64(), d.bytes(), d.str()

    def _check_writable(self) -> None:
        if not self._header.get("primary", True):
            raise RBDError(
                f"image {self.name!r} is non-primary (mirror target)")

    def resize(self, new_size: int) -> None:
        self._check_writable()
        self._journal_event("resize", new_size)
        self._resize_apply(new_size)

    def _resize_apply(self, new_size: int) -> None:
        old = self._header["size"]
        self._header["size"] = new_size
        self._save_header()
        if new_size < old:
            # shrink: zero the dropped tail so a later grow reads zeros
            # (object-level trim left as future work)
            self._data.size = min(self._data.size, new_size)
            self._data._write_meta()

    # -- data ----------------------------------------------------------
    def write(self, offset: int, data: bytes) -> int:
        self._check_writable()
        if offset + len(data) > self._header["size"]:
            raise RBDError("write past end of image")
        self._journal_event("write", offset, bytes(data))
        self._data.write(data, offset=offset)
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        end = min(offset + length, self._header["size"])
        if end <= offset:
            return b""
        want = end - offset
        out = self._data.read(want, offset)
        # unwritten ranges read as zeros (sparse image semantics)
        return out + b"\x00" * (want - len(out))

    def discard(self, offset: int, length: int) -> None:
        self._check_writable()
        self._journal_event("discard", offset,
                            length.to_bytes(8, "little"))
        self._data.write(b"\x00" * length, offset=offset)

    # -- snapshots ------------------------------------------------------
    def _snap_prefix(self, snap: str) -> str:
        return f"rbd_snap.{self.name}@{snap}"

    def snap_list(self) -> list[str]:
        return sorted(self._header["snaps"])

    def snap_create(self, snap: str) -> None:
        self._check_writable()
        if snap in self._header["snaps"]:
            raise RBDError(f"snap {snap!r} exists")
        self._journal_event("snap_create", arg=snap)
        self._snap_create_apply(snap)

    def _snap_create_apply(self, snap: str) -> None:
        content = self._data.read()      # point-in-time copy
        so = StripedObject(self.io, self._snap_prefix(snap),
                           self._data.layout)
        if content:
            so.write(content)
        self._header["snaps"][snap] = {"size": self._header["size"]}
        self._save_header()

    def snap_rollback(self, snap: str) -> None:
        self._check_writable()
        if snap not in self._header["snaps"]:
            raise RBDError(f"no snap {snap!r}")
        self._journal_event("snap_rollback", arg=snap)
        self._snap_rollback_apply(snap)

    def _snap_rollback_apply(self, snap: str) -> None:
        so = StripedObject(self.io, self._snap_prefix(snap))
        content = so.read()
        self._data.remove()
        self._data = StripedObject(self.io, f"rbd_data.{self.name}",
                                   so.layout)
        if content:
            self._data.write(content)
        self._header["size"] = self._header["snaps"][snap]["size"]
        self._save_header()

    def snap_remove(self, snap: str) -> None:
        self._check_writable()
        if snap not in self._header["snaps"]:
            raise RBDError(f"no snap {snap!r}")
        self._journal_event("snap_remove", arg=snap)
        self._snap_remove_apply(snap)

    def _snap_remove_apply(self, snap: str) -> None:
        StripedObject(self.io, self._snap_prefix(snap)).remove()
        del self._header["snaps"][snap]
        self._save_header()

    # -- replay-side application (rbd-mirror ImageReplayer) -------------
    def _apply_event(self, kind: str, offset: int, data: bytes,
                     arg: str) -> None:
        """Apply one journal event WITHOUT writability checks or
        re-journaling — the mirror target's replay path."""
        if kind == "write":
            self._data.write(data, offset=offset)
            if offset + len(data) > self._header["size"]:
                self._header["size"] = offset + len(data)
                self._save_header()
        elif kind == "discard":
            length = int.from_bytes(data, "little")
            self._data.write(b"\x00" * length, offset=offset)
        elif kind == "resize":
            self._resize_apply(offset)
        elif kind == "snap_create":
            if arg not in self._header["snaps"]:
                self._snap_create_apply(arg)
        elif kind == "snap_remove":
            if arg in self._header["snaps"]:
                self._snap_remove_apply(arg)
        elif kind == "snap_rollback":
            if arg in self._header["snaps"]:
                self._snap_rollback_apply(arg)
        else:
            raise RBDError(f"unknown journal event {kind!r}")
