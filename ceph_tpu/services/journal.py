"""journal — append-only event journal on RADOS (src/journal/ role).

Reference: src/journal/ (Journaler, JournalMetadata, ObjectRecorder):
librbd journaling appends every image mutation to a journal backed by
RADOS objects before applying it; rbd-mirror tails that journal from a
per-client commit position and replays onto the peer. This lite
version keeps the object model: entries are length-prefixed records
appended to chunk objects (``<name>.<chunk>``, SPLAY entries per chunk
— the object-set rotation of the reference), per-client commit
positions are tracked, and trim removes chunks every client has fully
committed.

Single-writer by default (the image holds the exclusive lock in the
reference; our writer is the opened primary image). The journal's
CONTROL PLANE — client registry, per-client commit positions, trim
floor — lives in the in-OSD ``journal`` object class on the
``<name>.cls`` metadata object (src/cls/journal/cls_journal.cc: the
client-side Journaler drives cls_journal, the reference's layering),
so registrations/commits/floor advances from any number of clients
mutate atomically under the PG lock; the writer's header ({entries})
stays a separate object, so appends never read-modify-write reader
state.

``multi_writer=True`` (the cephfs mdslog: several mounts journal
dirops concurrently) replaces the header read-modify-write with an
ATOMIC position allocator (cls numops counter — the in-OSD method
runs under the PG lock) plus the OSD's atomic byte-append into the
chunk object; records carry their own position, so interleaved
appends within a chunk need no ordering. A writer that dies between
allocating a position and appending its record leaves a HOLE, which
readers skip (an intent that was never durably journaled has, by
definition, not executed any step yet — there is nothing to replay).
"""

from __future__ import annotations

import json

from ceph_tpu.utils.encoding import Decoder, Encoder

#: entries per chunk object (object-set rotation granularity)
SPLAY = 64


class JournalError(Exception):
    pass


class JournalTrimmedError(JournalError):
    """The requested position was trimmed away — the events are gone
    for good (distinct from a transient read failure, which a reader
    must NOT treat as end-of-journal)."""


class Journaler:
    def __init__(self, ioctx, name: str,
                 multi_writer: bool = False) -> None:
        self.io = ioctx
        self.name = name
        self.multi_writer = multi_writer
        self.header_oid = f"journal.{name}"
        # per-instance caches (each client id is single-writer for its
        # own position, so commit() need not re-read the registry and
        # position objects on every call — three round trips saved per
        # image mutation)
        self._registered: set[str] = set()
        self._commit_cache: dict[str, int] = {}
        self._seq_seeded = False
        #: legacy-format probe runs at most once per instance
        self._legacy_checked = False
        from ceph_tpu.analysis.lock_witness import make_lock
        self._append_lock = make_lock("journal.append")

    # -- header --------------------------------------------------------
    def _load(self) -> dict:
        try:
            return json.loads(self.io.read(self.header_oid))
        except Exception:
            raise JournalError(f"no journal {self.name!r}") from None

    def _save(self, h: dict) -> None:
        self.io.write_full(self.header_oid,
                           json.dumps(h, sort_keys=True).encode())

    @property
    def _meta_oid(self) -> str:
        """The cls_journal metadata object (client registry + commit
        positions + trim floor, all mutated by in-OSD ``journal``
        class methods — the reference's Journaler/cls_journal
        layering, src/cls/journal/cls_journal.cc)."""
        return f"{self.header_oid}.cls"

    def _cls_meta(self) -> dict:
        """{"clients": {id: pos}, "minimum": n} from cls_journal.
        First touch of a journal written by the PREVIOUS format
        (registry log + per-client position objects + trim-floor
        object) migrates that state into the cls meta object — a
        replayer must resume from its real position, not restart at 0
        below an already-trimmed floor."""
        from ceph_tpu.client.rados import RadosError
        try:
            out = self.io.execute(self._meta_oid, "journal",
                                  "client_list", b"")
            meta = json.loads(out)
        except RadosError:
            meta = {"clients": {}, "minimum": 0}
        if not self._legacy_checked and not meta["clients"] and \
                not meta.get("minimum"):
            self._legacy_checked = True    # probe once per instance
            legacy = self._migrate_legacy()
            if legacy is not None:
                return legacy
        return meta

    def _migrate_legacy(self) -> dict | None:
        """One-shot import of pre-cls journal control state; returns
        the migrated view, or None when there is nothing legacy.
        ONLY a definitive -ENOENT counts as absent — a transient read
        error must surface rather than silently commit position 0 and
        delete the real one (the read_from contract)."""
        from ceph_tpu.client.rados import RadosError
        legacy_reg = f"{self.header_oid}.clients"
        legacy_trim = f"{self.header_oid}.trimmed"

        def read_or_absent(fn):
            try:
                return fn()
            except RadosError as exc:
                if exc.code == -2:
                    return None
                raise

        raw = read_or_absent(
            lambda: self.io.execute(legacy_reg, "log", "list", b""))
        entries = json.loads(raw) if raw else []
        raw = read_or_absent(lambda: self.io.read(legacy_trim))
        floor = int.from_bytes(raw, "little") if raw else 0
        if not entries and not floor:
            return None
        seen, retired = [], set()
        for entry in entries:
            cid = entry.get("data", "") if isinstance(entry, dict) \
                else str(entry)
            if cid.startswith("retired/"):
                retired.add(cid[len("retired/"):])
            elif cid and cid not in seen:
                seen.append(cid)
        clients = {}
        for cid in seen:
            if cid in retired:
                continue
            raw = read_or_absent(lambda c=cid: self.io.read(
                f"{self.header_oid}.client.{c}"))
            clients[cid] = int.from_bytes(raw, "little") if raw else 0

        def register(cid):
            # a concurrent migrator may have won (and possibly
            # already retired the id): -EEXIST means its view stands
            try:
                self.io.execute(self._meta_oid, "journal",
                                "client_register",
                                json.dumps({"id": cid}).encode())
                return True
            except RadosError as exc:
                if exc.code == -17:
                    return False
                raise

        for cid, pos in clients.items():
            if register(cid) and pos:
                self.io.execute(self._meta_oid, "journal",
                                "client_commit",
                                json.dumps({"id": cid,
                                            "pos": pos}).encode())
        for cid in retired:
            if register(cid):
                self.io.execute(self._meta_oid, "journal",
                                "client_unregister",
                                json.dumps({"id": cid}).encode())
        if floor:
            self.io.execute(self._meta_oid, "journal", "set_minimum",
                            json.dumps({"pos": floor}).encode())
        # retire the legacy objects so the migration never re-runs
        for oid in [legacy_reg, legacy_trim] + \
                [f"{self.header_oid}.client.{c}" for c in seen]:
            try:
                self.io.remove(oid)
            except Exception:
                pass
        return {"clients": clients, "minimum": floor}

    def _trimmed_to(self) -> int:
        return int(self._cls_meta().get("minimum", 0))

    def trim_floor(self) -> int:
        """Lowest position still readable (positions below were
        reclaimed): the replay start for a reader with no committed
        position of its own."""
        return self._trimmed_to()

    def create(self) -> None:
        self._save({"entries": 0})

    def exists(self) -> bool:
        try:
            self._load()
            return True
        except JournalError:
            return False

    def remove(self) -> None:
        self._load()
        end = self.end_position()
        for chunk in range(self._trimmed_to() // SPLAY,
                           -(-end // SPLAY) + 1):
            try:
                self.io.remove(self._chunk_oid(chunk))
            except Exception:
                pass
        for oid in (self._meta_oid, self._seq_oid,
                    f"{self.header_oid}.clients",
                    f"{self.header_oid}.trimmed"):
            try:
                self.io.remove(oid)
            except Exception:
                pass
        self.io.remove(self.header_oid)

    def _chunk_oid(self, chunk: int) -> str:
        return f"{self.header_oid}.{chunk:08x}"

    @property
    def _seq_oid(self) -> str:
        return f"{self.header_oid}.seq"

    # -- writer --------------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Append one entry; returns its position.

        Single-writer mode: the entry is durable (RADOS-committed)
        before the header advances, so a reader never sees a position
        without its entry; serialized per INSTANCE (the header advance
        is a read-modify-write; concurrent in-process writers — dirops
        run from many threads — would assign the same position and
        lose entries).

        Multi-writer mode: position from the atomic cls counter, then
        an OSD-atomic append; safe from any number of mounts."""
        if self.multi_writer:
            pos = self._alloc_pos()
            e = Encoder()
            e.u64(pos)
            e.bytes(payload)
            self.io.append(self._chunk_oid(pos // SPLAY), e.getvalue())
            return pos
        with self._append_lock:
            h = self._load()
            pos = h["entries"]
            e = Encoder()
            e.u64(pos)
            e.bytes(payload)
            self.io.append(self._chunk_oid(pos // SPLAY), e.getvalue())
            h["entries"] = pos + 1
            self._save(h)
            return pos

    def _alloc_pos(self) -> int:
        """Atomically allocate the next multi-writer position. A
        journal UPGRADED from single-writer mode has entries 0..N-1
        under the header counter and no seq object yet: the first
        allocation seeds the seq PAST the header count (value=N+1 in
        one atomic add), so new positions can never collide with
        legacy records. Two mounts racing the seed both add N+1 —
        that leaves a hole (tolerated), never a collision."""
        bump = 1
        if not self._seq_seeded:
            try:
                json.loads(self.io.read(self._seq_oid))
                self._seq_seeded = True
            except Exception:
                try:
                    bump = self._load()["entries"] + 1
                except JournalError:
                    bump = 1
        out = self.io.execute(
            self._seq_oid, "numops", "add",
            json.dumps({"key": "seq", "value": bump}).encode())
        self._seq_seeded = True
        return int(json.loads(out)["seq"]) - 1

    def end_position(self) -> int:
        if self.multi_writer:
            try:
                st = json.loads(self.io.read(self._seq_oid))
                return int(st.get("seq", 0))
            except Exception:
                # pre-upgrade journal: no seq object yet — the legacy
                # header count still bounds the replayable entries
                try:
                    return self._load()["entries"]
                except JournalError:
                    return 0
        return self._load()["entries"]

    # -- readers -------------------------------------------------------
    def read_from(self, pos: int):
        """Yield (position, payload) for every entry >= pos, in order.

        Raises JournalTrimmedError when ``pos`` is below the trim
        floor, and JournalError when a chunk below ``end`` cannot be
        read — a transient failure must surface, not silently end the
        stream (a replayer that mistook it for end-of-journal would
        advance its commit position past events it never applied)."""
        self._load()                       # journal-exists check
        end = self.end_position()
        floor = self._trimmed_to()
        if pos < floor:
            raise JournalTrimmedError(
                f"position {pos} already trimmed (floor {floor})")
        chunk = pos // SPLAY
        while chunk * SPLAY < end:
            try:
                raw = self.io.read(self._chunk_oid(chunk))
            except Exception as exc:
                if self.multi_writer and \
                        getattr(exc, "code", None) == -2:
                    # hole chunk: a writer allocated into it but died
                    # before appending — nothing journaled, nothing
                    # to replay
                    chunk += 1
                    continue
                raise JournalError(
                    f"journal chunk {chunk} unreadable: {exc}") \
                    from exc
            entries = []
            d = Decoder(raw)
            while not d.eof():
                epos = d.u64()
                payload = d.bytes()
                if pos <= epos < end:
                    entries.append((epos, payload))
            # multi-writer appends land in allocation order only
            # per-writer; replay order must be global position order
            yield from sorted(entries)
            chunk += 1

    # -- commit positions / trim ---------------------------------------
    def commit(self, client: str, pos: int) -> None:
        """Advance (monotonically) this client's commit position via
        cls_journal — the register + commit run as in-OSD methods
        under the PG lock (client_register once per client, then
        client_commit per advance; the server enforces monotonicity
        too)."""
        from ceph_tpu.client.rados import RadosError
        if client not in self._registered:
            # a journal whose FIRST control-plane touch is a commit
            # must still import legacy-format state before the
            # register seeds the cls meta (or the old positions and
            # trim floor would be silently abandoned)
            if not self._legacy_checked:
                self._cls_meta()
            try:
                self.io.execute(
                    self._meta_oid, "journal", "client_register",
                    json.dumps({"id": client}).encode())
            except RadosError as exc:
                if exc.code != -17:
                    raise               # -EEXIST = retired tombstone:
                # a resurrected id must not re-pin trim — surface it
                raise JournalError(
                    f"journal client {client!r} was retired") from None
            self._registered.add(client)
        prev = self._commit_cache.get(client)
        if prev is not None and pos <= prev:
            return                      # the server would no-op too
        try:
            self.io.execute(self._meta_oid, "journal",
                            "client_commit",
                            json.dumps({"id": client,
                                        "pos": pos}).encode())
        except RadosError as exc:
            if exc.code == -2:
                # retired out from under our local register cache
                self._registered.discard(client)
                raise JournalError(
                    f"journal client {client!r} was retired") from None
            raise
        self._commit_cache[client] = max(pos, prev or 0)

    def retire(self, client: str) -> None:
        """Deregister a client for good (clean unmount / session
        eviction role): its position no longer pins trim(). The
        tombstone lives in the cls metadata, so a concurrent
        registration cannot resurrect it."""
        from ceph_tpu.client.rados import RadosError
        try:
            self.io.execute(self._meta_oid, "journal",
                            "client_unregister",
                            json.dumps({"id": client}).encode())
        except RadosError:
            pass                        # unknown id: nothing pins
        self._registered.discard(client)
        self._commit_cache.pop(client, None)

    def committed(self, client: str) -> int:
        return int(self._cls_meta()["clients"].get(client, 0))

    def clients(self) -> dict[str, int]:
        return {c: int(p)
                for c, p in self._cls_meta()["clients"].items()}

    def trim(self) -> int:
        """Remove chunk objects every registered client has fully
        consumed; returns the new floor position. The floor advance is
        a cls_journal set_minimum (monotonic in-OSD). Single trimmer
        by design (the mirror daemon)."""
        meta = self._cls_meta()
        clients = meta["clients"]
        trimmed = int(meta.get("minimum", 0))
        if not clients:
            return trimmed
        floor = min(int(p) for p in clients.values())
        new_floor_chunk = floor // SPLAY
        for chunk in range(trimmed // SPLAY, new_floor_chunk):
            try:
                self.io.remove(self._chunk_oid(chunk))
            except Exception:
                pass
        new_floor = new_floor_chunk * SPLAY
        if new_floor > trimmed:
            self.io.execute(self._meta_oid, "journal", "set_minimum",
                            json.dumps({"pos": new_floor}).encode())
        return max(new_floor, trimmed)
