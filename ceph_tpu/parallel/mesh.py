"""Device-mesh helpers for the sharded EC pipeline.

Mesh axes (the storage analog of DP/TP/SP — SURVEY.md §2.3 parallelism map):

- ``stripe``: data-parallel over stripe batches (the reference's per-stripe
  loop, ECUtil.cc:136-148, becomes this leading dimension);
- ``shard``:  parallel over the chunk byte dimension *and* the home axis for
  chunk placement collectives (the storage twin of tensor parallelism —
  one EC shard per OSD, doc/dev/osd_internals/erasure_coding/ecbackend.rst).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, stripe: int | None = None,
              shard: int | None = None, devices=None,
              chunk_count: int | None = None) -> Mesh:
    """Build a 2D ('stripe', 'shard') mesh over the first n devices.

    Default factorization: shard axis as large as possible up to the
    codec profile's chunk count when one is known (``chunk_count`` =
    k+m — the flagship k=8,m=3 profile wants all 8+ chips on the
    byte/shard axis, which a hardcoded cap of 4 denied it), else up
    to 4 (the historical small-EC-group default), remainder to
    stripe. The factorization choice is pinned in test_parallel.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if stripe is None or shard is None:
        cap = chunk_count if chunk_count else 4
        shard = shard or _largest_factor_leq(n_devices, cap)
        stripe = stripe or n_devices // shard
    assert stripe * shard == n_devices, (stripe, shard, n_devices)
    arr = np.array(devices).reshape(stripe, shard)
    return Mesh(arr, axis_names=("stripe", "shard"))


def _largest_factor_leq(n: int, cap: int) -> int:
    for f in range(min(cap, n), 0, -1):
        if n % f == 0:
            return f
    return 1


#: process-wide default mesh: when set, the OSD's device engine routes
#: stripe-batch flushes through the sharded encode step
#: (parallel/sharded_codec.py) instead of the single-chip kernel —
#: the multi-chip deployment switch (dryrun/tests set it; a pod
#: deployment sets it at daemon start)
_default_mesh: Mesh | None = None


def set_default_mesh(mesh: Mesh | None) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Mesh | None:
    return _default_mesh
