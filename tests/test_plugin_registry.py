"""Plugin registry loading + failure-mode tests.

Mirrors src/test/erasure-code/TestErasureCodePlugin.cc and its purpose-built
broken plugins (ErasureCodePluginFailToInitialize.cc, …FailToRegister.cc,
…MissingEntryPoint.cc, …MissingVersion.cc).
"""

import textwrap
import threading

import pytest

from ceph_tpu.models.registry import (
    ErasureCodePluginRegistry,
    PluginLoadError,
    PLUGIN_VERSION,
)


@pytest.fixture()
def registry():
    return ErasureCodePluginRegistry()  # fresh, not the singleton


def _write_plugin(tmp_path, name, body):
    (tmp_path / f"ec_{name}.py").write_text(textwrap.dedent(body))
    return str(tmp_path)


def test_load_builtin(registry):
    plugin = registry.load("example")
    codec = plugin.factory({"k": "2", "m": "1"})
    assert codec.get_chunk_count() == 3


def test_factory_end_to_end(registry):
    codec = registry.factory("jerasure", {"k": "4", "m": "2"})
    assert codec.get_chunk_count() == 6


def test_unknown_plugin(registry):
    with pytest.raises(PluginLoadError):
        registry.load("no_such_plugin")


def test_missing_version(registry, tmp_path):
    d = _write_plugin(tmp_path, "nover", """
        def __erasure_code_init__(name, registry):
            pass
    """)
    with pytest.raises(PluginLoadError, match="version"):
        registry.load("nover", d)


def test_version_mismatch(registry, tmp_path):
    d = _write_plugin(tmp_path, "badver", """
        __erasure_code_version__ = "something-else"
        def __erasure_code_init__(name, registry):
            pass
    """)
    with pytest.raises(PluginLoadError, match="version"):
        registry.load("badver", d)


def test_missing_entry_point(registry, tmp_path):
    d = _write_plugin(tmp_path, "noentry", f"""
        __erasure_code_version__ = {PLUGIN_VERSION!r}
    """)
    with pytest.raises(PluginLoadError, match="entry point"):
        registry.load("noentry", d)


def test_fail_to_initialize(registry, tmp_path):
    d = _write_plugin(tmp_path, "failinit", f"""
        __erasure_code_version__ = {PLUGIN_VERSION!r}
        def __erasure_code_init__(name, registry):
            raise RuntimeError("boom")
    """)
    with pytest.raises(PluginLoadError, match="init failed"):
        registry.load("failinit", d)


def test_fail_to_register(registry, tmp_path):
    d = _write_plugin(tmp_path, "noreg", f"""
        __erasure_code_version__ = {PLUGIN_VERSION!r}
        def __erasure_code_init__(name, registry):
            pass  # forgets to register
    """)
    with pytest.raises(PluginLoadError, match="did not register"):
        registry.load("noreg", d)


def test_missing_file(registry, tmp_path):
    with pytest.raises(PluginLoadError, match="no plugin file"):
        registry.load("ghost", str(tmp_path))


def test_double_register(registry):
    registry.load("example")
    with pytest.raises(PluginLoadError, match="already registered"):
        registry.load("example", None) if False else registry.add(
            "example", registry.get("example"))


def test_preload(registry):
    registry.preload(["example", "jerasure", "isa"])
    for name in ("example", "jerasure", "isa"):
        assert registry.get(name) is not None


def test_concurrent_load(registry):
    """Thread-safety of load (reference guards with a Mutex +
    ceph_assert(lock.is_locked()), ErasureCodePlugin.cc:62,131)."""
    errors = []

    def worker():
        try:
            registry.load("jerasure")
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert registry.get("jerasure") is not None
