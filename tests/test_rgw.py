"""rgw-lite object gateway (src/rgw role, reduced): bucket index via
the in-OSD rgw class, striped object data, S3-path-shaped HTTP."""

import json
import os
import urllib.error
import urllib.request

import pytest

from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.services.rgw import RGWError, RGWGateway, RGWServer


@pytest.fixture(scope="module")
def setup():
    with MiniCluster(n_osds=3) as c:
        rados = c.client()
        c.create_pool("rgwpool", pg_num=4, size=2)
        io = rados.open_ioctx("rgwpool")
        srv = RGWServer(io)
        port = srv.start()
        yield io, srv.gateway, f"http://127.0.0.1:{port}"
        srv.stop()


def test_gateway_api(setup):
    io, gw, _ = setup
    gw.create_bucket("photos")
    gw.create_bucket("photos")          # idempotent
    assert "photos" in gw.list_buckets()
    data = os.urandom(3 << 20)          # striped (3 pieces)
    etag = gw.put_object("photos", "a/b.jpg", data)
    got, meta = gw.get_object("photos", "a/b.jpg")
    assert got == data and meta["etag"] == etag
    assert meta["size"] == len(data)
    gw.put_object("photos", "a/c.jpg", b"tiny")
    gw.put_object("photos", "z.txt", b"zzz")
    assert sorted(gw.list_objects("photos")) == \
        ["a/b.jpg", "a/c.jpg", "z.txt"]
    assert sorted(gw.list_objects("photos", prefix="a/")) == \
        ["a/b.jpg", "a/c.jpg"]
    with pytest.raises(RGWError):
        gw.delete_bucket("photos")      # not empty
    gw.delete_object("photos", "a/b.jpg")
    with pytest.raises(RGWError):
        gw.get_object("photos", "a/b.jpg")
    gw.delete_object("photos", "a/c.jpg")
    gw.delete_object("photos", "z.txt")
    gw.delete_bucket("photos")
    assert "photos" not in gw.list_buckets()


def _req(url, method="GET", data=None):
    req = urllib.request.Request(url, data=data, method=method)
    return urllib.request.urlopen(req, timeout=10)


def test_http_s3_path_flow(setup):
    _, _, base = setup
    _req(f"{base}/webdata", "PUT")
    body = os.urandom(100_000)
    r = _req(f"{base}/webdata/docs/readme.bin", "PUT", data=body)
    etag = r.headers["ETag"]
    # bucket listing
    listing = json.loads(_req(f"{base}/webdata").read())
    assert "docs/readme.bin" in listing["objects"]
    # root listing
    assert "webdata" in json.loads(_req(base + "/").read())["buckets"]
    # GET round trip + etag
    r = _req(f"{base}/webdata/docs/readme.bin")
    assert r.read() == body and r.headers["ETag"] == etag
    # HEAD
    r = _req(f"{base}/webdata/docs/readme.bin", "HEAD")
    assert int(r.headers["Content-Length"]) == len(body)
    # 404s
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(f"{base}/webdata/missing")
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(f"{base}/nobucket/x")
    assert ei.value.code == 404
    # delete object then bucket
    _req(f"{base}/webdata/docs/readme.bin", "DELETE")
    _req(f"{base}/webdata", "DELETE")
    with pytest.raises(urllib.error.HTTPError):
        _req(f"{base}/webdata")
