"""Versioned binary wire encoding — the src/include/encoding.h role.

The reference serializes every map/message/txn with ENCODE_START /
ENCODE_FINISH versioned sections and little-endian primitive encoders.
Same contract here: explicit little-endian primitives, length-prefixed
bytes/str, and versioned sections that let a decoder skip trailing
fields added by newer encoders (forward/backward compatibility —
encoding.h's compat_version semantics).

No pickle anywhere: wire bytes are data, never code.
"""

from __future__ import annotations

import struct


class Encoder:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    # primitives (little-endian, like encoding.h)
    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v)); return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<H", v)); return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v)); return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v)); return self

    def i32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<i", v)); return self

    def i64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<q", v)); return self

    def f64(self, v: float) -> "Encoder":
        self._parts.append(struct.pack("<d", v)); return self

    def bool(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def bytes(self, v: bytes) -> "Encoder":
        self.u32(len(v)); self._parts.append(bytes(v)); return self

    def str(self, v: str) -> "Encoder":
        return self.bytes(v.encode())

    def list(self, vals, item_fn) -> "Encoder":
        self.u32(len(vals))
        for v in vals:
            item_fn(self, v)
        return self

    def map(self, d: dict, key_fn, val_fn) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            key_fn(self, k)
            val_fn(self, d[k])
        return self

    def str_map(self, d: dict) -> "Encoder":
        return self.map(d, Encoder.str, Encoder.str)

    def section(self, version: int, body: "Encoder",
                compat: int = 1) -> "Encoder":
        """ENCODE_START(version, compat, ...) ... ENCODE_FINISH:
        version + compat bytes + length-prefixed body. ``compat`` is the
        oldest decoder version able to read this encoding; decoders skip
        trailing bytes they don't parse."""
        payload = body.getvalue()
        self.u8(version)
        self.u8(compat)
        self.bytes(payload)
        return self

    # -- scatter-gather surface (ROADMAP 1c) --------------------------
    def raw(self, v: bytes) -> "Encoder":
        """Append pre-encoded bytes as their own part, by reference:
        ``getparts`` hands it through uncopied (length prefixes are
        the caller's job — pair with an explicit ``u32``)."""
        self._parts.append(v)
        return self

    def getparts(self) -> list[bytes]:
        """The encoded buffers WITHOUT the final join — the sendmsg-
        style scatter list whose concatenation == ``getvalue()``."""
        return list(self._parts)

    def nbytes(self) -> int:
        return sum(len(p) for p in self._parts)

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class DecodeError(Exception):
    pass


class Decoder:
    def __init__(self, buf: bytes, off: int = 0) -> None:
        self._buf = buf
        self._off = off

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._buf):
            raise DecodeError(
                f"short buffer: need {n} at {self._off}, have {len(self._buf)}")
        v = self._buf[self._off:self._off + n]
        self._off += n
        return v

    def u8(self) -> int: return struct.unpack("<B", self._take(1))[0]
    def u16(self) -> int: return struct.unpack("<H", self._take(2))[0]
    def u32(self) -> int: return struct.unpack("<I", self._take(4))[0]
    def u64(self) -> int: return struct.unpack("<Q", self._take(8))[0]
    def i32(self) -> int: return struct.unpack("<i", self._take(4))[0]
    def i64(self) -> int: return struct.unpack("<q", self._take(8))[0]
    def f64(self) -> float: return struct.unpack("<d", self._take(8))[0]
    def bool(self) -> bool: return self.u8() != 0

    def bytes(self) -> bytes:
        return self._take(self.u32())

    def str(self) -> str:
        return self.bytes().decode()

    def list(self, item_fn) -> list:
        return [item_fn(self) for _ in range(self.u32())]

    def map(self, key_fn, val_fn) -> dict:
        n = self.u32()
        return {key_fn(self): val_fn(self) for _ in range(n)}

    def str_map(self) -> dict:
        return self.map(Decoder.str, Decoder.str)

    def section(self, max_supported: int) -> tuple[int, "Decoder"]:
        """DECODE_START: returns (version, sub-decoder over the section
        body). A newer encoding is readable as long as its ``compat``
        floor is within what this reader supports (the known field
        prefix decodes; unknown trailing bytes are skipped). Raises
        DecodeError when the encoder declared itself incompatible."""
        version = self.u8()
        compat = self.u8()
        body = self.bytes()
        if compat > max_supported:
            raise DecodeError(
                f"encoding v{version} requires decoder >= v{compat}, "
                f"this reader supports <= v{max_supported}")
        return version, Decoder(body)

    def remaining(self) -> int:
        return len(self._buf) - self._off

    def eof(self) -> bool:
        return self._off >= len(self._buf)
