"""OSDMap + wire-encoding tests (reference: src/osd/OSDMap, encoding.h)."""

from ceph_tpu.parallel import crush
from ceph_tpu.parallel.osdmap import OSDMap
from ceph_tpu.utils.encoding import Decoder, Encoder


def test_encoder_roundtrip_primitives():
    e = Encoder()
    e.u8(7).u16(65535).u32(123456).u64(1 << 40).i32(-5).i64(-(1 << 40))
    e.f64(3.5).bool(True).bytes(b"\x00\x01").str("héllo")
    e.list([1, 2, 3], Encoder.u32)
    e.str_map({"a": "1", "b": "2"})
    d = Decoder(e.getvalue())
    assert d.u8() == 7 and d.u16() == 65535 and d.u32() == 123456
    assert d.u64() == 1 << 40 and d.i32() == -5 and d.i64() == -(1 << 40)
    assert d.f64() == 3.5 and d.bool() is True
    assert d.bytes() == b"\x00\x01" and d.str() == "héllo"
    assert d.list(Decoder.u32) == [1, 2, 3]
    assert d.str_map() == {"a": "1", "b": "2"}
    assert d.eof()


def test_versioned_section_skips_unknown_tail():
    inner = Encoder()
    inner.u32(42).str("future-field")
    e = Encoder()
    e.section(3, inner, compat=1)  # newer encoding, old readers OK
    e.u32(99)  # data after the section
    d = Decoder(e.getvalue())
    ver, body = d.section(max_supported=1)
    assert ver == 3
    assert body.u32() == 42  # known prefix decodes
    assert d.u32() == 99     # outer stream not corrupted by unread tail


def test_versioned_section_compat_floor_rejected():
    import pytest
    from ceph_tpu.utils.encoding import DecodeError
    e = Encoder()
    e.section(5, Encoder().u32(1), compat=4)  # needs a v4+ reader
    with pytest.raises(DecodeError):
        Decoder(e.getvalue()).section(max_supported=3)


def make_map(n_osds=6):
    m = OSDMap()
    m.crush = crush.build_flat_map(n_osds)
    for o in range(n_osds):
        m.add_osd(o, addr=f"127.0.0.1:{6800 + o}")
        m.mark_up(o, f"127.0.0.1:{6800 + o}")
    m.create_pool("ecpool", pg_num=8, rule="data", size=5, min_size=4,
                  ec_profile={"plugin": "jerasure", "k": "4", "m": "1"})
    return m


def test_object_mapping_deterministic_and_in_range():
    m = make_map()
    pid = m.pool_by_name["ecpool"]
    ps, acting, primary = m.object_locator(pid, "obj-1")
    assert 0 <= ps < 8
    assert len(acting) == 5
    assert primary == acting[0]
    assert m.object_locator(pid, "obj-1") == (ps, acting, primary)


def test_mark_down_changes_mapping_and_epoch_is_manual():
    m = make_map()
    pid = m.pool_by_name["ecpool"]
    locs = {n: m.object_locator(pid, f"o{n}") for n in range(50)}
    m.mark_down(2)
    for n, (ps, acting, primary) in locs.items():
        ps2, acting2, primary2 = m.object_locator(pid, f"o{n}")
        assert 2 not in acting2
        if 2 not in acting:
            assert (ps2, acting2) == (ps, acting)


def test_pg_temp_overrides_acting():
    m = make_map()
    pid = m.pool_by_name["ecpool"]
    ps, acting, _ = m.object_locator(pid, "x")
    override = list(reversed(acting))
    m.pg_temp[(pid, ps)] = override
    _, acting2, primary2 = m.pg_to_up_acting(pid, ps)
    assert acting2 == override
    assert primary2 == override[0]


def test_osdmap_encode_decode_roundtrip():
    m = make_map()
    m.epoch = 17
    m.mark_down(1)
    m.mark_out(3)
    pid = m.pool_by_name["ecpool"]
    m.pg_temp[(pid, 2)] = [4, 5, 0, crush.NONE, 2]
    m2 = OSDMap.decode(m.encode())
    assert m2.epoch == 17
    assert m2.osds[1].up is False and m2.osds[3].in_cluster is False
    assert m2.pools[pid].ec_profile["plugin"] == "jerasure"
    assert m2.pg_temp[(pid, 2)] == [4, 5, 0, crush.NONE, 2]
    # mappings must be identical through the wire
    for n in range(30):
        assert m.object_locator(pid, f"w{n}") == m2.object_locator(pid, f"w{n}")
