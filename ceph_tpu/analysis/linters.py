"""Codebase-specific AST lints (ISSUE 11, half 2).

Four checker families over the ``ceph_tpu`` package source — each one
the *static twin* of a runtime contract this repo already gates:

1. **wire symmetry** — every message class in ``parallel/messages.py``
   must encode and decode the same field sequence in the same order.
   The schema-generated path (``FIELDS`` drives both directions) is
   symmetric by construction; the lint pins the schema well-formedness
   (known kinds, unique names, unique MSG_TYPE) and polices manual
   ``encode_payload``/``decode_payload`` overrides: both or neither,
   identical field order, tail-tolerant decode (the appended-optional
   ``stages``/``trace`` pattern).

2. **jit hygiene** — inside ``@jax.jit``/Pallas-wrapped functions in
   ``ops/``/``models/``/``parallel/``: Python ``if``/``while`` on
   traced values, ``int()``/``float()``/``bool()``/``.item()`` host
   coercions of traced values, ``np.asarray`` host pulls, and
   closure-captured device arrays — the static twin of
   device_telemetry's runtime ``recompiles`` counter (the shape-leak
   class PR 2 can only detect after it fires).

3. **registry drift** — every PerfCounters key *updated* must be
   registered and vice versa (static twin of test_counter_schema's
   exporter lints); every ``g_conf`` key read must be a declared
   Option; every ``asok_command`` invocation must name a prefix some
   daemon registers.

4. **lock discipline** — in classes that own a ``_lock``, methods
   mutating attributes that are elsewhere accessed under that lock
   must themselves hold it.

5. **fsync seam** (ISSUE 14) — every durability barrier under
   ``ceph_tpu/store/`` must go through the named timed-fsync seam
   (``utils/store_telemetry.timed_fsync``/``timed_fdatasync``/
   ``timed_sync``): a direct ``os.fsync``/``os.fdatasync`` call is an
   unmeasured commit stall the commit-path X-ray cannot see — the
   exact blind spot this PR closed; future stores don't get to
   reopen it.

6. **reactor affinity** (ISSUE 18) — shared-nothing discipline for
   ``ceph_tpu/crimson/``: no module-global mutable state, no blocking
   ``time.sleep`` inside reactor coroutines, no raw ``threading``
   sync primitives outside the witnessed ``make_lock`` seam. The
   static twin of the runtime hop counters (``wq_continuation == 0``)
   and the lock witness.

7. **flow context** (ISSUE 20) — every enqueue seam accepting a
   ``qos=`` parameter must thread the per-tenant flow context
   (``capture_flow``/``current_flow``) across the handoff; one that
   doesn't silently drops the tenant label and erodes the >=95%%
   attribution coverage gate.

Findings diff against the justified allowlist in
``analysis/baseline.json``; any NEW finding (or a stale baseline
entry) fails ``tests/test_static_analysis.py`` in tier-1. Keys carry
no line numbers, so routine edits don't churn the baseline.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

#: field kinds the Encoder/Decoder tables support (mirrors the _ENC
#: table in parallel/messages.py; the checker prefers the table parsed
#: from the file itself when present)
DEFAULT_KINDS = frozenset((
    "u8", "u16", "u32", "u64", "i32", "i64", "f64", "bool", "str",
    "bytes", "str_map", "bytes_map", "i32_list", "u64_list",
    "str_list", "bytes_list"))

#: jit-hygiene scope (repo-relative directory prefixes)
JIT_DIRS = ("ceph_tpu/ops", "ceph_tpu/models", "ceph_tpu/parallel")

#: attribute reads that turn a traced value into static metadata
_STATIC_ATTRS = frozenset((
    "shape", "ndim", "dtype", "size", "itemsize", "nbytes", "sharding"))
#: calls whose result is static regardless of argument taint
_STATIC_CALLS = frozenset((
    "len", "isinstance", "type", "hasattr", "getattr", "id", "repr"))


@dataclass(frozen=True)
class Finding:
    checker: str
    path: str          # repo-relative
    line: int
    key: str           # stable id (no line numbers) for the baseline
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] " \
               f"{self.message}  ({self.key})"


class SourceFile:
    def __init__(self, path: str, text: str,
                 rel: str | None = None) -> None:
        self.path = path
        self.rel = rel or os.path.relpath(path, REPO_ROOT)
        self.text = text
        self.tree = ast.parse(text, filename=path)


def iter_sources(root: str = PKG_ROOT) -> list[SourceFile]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                out.append(SourceFile(path, text))
            except SyntaxError as exc:       # pragma: no cover
                raise RuntimeError(f"unparseable {path}: {exc}")
    return out


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:                        # pragma: no cover
        return "<expr>"


def _walk_in_order(node: ast.AST):
    """DFS in source order (ast.walk is BFS; order matters for the
    encode/decode sequence extraction)."""
    for child in ast.iter_child_nodes(node):
        yield child
        yield from _walk_in_order(child)


# ---------------------------------------------------------------------------
# 1. wire symmetry
# ---------------------------------------------------------------------------

def _literal_fields(node: ast.AST) -> list[tuple[str, str]] | None:
    """Parse a ``FIELDS = [(name, kind), ...]`` literal."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    out = []
    for elt in node.elts:
        if not (isinstance(elt, ast.Tuple) and len(elt.elts) == 2
                and all(isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        for e in elt.elts)):
            return None
        out.append((elt.elts[0].value, elt.elts[1].value))
    return out


def _self_attr_reads(fn: ast.FunctionDef, names: set[str]) -> list[str]:
    """``self.X`` loads in source order, X restricted to ``names``."""
    out = []
    for node in _walk_in_order(fn):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in names:
            out.append(node.attr)
    return out


def _attr_stores(fn: ast.FunctionDef, names: set[str]) -> list[str]:
    """``<obj>.X = ...`` stores (plus ``setattr(obj, "X", ...)``) in
    source order, X restricted to ``names``."""
    out = []
    for node in _walk_in_order(fn):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Store) and node.attr in names:
            out.append(node.attr)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "setattr" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                node.args[1].value in names:
            out.append(node.args[1].value)
    return out


def check_wire_symmetry(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    kinds = set(DEFAULT_KINDS)
    # prefer the module's own _ENC table as ground truth
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "_ENC"
                    for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            parsed = {k.value for k in node.value.keys
                      if isinstance(k, ast.Constant)}
            if parsed:
                kinds = parsed

    msg_types: dict[int, str] = {}
    for cls in src.tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        fields = None
        mtype = None
        encode_fn = decode_fn = None
        for item in cls.body:
            if isinstance(item, ast.Assign):
                for t in item.targets:
                    if isinstance(t, ast.Name) and t.id == "FIELDS":
                        fields = _literal_fields(item.value)
                    elif isinstance(t, ast.Name) and t.id == "MSG_TYPE" \
                            and isinstance(item.value, ast.Constant):
                        mtype = item.value.value
            elif isinstance(item, ast.FunctionDef):
                if item.name == "encode_payload":
                    encode_fn = item
                elif item.name == "decode_payload":
                    decode_fn = item
        if fields is None and mtype is None:
            continue

        def add(code: str, message: str, line: int = cls.lineno):
            findings.append(Finding(
                "wire_symmetry", src.rel, line,
                f"wire_symmetry:{src.rel}:{cls.name}:{code}", message))

        if fields:
            seen: set[str] = set()
            for name, kind in fields:
                if kind not in kinds:
                    add(f"unknown-kind:{name}",
                        f"{cls.name}.{name}: unknown wire kind "
                        f"{kind!r} (no encoder/decoder)")
                if name in seen:
                    add(f"dup-field:{name}",
                        f"{cls.name}: duplicate field {name!r}")
                seen.add(name)
        if isinstance(mtype, int) and mtype:
            if mtype in msg_types:
                add(f"dup-msg-type:{mtype}",
                    f"{cls.name}: MSG_TYPE {mtype} already used by "
                    f"{msg_types[mtype]}")
            else:
                msg_types[mtype] = cls.name

        if fields and (encode_fn or decode_fn):
            names = {n for n, _ in fields}
            if encode_fn is None or decode_fn is None:
                side = "encode_payload" if encode_fn else \
                    "decode_payload"
                add("override-asymmetry",
                    f"{cls.name}: overrides only {side} — the "
                    "generated twin no longer mirrors it")
            else:
                enc = _self_attr_reads(encode_fn, names)
                dec = _attr_stores(decode_fn, names)
                if enc != dec:
                    add("field-order-asymmetry",
                        f"{cls.name}: encode order {enc} != decode "
                        f"order {dec}")
                field_order = [n for n, _ in fields if n in set(enc)]
                if enc and enc != field_order:
                    add("encode-diverges-from-fields",
                        f"{cls.name}: encode order {enc} diverges "
                        f"from FIELDS order {field_order}")
                dec_src = ast.get_source_segment(
                    src.text, decode_fn) or ""
                if dec and "eof(" not in dec_src:
                    add("decode-not-tail-tolerant",
                        f"{cls.name}: custom decode_payload has no "
                        "eof() guard — appended-optional fields from "
                        "newer peers will not be tail-tolerated")
    return findings


# ---------------------------------------------------------------------------
# 2. jit hygiene
# ---------------------------------------------------------------------------

def _jit_static_argnames(dec: ast.AST) -> tuple[bool, set[str]]:
    """(is_jit_decorator, static_argnames) for one decorator node."""
    if isinstance(dec, ast.IfExp):      # `... if HAVE_JAX else (f->f)`
        return _jit_static_argnames(dec.body)
    target = dec
    statics: set[str] = set()
    if isinstance(dec, ast.Call):
        fname = _unparse(dec.func)
        if fname.endswith("partial") and dec.args:
            target = dec.args[0]
        for kw in dec.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                val = kw.value
                if isinstance(val, ast.Constant) and \
                        isinstance(val.value, str):
                    statics.add(val.value)
                elif isinstance(val, (ast.Tuple, ast.List)):
                    statics |= {e.value for e in val.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)}
        if target is dec:
            target = dec.func
    name = _unparse(target)
    is_jit = name == "jit" or name.endswith(".jit")
    return is_jit, statics


def _expr_tainted(node: ast.AST, tainted: set[str]) -> bool:
    """Does this expression carry a traced value? Static metadata
    accessors (shape/ndim/dtype/len/...) sanitize."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _STATIC_CALLS:
            return False
        parts = [fn.value] if isinstance(fn, ast.Attribute) else []
        parts += list(node.args) + [kw.value for kw in node.keywords]
        return any(_expr_tainted(p, tainted) for p in parts)
    if isinstance(node, ast.Subscript):
        return _expr_tainted(node.value, tainted)
    if isinstance(node, (ast.Constant, ast.Lambda)):
        return False
    return any(_expr_tainted(c, tainted)
               for c in ast.iter_child_nodes(node))


def _assigned_names(target: ast.AST) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    return []


def _check_jit_function(src: SourceFile, fn: ast.FunctionDef,
                        statics: set[str],
                        enclosing_arrayish: dict[str, int]
                        ) -> list[Finding]:
    findings: list[Finding] = []

    def add(code: str, message: str, line: int):
        findings.append(Finding(
            "jit_hygiene", src.rel, line,
            f"jit_hygiene:{src.rel}:{fn.name}:{code}", message))

    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs)]
    tainted: set[str] = {p for p in params
                         if p not in statics
                         and p not in ("self", "cls")}

    # taint propagation, two passes for loop-carried names
    for _pass in (0, 1):
        for node in _walk_in_order(fn):
            if isinstance(node, ast.Assign):
                t = _expr_tainted(node.value, tainted)
                for tgt in node.targets:
                    for name in _assigned_names(tgt):
                        if t:
                            tainted.add(name)
                        else:
                            tainted.discard(name)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                if _expr_tainted(node.value, tainted):
                    tainted.add(node.target.id)
            elif isinstance(node, ast.For):
                t = _expr_tainted(node.iter, tainted)
                for name in _assigned_names(node.target):
                    if t:
                        tainted.add(name)

    locals_assigned = set()
    for node in _walk_in_order(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                locals_assigned.update(_assigned_names(tgt))
        elif isinstance(node, (ast.For,)):
            locals_assigned.update(_assigned_names(node.target))

    for node in _walk_in_order(fn):
        if isinstance(node, (ast.If, ast.While)) and \
                _expr_tainted(node.test, tainted):
            snippet = _unparse(node.test)[:48]
            add(f"traced-branch:{snippet}",
                f"{fn.name}: Python "
                f"{'if' if isinstance(node, ast.If) else 'while'} on "
                f"traced value `{snippet}` — trace-time branch, "
                "recompiles per value or raises TracerBoolError",
                node.lineno)
        elif isinstance(node, ast.Call):
            cfn = node.func
            if isinstance(cfn, ast.Name) and \
                    cfn.id in ("int", "float", "bool") and node.args \
                    and _expr_tainted(node.args[0], tainted):
                add(f"traced-coercion:{cfn.id}:"
                    f"{_unparse(node.args[0])[:32]}",
                    f"{fn.name}: {cfn.id}() on traced value "
                    f"`{_unparse(node.args[0])[:48]}` forces a host "
                    "sync / ConcretizationTypeError under jit",
                    node.lineno)
            elif isinstance(cfn, ast.Attribute) and \
                    cfn.attr in ("item", "tolist") and \
                    not node.args and \
                    _expr_tainted(cfn.value, tainted):
                add(f"traced-coercion:{cfn.attr}:"
                    f"{_unparse(cfn.value)[:32]}",
                    f"{fn.name}: .{cfn.attr}() on traced value "
                    f"`{_unparse(cfn.value)[:48]}` — device barrier "
                    "inside a traced function", node.lineno)
            elif isinstance(cfn, ast.Attribute) and \
                    cfn.attr == "asarray" and \
                    isinstance(cfn.value, ast.Name) and \
                    cfn.value.id == "np" and node.args and \
                    _expr_tainted(node.args[0], tainted):
                add(f"host-pull:{_unparse(node.args[0])[:32]}",
                    f"{fn.name}: np.asarray on traced value "
                    f"`{_unparse(node.args[0])[:48]}` pulls the "
                    "array to host inside the trace", node.lineno)

    # closure-captured device arrays: free names assigned in an
    # enclosing function from jnp.*/device_put calls
    params_set = set(params)
    for node in _walk_in_order(fn):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id in enclosing_arrayish and \
                node.id not in params_set and \
                node.id not in locals_assigned:
            add(f"closure-device-array:{node.id}",
                f"{fn.name}: closure-captures device array "
                f"`{node.id}` (built at "
                f"line {enclosing_arrayish[node.id]}) — baked in as "
                "a constant; a new array identity per call "
                "recompiles (the shape-leak class)", node.lineno)
            break        # one per function is enough signal
    return findings


_ARRAYISH_CALLS = ("jnp.asarray", "jnp.array", "jnp.zeros", "jnp.ones",
                   "jax.device_put", "jnp.arange")


def _wrapped_callee_names(tree: ast.AST) -> set[str]:
    """Function names handed to a mesh compile wrapper — traced
    exactly like a decorated jit body, so the hygiene rules apply the
    same (ISSUE 12): positional args of ``*shard_map(...)`` calls,
    first args of ``jit(...)`` calls carrying in_/out_shardings, and
    the ``global_fn=``/``shard_fn=`` kwargs of the
    ``mesh_compile.compile_step`` seam."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _unparse(node.func)
        if fname == "shard_map" or fname.endswith("shard_map"):
            if node.args and isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
        elif fname == "jit" or fname.endswith(".jit"):
            if any(kw.arg in ("in_shardings", "out_shardings")
                   for kw in node.keywords) and node.args and \
                    isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
        elif fname.endswith("compile_step"):
            for kw in node.keywords:
                if kw.arg in ("global_fn", "shard_fn") and \
                        isinstance(kw.value, ast.Name):
                    out.add(kw.value.id)
    return out


def check_jit_hygiene(src: SourceFile) -> list[Finding]:
    if not any(src.rel.startswith(d + "/") or src.rel.startswith(d)
               for d in JIT_DIRS):
        return []
    findings: list[Finding] = []
    wrapped = _wrapped_callee_names(src.tree)

    def visit(node: ast.AST, arrayish: dict[str, int]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                statics: set[str] = set()
                is_jit = child.name in wrapped
                for dec in child.decorator_list:
                    j, s = _jit_static_argnames(dec)
                    if j:
                        is_jit = True
                        statics |= s
                if is_jit:
                    findings.extend(_check_jit_function(
                        src, child, statics, arrayish))
                # nested scope: record this function's arrayish
                # assignments for ITS children
                inner = dict(arrayish)
                for n in ast.walk(child):
                    if isinstance(n, ast.Assign) and \
                            isinstance(n.value, ast.Call):
                        fname = _unparse(n.value.func)
                        if fname in _ARRAYISH_CALLS:
                            for tgt in n.targets:
                                for name in _assigned_names(tgt):
                                    inner[name] = n.lineno
                visit(child, inner)
            elif isinstance(child, ast.ClassDef):
                visit(child, dict(arrayish))
            else:
                visit(child, arrayish)

    visit(src.tree, {})
    return findings


# ---------------------------------------------------------------------------
# 3. registry drift (counters / config / asok)
# ---------------------------------------------------------------------------

_COUNTER_REG = {"add_u64_counter": "u64", "add_gauge": "gauge",
                "add_time_avg": "time_avg", "add_histogram": "hist"}
#: update methods that are distinctive enough to always count
_COUNTER_USE_STRONG = ("ginc", "tinc", "hinc",
                       # the tuner's guarded-update seams (ISSUE 13:
                       # publish_perf=False engines skip counters,
                       # so every update routes through these)
                       "_count", "_count_gauge")
#: generic names counted only on perf-ish receivers ("logger" is the
#: reference's name for a PerfCounters instance)
_COUNTER_USE_WEAK = ("inc", "set_gauge", "time")
_PERF_RECV_HINTS = ("perf", "counter", "logger")


def _fstring_affix(node: ast.AST) -> tuple[str, str] | None:
    """(leading, trailing) constant parts of an f-string key — how
    dynamic registry keys (``f"faults_{kind}"``,
    ``f"{name}_tracing"``) still mark their key family as used."""
    if not isinstance(node, ast.JoinedStr) or not node.values:
        return None
    lead = node.values[0]
    trail = node.values[-1]
    prefix = lead.value if isinstance(lead, ast.Constant) and \
        isinstance(lead.value, str) else ""
    suffix = trail.value if isinstance(trail, ast.Constant) and \
        isinstance(trail.value, str) else ""
    if not prefix and not suffix:
        return None
    return (prefix, suffix)


def _affix_match(key: str, affixes: list[tuple[str, str]]) -> bool:
    return any(key.startswith(p) and key.endswith(s)
               for p, s in affixes)


class RegistryDrift:
    """Cross-file collector: feed every SourceFile through
    :meth:`collect`, then read :meth:`findings`."""

    def __init__(self) -> None:
        self.counters_registered: dict[str, tuple[str, int]] = {}
        self.counters_used: dict[str, tuple[str, int]] = {}
        self.options_declared: dict[str, tuple[str, int]] = {}
        self.options_read: dict[str, tuple[str, int]] = {}
        self.asok_registered: dict[str, tuple[str, int]] = {}
        self.asok_invoked: dict[str, tuple[str, int]] = {}
        #: options consumed through a config observer (ISSUE 13: the
        #: cached-read discipline tuner-managed knobs must follow)
        self.options_observed: dict[str, tuple[str, int]] = {}
        #: (prefix, suffix) families touched via f-string keys
        self.counter_affixes: list[tuple[str, str]] = []
        self.option_affixes: list[tuple[str, str]] = []
        #: knobs named by tuner policy Rules (ROADMAP 3 read-path
        #: widening): every rule's actuator must be a registered
        #: TUNER_KNOBS entry, or its firings silently step nothing
        self.rule_knobs: dict[str, tuple[str, int]] = {}

    # -- collection ----------------------------------------------------
    def collect(self, src: SourceFile) -> None:
        conf_aliases = {"conf", "cfg", "_conf", "_g_conf"}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _unparse(node.value.func).endswith("g_conf"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        conf_aliases.add(tgt.id)
        for node in ast.walk(src.tree):
            # the loop-over-keys observer idiom (utils/tracing):
            # `_CFG_KEYS = ("a", "b", ...)` + `for key in _CFG_KEYS:
            # conf.add_observer(key, ...)` — the tuple constant IS
            # the observation declaration
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Tuple):
                names = [t.id if isinstance(t, ast.Name) else
                         getattr(t, "attr", "")
                         for t in node.targets]
                if any("CFG_KEYS" in (n or "") for n in names):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and \
                                isinstance(elt.value, str):
                            self.options_observed.setdefault(
                                elt.value, (src.rel, node.lineno))
            if not isinstance(node, ast.Call):
                if isinstance(node, ast.Subscript) and \
                        self._is_conf(node.value, conf_aliases):
                    if isinstance(node.slice, ast.Constant) and \
                            isinstance(node.slice.value, str):
                        self.options_read.setdefault(
                            node.slice.value,
                            (src.rel, node.lineno))
                    else:
                        affix = _fstring_affix(node.slice)
                        if affix:
                            self.option_affixes.append(affix)
                continue
            fn = node.func
            lit0 = node.args[0].value if (
                node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)) else None
            dyn0 = _fstring_affix(node.args[0]) if node.args else None
            # `inc("a" if hit else "b")`: both branches are keys
            cond0: list[str] = []
            if node.args and isinstance(node.args[0], ast.IfExp):
                cond0 = [e.value for e in (node.args[0].body,
                                           node.args[0].orelse)
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
            if isinstance(fn, ast.Attribute):
                recv = _unparse(fn.value).lower()
                perfish = any(h in recv for h in _PERF_RECV_HINTS)
                if fn.attr in _COUNTER_REG and lit0:
                    self.counters_registered.setdefault(
                        lit0, (src.rel, node.lineno))
                elif fn.attr in _COUNTER_USE_STRONG or \
                        (fn.attr in _COUNTER_USE_WEAK and perfish):
                    if lit0:
                        self.counters_used.setdefault(
                            lit0, (src.rel, node.lineno))
                    elif dyn0:
                        self.counter_affixes.append(dyn0)
                    for key in cond0:
                        self.counters_used.setdefault(
                            key, (src.rel, node.lineno))
                elif fn.attr in ("get", "set") and \
                        self._is_conf(fn.value, conf_aliases):
                    if lit0:
                        self.options_read.setdefault(
                            lit0, (src.rel, node.lineno))
                    elif dyn0:
                        self.option_affixes.append(dyn0)
                elif fn.attr in ("add_observer",
                                 "_observe_knob") and lit0:
                    # direct observer registration, or the device
                    # engine's _observe_knob seam (same contract:
                    # first arg is the option, consumer caches)
                    self.options_observed.setdefault(
                        lit0, (src.rel, node.lineno))
                elif fn.attr == "register_command" and lit0:
                    self.asok_registered.setdefault(
                        lit0, (src.rel, node.lineno))
                elif fn.attr == "asok_command" and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Constant):
                    self.asok_invoked.setdefault(
                        node.args[1].value, (src.rel, node.lineno))
            elif isinstance(fn, ast.Name):
                if fn.id == "Option" and lit0:
                    self.options_declared.setdefault(
                        lit0, (src.rel, node.lineno))
                elif fn.id == "asok_command" and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Constant):
                    self.asok_invoked.setdefault(
                        node.args[1].value, (src.rel, node.lineno))
                elif fn.id == "Rule" and len(node.args) >= 3 and \
                        isinstance(node.args[1], ast.Constant) and \
                        isinstance(node.args[1].value, str) and \
                        isinstance(node.args[2], ast.Constant) and \
                        node.args[2].value in ("up", "down"):
                    # a tuner policy rule (Rule(name, knob, dir, ...));
                    # the direction literal disambiguates it from
                    # crush/fault Rule constructors
                    self.rule_knobs.setdefault(
                        node.args[1].value, (src.rel, node.lineno))

    @staticmethod
    def _is_conf(recv: ast.AST, aliases: set[str]) -> bool:
        if isinstance(recv, ast.Call):
            return _unparse(recv.func).endswith("g_conf")
        if isinstance(recv, ast.Name):
            return recv.id in aliases
        if isinstance(recv, ast.Attribute):
            return recv.attr in ("conf", "_conf")
        return False

    # -- findings ------------------------------------------------------
    def findings(self) -> list[Finding]:
        out: list[Finding] = []

        def add(kind: str, key: str, where: tuple[str, int],
                message: str):
            out.append(Finding(
                "registry_drift", where[0], where[1],
                f"registry_drift:{kind}:{key}", message))

        for key, where in sorted(self.counters_used.items()):
            if key not in self.counters_registered:
                add("counter-unregistered", key, where,
                    f"counter {key!r} updated but never registered "
                    "(runtime KeyError the first time it fires)")
        for key, where in sorted(self.counters_registered.items()):
            if key not in self.counters_used and \
                    not _affix_match(key, self.counter_affixes):
                add("counter-unused", key, where,
                    f"counter {key!r} registered but never updated "
                    "anywhere — dead metric, dashboards read 0")
        for key, where in sorted(self.options_read.items()):
            if key not in self.options_declared:
                add("unknown-option", key, where,
                    f"config key {key!r} read but not declared as an "
                    "Option (g_conf raises KeyError)")
        for key, where in sorted(self.options_declared.items()):
            if key not in self.options_read and \
                    not _affix_match(key, self.option_affixes):
                add("option-unread", key, where,
                    f"option {key!r} declared but never read in the "
                    "package — dead knob")
        for key, where in sorted(self.asok_invoked.items()):
            if key not in self.asok_registered:
                add("asok-unregistered", key, where,
                    f"asok command {key!r} invoked but no daemon "
                    "registers it")
        # ISSUE 13: every tuner-managed knob must be consumed through
        # a config OBSERVER somewhere — the tuner mutates these at
        # runtime, so a consumer re-reading g_conf per-op/per-flush
        # pays the RLock the tracing PR measured, and a consumer that
        # caches WITHOUT an observer silently ignores the tuner
        for key in self._tuner_knob_names():
            if key in self.options_declared and \
                    key not in self.options_observed:
                add("tuner-knob-unobserved", key,
                    self.options_declared[key],
                    f"tuner-managed knob {key!r} has no add_observer "
                    "consumer: runtime pushes either cost a hot-path "
                    "config read or never reach the daemon")
        # every tuner policy rule must actuate a registered Knob —
        # a typo'd knob name makes the rule's firings step nothing
        # (the engine looks the knob up and skips silently)
        knob_names = set(self._tuner_knob_names())
        if knob_names:
            for key, where in sorted(self.rule_knobs.items()):
                if key not in knob_names:
                    add("rule-knob-unregistered", key, where,
                        f"tuner rule steps knob {key!r} but "
                        "TUNER_KNOBS has no such entry — the rule "
                        "can never actuate")
        return out

    @staticmethod
    def _tuner_knob_names() -> list[str]:
        """The actuator registry (utils/knobs.TUNER_KNOBS) — imported
        live rather than re-parsed: the registry IS the contract."""
        try:
            from ceph_tpu.utils.knobs import tuner_managed_names
            return tuner_managed_names()
        except Exception:
            return []


# ---------------------------------------------------------------------------
# 4. lock discipline
# ---------------------------------------------------------------------------

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "make_lock",
               "make_rlock", "lock_witness.make_lock",
               "lock_witness.make_rlock")


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            fname = _unparse(node.value.func)
            if fname in _LOCK_CTORS or fname.endswith(".make_lock") \
                    or fname.endswith(".make_rlock"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        out.add(tgt.attr)
    return out


def _with_lock_items(node: ast.With, locks: set[str]) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Attribute) and \
                isinstance(ctx.value, ast.Name) and \
                ctx.value.id == "self" and ctx.attr in locks:
            return True
    return False


def _locked_context_methods(methods: list[ast.FunctionDef],
                            locks: set[str]) -> set[str]:
    """Methods only ever called (within this class) while the lock is
    held — the caller-holds-lock idiom (mon's ``_dispatch`` takes
    ``self._lock`` once and fans out to every handler). Computed to a
    fixpoint so a handler's helpers inherit the context. A method with
    any call site outside a locked region (or no internal call sites
    at all — public API) is NOT lock-held context."""
    names = {m.name for m in methods}
    # method -> list of (callee, in_with_lock_span) call sites
    sites: dict[str, list[tuple[str, bool]]] = {n: [] for n in names}
    for m in methods:
        spans = [(n.lineno, n.end_lineno or n.lineno)
                 for n in ast.walk(m)
                 if isinstance(n, ast.With)
                 and _with_lock_items(n, locks)]
        for node in ast.walk(m):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and \
                    node.func.attr in names:
                in_span = any(a <= node.lineno <= b
                              for a, b in spans)
                sites[node.func.attr].append((m.name, in_span))
    # greatest fixpoint: assume every internally-called method is
    # lock-held, then evict any with a call site that is neither
    # inside a with-lock span nor from a (still-)locked caller —
    # mutually-recursive helper clusters (paxos pump/collect/begin)
    # whose every external entry is locked stay locked
    locked: set[str] = {n for n in names if sites[n]}
    changed = True
    while changed:
        changed = False
        for name in sorted(locked):
            if not all(in_span or caller in locked
                       for caller, in_span in sites[name]):
                locked.discard(name)
                changed = True
    return locked


def check_lock_discipline(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for cls in [n for n in ast.walk(src.tree)
                if isinstance(n, ast.ClassDef)]:
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = [n for n in cls.body
                   if isinstance(n, ast.FunctionDef)]

        # attrs touched inside with-self-lock blocks anywhere
        protected: set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.With) and \
                        _with_lock_items(node, locks):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute) and \
                                isinstance(sub.value, ast.Name) and \
                                sub.value.id == "self" and \
                                sub.attr not in locks:
                            protected.add(sub.attr)
        if not protected:
            continue
        locked_ctx = _locked_context_methods(methods, locks)

        for m in methods:
            if m.name == "__init__":
                continue
            # caller-holds-lock conventions: the documented ``_locked``
            # name suffix, and methods only reachable under the lock
            if m.name.endswith("_locked") or m.name in locked_ctx:
                continue
            src_seg = ast.get_source_segment(src.text, m) or ""
            if ".acquire(" in src_seg:
                continue           # manual acquire/release pattern

            # collect assignments to protected attrs OUTSIDE any
            # with-self-lock block
            locked_spans: list[tuple[int, int]] = []
            for node in ast.walk(m):
                if isinstance(node, ast.With) and \
                        _with_lock_items(node, locks):
                    locked_spans.append(
                        (node.lineno, node.end_lineno or node.lineno))

            def in_locked(line: int) -> bool:
                return any(a <= line <= b for a, b in locked_spans)

            for node in ast.walk(m):
                target = None
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and \
                                tgt.attr in protected:
                            target = tgt
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(node.target, ast.Attribute) and \
                        isinstance(node.target.value, ast.Name) and \
                        node.target.value.id == "self" and \
                        node.target.attr in protected:
                    target = node.target
                if target is not None and not in_locked(node.lineno):
                    findings.append(Finding(
                        "lock_discipline", src.rel, node.lineno,
                        f"lock_discipline:{src.rel}:{cls.name}."
                        f"{m.name}:{target.attr}",
                        f"{cls.name}.{m.name}: mutates "
                        f"self.{target.attr} (elsewhere accessed "
                        f"under {sorted(locks)}) without holding "
                        "the lock"))
    return findings


#: call spellings that construct a condition variable (own-lock arg
#: recorded so notifying under the cond's OWN lock never flags)
_COND_CTORS = ("threading.Condition", "make_condition",
               "lock_witness.make_condition")


def _cond_attrs(cls: ast.ClassDef) -> dict[str, str | None]:
    """``self.<attr>`` condition variables of this class ->
    the ``self.<lock>`` attr passed as their lock (None when the
    cond owns its lock)."""
    out: dict[str, str | None] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            fname = _unparse(node.value.func)
            if fname not in _COND_CTORS and \
                    not fname.endswith(".make_condition"):
                continue
            own = None
            args = list(node.value.args) + [
                kw.value for kw in node.value.keywords
                if kw.arg == "lock"]
            for a in args:
                if isinstance(a, ast.Attribute) and \
                        isinstance(a.value, ast.Name) and \
                        a.value.id == "self":
                    own = a.attr
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    out[tgt.attr] = own
    return out


def check_notify_under_lock(src: SourceFile) -> list[Finding]:
    """ISSUE 17: ``self.<cond>.notify()``/``notify_all()`` executed
    lexically inside a ``with self.<lock>`` span where ``<lock>`` is a
    DIFFERENT lock of the same class than the cond's own. The woken
    thread's first act is usually to take that other lock — signalling
    while still holding it turns every wakeup into an immediate block
    (the hurry-up-and-wait shape the dispatch X-ray's wakeup-latency
    plane measures at runtime); notify after release instead. The
    cond's OWN lock is exempt: Python requires holding it to
    notify."""
    findings: list[Finding] = []
    for cls in [n for n in ast.walk(src.tree)
                if isinstance(n, ast.ClassDef)]:
        locks = _lock_attrs(cls)
        conds = _cond_attrs(cls)
        if not locks or not conds:
            continue
        for m in [n for n in cls.body
                  if isinstance(n, ast.FunctionDef)]:
            spans: list[tuple[int, int, str]] = []
            for node in ast.walk(m):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) and \
                            isinstance(ctx.value, ast.Name) and \
                            ctx.value.id == "self" and \
                            ctx.attr in locks and \
                            ctx.attr not in conds:
                        spans.append((node.lineno,
                                      node.end_lineno or node.lineno,
                                      ctx.attr))
            if not spans:
                continue
            for node in ast.walk(m):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("notify",
                                               "notify_all")):
                    continue
                recv = node.func.value
                if not (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and recv.attr in conds):
                    continue
                own = conds[recv.attr]
                held = [lk for a, b, lk in spans
                        if a <= node.lineno <= b
                        and lk != own and lk != recv.attr]
                if held:
                    findings.append(Finding(
                        "notify_under_lock", src.rel, node.lineno,
                        f"notify_under_lock:{src.rel}:{cls.name}."
                        f"{m.name}:{recv.attr}",
                        f"{cls.name}.{m.name}: notifies "
                        f"self.{recv.attr} while holding "
                        f"self.{held[0]} — the woken thread blocks "
                        "right back on that lock; release before "
                        "signalling"))
    return findings


# ---------------------------------------------------------------------------
# 5. fsync seam (ISSUE 14)
# ---------------------------------------------------------------------------

#: the directory whose durability barriers must be timed (repo-
#: relative prefix)
FSYNC_SEAM_DIR = "ceph_tpu/store"

#: call spellings that ARE a raw durability barrier
_RAW_SYNC_CALLS = frozenset((
    "os.fsync", "os.fdatasync", "fsync", "fdatasync"))


def check_fsync_seam(src: SourceFile) -> list[Finding]:
    """Direct ``os.fsync``/``os.fdatasync`` calls under
    ``ceph_tpu/store/`` — untimed commit stalls. The store layer must
    route every barrier through ``utils/store_telemetry``'s named
    seam so fsync count/bytes/wall land per call site; a store that
    syncs directly reopens the pre-ISSUE-14 blind spot under
    ``commit_wait``."""
    rel = src.rel.replace(os.sep, "/")
    if not rel.startswith(FSYNC_SEAM_DIR + "/"):
        return []
    findings: list[Finding] = []

    def visit(node: ast.AST, func: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = func
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                name = child.name
            if isinstance(child, ast.Call) and \
                    _unparse(child.func) in _RAW_SYNC_CALLS:
                findings.append(Finding(
                    "fsync_seam", src.rel, child.lineno,
                    f"untimed-fsync:{rel}:{func}",
                    f"{_unparse(child.func)} in {func}(): durability "
                    "barrier bypasses the timed-fsync seam "
                    "(store_telemetry.timed_fsync/timed_fdatasync/"
                    "timed_sync) — an unmeasured commit stall"))
            visit(child, name)

    visit(src.tree, "<module>")
    return findings


#: reactor-affinity scope (repo-relative directory prefix): the
#: shard-per-core subsystem whose run-to-completion discipline the
#: checker pins statically
REACTOR_DIR = "ceph_tpu/crimson"

#: sync primitives whose DIRECT construction inside crimson bypasses
#: the lock witness (cross-shard edges must go through make_lock /
#: make_condition so contention is attributable)
_RAW_LOCK_CALLS = frozenset((
    "threading.Lock", "threading.RLock", "threading.Condition"))


def check_reactor_affinity(src: SourceFile) -> list[Finding]:
    """Shared-nothing discipline for ``ceph_tpu/crimson/`` (ISSUE
    18) — the static twin of the runtime hop counters (``ophop_
    wq_continuation == 0``) and the lock witness. Three violation
    classes:

    * ``global`` statements — module-level mutable state is shared
      across every reactor thread; crimson state lives on the shard
      (``Reactor``/``ReactorServices``) or on the OSD control plane,
      never in module globals.
    * blocking ``time.sleep`` inside ``async def`` — parks the whole
      reactor (every PG pinned to it stalls admission-to-commit);
      coroutines use ``asyncio.sleep`` or an injectable seam.
    * direct ``threading.Lock/RLock/Condition`` construction — a
      cross-shard edge the lock witness cannot see; the deliberate
      edges (map waiters, tid counter, sub-write batch fan-in) go
      through ``make_lock`` and are witnessed.
    """
    rel = src.rel.replace(os.sep, "/")
    if not rel.startswith(REACTOR_DIR + "/"):
        return []
    findings: list[Finding] = []

    def visit(node: ast.AST, func: str, in_async: bool) -> None:
        for child in ast.iter_child_nodes(node):
            name, is_async = func, in_async
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                name = child.name
                is_async = isinstance(child, ast.AsyncFunctionDef)
            if isinstance(child, ast.Global):
                findings.append(Finding(
                    "reactor_affinity", src.rel, child.lineno,
                    f"reactor-affinity:{rel}:{func}:global",
                    f"global {', '.join(child.names)} in {func}(): "
                    "module-level mutable state is visible to every "
                    "reactor — shared-nothing state lives on the "
                    "shard or the OSD control plane"))
            if isinstance(child, ast.Call):
                callee = _unparse(child.func)
                if in_async and callee == "time.sleep":
                    findings.append(Finding(
                        "reactor_affinity", src.rel, child.lineno,
                        f"reactor-affinity:{rel}:{func}:"
                        "blocking-sleep",
                        f"time.sleep in async {func}(): blocks the "
                        "whole reactor (every PG pinned to it) — "
                        "use asyncio.sleep or an injectable seam"))
                if callee in _RAW_LOCK_CALLS:
                    findings.append(Finding(
                        "reactor_affinity", src.rel, child.lineno,
                        f"reactor-affinity:{rel}:{func}:raw-lock",
                        f"{callee}() in {func}(): cross-shard sync "
                        "primitive invisible to the lock witness — "
                        "route through analysis.lock_witness."
                        "make_lock/make_condition"))
            visit(child, name, is_async)

    visit(src.tree, "<module>", False)
    return findings


# ---------------------------------------------------------------------------
# 7. flow context (ISSUE 20)
# ---------------------------------------------------------------------------

#: the module that DEFINES the flow-context seam — its own helpers
#: take ``qos`` by construction and are exempt
FLOW_SEAM_MODULE = "ceph_tpu/utils/flow_telemetry.py"


def check_flow_context(src: SourceFile) -> list[Finding]:
    """Every enqueue seam that accepts a ``qos=`` parameter must
    thread the flow context across the handoff (ISSUE 20): a queue
    admission point classifies the op for scheduling, which is exactly
    where the submitting thread's flow label dies unless the seam
    captures it (``flow_telemetry.capture_flow(qos)``) or reads it
    (``current_flow()``) into whatever rides the queue. A ``qos``
    parameter with neither is a per-tenant attribution hole: every op
    through it lands in the unattributed bucket and the gap_report
    coverage gate erodes silently. Static twin of the >=95%%
    ops+bytes attribution acceptance run."""
    rel = src.rel.replace(os.sep, "/")
    if rel == FLOW_SEAM_MODULE:
        return []
    findings: list[Finding] = []

    def visit(node: ast.AST, owner: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = owner
            if isinstance(child, ast.ClassDef):
                name = child.name
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                args = child.args
                params = {a.arg for a in (args.posonlyargs + args.args
                                          + args.kwonlyargs)}
                if "qos" in params:
                    seg = ast.get_source_segment(src.text, child) or ""
                    if "capture_flow" not in seg and \
                            "current_flow" not in seg:
                        qual = f"{owner}.{child.name}" if owner \
                            else child.name
                        findings.append(Finding(
                            "flow_context", src.rel, child.lineno,
                            f"flow_context:{rel}:{qual}",
                            f"{qual}: accepts qos= but never threads "
                            "the flow context (capture_flow/"
                            "current_flow) — ops crossing this seam "
                            "lose their tenant label and land "
                            "unattributed"))
                name = child.name
            visit(child, name)

    visit(src.tree, "")
    return findings


# ---------------------------------------------------------------------------
# driver + baseline
# ---------------------------------------------------------------------------

def run_all(root: str = PKG_ROOT,
            sources: list[SourceFile] | None = None) -> list[Finding]:
    if sources is None:
        sources = iter_sources(root)
    findings: list[Finding] = []
    drift = RegistryDrift()
    for src in sources:
        findings.extend(check_wire_symmetry(src))
        findings.extend(check_jit_hygiene(src))
        findings.extend(check_lock_discipline(src))
        findings.extend(check_notify_under_lock(src))
        findings.extend(check_fsync_seam(src))
        findings.extend(check_reactor_affinity(src))
        findings.extend(check_flow_context(src))
        drift.collect(src)
    findings.extend(drift.findings())
    findings.sort(key=lambda f: (f.path, f.line, f.key))
    return findings


def load_baseline(path: str = BASELINE_PATH) -> dict:
    if not os.path.exists(path):
        return {"lint": [], "witness": []}
    with open(path) as f:
        return json.load(f)


def diff_baseline(findings: list[Finding],
                  baseline: dict | None = None
                  ) -> tuple[list[Finding], list[dict]]:
    """(new findings not in the baseline, stale baseline entries whose
    violation no longer exists). Both must be empty for the gate."""
    if baseline is None:
        baseline = load_baseline()
    allow = {e["key"]: e for e in baseline.get("lint", ())}
    keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in allow]
    stale = [e for k, e in sorted(allow.items()) if k not in keys]
    return new, stale
