"""Pallas TPU kernel for GF(2^8) matrix-stripe multiply.

The plain-XLA bit-sliced path (ops/gf_jax.py) materializes the 8x bit-plane
expansion in HBM (XLA does not fuse elementwise producers into dot
operands), so encode pays ~30x HBM amplification. This kernel does
unpack -> MXU matmul -> pack entirely in VMEM per tile: HBM traffic drops
to data-in + parity-out, the same minimal movement the reference's SIMD
loop achieves in L1 (isa-l ``ec_encode_data``; call site
src/erasure-code/isa/ErasureCodeIsa.cc:118-130).

Math per grid step (g independent lane-groups of T bytes each):

    d        : [k, g*T] uint8
    bits     : [g*8k, T]  — per group q, 8 bit planes of its T lanes (VPU)
    acc      : Bg @ bits  with Bg = blockdiag_g([8m, 8k] binary)  (MXU, f32)
    parity   : Pg @ (acc & 1) with Pg = blockdiag_g(2^r pack)     (MXU, f32)
               -> [g*m, T] -> regrouped to [m, g*T] uint8

The g-fold block-diagonal stacking fills the MXU's 128-deep contraction
dimension (8k = 64 for k=8 would otherwise leave half the systolic array
idle): one pass processes g groups' bits, doubling (k=8) or quadrupling
(k=4) throughput over the naive [8m, 8k] matmul. Bit-packing runs as a
second tiny matmul with power-of-two weights instead of a scalar row loop.
Exactness: accumulator values are <= 8k <= 2048 < 2^24, exact in f32; pack
weights (2^r <= 128) and 0/1 bits are exact in bf16 with f32 accumulate,
so output is byte-identical to the numpy oracle (tests/test_gf_pallas.py).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.ops import bitmatrix

#: total lanes (chunk bytes across all g groups) per grid step; small
#: blocks double-buffer better through VMEM (measured optimum on v5e)
DEFAULT_TILE = 8192

#: MXU contraction depth to fill with g-fold stacking
_MXU_DEPTH = 128


def _fold(k: int) -> int:
    return max(1, _MXU_DEPTH // (8 * k))


def _permute_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """[m,k] GF matrix -> [8m, 8k] binary matrix, columns regrouped by bit:
    out[:, c*k + j] = B[:, 8j + c]."""
    bmat = bitmatrix.expand_bitmatrix(mat)  # [8m, 8k]
    r, kc = bmat.shape
    k = kc // 8
    perm = [c * k + j for j in range(k) for c in range(8)]
    inv = np.empty(kc, dtype=np.int64)
    inv[perm] = np.arange(kc)
    # column 8j+c of bmat must land at c*k+j
    out = np.empty_like(bmat)
    for j in range(k):
        for c in range(8):
            out[:, c * k + j] = bmat[:, 8 * j + c]
    return out


def _gf_matvec_kernel(bmat_ref, data_ref, out_ref, *,
                      k: int, m_out: int, g: int, t: int):
    d = data_ref[:].astype(jnp.int32)              # [k, g*t]
    # per-group bit planes stacked on sublanes: row q*8k + c*k + j holds
    # bit c of data byte j of group q — matching blockdiag(Bperm) columns
    parts = []
    for q in range(g):
        grp = d[:, q * t:(q + 1) * t]
        for c in range(8):
            parts.append((grp >> c) & 1)
    bits = jnp.concatenate(parts, axis=0)          # [g*8k, t] int32
    acc = jax.lax.dot_general(
        bmat_ref[:].astype(jnp.bfloat16), bits.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    b = acc.astype(jnp.int32) & 1                  # [g*8m, t]
    # pack on the VPU: output byte (q,i) = sum_r b[8*(q*m+i)+r] << r —
    # one weighted sublane reduction per row (a second matmul here would
    # cost a full column-stream MXU pass)
    w = jnp.left_shift(
        1, jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0))
    rows = []
    for j in range(g * m_out):
        bb = b[8 * j:8 * j + 8]                    # [8, t]
        rows.append(jnp.sum(bb * w, axis=0, keepdims=True))
    pb = jnp.concatenate(rows, axis=0).astype(jnp.uint8)   # [g*m, t]
    for q in range(g):
        out_ref[:, q * t:(q + 1) * t] = pb[q * m_out:(q + 1) * m_out, :]


def _matvec_padded_impl(bmat: jax.Array, data: jax.Array,
                        k: int, m_out: int, g: int,
                        tile: int) -> jax.Array:
    n = data.shape[1]
    block = g * tile
    grid = (n // block,)
    return pl.pallas_call(
        functools.partial(_gf_matvec_kernel, k=k, m_out=m_out, g=g,
                          t=tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((g * 8 * m_out, g * 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m_out, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_out, n), jnp.uint8),
    )(bmat, data)


_matvec_padded = jax.jit(
    _matvec_padded_impl, static_argnames=("k", "m_out", "g", "tile"))

#: donating variant: the data buffer's HBM is handed to XLA for reuse,
#: so steady-state encode stops allocating a fresh input block per
#: launch. Used ONLY when matvec_device owns the buffer (host input,
#: or a fresh pad copy) — a caller-retained jax array must never be
#: invalidated under its owner. Parity [m, N] cannot alias the larger
#: [k, N] input as an output, so XLA's "not usable" aliasing warning
#: is suppressed (the win is the freed block covering the in-VMEM/HBM
#: intermediates, not output aliasing).
import warnings as _warnings  # noqa: E402

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

_matvec_padded_donated = jax.jit(
    _matvec_padded_impl, static_argnames=("k", "m_out", "g", "tile"),
    donate_argnums=(1,))


def _tracing() -> bool:
    from ceph_tpu.ops.jax_util import tracing_active
    return tracing_active()


class _PermMatrixCache:
    """Caches the block-diagonal bit matrix: host-side always, plus a
    device copy used only OUTSIDE tracing. Under an outer jit the
    numpy constant is embedded per-trace (handing out a cached device
    array there would leak a tracer); on the eager hot path the device
    copy avoids re-uploading the matrix every call."""

    def __init__(self) -> None:
        self._host: dict[bytes, np.ndarray] = {}
        self._dev: dict[bytes, jax.Array] = {}

    def get(self, mat: np.ndarray, g: int):
        key = (mat.shape[0].to_bytes(2, "little") +
               g.to_bytes(2, "little") + mat.tobytes())
        big = self._host.get(key)
        if big is None:
            perm = _permute_bitmatrix(mat).astype(np.int32)
            r, c = perm.shape
            big = np.zeros((g * r, g * c), dtype=np.int32)
            for q in range(g):
                big[q * r:(q + 1) * r, q * c:(q + 1) * c] = perm
            self._host[key] = big
        if _tracing():
            return jnp.asarray(big)
        dev = self._dev.get(key)
        if dev is None:
            dev = self._dev[key] = jnp.asarray(big)
        return dev


_perm_cache = _PermMatrixCache()


def matvec_device(mat: np.ndarray, data, tile: int = DEFAULT_TILE):
    """Device-in/device-out GF matvec via the Pallas kernel.

    data: [k, N] uint8 (jax or numpy). N is padded UP TO A POW2 GRID
    BUCKET with zeros (GF-linear => padding encodes to zeros and is
    sliced off). Bucketing bounds the compile count to O(log N) — the
    OSD's batch engine feeds arbitrary batch sizes, and an exact-fit
    grid would recompile (~30s over the chip tunnel) per size.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    m_out, k = mat.shape
    g = _fold(k)
    bmat = _perm_cache.get(mat, g)
    # we own (and may donate) the device buffer unless the CALLER
    # handed us a live jax array — jnp.asarray is a no-op then, and
    # donating it would invalidate the caller's copy
    owned = not isinstance(data, jax.Array)
    data = jnp.asarray(data, dtype=jnp.uint8)
    n = data.shape[1]
    t = min(tile // g, max(128, _round_up(-(-n // g), 128)))
    block = g * t
    nb = block
    while nb < n:
        nb <<= 1
    pad = nb - n
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
        owned = True               # the pad copy is ours to donate
    if _tracing():
        # under an outer jit the call inlines into the caller's trace:
        # timing/cache introspection would account the OUTER compile
        # (and donation is meaningless on a traced value)
        out = _matvec_padded(bmat, data, k, m_out, g, t)
    else:
        from ceph_tpu.utils.device_telemetry import telemetry
        fn = _matvec_padded_donated if owned else _matvec_padded
        out = telemetry().timed_call(
            f"gf_pallas[{m_out}x{k}]g{g}t{t}N{nb}"
            + ("d" if owned else ""),
            fn, bmat, data, k, m_out, g, t)
    return out[:, :n] if pad else out


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


#: beyond this the in-VMEM bit-plane expansion and the unrolled pack loop
#: stop fitting/compiling well; bigger matrices (e.g. Clay's linearized
#: [m*subchunks, k*subchunks] transforms) take the plain-XLA bit-sliced
#: path, which tiles arbitrary shapes through the MXU.
_MAX_M, _MAX_K = 32, 128


def matvec(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Host-in/host-out wrapper (ops.backend contract)."""
    m_out, k = mat.shape
    if m_out > _MAX_M or k > _MAX_K:
        from ceph_tpu.ops import gf_jax
        return gf_jax.matvec(mat, data)
    return np.asarray(jax.device_get(matvec_device(mat, data)))
