"""Stripe math + batched encode/decode — the ECUtil role, TPU-batched.

Reference: src/osd/ECUtil.{h,cc}. ``stripe_info_t`` (ECUtil.h:27-80) maps
logical object offsets to stripes and chunk offsets; ``ECUtil::encode``
loops ``ec_impl->encode`` once per stripe_width window (ECUtil.cc:120-159).

The TPU translation (SURVEY.md §5 "stripe batch = leading vmap dim"): the
per-stripe loop disappears. For matrix codecs the position-wise math lets S
stripes fold into one [k, S*chunk_size] kernel call — one launch for a
whole append batch instead of S launches; the generic fallback loops for
codecs with cross-position structure (Clay).

``HashInfo`` is the cumulative per-shard crc xattr (ECUtil.h:101-162,
append logic ECUtil.cc:161-177, stored under the hinfo key :235): every
shard append folds the new chunk bytes into a running crc32c so scrub can
verify a shard without reading its peers.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.models.interface import ErasureCodeError
from ceph_tpu.utils import checksum
from ceph_tpu.utils.dout import Dout

log = Dout("osd")

#: initial per-shard crc seed (the reference seeds with -1, ECUtil.h:117)
HINFO_SEED = 0xFFFFFFFF


@dataclass(frozen=True)
class StripeInfo:
    """stripe_width/chunk offset algebra (stripe_info_t, ECUtil.h:27-80)."""

    stripe_width: int   # k * chunk_size bytes of logical data per stripe
    chunk_size: int     # bytes per chunk per stripe

    def __post_init__(self):
        if self.stripe_width % self.chunk_size:
            raise ValueError(
                f"stripe_width {self.stripe_width} not a multiple of "
                f"chunk_size {self.chunk_size}")

    @property
    def k(self) -> int:
        return self.stripe_width // self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int,
                                    length: int) -> tuple[int, int]:
        """Expand [offset, offset+length) to stripe-aligned bounds
        (ECUtil.h:72-79)."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start


def encode(sinfo: StripeInfo, codec, data: bytes | np.ndarray,
           want: list[int] | None = None) -> dict[int, np.ndarray]:
    """Encode a stripe-aligned logical extent into per-shard buffers.

    data length must be a multiple of stripe_width; the result maps shard
    id -> concatenated chunk bytes across all S stripes (what each shard
    OSD stores contiguously). Matrix codecs encode all S stripes in ONE
    kernel call; others loop (ECUtil.cc:136-148 semantics).
    """
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8, copy=False).ravel()
    sw, cs = sinfo.stripe_width, sinfo.chunk_size
    if len(buf) % sw:
        raise ErasureCodeError(
            f"encode: length {len(buf)} not a multiple of stripe_width {sw}")
    s = len(buf) // sw
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    assert sw == k * cs, (sw, k, cs)
    want = list(range(n)) if want is None else list(want)
    # [S, k, cs] -> per-shard contiguous [S*cs]
    stripes = buf.reshape(s, k, cs)
    data_shards = stripes.transpose(1, 0, 2).reshape(k, s * cs)
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    out: dict[int, np.ndarray] = {}
    if isinstance(codec, MatrixErasureCode) and not codec.chunk_mapping:
        # position-wise codec: stripes fold into the byte axis
        parity = codec._matvec(codec.coding_matrix, data_shards)
        for i in want:
            out[i] = data_shards[i] if i < k else parity[i - k]
    else:
        per_stripe = [codec.encode_chunks(
            want, {j: stripes[si, j] for j in range(k)}) for si in range(s)]
        for i in want:
            if i < k:
                out[i] = data_shards[i]
            else:
                out[i] = np.concatenate([per_stripe[si][i] for si in range(s)])
    return out


def xor_decodable(codec, shards: dict[int, np.ndarray],
                  missing: list[int]) -> bool:
    """True when reconstructing ``missing`` from ``shards`` reduces
    to bitwise XOR — the decode matrix for this erasure signature has
    only 0/1 coefficients (GF multiply by 1 is identity, GF add is
    XOR). Single-parity RS and XOR-structured codes hit this on every
    single-erasure signature; for those a host XOR beats any device
    staging round-trip, so callers use this to skip the engine.
    Mirrors decode_chunks' survivor selection (sorted, first k)."""
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    if not missing or not isinstance(codec, MatrixErasureCode):
        return False
    have = sorted(shards)
    k = codec.get_data_chunk_count()
    if len(have) < k:
        return False
    try:
        dmat = codec._decode_matrix(tuple(have[:k]), tuple(missing))
    except Exception:
        return False
    return bool(((dmat == 0) | (dmat == 1)).all())


def decode(sinfo: StripeInfo, codec, shards: dict[int, np.ndarray],
           want: list[int]) -> dict[int, np.ndarray]:
    """Reconstruct wanted shards from surviving per-shard buffers
    (ECUtil.cc:47-118). Shard buffers hold S concatenated chunks."""
    some = next(iter(shards.values()))
    cs = sinfo.chunk_size
    if len(some) % cs:
        raise ErasureCodeError(
            f"decode: shard length {len(some)} not a multiple of {cs}")
    s = len(some) // cs
    missing = [i for i in want if i not in shards]
    if not missing:
        return {i: np.asarray(shards[i], dtype=np.uint8) for i in want}
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    if isinstance(codec, MatrixErasureCode) and not codec.chunk_mapping:
        # one kernel call across all stripes
        return codec.decode_chunks(
            want, {i: np.asarray(v, dtype=np.uint8)
                   for i, v in shards.items()})
    out = {i: np.zeros(s * cs, dtype=np.uint8) for i in want}
    for si in range(s):
        got = codec.decode_chunks(
            want, {i: np.asarray(v[si * cs:(si + 1) * cs], dtype=np.uint8)
                   for i, v in shards.items()})
        for i in want:
            out[i][si * cs:(si + 1) * cs] = got[i]
    return out


class HashInfo:
    """Cumulative per-shard crc32c (ECUtil.h:101-162).

    Updated on every append; serialized as a shard xattr so
    handle_sub_read can verify a shard against it (ECBackend.cc:1032-1051).
    """

    def __init__(self, num_chunks: int) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [HINFO_SEED] * num_chunks

    def append(self, old_size: int, shard_chunks: dict[int, np.ndarray]):
        """Fold an append at chunk-offset ``old_size`` into the crcs
        (ECUtil.cc:161-177: appends must be contiguous)."""
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"hinfo append at {old_size} != current size "
                f"{self.total_chunk_size} (appends must be contiguous)")
        sizes = {len(v) for v in shard_chunks.values()}
        if len(sizes) != 1:
            raise ValueError("hinfo append: unequal shard chunk sizes")
        for shard, data in shard_chunks.items():
            self.cumulative_shard_hashes[shard] = checksum.crc32c(
                data, self.cumulative_shard_hashes[shard])
        self.total_chunk_size += sizes.pop()

    def append_linear(self, old_size: int, linear: dict[int, int],
                      chunk_len: int) -> None:
        """Fold an append whose per-shard LINEAR crc parts were
        computed on device (ops/crc32c_device.py): the running crc is
        recovered host-side as L(chunk) ^ crc32c(0^len, prev) — the
        affine identity — in O(32^2 log len), no byte re-hash."""
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"hinfo append at {old_size} != current size "
                f"{self.total_chunk_size} (appends must be contiguous)")
        from ceph_tpu.ops.crc32c_device import zeros_crc
        for shard, lv in linear.items():
            self.cumulative_shard_hashes[shard] = int(lv) ^ zeros_crc(
                chunk_len, self.cumulative_shard_hashes[shard])
        self.total_chunk_size += chunk_len

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "hashes": list(self.cumulative_shard_hashes)}

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        hi = cls(len(d["hashes"]))
        hi.total_chunk_size = d["total_chunk_size"]
        hi.cumulative_shard_hashes = list(d["hashes"])
        return hi


class StripeBatcher:
    """Device-side stripe batch accumulator (SURVEY.md §7.5, the novel
    piece): coalesce many small sub-writes into one kernel launch.

    Appends are queued host-side; ``flush()`` encodes everything queued in
    a single batched call and returns per-op shard buffers in submission
    order (commit order is preserved — the pipeline-ordering invariant of
    ECBackend::check_ops, ECBackend.cc:2107). Size-triggered auto-flush;
    the OSD write pipeline calls flush() at commit points.
    """

    def __init__(self, sinfo: StripeInfo, codec,
                 flush_bytes: int = 8 << 20, mesh=None,
                 on_fallback=None) -> None:
        self.sinfo = sinfo
        self.codec = codec
        self.flush_bytes = flush_bytes
        #: jax.sharding.Mesh: when set (and the codec is a plain
        #: matrix codec), flushes run the DISTRIBUTED encode step over
        #: the mesh (sharded_codec.make_encode_step) — stripe batches
        #: shard over ('stripe' x 'shard'), parity computes with zero
        #: communication, integrity stats psum over ICI
        self.mesh = mesh
        #: on_fallback(path, exc): a mesh/fused flush failed and the
        #: batch re-ran on the plain path — callers count it (the
        #: engine's device_fused_fallbacks stat); a persistent
        #: regression must not silently degrade every flush while
        #: stats still claim device batches (r2 verdict weak #3)
        self.on_fallback = on_fallback
        self._pending: list[tuple[object, np.ndarray]] = []
        self._pending_bytes = 0
        #: zero-copy staging (ISSUE 9): when the appended buffers are
        #: adjacent views into ONE contiguous array (the engine's
        #: per-signature concat buffer, filled at stage time), the
        #: caller hands that array here and flush skips its own
        #: np.concatenate — the flush-time copy the old path paid
        self._preconcat: np.ndarray | None = None

    def append(self, op_id, data: bytes | np.ndarray) -> None:
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data
        if len(buf) % self.sinfo.stripe_width:
            raise ErasureCodeError(
                f"append: {len(buf)} bytes not stripe-aligned")
        self._pending.append((op_id, buf))
        self._pending_bytes += len(buf)

    def set_preconcat(self, batch: np.ndarray) -> None:
        """Declare that every appended buffer is a view into ``batch``
        in append order (total length must match); flush then uses
        ``batch`` directly instead of concatenating."""
        self._preconcat = batch

    def should_flush(self) -> bool:
        return self._pending_bytes >= self.flush_bytes

    def flush(self, with_crcs: bool = False
              ) -> list[tuple[object, dict[int, np.ndarray],
                              dict[int, int] | None]]:
        """Encode all queued ops in one batch; returns
        [(op_id, shards, crcs-or-None)] in submission order.

        ``with_crcs`` computes each op's per-shard LINEAR crc parts on
        device from the same buffers as the encode (SURVEY.md §0 item
        (c) — the Checksummer/BlueStore-verify pass riding the encode's
        HBM residency); only available on the fused device path, None
        otherwise (callers fall back to host hashing).
        """
        return self.flush_async(with_crcs)()

    def flush_async(self, with_crcs: bool = False):
        """Launch the batch and return ``finalize() -> results``.

        On the fused device path the launch is ASYNC (jax dispatch):
        finalize blocks on the download. The engine exploits this to
        double-buffer — stage and launch batch N+1 while N's results
        stream back, which is what amortizes a high-latency link
        (axon tunnel) the way a locally-attached chip amortizes
        dispatch. The mesh and plain paths compute synchronously here
        and finalize trivially. Device faults surface from
        finalize() — callers route them to their host fallback."""
        if not self._pending:
            return lambda: []
        ops, bufs = zip(*self._pending)
        preconcat = self._preconcat
        if preconcat is not None and \
                len(preconcat) != sum(len(b) for b in bufs):
            preconcat = None       # caller's contract broken: re-copy
        self._pending, self._pending_bytes = [], 0
        self._preconcat = None
        if self.mesh is not None and _device_fusable(self.codec):
            try:
                # ASYNC since ISSUE 12: the mesh step launches here
                # (jax async dispatch) and the returned finalize
                # downloads — mesh flushes ride the engine's in-flight
                # window like fused single-chip flushes, so flushes on
                # DIFFERENT placement slots (disjoint devices) overlap
                return _flush_mesh(self.mesh, self.sinfo,
                                   self.codec, ops, bufs,
                                   batch=preconcat)
            except Exception as exc:
                self._note_fallback("mesh", exc)
                # single-device fallback below
        if with_crcs and _device_fusable(self.codec):
            try:
                return _flush_device_fused_async(
                    self.sinfo, self.codec, ops, bufs,
                    batch=preconcat)
            except Exception as exc:
                # fused path failure must not lose the batch: the
                # plain path below re-encodes (host or device)
                self._note_fallback("fused_crc", exc)
        batch = preconcat if preconcat is not None \
            else np.concatenate(bufs)
        shards = encode(self.sinfo, self.codec, batch)
        results = []
        cs, sw = self.sinfo.chunk_size, self.sinfo.stripe_width
        off = 0  # in chunk units per shard
        for op_id, buf in zip(ops, bufs):
            nchunk = len(buf) // sw * cs
            results.append((op_id, {
                i: v[off:off + nchunk] for i, v in shards.items()},
                None))
            off += nchunk
        return lambda: results

    #: failure classes already logged (log once per class per process:
    #: a persistent fault would otherwise spam every flush)
    _logged_fallbacks: set = set()

    def _note_fallback(self, path: str, exc: Exception) -> None:
        cls = (path, type(exc).__name__)
        if cls not in StripeBatcher._logged_fallbacks:
            StripeBatcher._logged_fallbacks.add(cls)
            log(0, f"{path} flush path failed "
                f"({type(exc).__name__}: {exc}); falling back to the "
                "plain flush (logged once per failure class)")
        if self.on_fallback is not None:
            try:
                self.on_fallback(path, exc)
            except Exception:
                pass


#: pool-profile backends whose matvec runs on the accelerator
_DEVICE_MATVEC = {"jax", "pallas"}

#: upper bound on the fused path's padded crc working set (the bit
#: unpack amplifies 8x in device memory; a ragged op mix must fall
#: back to the plain flush instead of OOMing the runtime)
_FUSE_CRC_MAX_SEG_BYTES = 256 << 20


def _device_fusable(codec) -> bool:
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    return (isinstance(codec, MatrixErasureCode)
            and not codec.chunk_mapping
            and getattr(codec, "backend", "") in _DEVICE_MATVEC)


def host_flushable(codec) -> bool:
    """Whether the engine's SMALL-flush host route can take this
    codec: plain matrix codecs encode with one host matvec over the
    coding matrix (layered/chunk-mapped codecs keep their own encode
    path)."""
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    return (isinstance(codec, MatrixErasureCode)
            and not codec.chunk_mapping
            and codec.coding_matrix is not None)


_host_matvec_backend: str | None = None


def _host_backend() -> str:
    global _host_matvec_backend
    if _host_matvec_backend is None:
        from ceph_tpu.ops import backend as backend_mod
        avail = backend_mod.available_backends()
        _host_matvec_backend = \
            "native" if "native" in avail else "numpy"
    return _host_matvec_backend


def flush_host_async(sinfo: StripeInfo, codec, ops, bufs,
                     batch=None):
    """Small-flush HOST route (bulk-ingest ISSUE 9): same
    ``finalize() -> [(op_id, shards, None)]`` contract as
    :func:`_flush_device_fused_async`, but the encode is one host
    matvec (native/numpy) run at finalize time — below the engine's
    ``host_flush_bytes`` threshold the FIXED device dispatch cost
    (jit call + transfer round trip, measured ~5 ms on the CPU quick
    run) dwarfs the ~0.4 ms host encode of a 64 KiB flush. crcs are
    None: the backend hashes on host, which is in the same noise
    floor at these sizes."""
    cs, sw = sinfo.chunk_size, sinfo.stripe_width
    k = codec.get_data_chunk_count()
    lens = [len(b) // sw * cs for b in bufs]
    if batch is None:
        batch = np.concatenate(bufs)
    mat = codec.coding_matrix
    backend = _host_backend()

    def finalize():
        from ceph_tpu.ops import backend as backend_mod
        s = len(batch) // sw
        data_shards = np.ascontiguousarray(
            batch.reshape(s, k, cs).transpose(1, 0, 2)
            .reshape(k, s * cs))
        parity = backend_mod.matvec(mat, data_shards, backend)
        results = []
        off = 0
        for op_id, ln in zip(ops, lens):
            shards = {i: data_shards[i, off:off + ln]
                      for i in range(k)}
            for j in range(parity.shape[0]):
                shards[k + j] = parity[j, off:off + ln]
            results.append((op_id, shards, None))
            off += ln
        return results

    return finalize


def device_decodable(codec) -> bool:
    """Whether the daemon's batched DECODE path can take this codec:
    plain matrix codecs reconstruct with one signature-keyed matmul
    (decode() above collapses to a single device launch); layered/
    mapped codecs (clay, lrc) keep their host machinery."""
    return _device_fusable(codec)


def fuse_crc_policy(codec) -> bool:
    """Whether the engine should ask for device-fused crcs: on the
    real accelerator (pallas) yes; the plain-XLA jax backend — which
    mostly means CPU CI, where the crc bit-unpack's 8x memory
    amplification across many in-process OSDs thrashes the host —
    only when explicitly forced (CEPH_TPU_FUSE_CRC=1)."""
    import os
    if not _device_fusable(codec):
        return False
    return codec.backend == "pallas" or \
        bool(os.environ.get("CEPH_TPU_FUSE_CRC"))


#: (backend, matrix bytes, Nb, lmax_b, nops_b) -> jitted fused fn —
#: all dimensions are pow2-BUCKETED so the compile cache stays small
#: no matter what op-size mixes the daemon sees (an unbucketed
#: signature recompiles per batch shape and stalls the op path)
_fused_cache: dict = {}


def _pow2_bucket(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


#: id(mesh) -> {(matrix bytes): jitted encode step}; bounded — each
#: closure pins its mesh + compiled executables, so unbounded growth
#: across mesh reconfigurations would leak device programs
_mesh_step_cache: dict = {}
_MESH_STEP_CACHE_MAX = 8


def _mesh_step(mesh, key, build):
    """One slot of the bounded per-mesh step cache: each compiled
    step pins its mesh + executables, so growth across mesh
    reconfigurations (or placement submeshes) stays bounded."""
    if id(mesh) not in _mesh_step_cache and \
            len(_mesh_step_cache) >= _MESH_STEP_CACHE_MAX:
        _mesh_step_cache.clear()
    per_mesh = _mesh_step_cache.setdefault(id(mesh), {})
    step = per_mesh.get(key)
    if step is None:
        step = per_mesh[key] = build()
    return step


def _round_stripes(data: np.ndarray, n_stripe: int) -> np.ndarray:
    """pow2-bucket the stripe count (bounds compiles) and round to
    the stripe axis; zero stripes encode/decode to zero and slice
    off."""
    s = data.shape[0]
    s_pad = _pow2_bucket(max(s, n_stripe), n_stripe)
    if s_pad % n_stripe:
        s_pad = -(-s_pad // n_stripe) * n_stripe
    if s_pad != s:
        pad = np.zeros((s_pad - s,) + data.shape[1:], dtype=np.uint8)
        data = np.concatenate([data, pad])
    return data


def _flush_mesh(mesh, sinfo: StripeInfo, codec, ops, bufs,
                batch=None):
    """Flush the batch through the MULTI-CHIP encode step: stripes
    shard over the mesh's ('stripe' x 'shard') axes, parity computes
    locally on every chip (position-wise math — zero communication),
    and the integrity stat reduces over ICI. Parity bytes are
    bit-exact vs the host codec (place=False keeps them home; the TCP
    messenger owns shard placement in this architecture).

    Returns ``finalize() -> results`` (ISSUE 12): the step call here
    only LAUNCHES the sharded program (jax async dispatch); finalize
    downloads — the engine parks mesh flushes on its in-flight window
    so different placement slots' flushes overlap on their disjoint
    devices."""
    from ceph_tpu.parallel import sharded_codec
    cs, sw = sinfo.chunk_size, sinfo.stripe_width
    k = codec.get_data_chunk_count()
    n_chunks = codec.get_chunk_count()
    lens = [len(b) // sw * cs for b in bufs]
    if batch is None:
        batch = np.concatenate(bufs)
    s = len(batch) // sw
    data = _round_stripes(batch.reshape(s, k, cs),
                          mesh.shape["stripe"])
    step = _mesh_step(
        mesh, codec.coding_matrix.tobytes(),
        lambda: sharded_codec.make_encode_step(
            mesh, np.asarray(codec.coding_matrix, dtype=np.uint8),
            place=False))
    chunks_dev, _csum = step(
        sharded_codec.shard_stripe_batch(mesh, data))

    def finalize():
        chunks = np.asarray(chunks_dev)[:s]    # [s, k+m, cs]
        streams = {i: np.ascontiguousarray(
            chunks[:, i, :]).reshape(-1) for i in range(n_chunks)}
        results = []
        off = 0
        for op_id, ln in zip(ops, lens):
            results.append((op_id,
                            {i: streams[i][off:off + ln]
                             for i in range(n_chunks)}, None))
            off += ln
        return results

    return finalize


def flush_decode_mesh(mesh, sinfo: StripeInfo, codec,
                      shards: dict[int, np.ndarray],
                      want: list[int]) -> dict[int, np.ndarray]:
    """Mesh twin of :func:`decode` (ISSUE 12): the engine's
    signature-batched reconstruct as ONE sharded matmul — stripes
    over the ``stripe`` axis, chunk bytes over ``shard``, the decode
    matrix keyed by the erasure signature exactly like the single-chip
    route. Present rows return verbatim; bit-exactness vs the host
    corpus is gated in tier-1. Raises on shapes the mesh cannot take
    (callers fall back to the single-chip/host path)."""
    from ceph_tpu.ops import gf256
    from ceph_tpu.parallel import sharded_codec
    cs = sinfo.chunk_size
    present = sorted(shards)
    missing = [i for i in want if i not in shards]
    out = {i: np.asarray(shards[i], dtype=np.uint8)
           for i in want if i in shards}
    if not missing:
        return out
    n_shard = mesh.shape["shard"]
    if cs % n_shard:
        raise ErasureCodeError(
            f"chunk size {cs} does not shard over {n_shard} devices")
    k = codec.get_data_chunk_count()
    if len(present) < k:
        raise ErasureCodeError(
            f"{len(present)} survivors < k={k}")
    # any k survivors reconstruct the same bytes (MDS); take the
    # first k deterministically so the decode matrix signature is
    # stable per erasure signature
    present = present[:k]
    some = np.asarray(next(iter(shards.values())))
    s = len(some) // cs
    x = np.stack([np.asarray(shards[i], dtype=np.uint8).reshape(s, cs)
                  for i in present], axis=1)       # [s, k, cs]
    x = _round_stripes(x, mesh.shape["stripe"])
    mat = np.asarray(codec.coding_matrix, dtype=np.uint8)
    step = _mesh_step(
        mesh, ("dec", mat.tobytes(), tuple(present), tuple(missing)),
        lambda: sharded_codec.make_degraded_read_step(
            mesh, gf256.systematic_generator(mat),
            list(present), list(missing), gather=False))
    rec = step(sharded_codec.shard_stripe_batch(mesh, x))
    rec = np.asarray(rec)[:s]                      # [s, w, cs]
    for j, c in enumerate(missing):
        out[c] = np.ascontiguousarray(rec[:, j, :]).reshape(-1)
    return out


def _flush_device_fused_async(sinfo: StripeInfo, codec, ops, bufs,
                              batch=None):
    """One device program per bucketed batch signature: upload the
    stripe batch once, encode parity, and take every op's per-shard
    crc linear part from the SAME device-resident shards (one download
    round trip for parity + 4 bytes/shard of crcs). Per-op segment
    boundaries are DYNAMIC inputs (offsets/lengths arrays), with
    front-zero padding — free under crc linearity — masking the
    neighbour bytes a fixed-width window drags in.

    Returns ``finalize() -> results``: the jit call here only QUEUES
    the program (jax async dispatch); finalize downloads — callers
    can launch the next batch before finalizing this one."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ceph_tpu.ops import crc32c_device as cd
    cs, sw = sinfo.chunk_size, sinfo.stripe_width
    k = codec.get_data_chunk_count()
    n_chunks = codec.get_chunk_count()
    m = n_chunks - k
    lens = [len(b) // sw * cs for b in bufs]
    if batch is None:
        batch = np.concatenate(bufs)
    s = len(batch) // sw
    n_bytes = s * cs
    data_shards = np.ascontiguousarray(
        batch.reshape(s, k, cs).transpose(1, 0, 2).reshape(k, n_bytes))

    n_b = _pow2_bucket(n_bytes, 1 << 14)
    lmax_b = _pow2_bucket(max(lens), max(cd.ROW_BYTES, 1 << 12))
    nops_b = _pow2_bucket(len(ops), 1)
    if nops_b * n_chunks * lmax_b > _FUSE_CRC_MAX_SEG_BYTES:
        raise ValueError("fused crc working set too large; "
                         "plain flush")
    key = (codec.backend, codec.coding_matrix.tobytes(),
           n_b, lmax_b, nops_b)
    fn = _fused_cache.get(key)
    fn_is_new = fn is None
    if fn is None:
        if len(_fused_cache) > 256:
            _fused_cache.clear()
        if codec.backend == "pallas":
            from ceph_tpu.ops import gf_pallas as dev
        else:
            from ceph_tpu.ops import gf_jax as dev
        mat = np.asarray(codec.coding_matrix, dtype=np.uint8)

        def fused(data, offs, seg_lens):
            parity = dev.matvec_device(mat, data)
            shards = jnp.concatenate(
                [data, parity.astype(jnp.uint8)], axis=0)
            padded = jnp.pad(shards, ((0, 0), (lmax_b, 0)))

            def seg(off, ln):
                # window ENDING at the segment end; bytes before the
                # segment (neighbour ops / padding) masked to zero
                win = lax.dynamic_slice(
                    padded, (0, off + ln), (n_chunks, lmax_b))
                mask = jnp.arange(lmax_b) >= (lmax_b - ln)
                return win * mask.astype(jnp.uint8)

            segs = jax.vmap(seg)(offs, seg_lens)
            lin = cd.crc_linear_device(
                segs.reshape(nops_b * n_chunks, lmax_b))
            return parity, lin

        fn = _fused_cache[key] = jax.jit(fused)
    if n_b != n_bytes:
        data_dev = np.zeros((k, n_b), dtype=np.uint8)
        data_dev[:, :n_bytes] = data_shards
    else:
        data_dev = data_shards
    offs_arr = np.zeros(nops_b, dtype=np.int32)
    offs_arr[:len(ops)] = np.cumsum([0] + lens[:-1])
    lens_arr = np.zeros(nops_b, dtype=np.int32)
    lens_arr[:len(ops)] = lens
    from ceph_tpu.utils.device_telemetry import telemetry
    signature = (f"fused_crc[{codec.backend}"
                 f"{list(codec.coding_matrix.shape)}]"
                 f"N{n_b}L{lmax_b}ops{nops_b}")
    if fn_is_new:
        import os as _os
        if _os.environ.get("CEPH_TPU_COST_ANALYSIS"):
            # per-signature compiled cost analysis (FLOPs / bytes
            # accessed) into the device telemetry table; opt-in — the
            # AOT lower+compile does not share the jit call cache, so
            # it would double the cold-compile cost of the hot path
            from ceph_tpu.ops import cost_model
            cost_model.analyze(fn, data_dev, offs_arr, lens_arr,
                               signature=signature)
    parity_dev, lin_dev = telemetry().timed_call(
        signature, fn, data_dev, offs_arr, lens_arr)

    def finalize():
        parity = np.asarray(parity_dev)
        lin = np.asarray(lin_dev).reshape(nops_b, n_chunks)
        results = []
        off = 0
        for idx, (op_id, ln) in enumerate(zip(ops, lens)):
            shards = {i: data_shards[i, off:off + ln]
                      for i in range(k)}
            for j in range(m):
                shards[k + j] = parity[j, off:off + ln]
            crcs = {i: int(lin[idx, i]) for i in range(n_chunks)}
            results.append((op_id, shards, crcs))
            off += ln
        return results

    # expose the compiled program + staged host inputs for harnesses
    # (bench/engine_loop.py measures THIS exact program — reaching
    # into the cache with a hand-copied key would silently drift)
    finalize.fused_fn = fn
    finalize.staged = (data_dev, offs_arr, lens_arr)
    return finalize
