"""OpTracker — per-op event timelines and slow-op detection.

Reference: src/common/TrackedOp.{h,cc} + src/osd/OpRequest.h. Every
client op gets a TrackedOp; code marks named events as the op moves
through the pipeline (queued -> reached_pg -> sub_op_sent -> commit).
Ops alive longer than ``osd_op_complaint_time`` are reported as slow;
finished ops land in a bounded history ring served over the admin
socket (dump_historic_ops), like the reference's.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from ceph_tpu.utils.dout import Dout

log = Dout("optracker")


class TrackedOp:
    __slots__ = ("seq", "desc", "start", "events", "_tracker")

    def __init__(self, seq: int, desc: str, tracker: "OpTracker") -> None:
        self.seq = seq
        self.desc = desc
        self.start = time.monotonic()
        self.events: list[tuple[float, str]] = [(self.start, "initiated")]
        self._tracker = tracker

    def mark_event(self, name: str) -> None:
        self.events.append((time.monotonic(), name))

    def finish(self) -> None:
        self.mark_event("done")
        self._tracker._finish(self)

    @property
    def age(self) -> float:
        return time.monotonic() - self.start

    def dump(self) -> dict:
        return {
            "seq": self.seq,
            "desc": self.desc,
            "age": round(self.age, 6),
            "events": [{"t": round(t - self.start, 6), "event": e}
                       for t, e in self.events],
        }


class OpTracker:
    def __init__(self, complaint_time: float = 30.0,
                 history_size: int = 20) -> None:
        self.complaint_time = complaint_time
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._in_flight: dict[int, TrackedOp] = {}
        self._history: deque[dict] = deque(maxlen=history_size)
        self._slowest: deque[dict] = deque(maxlen=history_size)

    def create(self, desc: str) -> TrackedOp:
        op = TrackedOp(next(self._seq), desc, self)
        with self._lock:
            self._in_flight[op.seq] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        with self._lock:
            self._in_flight.pop(op.seq, None)
            d = op.dump()
            self._history.append(d)
            if not self._slowest or d["age"] >= min(
                    s["age"] for s in self._slowest):
                self._slowest.append(d)

    # -- introspection (asok command backends) ------------------------
    def dump_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._in_flight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic(self) -> dict:
        with self._lock:
            return {"num_ops": len(self._history),
                    "ops": list(self._history)}

    def get_slow_ops(self) -> list[dict]:
        """Ops in flight longer than the complaint time (the reference
        logs these as 'slow requests')."""
        with self._lock:
            return [op.dump() for op in self._in_flight.values()
                    if op.age > self.complaint_time]

    def check_slow(self) -> int:
        slow = self.get_slow_ops()
        for s in slow:
            log(1, f"slow request {s['desc']} "
                f"in flight for {s['age']:.1f}s")
        return len(slow)
