"""cephfs-lite — a POSIX-ish filesystem on RADOS (src/mds + src/client
roles, massively reduced).

Reference: CephFS keeps a metadata tree in the MDS (journaled to RADOS
via osdc/Journaler) and file data striped over RADOS objects by
file_layout_t. This lite version drops the separate MDS daemon and
stores metadata DIRECTLY in RADOS, with the dirop atomicity the MDS
journal provides coming from in-OSD object-class methods instead:

- ``.fs_super``     — inode allocator (cls fs.alloc_ino)
- ``inode.<ino>``   — json inode: dirs carry {name: ino} entries
                      (mutated only via cls fs.dir_link/dir_unlink,
                      so concurrent clients cannot corrupt a dir),
                      files carry size/mtime
- ``fsdata.<ino>``  — file content through the striper

API mirrors libcephfs: mkdir/rmdir/readdir, open/read/write, unlink,
rename, stat. Reductions (documented): no hard links across dirs; no
permissions/uids; one flat namespace per pool; single active
metadata writer (the MDS role — the journal assumes one, like the
reference's single-active-MDS rank).

Metadata journaling (the osdc/Journaler + MDLog role): every
MULTI-STEP namespace op (mkdir/create/unlink/rmdir/rename) appends an
intent record to the ``mdslog`` journal before executing its steps;
mount replays the un-committed tail, re-executing steps idempotently.
That closes the crash windows the reference closes with the MDS
journal — most importantly rename's link-then-unlink window (a crash
between the two no longer leaves both names) — and is the MDS
FAILOVER story: the next mount (the standby taking over) recovers the
half-done op from the journal, exactly as a standby MDS replays the
failed rank's journal.
"""

from __future__ import annotations

import errno
import json
import time

from ceph_tpu.client.striper import FileLayout, StripedObject
from ceph_tpu.services.journal import Journaler, JournalError

ROOT_INO = 1
SUPER_OID = ".fs_super"

#: the metadata writer's journal-client id (single active MDS rank)
MDS_CLIENT = "mds"


class FSError(Exception):
    def __init__(self, err: int, message: str = "") -> None:
        super().__init__(message or errno.errorcode.get(err, str(err)))
        self.errno = err


class CephFS:
    """A mounted filesystem (libcephfs ceph_mount role)."""

    def __init__(self, ioctx, layout: FileLayout | None = None,
                 journaling: bool = True) -> None:
        self.io = ioctx
        self.layout = layout or FileLayout(stripe_unit=1 << 20,
                                           stripe_count=1,
                                           object_size=1 << 20)
        self.journal = Journaler(self.io, "mdslog") if journaling \
            else None
        import threading
        self._mds_lock = threading.Lock()
        self._mds_pos = 0            # next position to commit
        self._mds_done: set[int] = set()
        if self.journal is not None:
            if not self.journal.exists():
                self.journal.create()
            self._replay_mds_tail()
        # bootstrap the root directory (idempotent)
        try:
            self._read_inode(ROOT_INO)
        except FSError:
            self._write_inode(ROOT_INO, {
                "type": "dir", "entries": {}, "mtime": time.time()})

    # -- MDS journal (osdc/Journaler + MDLog roles) -------------------
    def _replay_mds_tail(self) -> None:
        """Mount-time recovery (the standby-MDS replay): re-execute
        journaled intents the previous writer never completed. Steps
        are idempotent-tolerant, so replaying an op that partially
        (or fully) applied converges."""
        try:
            end = self.journal.end_position()
        except JournalError:
            return
        pos = self.journal.committed(MDS_CLIENT)
        applied = min(pos, end)
        try:
            for epos, payload in self.journal.read_from(applied):
                self._apply_mds_event(json.loads(payload))
                applied = epos + 1
        except JournalError:
            pass            # commit only the prefix that applied
        self._mds_pos = applied
        self.journal.commit(MDS_CLIENT, applied)

    def _mds_event(self, op: str, **args) -> int | None:
        if self.journal is None:
            return None
        return self.journal.append(
            json.dumps({"op": op, **args}).encode())

    def _mds_committed(self, pos: int | None) -> None:
        """Mark an op's intent completed — including DELIBERATE
        failures (EEXIST etc.): only a crash mid-steps may leave an
        intent for replay. The commit pointer advances over the
        CONTIGUOUS prefix of completed positions (concurrent dirops
        finish out of order; a naive equals-check would freeze the
        pointer forever after the first inversion, and a later mount
        would replay stale completed intents — unlink/rename replays
        that name-match objects re-created since: data loss)."""
        if self.journal is None or pos is None:
            return
        with self._mds_lock:
            self._mds_done.add(pos)
            old_pos = self._mds_pos
            while self._mds_pos in self._mds_done:
                self._mds_done.discard(self._mds_pos)
                self._mds_pos += 1
            if self._mds_pos != old_pos:
                self.journal.commit(MDS_CLIENT, self._mds_pos)
                # boundary-crossing check: out-of-order completion can
                # advance PAST a multiple of 128 in one step
                if old_pos // 128 != self._mds_pos // 128:
                    # reclaim consumed journal chunks (the reference
                    # trims MDLog segments the same way); without this
                    # the journal grows one entry per dirop forever
                    self.journal.trim()

    @staticmethod
    def _step(fn) -> None:
        """Run one replay step, tolerating already-applied state
        (EEXIST/ENOENT from a step that landed before the crash):
        tolerance must be PER STEP — an op's later steps are exactly
        what the replay exists to finish."""
        try:
            fn()
        except Exception:
            pass

    def _apply_mds_event(self, rec: dict) -> None:
        op = rec["op"]
        if op in ("mkdir", "create"):
            kind = "dir" if op == "mkdir" else "file"
            inode = {"type": kind, "mtime": time.time()}
            inode.update({"entries": {}} if kind == "dir"
                         else {"size": 0})

            def mk():
                try:
                    self._read_inode(rec["ino"])
                except FSError:
                    self._write_inode(rec["ino"], inode)
            self._step(mk)
            self._step(lambda: self._dir_link(rec["parent"],
                                              rec["name"],
                                              rec["ino"]))
        elif op == "unlink":
            self._step(lambda: self._dir_unlink(rec["parent"],
                                                rec["name"]))
            self._step(lambda: StripedObject(
                self.io, f"fsdata.{rec['ino']}").remove())
            self._step(lambda: self.io.remove(f"inode.{rec['ino']}"))
        elif op == "rmdir":
            self._step(lambda: self._dir_unlink(rec["parent"],
                                                rec["name"]))
            self._step(lambda: self.io.remove(f"inode.{rec['ino']}"))
        elif op == "rename":
            self._step(lambda: self._dir_link(rec["new_parent"],
                                              rec["new_name"],
                                              rec["ino"]))
            self._step(lambda: self._dir_unlink(rec["old_parent"],
                                                rec["old_name"]))

    # -- inode plumbing ------------------------------------------------
    def _read_inode(self, ino: int) -> dict:
        try:
            return json.loads(self.io.read(f"inode.{ino}"))
        except Exception:
            raise FSError(errno.ENOENT, f"no inode {ino}")

    def _write_inode(self, ino: int, inode: dict) -> None:
        self.io.write_full(f"inode.{ino}", json.dumps(inode).encode())

    def _alloc_ino(self) -> int:
        out = self.io.execute(SUPER_OID, "fs", "alloc_ino")
        return json.loads(out)["ino"]

    def _resolve(self, path: str) -> tuple[int, dict]:
        """path -> (ino, inode); raises ENOENT/ENOTDIR."""
        ino, inode = ROOT_INO, self._read_inode(ROOT_INO)
        for part in [p for p in path.split("/") if p]:
            if inode["type"] != "dir":
                raise FSError(errno.ENOTDIR, path)
            child = inode["entries"].get(part)
            if child is None:
                raise FSError(errno.ENOENT, path)
            ino, inode = child, self._read_inode(child)
        return ino, inode

    def _resolve_parent(self, path: str) -> tuple[int, str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FSError(errno.EINVAL, "root has no parent")
        parent = "/".join(parts[:-1])
        ino, inode = self._resolve(parent)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, parent)
        return ino, parts[-1]

    def _dir_link(self, dir_ino: int, name: str, ino: int) -> None:
        from ceph_tpu.client.rados import RadosError
        try:
            self.io.execute(f"inode.{dir_ino}", "fs", "dir_link",
                            json.dumps({"name": name,
                                        "ino": ino}).encode())
        except RadosError as exc:
            raise FSError(-exc.code) from None

    def _dir_unlink(self, dir_ino: int, name: str) -> int:
        from ceph_tpu.client.rados import RadosError
        try:
            out = self.io.execute(f"inode.{dir_ino}", "fs",
                                  "dir_unlink",
                                  json.dumps({"name": name}).encode())
        except RadosError as exc:
            raise FSError(-exc.code) from None
        return json.loads(out)["ino"]

    # -- namespace ops (libcephfs surface) ----------------------------
    def mkdir(self, path: str) -> None:
        parent, name = self._resolve_parent(path)
        ino = self._alloc_ino()
        pos = self._mds_event("mkdir", parent=parent, name=name,
                              ino=ino)
        try:
            self._write_inode(ino, {"type": "dir", "entries": {},
                                    "mtime": time.time()})
            self._dir_link(parent, name, ino)
        finally:
            self._mds_committed(pos)

    def readdir(self, path: str) -> list[str]:
        _, inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        return sorted(inode["entries"])

    def stat(self, path: str) -> dict:
        ino, inode = self._resolve(path)
        out = {"ino": ino, "type": inode["type"],
               "mtime": inode["mtime"]}
        if inode["type"] == "file":
            out["size"] = inode.get("size", 0)
        else:
            out["nentries"] = len(inode["entries"])
        return out

    def rmdir(self, path: str) -> None:
        ino, inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FSError(errno.ENOTDIR, path)
        if inode["entries"]:
            raise FSError(errno.ENOTEMPTY, path)
        parent, name = self._resolve_parent(path)
        pos = self._mds_event("rmdir", parent=parent, name=name,
                              ino=ino)
        try:
            self._dir_unlink(parent, name)
            self.io.remove(f"inode.{ino}")
        finally:
            self._mds_committed(pos)

    def create(self, path: str) -> "File":
        parent, name = self._resolve_parent(path)
        ino = self._alloc_ino()
        pos = self._mds_event("create", parent=parent, name=name,
                              ino=ino)
        try:
            self._write_inode(ino, {"type": "file", "size": 0,
                                    "mtime": time.time()})
            self._dir_link(parent, name, ino)
        finally:
            self._mds_committed(pos)
        return File(self, ino)

    def open(self, path: str, create: bool = False) -> "File":
        try:
            ino, inode = self._resolve(path)
        except FSError as exc:
            if create and exc.errno == errno.ENOENT:
                return self.create(path)
            raise
        if inode["type"] != "file":
            raise FSError(errno.EISDIR, path)
        return File(self, ino)

    def unlink(self, path: str) -> None:
        ino, inode = self._resolve(path)
        if inode["type"] == "dir":
            raise FSError(errno.EISDIR, path)
        parent, name = self._resolve_parent(path)
        pos = self._mds_event("unlink", parent=parent, name=name,
                              ino=ino)
        try:
            self._dir_unlink(parent, name)
            StripedObject(self.io, f"fsdata.{ino}").remove()
            self.io.remove(f"inode.{ino}")
        finally:
            self._mds_committed(pos)

    def rename(self, old: str, new: str) -> None:
        """Link under the new name, then unlink the old. The journaled
        intent makes the pair crash-atomic: a mount after a crash
        between the steps replays the intent and finishes the unlink
        (the MDS journal's dirop atomicity, MDLog/EUpdate role)."""
        ino, _ = self._resolve(old)
        new_parent, new_name = self._resolve_parent(new)
        old_parent, old_name = self._resolve_parent(old)
        pos = self._mds_event(
            "rename", ino=ino, new_parent=new_parent,
            new_name=new_name, old_parent=old_parent,
            old_name=old_name)
        try:
            self._dir_link(new_parent, new_name, ino)
            self._dir_unlink(old_parent, old_name)
        finally:
            self._mds_committed(pos)


class File:
    """An open file handle (libcephfs Fh role)."""

    def __init__(self, fs: CephFS, ino: int) -> None:
        self.fs = fs
        self.ino = ino
        self._data = StripedObject(fs.io, f"fsdata.{ino}", fs.layout)

    def write(self, data: bytes, offset: int = 0) -> int:
        self._data.write(data, offset=offset)
        inode = self.fs._read_inode(self.ino)
        inode["size"] = max(inode.get("size", 0), offset + len(data))
        inode["mtime"] = time.time()
        self.fs._write_inode(self.ino, inode)
        return len(data)

    def read(self, length: int | None = None, offset: int = 0) -> bytes:
        inode = self.fs._read_inode(self.ino)
        size = inode.get("size", 0)
        if length is None:
            length = max(size - offset, 0)
        length = min(length, max(size - offset, 0))
        if length <= 0:
            return b""
        out = self._data.read(length, offset)
        return out + b"\x00" * (length - len(out))

    def truncate(self, size: int) -> None:
        inode = self.fs._read_inode(self.ino)
        inode["size"] = size
        self.fs._write_inode(self.ino, inode)
        self._data.size = min(self._data.size, size)
        self._data._write_meta()

    def size(self) -> int:
        return self.fs._read_inode(self.ino).get("size", 0)
