"""Per-value Paxos log (Paxos.cc share_state role) + real Elector
(src/mon/Elector.cc propose/defer/victory): commit replication and
rejoin catch-up ride per-value DELTAS sized by the change, and
leadership moves through election epochs."""

import time

import pytest

from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils.config import g_conf


@pytest.fixture
def fast():
    conf = g_conf()
    keys = ("osd_heartbeat_interval", "osd_heartbeat_grace",
            "mon_election_timeout", "mon_commit_timeout")
    old = {k: conf[k] for k in keys}
    conf.set("osd_heartbeat_interval", 0.25)
    conf.set("osd_heartbeat_grace", 2.0)
    conf.set("mon_election_timeout", 0.8)
    conf.set("mon_commit_timeout", 1.5)
    yield
    for k, v in old.items():
        conf.set(k, v)


def _wait(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(msg)


def test_rejoin_catchup_rides_deltas_not_snapshots(fast):
    """Partition one mon away, commit K map changes, heal: the
    laggard catches up via K per-value deltas; nobody ships a
    snapshot (the share_state discipline)."""
    with MiniCluster(n_osds=2, n_mons=3) as cluster:
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1)
        cluster.create_pool("base", pg_num=2, size=2)
        _wait(lambda: len({m._last_committed()
                           for m in cluster.mons.values()}) == 1)
        mons = cluster.mons
        full_before = {r: m.paxos_stats["full_sent"]
                       for r, m in mons.items()}
        lagger = mons[2]
        applied_before = dict(lagger.paxos_stats)
        cluster.partition_mons([0, 1], [2])
        for i in range(5):
            code, outs, _ = cluster.mon_cmd(
                prefix="osd pool create", pool=f"delta{i}",
                pg_num=2, size=2)
            assert code == 0, outs
        cluster.heal_mons()
        _wait(lambda: lagger._last_committed() ==
              mons[0]._last_committed(),
              msg="laggard never caught up")
        assert all(f"delta{i}" in lagger.osdmap.pool_by_name
                   for i in range(5))
        # the catch-up was DELTA transfer: the laggard applied >= 5
        # deltas and zero snapshots; no mon shipped a snapshot
        d_applied = lagger.paxos_stats["delta_applied"] - \
            applied_before["delta_applied"]
        f_applied = lagger.paxos_stats["full_applied"] - \
            applied_before["full_applied"]
        assert d_applied >= 5, d_applied
        assert f_applied == 0, f_applied
        for r, m in mons.items():
            assert m.paxos_stats["full_sent"] == full_before[r], \
                f"mon rank {r} shipped a snapshot during catch-up"


def test_steady_state_commits_are_delta_replicated(fast):
    with MiniCluster(n_osds=2, n_mons=3) as cluster:
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1)
        cluster.create_pool("p0", pg_num=2, size=2)
        _wait(lambda: len({m._last_committed()
                           for m in cluster.mons.values()}) == 1)
        peons = [m for m in cluster.mons.values()
                 if not m.is_leader()]
        leader = next(m for m in cluster.mons.values()
                      if m.is_leader())
        before = [dict(p.paxos_stats) for p in peons]
        full_before = leader.paxos_stats["full_sent"]
        for i in range(3):
            code, _, _ = cluster.mon_cmd(
                prefix="osd pool create", pool=f"st{i}", pg_num=2,
                size=2)
            assert code == 0
        _wait(lambda: len({m._last_committed()
                           for m in cluster.mons.values()}) == 1)
        assert leader.paxos_stats["full_sent"] == full_before
        for p, b in zip(peons, before):
            assert p.paxos_stats["delta_applied"] > \
                b["delta_applied"]
            assert p.paxos_stats["full_applied"] == b["full_applied"]


def test_trimmed_log_falls_back_to_snapshot(fast):
    with MiniCluster(n_osds=2, n_mons=3) as cluster:
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1)
        for m in cluster.mons.values():
            m.PAXOS_KEEP = 3               # tiny log for the test
        cluster.create_pool("base", pg_num=2, size=2)
        _wait(lambda: len({m._last_committed()
                           for m in cluster.mons.values()}) == 1)
        lagger = cluster.mons[2]
        cluster.partition_mons([0, 1], [2])
        for i in range(6):                 # > PAXOS_KEEP: log trims
            code, _, _ = cluster.mon_cmd(
                prefix="osd pool create", pool=f"tr{i}", pg_num=2,
                size=2)
            assert code == 0
        leader = next(m for m in cluster.mons.values()
                      if m.is_leader())
        assert leader._trim_floor() > 0    # the log really trimmed
        before_full = lagger.paxos_stats["full_applied"]
        cluster.heal_mons()
        _wait(lambda: lagger._last_committed() ==
              leader._last_committed(),
              msg="laggard never caught up past the trim")
        assert all(f"tr{i}" in lagger.osdmap.pool_by_name
                   for i in range(6))
        assert lagger.paxos_stats["full_applied"] > before_full


def test_election_epochs_advance_through_failover(fast):
    with MiniCluster(n_osds=2, n_mons=3) as cluster:
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1)
        mons = cluster.mons
        # stable: every mon agrees on an EVEN epoch and the quorum
        _wait(lambda: len({m._election_epoch()
                           for m in mons.values()}) == 1)
        ep0 = mons[0]._election_epoch()
        assert ep0 % 2 == 0 and ep0 > 0
        leader = next(m for m in mons.values() if m.is_leader())
        assert sorted(leader._quorum) == [0, 1, 2]
        # kill the leader: the survivors elect through a NEWER epoch
        dead = leader.rank
        cluster.kill_mon(dead)
        _wait(lambda: sum(m.is_leader() for r, m in
                          cluster.mons.items() if r != dead) == 1,
              msg="no successor elected")
        successor = next(m for r, m in cluster.mons.items()
                         if r != dead and m.is_leader())
        ep1 = successor._election_epoch()
        assert ep1 > ep0 and ep1 % 2 == 0
        assert dead not in successor._quorum
        # commits still flow under the new reign
        code, outs, _ = cluster.mon_cmd(
            prefix="osd pool create", pool="after", pg_num=2, size=2)
        assert code == 0, outs


def test_healed_stale_leader_deposes_on_epoch(fast):
    """An isolated old leader must step down the moment it hears a
    NEWER election epoch — no dual-leader window survives a heal."""
    with MiniCluster(n_osds=2, n_mons=3) as cluster:
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1)
        leader = next(m for m in cluster.mons.values()
                      if m.is_leader())
        others = [r for r in cluster.mons if r != leader.rank]
        cluster.partition_mons([leader.rank], others)
        # majority side elects a new reign
        _wait(lambda: sum(cluster.mons[r].is_leader()
                          for r in others) == 1,
              msg="majority never elected")
        assert leader.is_leader()          # stale belief, minority
        cluster.heal_mons()
        _wait(lambda: sum(m.is_leader() for m in
                          cluster.mons.values()) == 1,
              msg="dual leaders survived the heal")
        assert not leader.is_leader() or \
            all(m._leader_rank == leader.rank
                for m in cluster.mons.values())
