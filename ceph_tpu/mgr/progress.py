"""progress — long-running recovery events with completion ratios.

Reference: src/pybind/mgr/progress/module.py: watches PG state changes
and surfaces "Rebalancing after osd.N marked out"-style events with a
progress bar. Here the module samples the mon's status (degraded object
counts per pool come from PG stats) and tracks each degraded episode
from first sight to drain.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.mgr.mgr_module import MgrModule


class Module(MgrModule):
    NAME = "progress"
    TICK_PERIOD = 1.0

    COMMANDS = ("ls", "show", "clear")

    def __init__(self, mgr) -> None:
        super().__init__(mgr)
        self.events: dict[str, dict] = {}       # id -> event
        self.completed: list[dict] = []

    @staticmethod
    def _degraded(status: dict) -> int:
        pgs = status.get("pgmap", {})
        if isinstance(pgs, dict):
            return int(pgs.get("degraded_pgs", 0) or 0)
        return 0

    def tick(self) -> None:
        try:
            status = self.get_status()
        except Exception:
            return
        degraded = self._degraded(status)
        ev = self.events.get("recovery")
        if degraded > 0:
            if ev is None:
                self.events["recovery"] = {
                    "id": "recovery",
                    "message": "Recovering degraded objects",
                    "started_at": time.time(),
                    "baseline": degraded,
                    "remaining": degraded,
                    "progress": 0.0,
                }
            else:
                ev["baseline"] = max(ev["baseline"], degraded)
                ev["remaining"] = degraded
                ev["progress"] = 1.0 - degraded / ev["baseline"]
        elif ev is not None:
            ev["progress"] = 1.0
            ev["remaining"] = 0
            ev["finished_at"] = time.time()
            self.completed.append(ev)
            del self.events["recovery"]
            if len(self.completed) > 50:
                self.completed = self.completed[-50:]

    def handle_command(self, cmd: dict) -> tuple[int, str, bytes]:
        sub = cmd.get("prefix", "ls")
        if sub in ("ls", "show"):
            return 0, "", json.dumps(
                {"events": list(self.events.values()),
                 "completed": self.completed}).encode()
        if sub == "clear":
            self.completed.clear()
            return 0, "cleared", b""
        return super().handle_command(cmd)
