"""Tier-1-safe smoke test for the BENCH pipeline wiring: bench.py must
import cleanly under JAX_PLATFORMS=cpu (the driver environment minus
the chip) and every metric line it emits must round-trip json.loads
INCLUDING the telemetry snapshot field — the schema the driver's
last-JSON-line reader and the BENCH history depend on."""

import json

import pytest


def test_bench_imports_cleanly():
    """Importing the module must not touch a device or run main()."""
    import bench
    assert callable(bench.main)
    assert bench.TOTAL_BUDGET < 870      # inside the driver timeout


def test_metric_line_roundtrips_with_telemetry(capsys):
    import bench

    # seed some real telemetry so the snapshot is non-trivial
    from ceph_tpu.utils.device_telemetry import telemetry
    telemetry().note_compile("bench_wiring_smoke", 0.01)

    bench.emit("smoke_metric", {"value": 1.23, "unit": "GB/s"})
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip()]
    rec = json.loads(lines[-1])
    assert rec["metric"] == "smoke_metric"
    assert rec["value"] == 1.23
    assert isinstance(rec["telemetry"], dict)
    assert rec["telemetry"].get("compiles", 0) >= 1
    # every metric line carries a structured health brief that
    # round-trips json.loads (HEALTH_OK-shaped on a clean CPU run)
    assert isinstance(rec["health"], dict)
    assert rec["health"]["status"] in ("HEALTH_OK", "HEALTH_WARN",
                                       "HEALTH_ERR")
    assert isinstance(rec["health"]["checks"], dict)
    # the combined (historical-schema) line carries both too
    combined = bench._combined(any_contended=False)
    rec2 = json.loads(json.dumps(combined))
    assert isinstance(rec2["telemetry"], dict)
    assert rec2["health"]["status"].startswith("HEALTH")
    bench._RESULTS.pop("smoke_metric", None)


def test_telemetry_snapshot_degrades_to_empty(monkeypatch):
    """A telemetry fault must never cost a metric line."""
    import bench

    import ceph_tpu.utils.device_telemetry as dt

    def boom():
        raise RuntimeError("telemetry down")

    monkeypatch.setattr(dt, "telemetry", boom)
    assert bench._telemetry_snapshot() == {}


def test_health_snapshot_degrades_to_ok_shape(monkeypatch):
    """A health-engine fault must never cost a metric line: the field
    degrades to a HEALTH_OK-shaped brief, not an exception."""
    import bench

    import ceph_tpu.mgr.health as hm

    def boom():
        raise RuntimeError("health engine down")

    monkeypatch.setattr(hm, "device_health_brief", boom)
    assert bench._health_snapshot() == {"status": "HEALTH_OK",
                                        "checks": {}}


class _StubIo:
    """Minimal io surface _bench drives (write_full/read/remove)."""

    def __init__(self):
        self.objects = {}

    def write_full(self, oid, data):
        self.objects[oid] = bytes(data)
        return 1

    def read(self, oid):
        return self.objects[oid]

    def remove(self, oid):
        self.objects.pop(oid, None)


def test_cluster_bench_line_carries_p50_p99_and_stage_breakdown():
    """ISSUE 6 satellites, pinned: cluster_bench metric lines carry
    p50_ms/p99_ms (from the same timed ops, zero extra budget) and a
    stage_breakdown — and the whole line round-trips json.loads."""
    from ceph_tpu.bench import cluster_bench
    from ceph_tpu.tools.rados_cli import _bench
    from ceph_tpu.utils.dataplane import dataplane

    # seed the stage registry so the breakdown is non-trivial
    dataplane().record_stages([("wire", 0.001),
                               ("commit_wait", 0.003)])
    dataplane().perf.hinc("op_total_us", 4000.0)
    dataplane().perf.tinc("op_total", 0.004)
    dataplane().perf.inc("ops_timed")

    out = _bench(_StubIo(), 0.05, "write", 1024, 2)
    cluster_bench.attach_stage_breakdown(out)
    rec = json.loads(json.dumps(out))
    assert rec["p99_ms"] >= rec["p50_ms"] > 0
    bd = rec["stage_breakdown"]
    assert bd["ops"] >= 1
    assert "wire" in bd["stages"]
    assert bd["stages"]["wire"]["share_pct"] >= 0
    assert "coverage_pct" in bd
    # ISSUE 14: the commit-path store brief rides the same line
    assert "store" in rec
    assert "txns" in rec["store"] and "fsyncs" in rec["store"]


def test_stage_breakdown_degrades_to_empty(monkeypatch):
    """A dataplane fault must never cost a cluster_bench line."""
    from ceph_tpu.bench import cluster_bench

    import ceph_tpu.utils.dataplane as dp

    def boom():
        raise RuntimeError("dataplane down")

    monkeypatch.setattr(dp, "dataplane", boom)
    out = cluster_bench.attach_stage_breakdown({"value": 1})
    assert out["stage_breakdown"] == {}
    json.loads(json.dumps(out))


def test_cost_fields_roofline_next_to_measured(capsys, monkeypatch):
    """ISSUE 7 satellite: device metric lines carry cost_flops /
    cost_bytes / roofline_GBps from the compiled cost analysis of
    the exact step — and the whole line still round-trips json."""
    import time

    import jax.numpy as jnp

    import bench

    monkeypatch.setattr(bench, "_T0", time.perf_counter())

    def step(x):
        return (x.astype(jnp.float32) * 2).sum()

    x = jnp.zeros((1 << 14,), jnp.uint8)
    fields = bench._cost_fields(step, (x,), 1 << 14,
                                "bench[wiring_smoke]")
    # CPU XLA reports cost analysis; if a backend ever stops, the
    # contract is graceful degradation to {}
    if fields:
        assert fields["cost_flops"] > 0
        assert fields["cost_bytes"] > 0
        assert fields["roofline_GBps"] > 0
        # the signature landed in the device cost table
        from ceph_tpu.utils.device_telemetry import telemetry
        snap = telemetry().snapshot()
        assert "bench[wiring_smoke]" in snap["costs_by_signature"]
    line = {"value": 1.0, "unit": "GB/s"}
    line.update(fields)
    bench.emit("cost_smoke", line)
    out = [ln for ln in capsys.readouterr().out.splitlines()
           if ln.strip()]
    rec = json.loads(out[-1])
    assert rec["metric"] == "cost_smoke"
    if fields:
        assert rec["roofline_GBps"] == fields["roofline_GBps"]
    bench._RESULTS.pop("cost_smoke", None)


def test_cost_fields_degrade_and_respect_deadline(monkeypatch):
    """A cost-model fault returns {} (never costs a metric line), and
    a nearly-spent global deadline skips the extra compile entirely
    (the test_measure_guard budget identity stays intact)."""
    import time

    import bench
    from ceph_tpu.ops import cost_model

    monkeypatch.setattr(bench, "_T0", time.perf_counter())

    def boom(*a, **k):
        raise RuntimeError("cost model down")

    monkeypatch.setattr(cost_model, "bench_fields", boom)
    assert bench._cost_fields(lambda x: x, (1,), 10, "sig") == {}
    # deadline nearly spent: the helper must not even try
    monkeypatch.setattr(
        bench, "_T0",
        time.perf_counter() - bench.TOTAL_BUDGET + 1.0)
    called = []
    monkeypatch.setattr(cost_model, "bench_fields",
                        lambda *a, **k: called.append(1) or {})
    assert bench._cost_fields(lambda x: x, (1,), 10, "sig") == {}
    assert not called, "cost analysis ran inside the compile tail"


def test_degraded_rows_emit_parseable_lines(capsys, monkeypatch):
    """ISSUE 8: the two degraded-mode serving rows. The GB/s row
    measures the exact signature-grouped decode matvec the batched
    decode-on-read route launches (bit-exactness gate inside), the
    p99 row times individual blocked launches of the same program —
    both must land parseable lines with the coalescing factor on
    them."""
    import time

    import bench

    monkeypatch.setitem(bench.BUDGETS, "degraded_read", (2.0, 0.0))
    monkeypatch.setitem(bench.BUDGETS, "degraded_p99", (1.0, 0.0))
    monkeypatch.setattr(bench, "_T0", time.perf_counter())
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 60.0)

    contended = bench._bench_degraded_read(lambda *a, **k: None, {})
    assert isinstance(contended, bool)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip()]
    recs = {json.loads(ln)["metric"]: json.loads(ln) for ln in lines}
    read = recs["degraded_read_GBps"]
    assert "error" not in read, read
    assert read["value"] > 0
    assert read["unit"] == "GB/s"
    assert read["objects_per_flush"] == bench.DEGRADED_OBJECTS
    assert isinstance(read["telemetry"], dict)
    p99 = recs["degraded_p99_ms"]
    assert "error" not in p99, p99
    assert p99["value"] > 0
    assert p99["unit"] == "ms"
    assert p99["p50_ms"] <= p99["value"]
    assert p99["samples"] >= 1
    # the per-object floor is the flush latency amortized over the
    # coalesced batch — the number the QoS bar is derived from
    assert p99["per_object_p99_ms"] == pytest.approx(
        p99["value"] / bench.DEGRADED_OBJECTS, rel=0.01)
    # the combined historical line carries both families
    combined = bench._combined(any_contended=False)
    assert "degraded_read_value" in combined
    assert "degraded_p99_value" in combined
    json.loads(json.dumps(combined))
    bench._RESULTS.pop("degraded_read_GBps", None)
    bench._RESULTS.pop("degraded_p99_ms", None)


def test_multichip_metric_emits_parseable_line(capsys, monkeypatch):
    """The round-9 acceptance gate, ISSUE 12 edition: on >= 2
    devices (the conftest's 8 virtual CPU devices here) bench's
    multichip family measures the real sharded encode step AND its
    decode sibling, and BOTH emitted lines parse with a positive
    GB/s value, n_devices, and a telemetry snapshot."""
    import time

    import bench

    # shrink sampling so the smoke test stays seconds, not the
    # driver-scale budget; the deadline is re-anchored to NOW (the
    # module-level _T0 is the import time of the whole test session)
    monkeypatch.setitem(bench.BUDGETS, "multichip_encode", (2.0, 0.0))
    monkeypatch.setitem(bench.BUDGETS, "multichip_decode", (2.0, 0.0))
    monkeypatch.setattr(bench, "_T0", time.perf_counter())
    monkeypatch.setattr(bench, "TOTAL_BUDGET", 60.0)

    contended = bench._bench_multichip(lambda *a, **k: None, {})
    assert isinstance(contended, bool)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip()]
    recs = {json.loads(ln)["metric"]: json.loads(ln)
            for ln in lines}
    for row in ("multichip_encode_GBps", "multichip_decode_GBps"):
        rec = recs[row]
        assert "skipped" not in rec and "error" not in rec, rec
        assert rec["n_devices"] >= 2
        assert rec["value"] > 0
        assert rec["unit"] == "GB/s"
        assert rec["compile_path"] in ("pjit", "shard_map")
        assert isinstance(rec["telemetry"], dict)
    # the mesh steps dispatched through the accounted entry
    assert recs["multichip_decode_GBps"]["telemetry"].get(
        "mesh_dispatches", 0) >= 2
    # the warmup compiles are ledger-accounted under the bench labels
    from ceph_tpu.utils.device_telemetry import telemetry
    assert telemetry().compile_count("bench[multichip_encode]") >= 1
    assert telemetry().compile_count("bench[multichip_decode]") >= 1
    bench._RESULTS.pop("multichip_encode_GBps", None)
    bench._RESULTS.pop("multichip_decode_GBps", None)
