"""ISSUE 13: the closed-loop tuner's control discipline and safety.

Everything here runs the REAL TunerEngine headless: scripted sensors,
scripted clock, a private ConfigProxy — deterministic by
construction. Pinned:

- Knob mechanics: bounded steps, type quantization, the operator-pin
  precedence (env/override outrank the tuner's mon-layer pushes);
- control discipline: hysteresis (a one-tick blip moves nothing),
  cool-down pacing (one actuation in flight, judged before the
  next), revert-on-regression with the bench_trend direction
  convention, escalating quarantine on repeated reverts;
- safety: a mgr killed mid-adjustment leaves every knob in bounds;
  tuner off is a literal NOOP (no engine, no counters registry, no
  knob writes, no threads);
- the actuator seam: a runtime knob push lands in a live
  DeviceEncodeEngine through its cached observer, and detaches at
  engine stop;
- load-aware placement weighting: imbalance publishes a weight
  vector, balance clears it back to hash-uniform.
"""

import threading

import pytest

from ceph_tpu.mgr.tuner import (
    DEFAULT_RULES,
    LiveSensors,
    Module as TunerModule,
    ScriptedSensors,
    TunerEngine,
    _set_active,
    status_if_active,
)
from ceph_tpu.utils.config import SCHEMA, ConfigProxy, g_conf
from ceph_tpu.utils.knobs import TUNER_KNOBS, Knob, KnobRegistry

BASE = {"p99_ms": 10.0, "mbps": 100.0, "hbm_live": 0,
        "hbm_limit": 1 << 30, "inflight": 3, "window": 3,
        "occupancy": 1, "flush_bytes_mean": 0, "health_rank": 0,
        "fault_events": 0, "mesh_slots": 0, "slot_staged": {}}

SATURATED = dict(BASE, inflight=3, window=3)          # window_grow
QUIET = dict(BASE, inflight=1)                        # nothing fires


def _engine(trace, conf=None, **kw):
    conf = conf or ConfigProxy(SCHEMA)
    clock = [0.0]

    def advance():
        clock[0] += 1.0
        return clock[0]

    eng = TunerEngine(ScriptedSensors(trace), conf=conf,
                      clock=lambda: clock[0], wall=lambda: clock[0],
                      publish_perf=False, **kw)
    return eng, conf, clock


def _run(eng, clock, ticks):
    out = []
    for _ in range(ticks):
        clock[0] += 1.0
        out.extend(eng.tick())
    return out


# -- knob mechanics ----------------------------------------------------

def test_knob_steps_clamp_and_quantize():
    conf = ConfigProxy(SCHEMA)
    w = TUNER_KNOBS.get("engine_window")
    assert w.up(3, conf) == 4 and w.down(3, conf) == 2
    assert w.down(1, conf) == 1 and w.up(16, conf) == 16   # clamped
    fb = TUNER_KNOBS.get("engine_flush_bytes")
    assert fb.up(1 << 20, conf) == 2 << 20
    assert fb.down(1 << 20, conf) == 1 << 20               # at lo
    assert isinstance(fb.up(1 << 20, conf), int)           # quantized
    hz = TUNER_KNOBS.get("profiler_hz")
    assert hz.up(50.0, conf) == 100.0                      # float knob


def test_knob_envelope_within_option_bounds():
    """Every declared knob's envelope must sit inside its Option's
    hard min/max — a tuner value an Option would reject could strand
    a daemon mid-push."""
    for knob in TUNER_KNOBS:
        opt = SCHEMA.get(knob.name)
        opt.coerce(knob.lo if opt.type is not int else int(knob.lo))
        opt.coerce(knob.hi if opt.type is not int else int(knob.hi))


def test_push_lands_on_mon_layer_and_pins_win():
    conf = ConfigProxy(SCHEMA)
    val, landed = TUNER_KNOBS.push("engine_window", 7, conf)
    assert (val, landed) == (7, True)
    assert conf.source_of("engine_window") == "mon"
    # an env-layer pin outranks the push: the tuner must SEE that
    conf.set("engine_window", 2, source="env")
    val, landed = TUNER_KNOBS.push("engine_window", 9, conf)
    assert not landed and conf["engine_window"] == 2
    detail = TUNER_KNOBS.vector_detail(conf)
    assert detail["engine_window"]["pinned"]
    assert detail["engine_flush_bytes"]["pinned"] is False


def test_duplicate_knob_rejected():
    reg = KnobRegistry([Knob("engine_window", 1, 8, 1, kind="add")])
    with pytest.raises(ValueError):
        reg.add(Knob("engine_window", 1, 8, 1, kind="add"))


# -- control discipline ------------------------------------------------

def test_hysteresis_one_tick_blip_moves_nothing():
    trace = [QUIET, SATURATED, QUIET, QUIET, QUIET, QUIET]
    eng, conf, clock = _engine(trace)
    _run(eng, clock, 6)
    assert conf["engine_window"] == SCHEMA.get(
        "engine_window").default
    assert eng.history_dump() == []


def test_step_then_cooldown_then_judgment():
    eng, conf, clock = _engine([SATURATED] * 20)
    decisions = _run(eng, clock, 8)
    kinds = [(d["kind"], d["t"]) for d in decisions]
    # hysteresis=2 -> step at t=2; cooldown 3 -> judged (confirmed,
    # flat objective) at t=5; next step waits a full cooldown more
    assert kinds[0] == ("step", 2.0)
    assert kinds[1] == ("confirm", 5.0)
    steps = [d for d in decisions if d["kind"] == "step"]
    assert all(b["t"] - a["t"] >= eng.cooldown_s
               for a, b in zip(steps, steps[1:]))
    # while a step is pending, nothing else actuates
    for a, b in zip(decisions, decisions[1:]):
        if a["kind"] == "step":
            assert b["knob"] == a["knob"]


def test_revert_on_regression_within_one_cooldown():
    bad = dict(SATURATED, p99_ms=40.0)     # 4x p99, flat throughput
    eng, conf, clock = _engine([SATURATED] * 2 + [bad] * 20)
    decisions = _run(eng, clock, 12)
    step = next(d for d in decisions if d["kind"] == "step")
    revert = next(d for d in decisions if d["kind"] == "revert")
    assert revert["t"] - step["t"] <= eng.cooldown_s
    assert revert["knob"] == "engine_window"
    assert revert["from"] == step["to"]
    assert revert["to"] == step["from"]
    assert conf["engine_window"] == step["from"]
    # the judgment is the bench_trend direction convention
    assert revert["judge"]["d_p99_pct"] < -eng.threshold_pct
    # the reverted knob is quarantined: no further window steps
    # inside the burn window
    later_steps = [d for d in decisions
                   if d["kind"] == "step" and d["t"] > revert["t"]
                   and d["knob"] == "engine_window"]
    assert all(d["t"] >= revert["t"] + 4 * eng.cooldown_s
               for d in later_steps)


def test_escalating_backoff_on_repeated_reverts():
    """Every consecutive revert of the same probe doubles the
    quarantine — the flap damper. Needs a RESPONSIVE plant (p99
    follows the knob): against a static trace the controller rightly
    concludes its step changed nothing and confirms it."""
    conf = ConfigProxy(SCHEMA)

    class Responsive:
        def sample(self):
            w = conf["engine_window"]
            return dict(SATURATED,
                        p99_ms=10.0 if w <= 3 else 40.0)

    clock = [0.0]
    eng = TunerEngine(Responsive(), conf=conf,
                      clock=lambda: clock[0], wall=lambda: clock[0],
                      publish_perf=False)
    decisions = _run(eng, clock, 150)
    reverts = [d["t"] for d in decisions
               if d["kind"] == "revert"
               and d["knob"] == "engine_window"]
    assert len(reverts) >= 3
    assert conf["engine_window"] == 3         # always rolled back
    gaps = [b - a for a, b in zip(reverts, reverts[1:])]
    assert all(b > a for a, b in zip(gaps, gaps[1:])), gaps


def test_pinned_knob_never_stepped():
    conf = ConfigProxy(SCHEMA)
    conf.set("engine_window", 3, source="env")     # operator pin
    eng, conf, clock = _engine([SATURATED] * 10, conf=conf)
    _run(eng, clock, 10)
    assert conf.source_of("engine_window") == "env"
    assert conf["engine_window"] == 3
    assert not any(d["kind"] == "step"
                   and d["knob"] == "engine_window"
                   for d in eng.history_dump())


def test_clamped_at_bound_counts_not_steps():
    conf = ConfigProxy(SCHEMA)
    conf.set("engine_window", 16)                  # knob hi
    # hbm pressure wants window DOWN; saturation wants UP — at the
    # hi bound the up-rule must clamp, not spin
    eng, conf2, clock = _engine([SATURATED] * 8, conf=conf)
    _run(eng, clock, 8)
    assert conf["engine_window"] == 16 or \
        conf.source_of("engine_window") == "override"
    assert all(d["to"] != d["from"] for d in eng.history_dump()
               if d["kind"] == "step")


def test_determinism_same_trace_same_history():
    bad = dict(SATURATED, p99_ms=40.0, mbps=60.0)
    trace = [SATURATED] * 3 + [bad] * 10 + [QUIET] * 10
    eng1, _, c1 = _engine(trace)
    eng2, _, c2 = _engine(trace)
    _run(eng1, c1, 23)
    _run(eng2, c2, 23)

    def strip(hist):
        return [{k: v for k, v in d.items() if k != "trace_id"}
                for d in hist]

    assert strip(eng1.history_dump()) == strip(eng2.history_dump())
    assert eng1.history_dump() != []


def test_mid_adjustment_kill_leaves_knobs_in_bounds():
    """A mgr that dies between step and judgment (shutdown without
    revert, or no shutdown at all) leaves every knob inside its
    declared envelope — pushes are clamped at the only write path."""
    chaos = []
    for i in range(40):
        chaos.append(dict(SATURATED,
                          p99_ms=10.0 * (1 + (i * 7) % 5),
                          hbm_live=(i % 3) * (1 << 29),
                          occupancy=(i * 3) % 8,
                          health_rank=i % 2))
    eng, conf, clock = _engine(chaos)
    _run(eng, clock, 17)      # stop mid-run: pending may be open
    del eng                   # the "kill": nobody judges or reverts
    for knob in TUNER_KNOBS:
        val = conf[knob.name]
        assert knob.lo <= val <= knob.hi, (knob.name, val)
        SCHEMA.get(knob.name).coerce(val)


# -- off = literal NOOP ------------------------------------------------

class _StubMgr:
    def __init__(self):
        self.modules = {}


def test_tuner_off_is_literal_noop(monkeypatch):
    from ceph_tpu.utils.perf_counters import collection
    monkeypatch.delenv("CEPH_TPU_TUNER", raising=False)
    assert g_conf()["tuner_enabled"] is False      # default OFF
    collection().remove("tuner")                   # fresh view
    before_threads = {t.name for t in threading.enumerate()}
    before_diff = dict(g_conf().diff())
    mod = TunerModule(_StubMgr())
    mod.tick()
    assert mod.engine is None
    assert mod.TICK_PERIOD == 0.0                  # never ticked
    assert collection().get("tuner") is None       # zero counters
    assert dict(g_conf().diff()) == before_diff    # zero knob writes
    assert {t.name for t in threading.enumerate()} == before_threads
    code, msg, data = mod.handle_command({"prefix": "status"})
    assert code == 0 and b'"enabled": false' in data
    mod.shutdown()


def test_env_switch_enables(monkeypatch):
    from ceph_tpu.mgr.tuner import tuner_on
    monkeypatch.delenv("CEPH_TPU_TUNER", raising=False)
    assert tuner_on() is False
    monkeypatch.setenv("CEPH_TPU_TUNER", "1")
    assert tuner_on() is True
    monkeypatch.setenv("CEPH_TPU_TUNER", "0")
    assert tuner_on() is False


# -- sensors -----------------------------------------------------------

def test_live_sensors_sample_shape():
    snap = LiveSensors().sample()
    assert isinstance(snap, dict)
    for key in ("p99_ms", "hbm_limit"):
        assert isinstance(snap.get(key, 0), (int, float))
    # never raises, even with no health source and a cold stack


def test_rules_cover_every_knob_family():
    """Every declared actuator has at least one rule that can move
    it — a knob no rule touches is dead weight in the registry."""
    ruled = {r.knob for r in DEFAULT_RULES}
    for name in TUNER_KNOBS.names():
        assert name in ruled or name == "host_flush_bytes", name
    # host_flush_bytes is registry-managed (bounds/pins/reporting)
    # but deliberately not auto-stepped yet: its crossover is
    # calibrated (BASELINE.md), not load-dependent


# -- the actuator seam (runtime observers) -----------------------------

def test_engine_window_push_lands_via_observer(monkeypatch):
    monkeypatch.delenv("CEPH_TPU_ENGINE_WINDOW", raising=False)
    monkeypatch.delenv("CEPH_TPU_ENGINE_FLUSH_BYTES", raising=False)
    from ceph_tpu.osd.device_engine import DeviceEncodeEngine
    eng = DeviceEncodeEngine(lambda k, f: f())
    try:
        assert eng._window == g_conf()["engine_window"]
        g_conf().set("engine_window", 5, source="mon")
        assert eng._window == 5
        g_conf().set("engine_flush_bytes", 128 << 20, source="mon")
        assert eng._flush_bytes == 128 << 20
        g_conf().set("mesh_flush_bytes", 2 << 20, source="mon")
        assert eng._mesh_flush_bytes == 2 << 20
        g_conf().set("host_flush_bytes", 256 << 10, source="mon")
        assert eng._host_flush_bytes == 256 << 10
    finally:
        eng.stop()
        g_conf().set_mon_layer({})
    # after stop the observers are detached: pushes no longer land
    g_conf().set("engine_window", 9, source="mon")
    try:
        assert eng._window == 5
    finally:
        g_conf().set_mon_layer({})


def test_engine_env_pin_freezes_knob(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_ENGINE_WINDOW", "2")
    from ceph_tpu.osd.device_engine import DeviceEncodeEngine
    eng = DeviceEncodeEngine(lambda k, f: f())
    try:
        assert eng._window == 2
        g_conf().set("engine_window", 8, source="mon")
        assert eng._window == 2                    # pinned
    finally:
        eng.stop()
        g_conf().set_mon_layer({})


# -- placement weighting ----------------------------------------------

def test_weights_rule_publishes_and_clears():
    from ceph_tpu.parallel import placement
    placement.set_slot_weights(None)
    hot = dict(BASE, mesh_slots=4,
               slot_staged={0: 900, 1: 30, 2: 40, 3: 30})
    balanced = dict(BASE, mesh_slots=4,
                    slot_staged={0: 25, 1: 25, 2: 25, 3: 25})
    eng, conf, clock = _engine([hot] * 4 + [balanced] * 4)
    try:
        _run(eng, clock, 4)
        weights = placement.slot_weights()
        assert weights is not None
        assert weights[0] < min(weights[s] for s in (1, 2, 3))
        kinds = [d["kind"] for d in eng.history_dump()]
        assert "weights" in kinds
        _run(eng, clock, 4)
        assert placement.slot_weights() is None    # back to uniform
    finally:
        eng.shutdown()
        placement.set_slot_weights(None)


def test_shutdown_clears_weights():
    from ceph_tpu.parallel import placement
    hot = dict(BASE, mesh_slots=2, slot_staged={0: 1000, 1: 10})
    eng, conf, clock = _engine([hot] * 4)
    _run(eng, clock, 3)
    assert placement.slot_weights() is not None
    eng.shutdown()
    assert placement.slot_weights() is None


# -- the bundle / status surface ---------------------------------------

def test_status_and_bundle_surface():
    bad = dict(SATURATED, p99_ms=40.0)
    eng, conf, clock = _engine([SATURATED] * 2 + [bad] * 10)
    _run(eng, clock, 8)
    st = eng.status()
    assert st["enabled"] and st["decisions"] >= 2
    assert set(st["knobs"]) == set(TUNER_KNOBS.names())
    _set_active(eng)
    try:
        brief = status_if_active()
        assert brief is not None
        assert any(d["kind"] == "revert" for d in brief["history"])
    finally:
        _set_active(None)
    assert status_if_active() is None
