"""Durability + network-fault QA tiers: whole-cluster restart from
disk (BlockStore), and workloads under messenger socket-failure
injection (the qa msgr-failures suites' role)."""

import os

import pytest

pytestmark = pytest.mark.slow  # tier-2: heavy cluster workload (tier-1 runs -m 'not slow')

from ceph_tpu.client.rados import RadosError
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils.config import g_conf


def test_cluster_restart_from_disk(tmp_path):
    """Stop every OSD, then boot a fresh cluster over the same
    BlockStore directories: all acked data must survive (the
    checkpoint/resume discipline: WAL'd kv + data file)."""
    data_dir = str(tmp_path)
    blobs = {f"o{i}": os.urandom(30_000 + i) for i in range(6)}
    with MiniCluster(n_osds=3, store="blockstore",
                     data_dir=data_dir) as c1:
        rados = c1.client()
        c1.create_ec_pool("dur", k=2, m=1, pg_num=2)
        c1.create_pool("durrep", pg_num=2, size=3)
        io_ec = rados.open_ioctx("dur")
        io_rep = rados.open_ioctx("durrep")
        for oid, blob in blobs.items():
            io_ec.write_full(oid, blob)
            io_rep.write_full(oid, blob)
        io_ec.write("o0", b"PATCH", offset=1000)   # partial overwrite
    # cluster fully stopped. Fresh daemons over the same stores; the
    # mon state is fresh (MemDB) so pools must be recreated with the
    # same ids — pool ids are allocated sequentially from 1, and PG
    # collections are keyed (pool_id, ps), so matching creation order
    # reattaches the data (the vstart restart discipline).
    with MiniCluster(n_osds=3, store="blockstore",
                     data_dir=data_dir) as c2:
        rados = c2.client()
        c2.create_ec_pool("dur", k=2, m=1, pg_num=2)
        c2.create_pool("durrep", pg_num=2, size=3)
        io_ec = rados.open_ioctx("dur")
        io_rep = rados.open_ioctx("durrep")
        expect0 = bytearray(blobs["o0"])
        expect0[1000:1005] = b"PATCH"
        assert io_ec.read("o0") == bytes(expect0)
        for oid, blob in blobs.items():
            if oid != "o0":
                assert io_ec.read(oid) == blob, f"ec/{oid}"
            assert io_rep.read(oid) == blob, f"rep/{oid}"
        assert c2.scrub_pool("dur", repair=False)["inconsistent"] == {}


def test_workload_under_socket_failures():
    """ms_inject_socket_failures (qa msgr-failures yamls): every Nth
    send drops the connection; acked writes must still read back."""
    conf = g_conf()
    old = conf["ms_inject_socket_failures"]
    conf.set("ms_inject_socket_failures", 150)
    try:
        with MiniCluster(n_osds=3) as c:
            rados = c.client()
            c.create_pool("msgr", pg_num=4, size=3)
            io = rados.open_ioctx("msgr")
            acked = {}
            for i in range(60):
                data = os.urandom(2000 + i)
                try:
                    io.write_full(f"m{i}", data)
                    acked[f"m{i}"] = data
                except RadosError:
                    pass
            assert len(acked) > 20, "injection drowned everything"
            for oid, data in acked.items():
                got = None
                for _ in range(5):      # reads may hit injections too
                    try:
                        got = io.read(oid)
                        break
                    except RadosError:
                        continue
                assert got == data, oid
    finally:
        conf.set("ms_inject_socket_failures", old)
