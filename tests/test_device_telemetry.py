"""Device-path telemetry (utils/device_telemetry): the PerfCounters
registry fed by the TPU EC pipeline — compile accounting with
recompile detection, batch-occupancy histograms, the queue-wait vs
device-time flush split, calibration outcomes — plus the trace-span
chain from a client write through the engine flush and the
``device perf dump`` admin command."""

import json
import threading
import time

import numpy as np
import pytest

from ceph_tpu.models import registry as ec_registry
from ceph_tpu.osd.device_engine import DeviceEncodeEngine
from ceph_tpu.osd.ec_util import StripeInfo
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils import tracing
from ceph_tpu.utils.admin_socket import asok_command
from ceph_tpu.utils.config import g_conf
from ceph_tpu.utils.device_telemetry import telemetry
from ceph_tpu.utils.perf_counters import PerfCounters


@pytest.fixture(autouse=True)
def _pin_device_route(monkeypatch):
    """These tests gate the DEVICE flush machinery (codec._matvec
    fakes, held StripeBatcher.flush_async); keep the tiny test
    flushes off the bulk-ingest small-flush host route, which
    encodes with a direct host matvec and would never hit the
    gates."""
    monkeypatch.setenv("CEPH_TPU_HOST_FLUSH_BYTES", "0")


def _codec(backend="numpy", k=2, m=1):
    return ec_registry.instance().factory(
        "jerasure", {"plugin": "jerasure", "k": str(k), "m": str(m),
                     "backend": backend})


def _counters():
    return telemetry().snapshot()["counters"]


# -- satellite: histogram bucket edges --------------------------------

def test_hinc_bucket_edges_pinned():
    """Bucket 0 = non-positive only; bucket b >= 1 = [2^(b-1), 2^b);
    positive sub-1.0 observations land in bucket 1 (not the zero
    bucket, which ``int(0.5) == 0`` used to send them to)."""
    pc = PerfCounters("hinc-edges")
    pc.add_histogram("h")
    cases = [
        (0, 0), (-1, 0),          # non-positive -> bucket 0
        (0.5, 1),                 # sub-1.0 positive -> bucket 1
        (1, 1), (1.9, 1),         # [1, 2)
        (2, 2), (3, 2),           # [2, 4)
        (4, 3), (7, 3),           # [4, 8)
        (8, 4), (15, 4),          # [8, 16)
        (2 ** 40, 31),            # clamped to the last bucket
    ]
    for value, want_bucket in cases:
        before = pc.get("h")
        pc.hinc("h", value)
        after = pc.get("h")
        got = [i for i in range(len(after))
               if after[i] != before[i]]
        assert got == [want_bucket], (value, got, want_bucket)


# -- compile accounting -----------------------------------------------

def test_recompile_counter_stays_at_one_across_100_calls():
    """100 same-signature calls through a device entry point compile
    exactly once; the recompile counter does not move (the pow2
    bucketing working as designed)."""
    from ceph_tpu.ops import gf256, gf_jax
    mat = gf256.rs_matrix_isa(3, 2)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(3, 5000), dtype=np.uint8)
    gf_jax.matvec(mat, data)          # first call: the compile
    snap1 = telemetry().snapshot()
    sigs1 = {s: v["compiles"]
             for s, v in snap1["compiles_by_signature"].items()
             if s.startswith("gf_jax[2x3]")}
    assert sigs1 and all(n == 1 for n in sigs1.values()), sigs1
    for _ in range(100):
        gf_jax.matvec(mat, data)
    snap2 = telemetry().snapshot()
    sigs2 = {s: v["compiles"]
             for s, v in snap2["compiles_by_signature"].items()
             if s.startswith("gf_jax[2x3]")}
    assert sigs2 == sigs1, (sigs1, sigs2)
    assert snap2["counters"]["recompiles"] == \
        snap1["counters"]["recompiles"]
    # compile wall time was accounted
    assert snap2["counters"]["compile_time"]["avgcount"] >= 1


def test_note_compile_flags_recompiles():
    tel = telemetry()
    before = _counters()["recompiles"]
    tel.note_compile("test_sig_recompile", 0.1)
    assert _counters()["recompiles"] == before
    tel.note_compile("test_sig_recompile", 0.1)
    assert _counters()["recompiles"] == before + 1
    assert tel.compile_count("test_sig_recompile") == 2


# -- engine flush counters --------------------------------------------

def test_counters_across_staged_encode_decode_round_trip():
    """A staged encode + signature-batched decode round trip on the
    CPU backend moves the always-on counters: occupancy histograms
    match the scripted flush pattern, bytes/queue-wait/device-time
    all advance."""
    from ceph_tpu.osd import ec_util

    codec = _codec(k=2, m=1)
    sinfo = StripeInfo(stripe_width=2 * 1024, chunk_size=1024)
    in_first = threading.Event()
    release = threading.Event()
    orig = codec._matvec
    calls = []

    def gated(mat, data):
        calls.append(1)
        if len(calls) == 1:
            in_first.set()
            release.wait(10)
        return orig(mat, data)

    codec._matvec = gated
    before = _counters()
    eng = DeviceEncodeEngine(lambda key, fn: fn())
    try:
        rng = np.random.default_rng(0)
        payloads = [rng.integers(0, 256, 2048, dtype=np.uint8)
                    for _ in range(6)]
        done = []
        eng.stage_encode("pg0", codec, sinfo, payloads[0],
                         lambda s, c, e: done.append(e))
        assert in_first.wait(10)      # flush 1 (1 op) holds the gate
        for p in payloads[1:]:        # flush 2 accumulates 5 ops
            eng.stage_encode("pg1", codec, sinfo, p,
                             lambda s, c, e: done.append(e))
        release.set()
        deadline = time.monotonic() + 10
        while len(done) < 6 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(done) == 6 and all(e is None for e in done)

        # decode leg of the round trip (one signature, 2 ops)
        host = _codec(k=2, m=1)
        full = ec_util.encode(sinfo, host, payloads[0])
        shards = {0: full[0], 2: full[2]}
        out = eng.decode_sync("pg0", codec, sinfo, shards, [0, 1])
        assert out is not None and \
            np.array_equal(np.asarray(out[1]), full[1])
    finally:
        eng.stop()

    after = _counters()
    # occupancy histogram: one 1-op flush (bucket 1) and one 5-op
    # flush (5 in [4,8) -> bucket 3), per the scripted pattern
    d_occ = [a - b for a, b in zip(after["encode_batch_ops"],
                                   before["encode_batch_ops"])]
    assert d_occ[1] == 1 and d_occ[3] == 1 and sum(d_occ) == 2, d_occ
    d_dec = [a - b for a, b in zip(after["decode_batch_ops"],
                                   before["decode_batch_ops"])]
    assert d_dec[1] == 1 and sum(d_dec) == 1, d_dec
    assert after["bytes_encoded"] - before["bytes_encoded"] == \
        2048 * 6
    assert after["bytes_decoded"] > before["bytes_decoded"]
    assert after["encode_queue_wait"]["avgcount"] - \
        before["encode_queue_wait"]["avgcount"] == 6
    assert after["decode_queue_wait"]["avgcount"] - \
        before["decode_queue_wait"]["avgcount"] == 1
    assert after["flush_device_time"]["avgcount"] - \
        before["flush_device_time"]["avgcount"] == 2
    assert after["decode_flush_device_time"]["avgcount"] - \
        before["decode_flush_device_time"]["avgcount"] == 1
    d_bytes = [a - b for a, b in zip(after["flush_bytes"],
                                     before["flush_bytes"])]
    # flush sizes: 2048 (bucket 12) and 5*2048 = 10240 (bucket 14)
    assert d_bytes[12] == 1 and d_bytes[14] == 1, d_bytes


def test_lin_matvec_cache_hit_miss_accounting():
    """Clay's linearized-transform LRU reports hits/misses: the first
    decode of a signature is a miss (matrix build), repeats hit."""
    codec = ec_registry.instance().factory(
        "clay", {"k": "4", "m": "2", "backend": "numpy"})
    rng = np.random.default_rng(1)
    size = codec.sub_chunk_no * 8
    chunks = {i: rng.integers(0, 256, size, dtype=np.uint8)
              for i in range(6)}
    enc = codec.encode_chunks(list(range(6)),
                              {i: chunks[i] for i in range(4)})
    whole = {i: (chunks[i] if i < 4 else enc[i]) for i in range(6)}
    before = _counters()
    got = dict(whole)
    del got[1]
    codec.decode_chunks([1], got)       # miss: builds the matrix
    mid = _counters()
    codec.decode_chunks([1], got)       # hit: same signature
    after = _counters()
    assert mid["lin_matvec_misses"] > before["lin_matvec_misses"]
    assert after["lin_matvec_hits"] > mid["lin_matvec_hits"]


def test_calibration_outcome_recorded():
    """build_decode_matvec lands its decision in telemetry (on CPU the
    measurement is skipped and dense wins, recorded as such)."""
    from ceph_tpu.models.clay_device import build_decode_matvec
    codec = ec_registry.instance().factory(
        "clay", {"k": "4", "m": "2", "backend": "numpy"})
    mat = codec._lin_cached(
        ("dec", (2, 3, 4, 5), (0, 1)),
        lambda: codec._decode_matrix((2, 3, 4, 5), (0, 1)))
    fn = build_decode_matvec(codec, mat, label="test_decode")
    assert fn.path == "dense"
    snap = telemetry().snapshot()
    rows = {s: v for s, v in snap["calibrations"].items()
            if s.startswith("test_decode|")}
    assert rows, snap["calibrations"]
    assert all(v["winner"] == "dense" for v in rows.values())
    assert _counters()["calibrations"] >= 1


# -- prometheus exposition --------------------------------------------

def test_prometheus_exports_device_histograms():
    """The device histograms render as cumulative le-bucketed series
    (raw Python lists would be invalid exposition)."""
    from ceph_tpu.utils.prometheus import render_text
    telemetry().perf.hinc("encode_batch_ops", 3)
    text = render_text()
    assert "ceph_tpu_encode_batch_ops_bucket" in text
    assert 'le="+Inf"' in text
    assert "ceph_tpu_encode_batch_ops_count" in text
    assert "[" not in text.split("ceph_tpu_encode_batch_ops")[1][:200]


# -- cluster integration: asok + trace chain --------------------------

def test_device_perf_dump_and_trace_chain():
    """One client EC write against a device-backend pool: (a)
    ``device perf dump`` over the admin socket returns non-trivial
    counters; (b) with trace_all set, the write's trace covers
    client op -> shard sub-op -> engine flush -> kernel dispatch,
    queryable via dump_traces."""
    conf = g_conf()
    old = conf["trace_all"]
    conf.set("trace_all", True)
    tracing.tracer().clear()
    try:
        with MiniCluster(n_osds=3) as cluster:
            rados = cluster.client()
            cluster.create_ec_pool("tel", k=2, m=1, pg_num=1,
                                   backend="jax")
            io = rados.open_ioctx("tel")
            io.write_full("tel_obj", b"t" * 20_000)

            # (a) the admin command
            osd = next(iter(cluster.osds.values()))
            dump = asok_command(osd.asok.path, "device perf dump")
            counters = dump["counters"]
            assert counters["bytes_encoded"] > 0, counters
            assert sum(counters["encode_batch_ops"]) > 0
            assert counters["flush_device_time"]["avgcount"] > 0
            assert "compiles" in counters
            json.dumps(dump)          # the payload is JSON-clean

            # (b) the causal chain, queryable via the dump_traces
            # admin command (the blkin surface)
            spans = asok_command(osd.asok.path, "dump_traces")
            roots = [s for s in spans
                     if s["service"].startswith("client")
                     and "op=1" in s["name"]]
            assert roots, spans
            chain = asok_command(osd.asok.path, "dump_traces",
                                 trace_id=roots[-1]["trace_id"])
            by_name = {}
            for s in chain:
                by_name.setdefault(s["name"].split("(")[0], []).append(s)
            assert "handle_osd_op" in by_name
            assert "ec_sub_write" in by_name
            assert "engine_flush" in by_name, sorted(by_name)
            assert "kernel_dispatch" in by_name, sorted(by_name)
            eng = by_name["engine_flush"][-1]
            kd = by_name["kernel_dispatch"][-1]
            # kernel dispatch is a child of the engine flush span,
            # which is a child of the op span
            assert kd["parent_id"] == eng["span_id"]
            op_ids = {s["span_id"] for s in by_name["handle_osd_op"]}
            assert eng["parent_id"] in op_ids
            events = {e["event"].split(" ")[0]
                      for e in eng["events"]}
            assert "staged" in events and "batch_flush" in events
    finally:
        conf.set("trace_all", old)
        tracing.tracer().clear()


def test_tracing_off_allocates_no_spans():
    """With trace_enabled=false (the literal-NOOP escape hatch under
    the ISSUE-10 always-on default) the engine path allocates no Span
    objects (the NOOP discipline: tracing off must stay free)."""
    conf = g_conf()
    old_enabled = conf["trace_enabled"]
    conf.set("trace_enabled", False)
    assert not tracing.tracer().enabled
    made = []
    orig_init = tracing.Span.__init__

    def counting_init(self, *a, **kw):
        made.append(1)
        return orig_init(self, *a, **kw)

    tracing.Span.__init__ = counting_init
    try:
        with MiniCluster(n_osds=3) as cluster:
            rados = cluster.client()
            cluster.create_ec_pool("notrace", k=2, m=1, pg_num=1,
                                   backend="jax")
            io = rados.open_ioctx("notrace")
            io.write_full("quiet_obj", b"q" * 20_000)
            assert io.read("quiet_obj") == b"q" * 20_000
    finally:
        tracing.Span.__init__ = orig_init
        conf.set("trace_enabled", old_enabled)
    assert not made, f"{len(made)} Span objects allocated untraced"


# -- satellite: optracker at op ingress -------------------------------

def test_optracker_reports_in_flight_ec_ops():
    """The optracker is registered at op ingress (osd.py
    _handle_osd_op): an EC write held up inside the device engine is
    visible via dump_ops_in_flight, and lands in dump_historic_ops
    with its event timeline once committed."""
    from ceph_tpu.osd import ec_util

    hold = threading.Event()
    entered = threading.Event()
    orig = ec_util.StripeBatcher.flush_async

    def gated(self, with_crcs=False):
        entered.set()
        hold.wait(10)
        return orig(self, with_crcs)

    ec_util.StripeBatcher.flush_async = gated
    try:
        with MiniCluster(n_osds=3) as cluster:
            rados = cluster.client()
            cluster.create_ec_pool("trk", k=2, m=1, pg_num=1,
                                   backend="jax")
            io = rados.open_ioctx("trk")
            result = []
            t = threading.Thread(
                target=lambda: result.append(
                    io.write_full("held_obj", b"h" * 20_000)))
            t.start()
            assert entered.wait(10), "write never reached the engine"
            # the op is in flight while the engine holds its batch
            found = None
            deadline = time.monotonic() + 10
            while found is None and time.monotonic() < deadline:
                for osd in cluster.osds.values():
                    dump = asok_command(osd.asok.path,
                                        "dump_ops_in_flight")
                    ops = [o for o in dump["ops"]
                           if "held_obj" in o["desc"]]
                    if ops:
                        found = ops[0]
                        break
                time.sleep(0.02)
            assert found is not None, "in-flight EC op not reported"
            events = {e["event"] for e in found["events"]}
            assert "reached_pg" in events, found
            hold.set()
            t.join(timeout=15)
            assert not t.is_alive()
            # finished: moved to the historic ring
            historic = []
            for osd in cluster.osds.values():
                dump = asok_command(osd.asok.path,
                                    "dump_historic_ops")
                historic += [o for o in dump["ops"]
                             if "held_obj" in o["desc"]]
            assert historic, "committed op missing from historic ops"
            assert any(e["event"] == "done"
                       for e in historic[-1]["events"])
    finally:
        ec_util.StripeBatcher.flush_async = orig
        hold.set()


# -- dashboard panel --------------------------------------------------

def test_dashboard_device_panel():
    import urllib.request
    with MiniCluster(n_osds=2) as c:
        c.create_pool("ddash", pg_num=2, size=2)
        mgr = c.start_mgr()
        out = asok_command(mgr.asok.path, "dashboard on")
        assert out["code"] == 0
        st = asok_command(mgr.asok.path, "dashboard status")
        url = st["data"]["url"]
        dev = json.loads(urllib.request.urlopen(
            url + "api/device", timeout=10).read())
        assert "counters" in dev and "calibrations" in dev
        page = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "<h3>device</h3>" in page
        assert asok_command(mgr.asok.path, "dashboard off")["code"] == 0
