"""rgw lifecycle processor (src/rgw/rgw_lc.cc RGWLC role).

The reference runs lifecycle as a radosgw background worker: RGWLC
shards buckets-with-rules into lc.N omap objects and ``RGWLC::process``
(rgw_lc.cc:679) walks one shard per pass, expiring current versions
(laying delete markers on versioned buckets), reaping noncurrent
generations past their age, and removing delete markers left with no
generations under them.

This processor keeps the same pass semantics over :class:`RGWGateway`:
``process()`` walks every bucket that has rules and applies each
Enabled rule by prefix. ``day_seconds`` compresses a "day" for tests —
the reference's ``rgw_lc_debug_interval`` does exactly this.

The processor is an internal system actor: it calls gateway methods
directly and is not subject to ACLs (like the reference's lc worker
running as the system user).
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.services.rgw import RGWError, RGWGateway


class LifecycleProcessor:
    def __init__(self, gw: RGWGateway,
                 day_seconds: float = 86400.0) -> None:
        self.gw = gw
        self.day_seconds = day_seconds

    # -- one pass (RGWLC::process role) -------------------------------
    def process(self, now: float | None = None) -> dict:
        """Apply every bucket's enabled rules once, then run the
        deferred-GC reaper (orphaned striped tails from a gateway
        crash mid-delete — RGWGC::process, src/rgw/rgw_gc.cc:257);
        returns {"expired": n, "noncurrent_reaped": n,
        "markers_cleaned": n, "gc_entries": n, "gc_objects": n}."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "noncurrent_reaped": 0,
                 "markers_cleaned": 0}
        for bucket in self.gw.list_buckets():
            try:
                rules = self.gw.bucket_meta(bucket).get("lifecycle")
            except RGWError:
                continue
            for rule in rules or []:
                if rule.get("status", "Enabled") != "Enabled":
                    continue
                self._apply_rule(bucket, rule, now, stats)
        gc = self.gw.gc_process()
        stats["gc_entries"] = gc["entries"]
        stats["gc_objects"] = gc["objects"]
        return stats

    def _apply_rule(self, bucket: str, rule: dict, now: float,
                    stats: dict) -> None:
        prefix = rule.get("prefix", "")
        days = rule.get("days")
        nc_days = rule.get("noncurrent_days")
        if days:
            self._expire_current(bucket, prefix,
                                 now - days * self.day_seconds, stats)
        if nc_days:
            self._reap_noncurrent(
                bucket, prefix, now - nc_days * self.day_seconds,
                stats)
        self._clean_orphan_markers(bucket, prefix, stats)

    def _expire_current(self, bucket: str, prefix: str,
                        cutoff: float, stats: dict) -> None:
        """Expire current objects older than ``cutoff``: versioned
        buckets get a delete marker (data retained for the noncurrent
        rule), unversioned buckets lose the object for good — the
        reference's RGWLC::handle_multipart/obj expiration split."""
        marker = ""
        while True:
            page = self.gw.list_objects(bucket, prefix=prefix,
                                        max_keys=1000, marker=marker)
            if not page:
                return
            for key in sorted(page):
                ent = page[key]
                if float(ent.get("mtime", now_inf())) < cutoff:
                    try:
                        self.gw.delete_object(bucket, key)
                        stats["expired"] += 1
                    except RGWError:
                        pass
            marker = max(page)

    def _reap_noncurrent(self, bucket: str, prefix: str,
                         cutoff: float, stats: dict) -> None:
        """Permanently remove noncurrent generations older than
        ``cutoff`` (NoncurrentVersionExpiration role)."""
        for ent in self.gw.list_versions(bucket, prefix=prefix):
            if ent["is_current"] or ent.get("dm"):
                continue
            if float(ent.get("mtime", now_inf())) < cutoff:
                try:
                    self.gw.delete_object(bucket, ent["key"],
                                          version_id=ent["vid"])
                    stats["noncurrent_reaped"] += 1
                except RGWError:
                    pass

    def _clean_orphan_markers(self, bucket: str, prefix: str,
                              stats: dict) -> None:
        """Remove delete markers that are the ONLY generation left of
        their key (the reference's ExpiredObjectDeleteMarker)."""
        by_key: dict[str, list] = {}
        for ent in self.gw.list_versions(bucket, prefix=prefix):
            by_key.setdefault(ent["key"], []).append(ent)
        for key, ents in by_key.items():
            if len(ents) == 1 and ents[0].get("dm"):
                try:
                    self.gw.delete_object(bucket, key,
                                          version_id=ents[0]["vid"])
                    stats["markers_cleaned"] += 1
                except RGWError:
                    pass


def now_inf() -> float:
    """Missing mtime (legacy cls-index entry) never expires."""
    return float("inf")


class LifecycleThread:
    """Background worker wrapper (the radosgw lc thread role)."""

    def __init__(self, gw: RGWGateway, interval: float = 60.0,
                 day_seconds: float = 86400.0) -> None:
        self.proc = LifecycleProcessor(gw, day_seconds=day_seconds)
        self.interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="rgw-lc", daemon=True)

    def start(self) -> "LifecycleThread":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.proc.process()
            except Exception:
                pass
