"""Monitor — the consensus/control plane (src/mon/ role).

Reference: ``Monitor`` + ``Paxos`` (src/mon/Paxos.h:174) + the
PaxosService subclasses, chiefly OSDMonitor (osdmap epochs, EC profile
commands) and ConfigMonitor. Collapsed here to one daemon class with:

  - a persisted commit log (MonitorDBStore role, backed by store/kv):
    every map change is a numbered committed value, replayed on
    restart — the Paxos log discipline.
  - Paxos (src/mon/Paxos.{h,cc} collect/begin/accept/commit) when
    started with a monmap of peers. Election (Elector role): mons
    exchange liveness/progress beacons and derive the leader as the
    most-advanced lowest-ranked live peer. A new leader then runs the
    COLLECT phase (phase 1): it picks a proposal number above every
    pn it has seen, gathers promises from a quorum, catches up to the
    most advanced committed state revealed, and COMPLETES any
    predecessor's accepted-but-uncommitted value (Paxos.cc collect/
    handle_last). Mutations run on a SCRATCH copy of the state and
    fan out as a BEGIN (phase 2): peers that promised no higher pn
    persist the value as pending and ack; on a quorum of accepts the
    leader commits (durable + visible + published) and replicates the
    commit. A minority or deposed leader can never commit: its begin
    is fenced by higher promised pns (or simply starves of acks) and
    the proposal times out with -110, leaving state untouched.
    Command replies for committed mutations ride IN the replicated
    state (the (client, tid) -> reply dedup survives leader
    failover, so a client retry attaches to the original execution).
    Remaining reduction vs the reference: values are full-state
    snapshots (no per-value log transfer; catch-up and commit are
    the same message). Reads are LEASE-bounded (Paxos.h:174 lease
    fields, Paxos.cc extend_lease role): the leader's heartbeats and
    commit replications grant peons a mon_lease window during which
    they may answer read-only commands from committed state; an
    expired lease (partitioned peon, deposed-but-unaware leader)
    answers EAGAIN instead of unboundedly stale state, and clients
    rotate to a mon that can serve.
  - OSDMonitor logic: MOSDBoot marks OSDs up (new epoch), failure
    reports and beacon-timeout mark them down (OSDMap epochs move
    forward only), pool/EC-profile commands validated by actually
    instantiating the codec — the reference validates profiles on the
    mon via the same plugin registry the OSDs use
    (OSDMonitor::prepare_command pattern, SURVEY §3.5).
  - map publication: subscribers (MMonSubscribe) get an MOSDMap push
    on every commit.
  - health: HEALTH_OK / HEALTH_WARN from up/in accounting.
"""

from __future__ import annotations

import json
import threading

from ceph_tpu.analysis.lock_witness import make_rlock
import time

from ceph_tpu.models import registry as ec_registry
from ceph_tpu.parallel import crush
from ceph_tpu.parallel import messages as M
from ceph_tpu.parallel.messenger import Connection, Messenger
from ceph_tpu.parallel.osdmap import OSDMap
from ceph_tpu.store.kv import KeyValueDB, MemDB, WriteBatch
from ceph_tpu.utils.config import g_conf
from ceph_tpu.utils.dout import Dout

log = Dout("mon")


#: command prefixes that never mutate state — answered straight from
#: committed state, bypassing the proposal pipeline
_READONLY_COMMANDS = frozenset({
    "osd erasure-code-profile ls", "osd erasure-code-profile get",
    "osd pool ls", "osd pool lssnap", "osd tree", "osd dump",
    "status", "health", "health detail", "config dump",
    "osd blocklist ls",
})

#: seconds after which a pushed mgr health report stops being merged
#: into status/health answers (a dead mgr must not pin stale checks)
MGR_HEALTH_STALE = 30.0


class Monitor:
    """A single monitor daemon ("mon.a")."""

    def __init__(self, name: str = "a", db: KeyValueDB | None = None,
                 keyring=None) -> None:
        self.name = name
        self.db = db or MemDB()
        self.auth_service = None
        if keyring is not None:
            from ceph_tpu.parallel import auth as A
            self.auth_service = A.AuthService(keyring)
        self.osdmap = OSDMap()
        self.ec_profiles: dict[str, dict] = {}
        self.msgr = Messenger(f"mon.{name}")
        self.msgr.set_dispatcher(self._dispatch)
        self.addr = ""
        # quorum state (single-mon default: rank 0, no peers, leader)
        self.rank = 0
        self.monmap: dict[int, str] = {}      # rank -> addr (peers+self)
        self._peer_seen: dict[int, tuple[float, int]] = {}
        self._leader_rank = 0
        self._lock = make_rlock("mon.state")
        self._subscribers: dict[str, Connection] = {}  # peer entity -> conn
        self._last_beacon: dict[int, float] = {}
        # osd -> (monotonic ts, [pg stat dicts]) — pgmap soft state
        # (the mgr's aggregation role)
        self._pg_stats: dict[int, tuple[float, list]] = {}
        # latest mgr health-engine report (monotonic ts, checks dict)
        # — soft state like pg stats, merged into status/health
        self._mgr_health: tuple[float, dict] | None = None
        self._failure_reports: dict[int, dict[int, float]] = {}
        # epoch at which each osd last booted (up_from role): failure
        # reports carrying an older epoch were formed before the boot
        # and must not count against the reborn daemon
        self._up_epoch: dict[int, int] = {}
        from ceph_tpu.utils.admin_socket import AdminSocket
        self.asok = AdminSocket(
            f"mon.{name}", g_conf()["admin_socket_dir"] or None)
        self._tick_stop = threading.Event()
        self._tick_thread: threading.Thread | None = None
        # -- paxos machine state (Paxos.h:174 roles) --
        #: the pn this mon leads with (0 = not established; set by a
        #: completed collect phase)
        self._leader_pn = 0
        #: in-flight phase-1: {"pn", "ts", "replies": {rank: (lc,
        #: state, (pending_pn, pending_v, pending_state))}}
        self._collect: dict | None = None
        #: in-flight phase-2: {"pn", "version", "state", "scratch",
        #: "entries", "acks", "ts"} — one proposal at a time
        self._proposal: dict | None = None
        #: queued mutations [{"fn", "done", "ts"}] folded into the
        #: next proposal (PaxosService pending role)
        self._mut_queue: list[dict] = []
        #: scratch-dirty marker set by _commit() during mutation runs
        self._dirty = False
        #: dedup for the tick's beacon-timeout mutation: while a
        #: proposal stalls, every tick would otherwise queue another
        #: identical osdmap scan
        self._beacon_check_queued = False
        #: same dedup for the blocklist-expiry prune mutation
        self._blocklist_prune_queued = False
        # "client|tid" -> [code, outs, data_hex]: REPLICATED command
        # dedup — part of the committed state, so a retry after leader
        # failover attaches to the original execution instead of
        # re-running the mutation (the reference's session dedup,
        # made durable)
        self._cmd_replies: dict[str, list] = {}
        # centralized config (ConfigMonitor role, src/mon/
        # ConfigMonitor.cc): replicated name->value map pushed to
        # subscribed daemons as MConfig on every commit; daemons apply
        # it into their 'mon' config source layer
        self._central_config: dict[str, str] = {}
        # in-memory dedup for commands still awaiting their proposal
        # (holds the waiting connections) + completed-reply LRU
        from ceph_tpu.utils.lru import BoundedLRU
        self._cmd_dedup: BoundedLRU = BoundedLRU(1024)
        #: monotonic deadline until which this PEON may serve reads
        #: from committed state (granted by leader HBs/commits —
        #: Paxos lease role; the leader's own lease is quorum
        #: visibility, see _lease_valid)
        self._lease_until = 0.0
        #: COMMITTED state as a chunk table (per-value log transfer:
        #: deltas are diffs of this table; see _state_chunks_of)
        self._chunks: dict[str, bytes] = {}
        #: wire accounting for the share_state discipline (tests
        #: assert catch-up rides deltas, not snapshots)
        self.paxos_stats = {"delta_sent": 0, "full_sent": 0,
                            "delta_applied": 0, "full_applied": 0}
        # -- elector state (src/mon/Elector.cc roles) --
        #: active candidacy: {"epoch", "ts", "defers": set} while WE
        #: stand in an election round
        self._election: dict | None = None
        #: sticky deferral for the current epoch: {"epoch", "rank",
        #: "key"} — re-defer within an epoch only to a strictly
        #: better candidate, so two majorities can never form
        self._deferred: dict | None = None
        #: the quorum the last victory announced (introspection)
        self._quorum: list[int] = []
        self._replay()
        self._chunks = self._state_chunks_of(
            self.osdmap, self.ec_profiles, self._cmd_replies,
            self._central_config)

    # -- lifecycle ----------------------------------------------------
    def prebind(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Bind the messenger before the monmap is known (multi-mon
        bootstrap: all mons bind, then everyone learns every addr)."""
        if not self.addr:
            addr = self.msgr.bind(host, port)
            with self._lock:
                self.addr = addr
        return self.addr

    def set_monmap(self, monmap: dict[int, str], rank: int) -> None:
        # under the lock: the messenger is already dispatching once
        # prebind bound it, so a peer's HB can race the map install
        with self._lock:
            self.monmap = dict(monmap)
            self.rank = rank
            # multi-mon: leadership is EARNED through an election
            # round (propose/defer/victory), never assumed at boot
            self._leader_rank = rank if len(self.monmap) <= 1 else -1

    def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        # the grace countdown for every replayed-up osd starts now: a
        # dead one that never re-beacons must still time out
        now = time.monotonic()
        for osd, info in self.osdmap.osds.items():
            if info.up:
                self._last_beacon.setdefault(osd, now)
        if self.auth_service is not None:
            from ceph_tpu.parallel import auth as A
            A.daemon_auth(self.msgr, self.auth_service.keyring,
                          f"mon.{self.name}")
        from ceph_tpu.utils.admin_socket import register_common_commands
        register_common_commands(self.asok)
        self.asok.register_command(
            "mon_status",
            lambda a: {"name": self.name, "addr": self.addr,
                       "epoch": self.osdmap.epoch,
                       "osds": {o: {"up": i.up, "in": i.in_cluster,
                                    "addr": i.addr}
                                for o, i in self.osdmap.osds.items()}},
            "monitor + osdmap summary")
        self.asok.register_command(
            "quorum_status",
            lambda a: {"rank": self.rank, "leader": self._leader_rank,
                       "is_leader": self.is_leader(),
                       "monmap": {str(r): a_ for r, a_ in
                                  self.monmap.items()},
                       "last_committed": self._last_committed(),
                       "state_bytes": getattr(self,
                                              "_last_state_bytes", 0)},
            "election/quorum state (Elector role)")
        self.asok.start()
        self.prebind(host, port)
        with self._lock:
            if not self.monmap:
                self.monmap = {self.rank: self.addr}
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"mon.{self.name}-tick",
            daemon=True)
        self._tick_thread.start()
        log(1, f"mon.{self.name} up at {self.addr}, "
            f"epoch {self.osdmap.epoch}")
        return self.addr

    def stop(self) -> None:
        self._tick_stop.set()
        if self._tick_thread:
            self._tick_thread.join(timeout=5)
        self.msgr.shutdown()
        self.asok.stop()
        self.db.close()

    # -- paxos durable state (Paxos.h:174) ----------------------------
    def _last_committed(self) -> int:
        raw = self.db.get("paxos/last_committed")
        return int(raw.decode()) if raw else 0

    def _accepted_pn(self) -> int:
        """Highest proposal number this mon has promised (persisted —
        the promise must survive restart or a deposed leader could be
        re-accepted)."""
        raw = self.db.get("paxos/accepted_pn")
        return int(raw.decode()) if raw else 0

    def _promise(self, pn: int) -> None:
        batch = WriteBatch()
        batch.put("paxos/accepted_pn", str(pn).encode())
        self.db.submit(batch, sync=True)

    def _pending(self) -> tuple[int, int, bytes] | None:
        """The durably ACCEPTED but uncommitted value (pn, version,
        state) — what a new leader's collect phase recovers."""
        raw = self.db.get("paxos/pending")
        if not raw:
            return None
        from ceph_tpu.utils.encoding import Decoder
        d = Decoder(raw)
        return d.u64(), d.u64(), d.bytes()

    def _set_pending(self, pn: int, version: int, state: bytes) -> None:
        """Durably accept a value (peon side of begin; leader
        self-accept). MUST hit disk before the accept ack goes out —
        that durability is exactly what collect recovery relies on."""
        from ceph_tpu.utils.encoding import Encoder
        e = Encoder()
        e.u64(pn)
        e.u64(version)
        e.bytes(state)
        batch = WriteBatch()
        batch.put("paxos/pending", e.getvalue())
        batch.put("paxos/accepted_pn", str(pn).encode())
        self.db.submit(batch, sync=True)

    def _commit(self) -> None:
        """Called by command/boot/failure handlers after mutating the
        map. Under real Paxos those handlers run against a SCRATCH
        copy inside _pump_proposals; this merely advances the epoch
        and marks the scratch dirty — visibility and durability happen
        in _commit_proposal once a quorum accepts (the reference's
        PaxosService::propose_pending seam)."""
        self.osdmap.epoch += 1
        self._dirty = True

    # -- quorum (Paxos/Elector roles) ---------------------------------
    def is_leader(self) -> bool:
        return self._leader_rank == self.rank

    def _lease_valid(self, now: float) -> bool:
        """May this mon answer reads from its committed state? (the
        Paxos lease contract, src/mon/Paxos.h:174 / Paxos.cc
        extend_lease): a single mon always may; the leader may while
        it can see a quorum (a partitioned minority 'leader' goes
        read-dark within mon_election_timeout); a peon may while the
        leader's heartbeat/commit lease grant is unexpired."""
        if len(self.monmap) <= 1:
            return True
        if self.is_leader():
            return len(self._alive_ranks(now)) >= self._majority()
        return now < self._lease_until

    def leader_addr(self) -> str:
        return self.monmap.get(self._leader_rank, self.addr)

    def _alive_ranks(self, now: float) -> dict[int, int]:
        """rank -> last_committed for every mon considered alive."""
        grace = g_conf()["mon_election_timeout"]
        alive = {self.rank: self._last_committed()}
        for rank, (ts, lc) in self._peer_seen.items():
            if now - ts <= grace and rank in self.monmap:
                alive[rank] = lc
        return alive

    # -- elector (src/mon/Elector.cc propose/defer/victory) -----------
    def _election_epoch(self) -> int:
        raw = self.db.get("paxos/election_epoch")
        return int(raw.decode()) if raw else 0

    def _set_election_epoch(self, ep: int) -> None:
        if ep <= self._election_epoch():
            return
        batch = WriteBatch()
        batch.put("paxos/election_epoch", str(ep).encode())
        self.db.submit(batch, sync=True)

    def _cand_key(self) -> tuple:
        """Candidate ordering: most-advanced commit log first (a stale
        rejoiner can never win), lowest rank breaking ties."""
        return (self._last_committed(), -self.rank)

    def _start_election(self, now: float) -> None:
        ep = self._election_epoch()
        ep = ep + 1 if ep % 2 == 0 else ep + 2   # next ODD epoch
        self._set_election_epoch(ep)
        self._election = {"epoch": ep, "ts": now,
                          "defers": {self.rank}}
        self._deferred = None
        log(1, f"mon.{self.name}: proposing election epoch {ep}")
        for rank, addr in self.monmap.items():
            if rank != self.rank:
                self.msgr.send_message(M.MMonElection(
                    op=M.ELECTION_PROPOSE, epoch=ep, rank=self.rank,
                    last_committed=self._last_committed()), addr)

    def _handle_election(self, msg: M.MMonElection,
                         now: float) -> None:
        if msg.op == M.ELECTION_PROPOSE:
            my_ep = self._election_epoch()
            if msg.epoch < my_ep:
                # stale candidate: educate it. A sitting leader
                # re-asserts its victory; a mon that is itself mid-
                # election answers with its candidacy at the current
                # height; a settled peon stays quiet (the rejoiner
                # converges via the HB election-epoch sync)
                addr = self.monmap.get(msg.rank)
                if addr is None:
                    return
                if self.is_leader():
                    self.msgr.send_message(M.MMonElection(
                        op=M.ELECTION_VICTORY, epoch=my_ep,
                        rank=self.rank, quorum=self._quorum), addr)
                elif self._election is not None:
                    self.msgr.send_message(M.MMonElection(
                        op=M.ELECTION_PROPOSE,
                        epoch=self._election["epoch"],
                        rank=self.rank,
                        last_committed=self._last_committed()), addr)
                return
            self._set_election_epoch(msg.epoch)
            theirs = (msg.last_committed, -msg.rank)
            mine = self._cand_key()
            if theirs > mine:
                # defer — STICKY within the epoch (re-defer only to a
                # strictly better candidate, so no two candidates can
                # both assemble a majority)
                d = self._deferred
                if d is not None and d["epoch"] == msg.epoch and \
                        d["key"] >= theirs:
                    return
                self._deferred = {"epoch": msg.epoch,
                                  "rank": msg.rank, "key": theirs,
                                  "ts": now}
                if self._election is not None and \
                        self._election["epoch"] <= msg.epoch:
                    self._election = None      # stand down
                addr = self.monmap.get(msg.rank)
                if addr:
                    self.msgr.send_message(M.MMonElection(
                        op=M.ELECTION_DEFER, epoch=msg.epoch,
                        rank=self.rank,
                        last_committed=self._last_committed()), addr)
            else:
                # we are the better candidate: contest this epoch.
                # BROADCAST the candidacy (answering only the proposer
                # would strand our defers at 1 while worse candidates
                # keep churning epochs — the boot-race livelock)
                if self._election is None or \
                        self._election["epoch"] < msg.epoch:
                    self._election = {"epoch": msg.epoch, "ts": now,
                                      "defers": {self.rank}}
                    for rank, addr in self.monmap.items():
                        if rank != self.rank:
                            self.msgr.send_message(M.MMonElection(
                                op=M.ELECTION_PROPOSE,
                                epoch=self._election["epoch"],
                                rank=self.rank,
                                last_committed=self._last_committed()),
                                addr)
                else:
                    addr = self.monmap.get(msg.rank)
                    if addr:
                        self.msgr.send_message(M.MMonElection(
                            op=M.ELECTION_PROPOSE,
                            epoch=self._election["epoch"],
                            rank=self.rank,
                            last_committed=self._last_committed()),
                            addr)
        elif msg.op == M.ELECTION_DEFER:
            el = self._election
            if el is None or msg.epoch != el["epoch"]:
                return
            el["defers"].add(msg.rank)
            self._maybe_win(now)
        elif msg.op == M.ELECTION_VICTORY:
            if msg.epoch < self._election_epoch():
                return
            if msg.epoch == self._election_epoch() and \
                    self._leader_rank >= 0 and \
                    msg.rank > self._leader_rank:
                # equal-epoch victory collision (possible under an
                # asymmetric partition where a mon deferred to two
                # candidates): the LOWER-ranked winner prevails
                # deterministically on every mon — the higher-ranked
                # one deposes itself when it hears the lower victory,
                # never the cross-deposition livelock
                return
            self._set_election_epoch(msg.epoch)
            self._election = None
            self._deferred = None
            self._quorum = list(msg.quorum)
            old = self._leader_rank
            self._leader_rank = msg.rank
            if old == self.rank and msg.rank != self.rank:
                # deposed: any in-flight proposal cannot be OUR commit
                # any more (the successor may still complete it via
                # collect; the replicated dedup answers retries)
                log(1, f"mon.{self.name}: deposed by election epoch "
                    f"{msg.epoch} (leader rank {msg.rank})")
                self._fail_proposal()
                self._leader_pn = 0
                self._collect = None

    def _maybe_win(self, now: float) -> None:
        """Win once every mon we can SEE has deferred (dead mons are
        excused; a live better candidate never defers, so it blocks
        us exactly as it should). The election-timeout fallback in
        _election_tick covers a wrong liveness view."""
        el = self._election
        if el is None or len(el["defers"]) < self._majority():
            return
        alive = set(self._alive_ranks(now))
        if alive <= el["defers"]:
            self._declare_victory(now)

    def _declare_victory(self, now: float) -> None:
        el = self._election
        ep = el["epoch"] + 1                     # even: stable
        self._set_election_epoch(ep)
        self._election = None
        self._deferred = None
        self._quorum = sorted(el["defers"])
        log(1, f"mon.{self.name}: election epoch {ep} won "
            f"(quorum {self._quorum})")
        for rank, addr in self.monmap.items():
            if rank != self.rank:
                self.msgr.send_message(M.MMonElection(
                    op=M.ELECTION_VICTORY, epoch=ep, rank=self.rank,
                    quorum=self._quorum), addr)
        was_leader = self._leader_rank == self.rank
        self._leader_rank = self.rank
        if not was_leader:
            # taking over: (a) every up OSD gets a fresh beacon grace
            # window — as a peon we forwarded beacons instead of
            # recording them; (b) push our state to every peer so a
            # healed split-brain twin at an EQUAL version adopts the
            # elected leader's truth; (c) run the collect phase to
            # establish a pn and recover the predecessor's in-flight
            # proposal (Paxos leader takeover)
            for osd, info in self.osdmap.osds.items():
                if info.up:
                    self._last_beacon[osd] = time.monotonic()
            state = self._encode_state()
            for rank, addr in self.monmap.items():
                if rank != self.rank:
                    self.paxos_stats["full_sent"] += 1
                    self.msgr.send_message(M.MPaxosCommit(
                        version=self._last_committed(),
                        state=state, rank=self.rank), addr)
        self._leader_pn = 0
        self._start_collect(now)

    def _election_tick(self, now: float) -> None:
        """Election upkeep + catch-up pull (runs from tick)."""
        el = self._election
        if el is not None:
            # a mon that fell out of the alive view since our last
            # defer may unblock the everyone-alive-deferred fast path
            self._maybe_win(now)
        el = self._election
        if el is not None and \
                now - el["ts"] > g_conf()["mon_election_timeout"]:
            if len(el["defers"]) >= self._majority():
                # window closed with a majority deferring and no
                # better candidate surfaced: win (the equal-epoch
                # tie-break above resolves the rare dual victory)
                self._declare_victory(now)
            else:
                self._election = None        # round died: try again
        alive = self._alive_ranks(now)
        if self._election is None:
            leader = self._leader_rank
            no_leader = leader < 0 or (
                leader != self.rank and leader not in alive)
            # deferred recently: hold off — OUR candidate's round is
            # in flight; re-proposing every tick would reset its
            # election window forever (the boot-race livelock)
            d = self._deferred
            deferred_fresh = d is not None and \
                now - d.get("ts", 0.0) < g_conf()["mon_election_timeout"]
            if no_leader and not deferred_fresh and \
                    len(alive) >= self._majority():
                self._start_election(now)
        # lagging behind a live peer: pull the missing values
        best = max(alive.values())
        if best > self._last_committed():
            ahead = min(r for r, lc in alive.items() if lc == best)
            if ahead != self.rank:
                self.msgr.send_message(
                    M.MPaxosPull(rank=self.rank,
                                 from_version=self._last_committed()),
                    self.monmap[ahead])

    # -- phase 1: collect (Paxos::collect / handle_collect) -----------
    def _next_pn(self) -> int:
        """A pn above everything seen, unique per mon (counter<<8 |
        rank — the reference's get_new_proposal_number shape)."""
        base = max(self._accepted_pn(), self._leader_pn) >> 8
        return ((base + 1) << 8) | (self.rank & 0xFF)

    def _start_collect(self, now: float) -> None:
        pn = self._next_pn()
        self._promise(pn)          # self-promise
        self._leader_pn = 0
        mine = self._pending() or (0, 0, b"")
        self._collect = {
            "pn": pn, "ts": now,
            "replies": {self.rank: (self._last_committed(), b"", mine)}}
        log(1, f"mon.{self.name}: collect phase, pn {pn}")
        for rank, addr in self.monmap.items():
            if rank != self.rank:
                self.msgr.send_message(M.MPaxosCollect(
                    pn=pn, rank=self.rank,
                    last_committed=self._last_committed()), addr)
        self._maybe_finish_collect()

    def _handle_collect(self, msg: M.MPaxosCollect) -> None:
        ok = msg.pn > self._accepted_pn()
        if ok:
            self._promise(msg.pn)
            # a higher pn is live: any proposal WE lead is fenced now
            self._leader_pn = 0
        lc = self._last_committed()
        state = self._encode_state() if lc > msg.last_committed else b""
        pend = self._pending() or (0, 0, b"")
        addr = self.monmap.get(msg.rank)
        if addr:
            self.msgr.send_message(M.MPaxosCollectReply(
                ok=ok, pn=msg.pn, accepted_pn=self._accepted_pn(),
                rank=self.rank, last_committed=lc, state=state,
                pending_pn=pend[0], pending_version=pend[1],
                pending_state=pend[2]), addr)

    def _handle_collect_reply(self, msg: M.MPaxosCollectReply) -> None:
        col = self._collect
        if col is None or msg.pn != col["pn"]:
            return
        if not msg.ok:
            # someone promised higher: stand down; election + a later
            # collect with a fresh pn sort it out
            log(1, f"mon.{self.name}: collect pn {col['pn']} refused "
                f"by rank {msg.rank} (accepted_pn {msg.accepted_pn})")
            self._collect = None
            return
        col["replies"][msg.rank] = (
            msg.last_committed, msg.state,
            (msg.pending_pn, msg.pending_version, msg.pending_state))
        self._maybe_finish_collect()

    def _maybe_finish_collect(self) -> None:
        col = self._collect
        if col is None or len(col["replies"]) < self._majority():
            return
        self._collect = None
        # catch up to the most advanced committed state a peer revealed
        best_lc, best_state = self._last_committed(), b""
        for lc, state, _pend in col["replies"].values():
            if lc > best_lc and state:
                best_lc, best_state = lc, state
        if best_state:
            self._adopt_state(best_lc, best_state)
        self._leader_pn = col["pn"]
        log(1, f"mon.{self.name}: leading with pn {col['pn']} "
            f"at v{self._last_committed()}")
        # complete the predecessor's in-flight value, if one survives:
        # among uncommitted accepted values, highest pn wins (the
        # Paxos recovery rule, Paxos.cc handle_last)
        cand = None
        for _lc, _state, pend in col["replies"].values():
            if pend[2] and pend[1] > self._last_committed():
                if cand is None or pend[0] > cand[0]:
                    cand = pend
        if cand is not None:
            log(1, f"mon.{self.name}: completing predecessor's "
                f"uncommitted proposal v{cand[1]} (pn {cand[0]})")
            scratch = self._decode_state(cand[2])
            self._begin(cand[2], max(cand[1],
                                     self._last_committed() + 1),
                        scratch, [])
        else:
            self._pump_proposals(time.monotonic())

    # -- phase 2: begin/accept (Paxos::begin / handle_begin) ----------
    def _pump_proposals(self, now: float) -> None:
        """Fold every queued mutation into one proposal (one in flight
        at a time — the single-decree pipeline). Mutations run on a
        SCRATCH copy: nothing becomes visible or durable unless a
        quorum accepts. Caller holds the lock."""
        if self._proposal is not None or not self._mut_queue or \
                not self.is_leader():
            return
        if self._leader_pn == 0 or \
                self._leader_pn < self._accepted_pn():
            # pn not established (or fenced by a higher promise):
            # phase 1 first
            if self._collect is None:
                self._start_collect(now)
            return
        entries = self._mut_queue
        self._mut_queue = []
        committed = (self.osdmap, self.ec_profiles,
                     self._cmd_replies, self._central_config)
        self.osdmap = OSDMap.decode(self.osdmap.encode())
        self.ec_profiles = json.loads(json.dumps(self.ec_profiles))
        self._cmd_replies = dict(self._cmd_replies)
        self._central_config = dict(self._central_config)
        batch_dirty = False
        for ent in entries:
            self._dirty = False     # per-mutation marker (dedup needs
            try:                    # to know if THIS one mutated)
                ent["fn"]()
            except Exception as exc:
                log(0, f"mon.{self.name}: mutation failed: {exc!r}")
            batch_dirty |= self._dirty
        scratch = (self.osdmap, self.ec_profiles, self._cmd_replies,
                   self._central_config)
        (self.osdmap, self.ec_profiles, self._cmd_replies,
         self._central_config) = committed
        dones = [ent.get("done") for ent in entries]
        if not batch_dirty:
            # nothing to commit (read-only/error commands): answer now
            for done in dones:
                if done is not None:
                    done(True)
            self._pump_proposals(now)
            return
        chunks = self._state_chunks_of(*scratch)
        self._begin(self._encode_chunks(chunks),
                    self._last_committed() + 1, scratch, dones,
                    chunks=chunks)

    def _begin(self, state: bytes, version: int, scratch,
               entries: list, chunks=None) -> None:
        pn = self._leader_pn
        self._set_pending(pn, version, state)    # leader self-accept
        # the VALUE travels as a delta against the committed chunk
        # table (share_state discipline): quorum peons sit at our
        # last_committed, reconstruct the full value locally, and the
        # wire cost scales with the change, not the map
        new_chunks = chunks if chunks is not None \
            else self._decode_chunks(state)
        delta = self._chunks_delta(new_chunks)
        self._proposal = {"pn": pn, "version": version, "state": state,
                          "chunks": new_chunks, "delta": delta,
                          "scratch": scratch, "entries": entries,
                          "acks": {self.rank}, "ts": time.monotonic()}
        if len(self._proposal["acks"]) >= self._majority():
            self._commit_proposal()              # single-mon fast path
            return
        base = self._last_committed()
        for rank, addr in self.monmap.items():
            if rank != self.rank:
                self.paxos_stats["delta_sent"] += 1
                self.msgr.send_message(M.MPaxosBegin(
                    pn=pn, version=version, state=b"",
                    rank=self.rank, base=base, delta=delta), addr)

    def _handle_begin(self, msg: M.MPaxosBegin) -> None:
        state = msg.state
        if not state and msg.delta:
            if msg.base == self._last_committed():
                self.paxos_stats["delta_applied"] += 1
                state = self._encode_chunks(
                    self._apply_delta_to(self._chunks, msg.delta))
            # else: we lag the leader's base — cannot materialize the
            # value; NAK below and catch up via pull
        ok = bool(state) and msg.pn >= self._accepted_pn() and \
            msg.version > self._last_committed()
        if ok:
            self._set_pending(msg.pn, msg.version, state)
        addr = self.monmap.get(msg.rank)
        if addr:
            self.msgr.send_message(M.MPaxosAccept(
                ok=ok, pn=msg.pn, version=msg.version, rank=self.rank,
                accepted_pn=self._accepted_pn()), addr)

    def _handle_accept(self, msg: M.MPaxosAccept) -> None:
        prop = self._proposal
        if prop is None or msg.pn != prop["pn"] or \
                msg.version != prop["version"]:
            return
        if not msg.ok:
            if msg.accepted_pn > prop["pn"]:
                # fenced: a newer leader's pn is promised out there —
                # this proposal can never reach quorum (dueling-leader
                # safety; the value may still be completed by the NEW
                # leader's collect, in which case the replicated dedup
                # answers the client's retry)
                log(1, f"mon.{self.name}: proposal v{prop['version']} "
                    f"fenced by pn {msg.accepted_pn}; standing down")
                self._fail_proposal()
                self._leader_pn = 0
            return
        prop["acks"].add(msg.rank)
        if len(prop["acks"]) >= self._majority():
            self._commit_proposal()

    def _commit_proposal(self) -> None:
        """Quorum accepted: make the value durable + visible, publish,
        replicate the commit (Paxos::commit). Caller holds the lock."""
        prop = self._proposal
        self._proposal = None
        version, state = prop["version"], prop["state"]
        base = self._last_committed()
        (self.osdmap, self.ec_profiles, self._cmd_replies,
         self._central_config) = prop["scratch"]
        delta = prop.get("delta") or self._chunks_delta(
            prop.get("chunks") or self._decode_chunks(state))
        batch = WriteBatch()
        batch.put(f"paxos/{version:016d}", state)
        batch.put(f"paxos/delta/{version:016d}", delta)
        batch.put("paxos/last_committed", str(version).encode())
        batch.delete("paxos/pending")
        self.db.submit(batch, sync=True)
        self._chunks = prop.get("chunks") or \
            self._decode_chunks(state)
        self._trim_values(version)
        log(10, f"committed version {version} "
            f"(epoch {self.osdmap.epoch})")
        self._publish()
        for rank, addr in self.monmap.items():
            if rank != self.rank:
                # the commit is DELTA-sized: quorum peons hold the
                # full value as pending (from the begin) or sit at
                # base and apply the delta; stragglers pull
                self.paxos_stats["delta_sent"] += 1
                self.msgr.send_message(M.MPaxosCommit(
                    version=version, state=b"", rank=self.rank,
                    base=base, delta=delta, pn=prop["pn"]), addr)
        for done in prop["entries"]:
            if done is not None:
                done(True)
        self._pump_proposals(time.monotonic())

    def _fail_proposal(self) -> None:
        """Drop the in-flight proposal WITHOUT committing: the scratch
        evaporates, state stays untouched (what -110 promises the
        client). The self-accepted pending value intentionally stays
        on disk — a successor's collect may still complete it."""
        prop = self._proposal
        self._proposal = None
        if prop is None:
            return
        for done in prop["entries"]:
            if done is not None:
                done(False)

    def _apply_remote_commit(self, msg: M.MPaxosCommit) -> None:
        """Adopt a commit from a more advanced mon. The common case is
        DELTA-sized (share_state): our pending value from the begin
        phase IS the full value, or the delta applies to our chunk
        table at ``base``. Full snapshots heal everything else. An
        EQUAL version from the mon we recognize as leader also applies
        — that heals a split-brain where both sides committed the same
        version number with different states."""
        if msg.version < self._last_committed():
            return
        if msg.rank == self._leader_rank and msg.rank != self.rank:
            # a commit from the leader is also a lease grant: after
            # applying it we hold exactly the leader's state
            self._lease_until = time.monotonic() + g_conf()["mon_lease"]
        if msg.version == self._last_committed() and (
                self.is_leader() or msg.rank != self._leader_rank):
            return
        state = msg.state
        if not state:
            pend = self._pending()
            if pend is not None and pend[1] == msg.version and \
                    msg.pn and pend[0] == msg.pn:
                # we durably accepted this exact PROPOSAL (version AND
                # pn match) in the begin phase: commit what we hold —
                # a deposed leader's own same-version pending never
                # matches the majority's pn and falls through.
                # (_handle_begin already counted the delta apply)
                state = pend[2]
            elif msg.delta and msg.base == self._last_committed():
                state = self._encode_chunks(
                    self._apply_delta_to(self._chunks, msg.delta))
                self.paxos_stats["delta_applied"] += 1
            else:
                # can't materialize the value: we lag — pull a
                # catch-up chain from the committer
                addr = self.monmap.get(msg.rank)
                if addr:
                    self.msgr.send_message(M.MPaxosPull(
                        rank=self.rank,
                        from_version=self._last_committed()), addr)
                return
        else:
            self.paxos_stats["full_applied"] += 1
        # (an equal-version split-brain heal can only arrive as a full
        # state — equal-version deltas don't exist)
        self._adopt_state(msg.version, state)

    def _adopt_state(self, version: int, state: bytes) -> None:
        """Install a committed value (remote commit / catch-up /
        collect recovery). Caller holds the lock."""
        new_chunks = self._decode_chunks(state)
        batch = WriteBatch()
        batch.put(f"paxos/{version:016d}", state)
        if version == self._last_committed() + 1:
            # contiguous: record the per-value delta so WE can serve
            # delta catch-up chains to mons behind us
            batch.put(f"paxos/delta/{version:016d}",
                      self._chunks_delta(new_chunks))
        else:
            # equal-version heal or snapshot jump: any delta we
            # recorded for this version described a DIFFERENT history
            # — serving it to a puller would fork the quorum's state
            batch.delete(f"paxos/delta/{version:016d}")
            # and everything below is unservable as a chain anyway
            # (we never held the intermediate deltas): advance the
            # trim floor so _trim_values stays O(actual log)
            if version > self._trim_floor():
                batch.put("paxos/trimmed_to", str(version).encode())
        batch.put("paxos/last_committed", str(version).encode())
        pend = self._pending()
        if pend is not None and pend[1] <= version:
            batch.delete("paxos/pending")    # superseded
        self.db.submit(batch, sync=True)
        (self.osdmap, self.ec_profiles, self._cmd_replies,
         self._central_config) = self._state_from_chunks(new_chunks)
        self._chunks = new_chunks
        self._trim_values(version)
        log(10, f"mon.{self.name}: adopted commit v{version} "
            f"(epoch {self.osdmap.epoch})")
        self._publish()

    def _encode_state(self) -> bytes:
        raw = self._encode_state_of(self.osdmap, self.ec_profiles,
                                    self._cmd_replies,
                                    self._central_config)
        self._last_state_bytes = len(raw)
        return raw

    # -- chunked state + per-value deltas (Paxos.cc share_state role) -
    # The replicated state is a CHUNK TABLE (osdmap chunks per osd /
    # pool / crush / meta, plus profiles, config, and one chunk per
    # dedup reply). A committed value's wire form is the DELTA —
    # chunks changed/removed since the previous version — so commit
    # replication and catch-up cost scale with the change, not the
    # map. Full snapshots (the encoded chunk table) remain the
    # bootstrap / trimmed-log fallback.

    @staticmethod
    def _state_chunks_of(osdmap, ec_profiles, cmd_replies,
                         central_config) -> dict[str, bytes]:
        chunks = {f"map/{k}": v
                  for k, v in osdmap.to_chunks().items()}
        chunks["profiles"] = json.dumps(ec_profiles,
                                        sort_keys=True).encode()
        chunks["config"] = json.dumps(central_config,
                                      sort_keys=True).encode()
        for k, v in cmd_replies.items():
            chunks[f"reply/{k}"] = json.dumps(
                v, sort_keys=True).encode()
        return chunks

    @staticmethod
    def _state_from_chunks(chunks: dict[str, bytes]):
        osdmap = OSDMap.from_chunks(
            {k[4:]: v for k, v in chunks.items()
             if k.startswith("map/")})
        profiles = json.loads(chunks.get("profiles", b"{}"))
        config = json.loads(chunks.get("config", b"{}"))
        replies = {k[6:]: json.loads(v) for k, v in chunks.items()
                   if k.startswith("reply/")}
        return osdmap, profiles, replies, config

    @classmethod
    def _encode_state_of(cls, osdmap, ec_profiles, cmd_replies,
                         central_config) -> bytes:
        return cls._encode_chunks(cls._state_chunks_of(
            osdmap, ec_profiles, cmd_replies, central_config))

    @classmethod
    def _decode_state(cls, raw: bytes):
        return cls._state_from_chunks(cls._decode_chunks(raw))

    @staticmethod
    def _encode_chunks(chunks: dict[str, bytes]) -> bytes:
        from ceph_tpu.utils.encoding import Encoder
        e = Encoder()
        e.map(chunks, Encoder.str, Encoder.bytes)
        return e.getvalue()

    @staticmethod
    def _decode_chunks(raw: bytes) -> dict[str, bytes]:
        from ceph_tpu.utils.encoding import Decoder
        return Decoder(raw).map(Decoder.str, Decoder.bytes)

    @staticmethod
    def _encode_delta(changed: dict[str, bytes],
                      removed: list[str]) -> bytes:
        from ceph_tpu.utils.encoding import Encoder
        e = Encoder()
        e.map(changed, Encoder.str, Encoder.bytes)
        e.list(sorted(removed), Encoder.str)
        return e.getvalue()

    @staticmethod
    def _decode_delta(raw: bytes) -> tuple[dict[str, bytes],
                                           list[str]]:
        from ceph_tpu.utils.encoding import Decoder
        d = Decoder(raw)
        return d.map(Decoder.str, Decoder.bytes), d.list(Decoder.str)

    def _chunks_delta(self, new_chunks: dict[str, bytes]) -> bytes:
        """Delta from the committed chunk table to ``new_chunks``."""
        old = self._chunks
        changed = {k: v for k, v in new_chunks.items()
                   if old.get(k) != v}
        removed = [k for k in old if k not in new_chunks]
        return self._encode_delta(changed, removed)

    def _apply_delta_to(self, chunks: dict[str, bytes],
                        delta: bytes) -> dict[str, bytes]:
        changed, removed = self._decode_delta(delta)
        out = dict(chunks)
        out.update(changed)
        for k in removed:
            out.pop(k, None)
        return out

    #: per-value log length (mon_max_log_epochs role): catch-up below
    #: the floor falls back to a full snapshot
    PAXOS_KEEP = 512

    def _trim_floor(self) -> int:
        raw = self.db.get("paxos/trimmed_to")
        return int(raw.decode()) if raw else 0

    def _trim_values(self, version: int) -> None:
        """Drop values/deltas older than PAXOS_KEEP (Paxos::trim):
        the log stays bounded; deep catch-up uses a snapshot."""
        floor = self._trim_floor()
        new_floor = version - self.PAXOS_KEEP
        if new_floor <= floor:
            return
        batch = WriteBatch()
        for v in range(floor, new_floor):
            batch.delete(f"paxos/{v:016d}")
            batch.delete(f"paxos/delta/{v:016d}")
        batch.put("paxos/trimmed_to", str(new_floor).encode())
        self.db.submit(batch)

    def _send_catchup(self, peer: str, from_version: int) -> None:
        """share_state: send the missing committed values as a chain
        of per-value deltas (each tiny); a gap (trimmed / adopted
        non-contiguously) falls back to ONE full snapshot."""
        lc = self._last_committed()
        deltas = []
        for v in range(from_version + 1, lc + 1):
            d = self.db.get(f"paxos/delta/{v:016d}")
            if d is None:
                deltas = None
                break
            deltas.append((v, d))
        if deltas is None:
            self.paxos_stats["full_sent"] += 1
            self.msgr.send_message(M.MPaxosCommit(
                version=lc, state=self._encode_state(),
                rank=self.rank), peer)
            return
        for v, d in deltas:
            self.paxos_stats["delta_sent"] += 1
            self.msgr.send_message(M.MPaxosCommit(
                version=v, state=b"", rank=self.rank,
                base=v - 1, delta=d), peer)

    def _replay(self) -> None:
        last = self._last_committed()
        if last == 0:
            return
        raw = self.db.get(f"paxos/{last:016d}")
        (self.osdmap, self.ec_profiles, self._cmd_replies,
         self._central_config) = self._decode_state(raw)
        # a restarted mon can't know which osds are still alive; they
        # re-boot or get timed out by the beacon grace
        log(1, f"mon.{self.name} replayed to version {last}, "
            f"epoch {self.osdmap.epoch}")

    def _publish(self) -> None:
        msg = M.MOSDMap(epoch=self.osdmap.epoch,
                        map_bytes=self.osdmap.encode())
        cfg = M.MConfig(config=dict(self._central_config))
        for name, conn in list(self._subscribers.items()):
            if conn.closed:
                del self._subscribers[name]   # dead clients drop out
                continue
            conn.send_message(msg)
            conn.send_message(cfg)

    # -- dispatch -----------------------------------------------------
    def _dedup_put(self, key, ent: dict) -> None:
        """Bounded insert that only evicts COMPLETED entries: evicting
        a still-deferred command would let a client retry re-run the
        mutation — the exact thing the dedup exists to prevent."""
        self._cmd_dedup[key] = ent
        self._cmd_dedup.move_to_end(key)
        while len(self._cmd_dedup) > self._cmd_dedup.maxsize:
            victim = next((k for k, v in self._cmd_dedup.items()
                           if v.get("state") == "done"), None)
            if victim is None:
                break          # all pending: overflow beats re-running
            del self._cmd_dedup[victim]

    def _majority(self) -> int:
        return len(self.monmap) // 2 + 1

    def _dispatch(self, msg: M.Message, conn: Connection) -> None:
        with self._lock:
            if isinstance(msg, M.MMonHB):
                now = time.monotonic()
                self._peer_seen[msg.rank] = (now, msg.last_committed)
                if msg.addr:     # revived mons rebind to a new port
                    self.monmap[msg.rank] = msg.addr
                if msg.election_epoch > self._election_epoch():
                    # the cluster elected past us (healed partition /
                    # long sleep): adopt the newer epoch's view; a
                    # stale "leader" deposes itself here
                    self._set_election_epoch(msg.election_epoch)
                    self._election = None
                    self._deferred = None
                    new_leader = msg.leader_p1 - 1
                    if new_leader >= 0:
                        old = self._leader_rank
                        self._leader_rank = new_leader
                        if old == self.rank and \
                                new_leader != self.rank:
                            log(1, f"mon.{self.name}: deposed (saw "
                                f"election epoch {msg.election_epoch})")
                            self._fail_proposal()
                            self._leader_pn = 0
                            self._collect = None
                if msg.rank == self._leader_rank and \
                        msg.rank != self.rank and msg.lease > 0 and \
                        msg.last_committed <= self._last_committed():
                    # lease grant/extension (Paxos.cc extend_lease
                    # role): the leader is at least as advanced as us
                    # AND itself quorum-visible (lease > 0 — a deposed
                    # minority leader keeps heartbeating but grants
                    # nothing, so our lease expires). A leader ahead
                    # of us grants nothing either (we are stale; the
                    # elect pump pulls its commit first).
                    self._lease_until = now + msg.lease
                return
            if isinstance(msg, M.MMonElection):
                self._handle_election(msg, time.monotonic())
                return
            if isinstance(msg, M.MPaxosCommit):
                # the committer provably has this version: advance our
                # view of it NOW, or the window between applying its
                # commit and its next HB makes us think we're the most
                # advanced mon and flap into competing leadership
                self._peer_seen[msg.rank] = (time.monotonic(),
                                             msg.version)
                self._apply_remote_commit(msg)
                return
            if isinstance(msg, M.MPaxosCollect):
                self._handle_collect(msg)
                return
            if isinstance(msg, M.MPaxosCollectReply):
                self._handle_collect_reply(msg)
                return
            if isinstance(msg, M.MPaxosBegin):
                self._handle_begin(msg)
                return
            if isinstance(msg, M.MPaxosAccept):
                self._handle_accept(msg)
                return
            if isinstance(msg, M.MPaxosPull):
                peer = self.monmap.get(msg.rank)
                if peer and self._last_committed() > msg.from_version:
                    self._send_catchup(peer, msg.from_version)
                return
            if isinstance(msg, M.MAuth):
                self._handle_auth(msg, conn)
            elif isinstance(msg, M.MAuthRotating):
                # rotating service-key fetch (KeyServer role): reply
                # sealed with the entity's own key; an entity outside
                # the keyring (revoked) gets EACCES — its cached
                # window ages out and fences it
                if self.auth_service is None:
                    conn.send_message(M.MAuthRotatingReply(
                        tid=msg.tid, code=0, sealed=b""))
                else:
                    sealed = self.auth_service.handle_rotating(
                        msg.entity, msg.nonce)
                    if sealed is None:
                        log(1, "auth: rotating-key fetch denied for "
                            f"{msg.entity!r}")
                        conn.send_message(M.MAuthRotatingReply(
                            tid=msg.tid, code=-13, sealed=b""))
                    else:
                        conn.send_message(M.MAuthRotatingReply(
                            tid=msg.tid, code=0, sealed=sealed))
            elif isinstance(msg, M.MPGStats):
                # soft state: every mon keeps what it hears AND relays
                # to the leader (whose status answers commands)
                try:
                    stats = json.loads(msg.stats)
                except ValueError:
                    stats = []
                self._pg_stats[msg.osd_id] = (time.monotonic(), stats)
                if not self.is_leader():
                    self.msgr.send_message(msg, self.leader_addr())
            elif isinstance(msg, M.MMgrHealthReport):
                # soft state like pg stats: keep what we hear AND
                # relay to the leader (whose status answers commands)
                try:
                    report = json.loads(msg.report)
                except ValueError:
                    report = {}
                if isinstance(report, dict):
                    self._mgr_health = (time.monotonic(), report)
                if not self.is_leader():
                    self.msgr.send_message(msg, self.leader_addr())
            elif isinstance(msg, (M.MOSDBoot, M.MOSDFailure,
                                  M.MOSDAlive)) and not self.is_leader():
                # only the leader mutates cluster state; relay the
                # report to it (the reference forwards to the leader).
                # No leader yet (election in flight): DROP — relaying
                # to leader_addr's self-fallback would loop the
                # message back to us forever; daemons re-send
                if self._leader_rank >= 0:
                    self.msgr.send_message(msg, self.leader_addr())
            elif isinstance(msg, M.MOSDBoot):
                self._enqueue_mutation(
                    lambda: self._handle_boot(msg, conn))
            elif isinstance(msg, M.MOSDAlive):
                self._last_beacon[msg.osd_id] = time.monotonic()
            elif isinstance(msg, M.MOSDFailure):
                self._enqueue_mutation(
                    lambda: self._handle_failure(msg))
            elif isinstance(msg, M.MMonSubscribe):
                self._subscribers[conn.peer_name] = conn
                conn.send_message(M.MOSDMap(
                    epoch=self.osdmap.epoch,
                    map_bytes=self.osdmap.encode()))
                conn.send_message(M.MConfig(
                    config=dict(self._central_config)))
            elif isinstance(msg, M.MMonCommand):
                if msg.cmd.get("prefix", "") in _READONLY_COMMANDS:
                    # reads serve from committed state on ANY mon —
                    # but only under a valid lease (Paxos lease role):
                    # a partitioned peon or quorum-less leader answers
                    # EAGAIN instead of unboundedly stale state
                    now = time.monotonic()
                    if self._lease_valid(now):
                        code, outs, data = self._handle_command(
                            dict(msg.cmd))
                        conn.send_message(M.MMonCommandReply(
                            tid=msg.tid, code=code, outs=outs,
                            data=data))
                    else:
                        conn.send_message(M.MMonCommandReply(
                            tid=msg.tid, code=-11,
                            outs="EAGAIN read lease expired "
                                 "(no reachable quorum/leader)",
                            data=b""))
                    return
                if not self.is_leader():
                    if self._leader_rank < 0:
                        # election in flight: a NOTLEADER pointing at
                        # OURSELVES would hot-loop the client; EAGAIN
                        # makes it back off and rotate instead
                        conn.send_message(M.MMonCommandReply(
                            tid=msg.tid, code=-11,
                            outs="EAGAIN no leader "
                                 "(election in progress)",
                            data=b""))
                        return
                    # clients re-target on this redirect
                    conn.send_message(M.MMonCommandReply(
                        tid=msg.tid, code=-11,
                        outs=f"NOTLEADER {self.leader_addr()}",
                        data=b""))
                    return
                self._handle_mon_command(msg, conn)

    def _handle_mon_command(self, msg: M.MMonCommand,
                            conn: Connection) -> None:
        """Leader command path: dedup, then queue the execution as a
        mutation folded into the next proposal. The reply defers until
        the proposal commits (quorum accepted) — the Paxos contract
        that a minority leader can never ack. Caller holds the lock."""
        # (read-only commands never reach here: _dispatch serves them
        # lease-gated from committed state on any mon)
        key = f"{conn.peer_name}|{msg.tid}"
        rep = self._cmd_replies.get(key)
        if rep is not None:
            # REPLICATED dedup: the original execution committed
            # (possibly under a previous leader) — a retry attaches
            # to it instead of re-running the mutation
            conn.send_message(M.MMonCommandReply(
                tid=msg.tid, code=rep[0], outs=rep[1],
                data=bytes.fromhex(rep[2])))
            return
        ent = self._cmd_dedup.get(key)
        if ent is not None:
            if ent["state"] == "done":
                code, outs, data = ent["reply"]
                conn.send_message(M.MMonCommandReply(
                    tid=msg.tid, code=code, outs=outs, data=data))
            else:              # still awaiting its proposal: attach
                ent["conns"].append((conn, msg.tid))
            return
        ent = {"state": "pending", "reply": None,
               "conns": [(conn, msg.tid)]}
        self._dedup_put(key, ent)

        def mutate(ent=ent, key=key, cmd=dict(msg.cmd)):
            # runs on the proposal's scratch state; _dirty was reset
            # by the pump so it reflects THIS command only
            try:
                code, outs, data = self._handle_command(cmd)
            except Exception as exc:
                # anything _handle_command's own guards miss must
                # still produce a reply — a None reply would crash
                # done() and wedge the command (and its retries, via
                # the pending dedup entry) forever
                code, outs, data = -22, f"internal error: {exc!r}", b""
            ent["reply"] = (code, outs, data)
            if self._dirty:
                # fold the reply into the replicated state itself: if
                # this proposal commits anywhere, the dedup travels
                # with it (survives leader failover — the reference's
                # session dedup made durable)
                replies = self._cmd_replies
                replies[key] = [code, outs, data.hex()]
                while len(replies) > 256:
                    replies.pop(next(iter(replies)))

        def done(acked: bool, ent=ent, key=key):
            if not acked:
                ent["reply"] = (
                    -110, "proposal not accepted by a monitor "
                    "majority", b"")
            elif ent["reply"] is None:     # mutation never ran/failed
                ent["reply"] = (-22, "command execution failed", b"")
            ent["state"] = "done"
            code, outs, data = ent["reply"]
            for c, t in ent.pop("conns", []):
                c.send_message(M.MMonCommandReply(
                    tid=t, code=code, outs=outs, data=data))
            ent["conns"] = []
            if not acked:
                # nothing committed: a retry must be free to re-run
                # (caching -110 would wedge the command forever)
                if self._cmd_dedup.get(key) is ent:
                    del self._cmd_dedup[key]

        self._mut_queue.append({"fn": mutate, "done": done,
                                "ts": time.monotonic()})
        self._pump_proposals(time.monotonic())

    def _enqueue_mutation(self, fn, done=None) -> None:
        """Queue an internal (no-reply) state mutation — osd boots,
        failure reports, beacon timeouts. ``done(ok)`` fires if the
        entry expires unproposed (mutations that guard a re-arm flag
        must clear it, or the state machine wedges). Caller holds the
        lock."""
        self._mut_queue.append({"fn": fn, "done": done,
                                "ts": time.monotonic()})
        self._pump_proposals(time.monotonic())

    def _handle_auth(self, msg: M.MAuth, conn: Connection) -> None:
        """AuthMonitor role: grant a ticket. An auth-disabled mon
        answers success with an empty ticket (client stays unsigned)."""
        if self.auth_service is None:
            conn.send_message(M.MAuthReply(
                code=0, ticket=b"", sealed_session_key=b"",
                tid=msg.tid))
            return
        got = self.auth_service.handle_request(msg.entity, msg.nonce)
        if got is None:
            log(1, f"auth: denied unknown entity {msg.entity!r}")
            conn.send_message(M.MAuthReply(
                code=-13, ticket=b"", sealed_session_key=b"",
                tid=msg.tid))
            return
        ticket, sealed = got
        conn.send_message(M.MAuthReply(
            code=0, ticket=ticket, sealed_session_key=sealed,
            tid=msg.tid))

    def _handle_boot(self, msg: M.MOSDBoot, conn: Connection) -> None:
        osd = msg.osd_id
        if osd not in self.osdmap.osds:
            self.osdmap.add_osd(osd, msg.addr)
        # crush self-registration (the reference's osd crush location
        # update on boot): root -> per-osd host bucket -> device, plus
        # the default "data" rule
        cm = self.osdmap.crush
        if "default" not in cm.by_name:
            cm.add_bucket("default", "root")
        if "data" not in cm.rules:
            cm.add_rule(crush.Rule("data", root="default",
                                   failure_domain="osd", mode="indep"))
        host = f"host-{osd}"
        if host not in cm.by_name:
            cm.add_bucket(host, "host", parent="default", weight=1.0)
        if osd not in cm.device_weights:
            cm.add_device(osd, host)
        self.osdmap.mark_up(osd, msg.addr)
        self._last_beacon[osd] = time.monotonic()
        self._failure_reports.pop(osd, None)
        log(1, f"osd.{osd} booted at {msg.addr}")
        self._commit()
        self._up_epoch[osd] = self.osdmap.epoch

    def _handle_failure(self, msg: M.MOSDFailure) -> None:
        target = msg.target_osd
        info = self.osdmap.osds.get(target)
        if info is None or not info.up:
            return
        if msg.epoch < self._up_epoch.get(target, 0):
            # report predates the target's boot (heartbeat reports
            # resend every tick; in-flight ones can land after the
            # revival map) — a stale opinion of the PREVIOUS daemon
            log(10, f"ignoring stale failure report for osd.{target} "
                f"(epoch {msg.epoch} < up_epoch "
                f"{self._up_epoch.get(target, 0)})")
            return
        now = time.monotonic()
        reporters = self._failure_reports.setdefault(target, {})
        reporters[msg.reporter] = now
        # stale reports age out (mon_osd_report_timeout role) so two
        # spurious reports hours apart can't combine against a live osd
        expiry = 2 * g_conf()["osd_heartbeat_grace"]
        for rep, ts in list(reporters.items()):
            if now - ts > expiry:
                del reporters[rep]
        # the reference requires mon_osd_min_down_reporters (default 2);
        # scaled to our small clusters: 1 reporter + beacon silence, or
        # 2 fresh reporters outright
        silent = (now - self._last_beacon.get(target, 0.0)) > \
            g_conf()["osd_heartbeat_grace"]
        if len(reporters) >= 2 or silent:
            log(1, f"osd.{target} marked down "
                f"({len(reporters)} reporters, silent={silent})")
            self.osdmap.mark_down(target)
            self._failure_reports.pop(target, None)
            self._commit()

    # -- beacon timeout backstop --------------------------------------
    def _tick_loop(self) -> None:
        interval = g_conf()["osd_heartbeat_interval"]
        while not self._tick_stop.wait(interval):
            self.tick()

    def tick(self) -> None:
        grace = g_conf()["osd_heartbeat_grace"] * 2  # mon backstop
        now = time.monotonic()
        with self._lock:
            # quorum upkeep: beacon peers, re-derive the leader. Only
            # a quorum-visible leader grants read leases with its HBs.
            grant = g_conf()["mon_lease"] \
                if self.is_leader() and self._lease_valid(now) else 0.0
            for rank, addr in self.monmap.items():
                if rank != self.rank:
                    self.msgr.send_message(M.MMonHB(
                        rank=self.rank, name=self.name,
                        last_committed=self._last_committed(),
                        addr=self.addr, lease=grant,
                        election_epoch=self._election_epoch(),
                        leader_p1=self._leader_rank + 1), addr)
            if len(self.monmap) > 1:
                self._election_tick(now)
            # paxos upkeep: a proposal that cannot gather a quorum
            # (minority leader, fenced pn) times out WITHOUT touching
            # state; a stalled collect retries; queued mutations that
            # never got proposed expire
            timeout = g_conf()["mon_commit_timeout"]
            if self._proposal is not None and \
                    now - self._proposal["ts"] > timeout:
                log(1, f"mon.{self.name}: proposal "
                    f"v{self._proposal['version']} gathered "
                    f"{len(self._proposal['acks'])}/{self._majority()}"
                    f" accepts in {timeout}s; failing it")
                self._fail_proposal()
            if self._collect is not None and \
                    now - self._collect["ts"] > \
                    g_conf()["mon_election_timeout"]:
                self._collect = None     # retried by the pump
            keep = []
            for ent in self._mut_queue:
                if now - ent["ts"] > timeout:
                    if ent["done"] is not None:
                        ent["done"](False)
                else:
                    keep.append(ent)
            self._mut_queue = keep
            if not self.is_leader():
                return   # peons never mutate (beacon state flows to
                # the leader via forwarding)
            self._pump_proposals(now)

            def check_beacons():
                self._beacon_check_queued = False
                changed = False
                for osd, info in self.osdmap.osds.items():
                    if info.up and now - self._last_beacon.get(
                            osd, now) > grace:
                        log(1, f"osd.{osd} beacon timeout, "
                            "marking down")
                        self.osdmap.mark_down(osd)
                        changed = True
                if changed:
                    self._commit()

            stale = [osd for osd, info in self.osdmap.osds.items()
                     if info.up and
                     now - self._last_beacon.get(osd, now) > grace]
            if stale and not self._beacon_check_queued:
                self._beacon_check_queued = True

                def rearm(ok: bool) -> None:
                    # the queued check can expire unproposed (stalled
                    # proposal window on a minority leader); without
                    # this the flag stays set forever and beacon
                    # mark-down is permanently disabled on this mon
                    self._beacon_check_queued = False

                self._enqueue_mutation(check_beacons, done=rearm)
            # prune lapsed blocklist entries (the reference's osdmap
            # blacklist expiry): enforcement is already lazy in
            # is_blocklisted, but without this the map grows with
            # every failover/lock-break forever and 'osd blocklist
            # ls' reports long-dead fences
            wall = time.time()
            lapsed = [ent for ent, until in self.osdmap.blocklist.items()
                      if until and until <= wall]
            if lapsed and not self._blocklist_prune_queued:
                self._blocklist_prune_queued = True

                def prune_blocklist():
                    self._blocklist_prune_queued = False
                    w = time.time()
                    dead = [ent for ent, until in
                            self.osdmap.blocklist.items()
                            if until and until <= w]
                    for ent in dead:
                        del self.osdmap.blocklist[ent]
                    if dead:
                        self._commit()

                def rearm_prune(ok: bool) -> None:
                    self._blocklist_prune_queued = False

                self._enqueue_mutation(prune_blocklist,
                                       done=rearm_prune)

    # -- command handling (OSDMonitor::prepare_command role) ----------
    def _handle_command(self, cmd: dict) -> tuple[int, str, bytes]:
        prefix = cmd.get("prefix", "")
        try:
            if prefix == "osd erasure-code-profile set":
                return self._cmd_profile_set(cmd)
            if prefix == "osd erasure-code-profile ls":
                return 0, "", json.dumps(
                    sorted(self.ec_profiles)).encode()
            if prefix == "osd erasure-code-profile get":
                name = cmd["name"]
                if name not in self.ec_profiles:
                    return -2, f"profile {name!r} not found", b""
                return 0, "", json.dumps(self.ec_profiles[name]).encode()
            if prefix == "osd pool create":
                return self._cmd_pool_create(cmd)
            if prefix == "osd pool ls":
                return 0, "", json.dumps(
                    sorted(self.osdmap.pool_by_name)).encode()
            if prefix == "osd pool mksnap":
                pid = self._resolve_pool(cmd["pool"])
                pool = self.osdmap.pools[pid]
                name = cmd["snap"]
                if pool.selfmanaged:
                    # the two snapshot modes never mix in one pool
                    # (pg_pool_t is_unmanaged_snaps_mode refusal)
                    return -22, "pool is in self-managed snap " \
                        "mode", b""
                if name in pool.snaps.values():
                    return -17, f"snap {name!r} exists", b""
                pool.snap_seq += 1
                pool.snaps[pool.snap_seq] = name
                self._commit()
                return (0, f"created pool snap {name!r}",
                        json.dumps({"snapid": pool.snap_seq}).encode())
            if prefix == "osd pool selfmanaged-snap create":
                # rados_ioctx_selfmanaged_snap_create role: allocate
                # a snapid from the pool's sequence; the APP supplies
                # SnapContexts per write (CephFS realms, rbd)
                pid = self._resolve_pool(cmd["pool"])
                pool = self.osdmap.pools[pid]
                if pool.snaps:
                    return -22, "pool has pool snapshots", b""
                pool.selfmanaged = True
                pool.snap_seq += 1
                self._commit()
                return (0, "allocated selfmanaged snap",
                        json.dumps({"snapid": pool.snap_seq,
                                    "epoch": self.osdmap.epoch
                                    }).encode())
            if prefix == "osd pool selfmanaged-snap rm":
                pid = self._resolve_pool(cmd["pool"])
                pool = self.osdmap.pools[pid]
                snapid = int(cmd["snapid"])
                if not pool.selfmanaged or snapid > pool.snap_seq:
                    return -2, f"no selfmanaged snap {snapid}", b""
                if snapid not in pool.removed_snaps:
                    pool.removed_snaps.append(snapid)
                    self._commit()   # OSD trimmers react to the map
                return (0, f"removed selfmanaged snap {snapid}",
                        json.dumps({"epoch": self.osdmap.epoch
                                    }).encode())
            if prefix == "osd pool rmsnap":
                pid = self._resolve_pool(cmd["pool"])
                pool = self.osdmap.pools[pid]
                sid = next((i for i, n in pool.snaps.items()
                            if n == cmd["snap"]), None)
                if sid is None:
                    return -2, f"no snap {cmd['snap']!r}", b""
                del pool.snaps[sid]
                self._commit()   # OSD trimmers react to the new map
                return 0, f"removed pool snap {cmd['snap']!r}", b""
            if prefix == "osd tier add":
                # cache tiering plumbing (OSDMonitor "osd tier *"
                # command family, src/mon/OSDMonitor.cc)
                base = self._resolve_pool(cmd["pool"])
                tier = self._resolve_pool(cmd["tierpool"])
                tp = self.osdmap.pools[tier]
                if base == tier:
                    return -22, "pool cannot tier itself", b""
                if tp.is_ec:
                    return -22, "an EC pool cannot be a cache tier", \
                        b""
                if tp.tier_of >= 0:
                    return -17, f"{cmd['tierpool']} is already a " \
                        "tier", b""
                if self.osdmap.pools[base].tier_of >= 0:
                    return -22, "base pool is itself a tier", b""
                if not cmd.get("force_nonempty"):
                    # pre-existing objects in the tier pool would
                    # SHADOW base objects once the overlay lands (and
                    # the agent would flush them over the real base
                    # copies) — the reference mon refuses the same
                    # way without --force-nonempty
                    seen: set[str] = set()
                    objs = 0
                    for _osd, (_ts, stats) in self._pg_stats.items():
                        for s in stats:
                            if s["pgid"] in seen:
                                continue
                            seen.add(s["pgid"])
                            if s["pgid"].startswith(f"{tier}."):
                                objs += s.get("objects", 0)
                    if objs:
                        return -39, "tier pool is non-empty (pass " \
                            "force_nonempty to override)", b""
                tp.tier_of = base
                self._commit()
                return 0, f"pool {cmd['tierpool']!r} is now (or " \
                    f"already was) a tier of {cmd['pool']!r}", b""
            if prefix == "osd tier cache-mode":
                tier = self._resolve_pool(cmd["pool"])
                mode = cmd["mode"]
                if mode not in ("none", "writeback"):
                    return -22, f"unsupported cache mode {mode!r}", b""
                tp = self.osdmap.pools[tier]
                if tp.tier_of < 0:
                    return -22, f"{cmd['pool']!r} is not a tier", b""
                bp = self.osdmap.pools.get(tp.tier_of)
                if mode == "none" and bp is not None and \
                        (bp.read_tier == tier or bp.write_tier == tier):
                    # clients still redirect here; turning the OSD
                    # machinery off now would serve whiteouts as
                    # empty objects and orphan dirty data
                    return -16, "remove the overlay first", b""
                tp.cache_mode = mode
                self._commit()
                return 0, f"set cache-mode of {cmd['pool']!r} to " \
                    f"{mode}", b""
            if prefix == "osd tier set-overlay":
                base = self._resolve_pool(cmd["pool"])
                tier = self._resolve_pool(cmd["overlaypool"])
                tp = self.osdmap.pools[tier]
                if tp.tier_of != base:
                    return -22, f"{cmd['overlaypool']!r} is not a " \
                        f"tier of {cmd['pool']!r}", b""
                bp = self.osdmap.pools[base]
                bp.read_tier = bp.write_tier = tier
                self._commit()
                return 0, f"overlay for {cmd['pool']!r} is now " \
                    f"{cmd['overlaypool']!r}", b""
            if prefix == "osd tier remove-overlay":
                base = self._resolve_pool(cmd["pool"])
                bp = self.osdmap.pools[base]
                bp.read_tier = bp.write_tier = -1
                self._commit()
                return 0, f"removed overlay for {cmd['pool']!r}", b""
            if prefix == "osd tier remove":
                base = self._resolve_pool(cmd["pool"])
                tier = self._resolve_pool(cmd["tierpool"])
                tp = self.osdmap.pools[tier]
                bp = self.osdmap.pools[base]
                if tp.tier_of != base:
                    return -22, f"{cmd['tierpool']!r} is not a tier " \
                        f"of {cmd['pool']!r}", b""
                if bp.read_tier == tier or bp.write_tier == tier:
                    return -16, "remove the overlay first", b""
                tp.tier_of = -1
                tp.cache_mode = "none"
                self._commit()
                return 0, f"pool {cmd['tierpool']!r} is no longer a " \
                    f"tier of {cmd['pool']!r}", b""
            if prefix == "osd pool set":
                pid = self._resolve_pool(cmd["pool"])
                pool = self.osdmap.pools[pid]
                var, val = cmd["var"], cmd["val"]
                if var == "target_max_objects":
                    pool.target_max_objects = int(val)
                elif var == "target_max_bytes":
                    pool.target_max_bytes = int(val)
                elif var == "hit_set_period":
                    pool.hit_set_period = float(val)
                elif var == "hit_set_count":
                    pool.hit_set_count = max(1, int(val))
                elif var == "min_read_recency_for_promote":
                    pool.min_read_recency_for_promote = int(val)
                else:
                    return -22, f"unsettable pool var {var!r}", b""
                self._commit()
                return 0, f"set pool {cmd['pool']!r} {var} = {val}", \
                    b""
            if prefix == "config set":
                from ceph_tpu.utils.config import SCHEMA
                name, value = cmd["name"], cmd["value"]
                try:
                    SCHEMA.get(name).coerce(value)
                except (KeyError, ValueError) as exc:
                    return -22, f"config set: {exc}", b""
                self._central_config[name] = str(value)
                self._commit()
                return 0, f"set {name} = {value}", b""
            if prefix == "config rm":
                if self._central_config.pop(cmd["name"], None) is None:
                    return -2, f"no central config {cmd['name']!r}", b""
                self._commit()
                return 0, f"removed {cmd['name']}", b""
            if prefix == "config dump":
                return 0, "", json.dumps(self._central_config,
                                         sort_keys=True).encode()
            if prefix == "osd pool lssnap":
                pid = self._resolve_pool(cmd["pool"])
                return 0, "", json.dumps(
                    {str(i): n for i, n in
                     self.osdmap.pools[pid].snaps.items()}).encode()
            if prefix == "osd tree":
                return 0, "", json.dumps(self._osd_tree()).encode()
            if prefix == "osd out":
                osd = int(cmd["id"])
                if osd not in self.osdmap.osds:
                    return -2, f"no osd.{osd}", b""
                self.osdmap.mark_out(osd)
                self._commit()
                return 0, f"marked out osd.{osd}", b""
            if prefix == "osd in":
                osd = int(cmd["id"])
                if osd not in self.osdmap.osds:
                    return -2, f"no osd.{osd}", b""
                self.osdmap.osds[osd].in_cluster = True
                self.osdmap.crush.reweight(osd, 1.0)
                self._commit()
                return 0, f"marked in osd.{osd}", b""
            if prefix == "osd blocklist":
                # the fencing primitive (OSDMonitor "osd blacklist"
                # command, src/mon/OSDMonitor.cc; map field
                # src/osd/OSDMap.h:561). addr is a client instance id
                # ("mds.a:3fb2c9d1") or a bare entity name fencing
                # every instance. The reply data carries the new map
                # epoch so the caller can wait for the fence to be
                # in force (MDSMonitor::fail_mds waits for the
                # osdmon the same way, src/mon/MDSMonitor.cc:729-741).
                op = cmd["blocklistop"]
                entity = cmd.get("addr", "")
                if op == "add":
                    if not entity:
                        return -22, "missing addr", b""
                    expire = float(cmd.get("expire", 3600.0))
                    until = time.time() + expire if expire > 0 else 0.0
                    self.osdmap.blocklist_add(entity, until)
                    self._commit()
                    return (0, f"blocklisting {entity}",
                            json.dumps(
                                {"epoch": self.osdmap.epoch}).encode())
                if op == "rm":
                    if not self.osdmap.blocklist_rm(entity):
                        return -2, f"{entity} is not blocklisted", b""
                    self._commit()
                    return (0, f"un-blocklisting {entity}",
                            json.dumps(
                                {"epoch": self.osdmap.epoch}).encode())
                return -22, f"unknown blocklistop {op!r}", b""
            if prefix == "osd blocklist ls":
                return 0, "", json.dumps(
                    self.osdmap.blocklist, sort_keys=True).encode()
            if prefix == "osd pg-upmap-items":
                return self._cmd_pg_upmap_items(cmd)
            if prefix == "osd rm-pg-upmap-items":
                pool_id = self._resolve_pool(cmd["pool"])
                ps = int(cmd["ps"])
                if self.osdmap.pg_upmap_items.pop((pool_id, ps), None) \
                        is not None:
                    self._commit()
                return 0, f"rm upmap for {pool_id}.{ps}", b""
            if prefix == "osd dump":
                return 0, "", json.dumps(self._osd_dump()).encode()
            if prefix == "status":
                return 0, "", json.dumps(self._status()).encode()
            if prefix == "health":
                return 0, self._health(), b""
            if prefix == "health detail":
                return 0, self._health(), json.dumps(
                    self._health_detail()).encode()
            return -22, f"unknown command {prefix!r}", b""
        except KeyError as exc:
            return -22, f"missing argument: {exc}", b""
        except (ValueError, TypeError) as exc:
            # bad ints, malformed JSON, wrong shapes — the client must
            # get a reply, not a timeout
            return -22, f"invalid argument: {exc}", b""

    def _cmd_profile_set(self, cmd: dict) -> tuple[int, str, bytes]:
        name = cmd["name"]
        # command maps are str->str on the wire; the profile itself
        # travels as a JSON string value
        raw = cmd.get("profile", "{}")
        parsed = json.loads(raw)
        if not isinstance(parsed, dict):
            raise ValueError(f"profile must be a JSON object, got "
                             f"{type(parsed).__name__}")
        profile = {k: str(v) for k, v in parsed.items()}
        profile.setdefault("plugin", "jerasure")
        # validate by instantiating the codec — exactly what the
        # reference's mon does before accepting a profile
        try:
            ec_registry.instance().factory(profile["plugin"], profile)
        except Exception as exc:
            return -22, f"invalid profile: {exc}", b""
        self.ec_profiles[name] = profile
        self._commit()
        return 0, f"profile {name} set", b""

    def _resolve_pool(self, pool) -> int:
        """Accept a pool id or name (commands take either)."""
        try:
            pid = int(pool)
        except (TypeError, ValueError):
            pid = self.osdmap.pool_by_name.get(str(pool), -1)
        if pid not in self.osdmap.pools:
            raise ValueError(f"no pool {pool!r}")
        return pid

    def _cmd_pg_upmap_items(self, cmd: dict) -> tuple[int, str, bytes]:
        """``osd pg-upmap-items`` (OSDMonitor::prepare_command upmap
        role): install per-PG (from,to) up-set remaps — the mgr
        balancer's mechanism. Validates each target exists, is up+in,
        and is not already a member of the PG's up set."""
        pool_id = self._resolve_pool(cmd["pool"])
        ps = int(cmd["ps"])
        pool = self.osdmap.pools[pool_id]
        if not 0 <= ps < pool.pg_num:
            return -22, f"ps {ps} out of range for pool {pool_id}", b""
        raw_items = json.loads(cmd["items"])
        if not isinstance(raw_items, list) or not all(
                isinstance(p, (list, tuple)) and len(p) == 2
                for p in raw_items):
            return -22, f"items must be [[from,to],...]: {raw_items}", b""
        pairs = [(int(f), int(t)) for f, t in raw_items]
        # validated against the RAW CRUSH up set: the command replaces
        # the PG's whole pair list, so re-sent already-applied pairs
        # must validate too (checking the post-upmap set would reject
        # every second balancer round)
        err = self.osdmap.validate_upmap_items(pool_id, ps, pairs)
        if err is not None:
            return err[0], err[1], b""
        self.osdmap.pg_upmap_items[(pool_id, ps)] = pairs
        self._commit()
        return 0, f"upmap {pool_id}.{ps} {pairs}", b""

    def _osd_dump(self) -> dict:
        """Map details the balancer needs (``osd dump`` role)."""
        return {
            "epoch": self.osdmap.epoch,
            "pools": {str(pid): {"name": p.name, "pg_num": p.pg_num,
                                 "size": p.size, "rule": p.rule,
                                 "ec": p.is_ec}
                      for pid, p in self.osdmap.pools.items()},
            "pg_upmap_items": [
                {"pool": pid, "ps": ps,
                 "items": [list(pair) for pair in pairs]}
                for (pid, ps), pairs in
                sorted(self.osdmap.pg_upmap_items.items())],
        }

    def _cmd_pool_create(self, cmd: dict) -> tuple[int, str, bytes]:
        name = cmd["pool"]
        if name in self.osdmap.pool_by_name:
            return -17, f"pool {name!r} already exists", b""
        pg_num = int(cmd.get("pg_num", 8))
        rule = cmd.get("rule", "data")
        if rule not in self.osdmap.crush.rules:
            return -2, f"no crush rule {rule!r} (boot an osd first)", b""
        profile_name = cmd.get("erasure_code_profile", "")
        if profile_name:
            if profile_name not in self.ec_profiles:
                return -2, f"no profile {profile_name!r}", b""
            profile = self.ec_profiles[profile_name]
            codec = ec_registry.instance().factory(
                profile.get("plugin", "jerasure"), profile)
            k = codec.get_data_chunk_count()
            size = codec.get_chunk_count()
            self.osdmap.create_pool(
                name, pg_num, rule, size=size, min_size=k,
                ec_profile=dict(profile))
        else:
            size = int(cmd.get("size", 3))
            self.osdmap.create_pool(
                name, pg_num, rule, size=size,
                min_size=max(1, size - 1))
        self._commit()
        return 0, f"pool {name!r} created", b""

    def _osd_tree(self) -> dict:
        return {
            "buckets": [
                {"id": b.id, "name": b.name, "type": b.type,
                 "children": b.items}
                for b in self.osdmap.crush.buckets.values()],
            "osds": [
                {"id": o.osd_id, "up": o.up, "in": o.in_cluster,
                 "addr": o.addr}
                for o in self.osdmap.osds.values()],
        }

    def _pgmap(self) -> dict:
        """Aggregate reported PG stats (the mgr pgmap in 'ceph -s')."""
        now = time.monotonic()
        stale_after = 10 * g_conf()["osd_heartbeat_interval"]
        by_state: dict[str, int] = {}
        degraded = 0
        objects = 0
        seen: set[str] = set()
        for osd, (ts, stats) in self._pg_stats.items():
            if now - ts > stale_after:
                continue
            for s in stats:
                if s["pgid"] in seen:
                    continue
                seen.add(s["pgid"])
                by_state[s["state"]] = by_state.get(s["state"], 0) + 1
                if s["missing"]:
                    degraded += 1
                objects += s.get("objects", 0)
        return {"num_pgs": len(seen), "by_state": by_state,
                "degraded_pgs": degraded, "num_objects": objects}

    def _status(self) -> dict:
        up = sum(1 for o in self.osdmap.osds.values() if o.up)
        inc = sum(1 for o in self.osdmap.osds.values() if o.in_cluster)
        checks = self._health_checks()
        return {
            "health": self._health(checks),
            "health_checks": checks,
            "epoch": self.osdmap.epoch,
            "num_osds": len(self.osdmap.osds),
            "num_up_osds": up,
            "num_in_osds": inc,
            "pools": sorted(self.osdmap.pool_by_name),
            "pgmap": self._pgmap(),
            "quorum": {"rank": self.rank,
                       "leader": self._leader_rank,
                       "mons": len(self.monmap)},
        }

    @staticmethod
    def _worst_severity(checks: dict) -> str:
        rank = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}
        out = "HEALTH_OK"
        for c in checks.values():
            if rank.get(c.get("severity"), 0) > rank[out]:
                out = c["severity"]
        return out

    def _health_checks(self) -> dict:
        """Structured named checks (health_check_map_t role): the
        mon's own up/in + pg accounting, merged with the latest
        mgr health-engine report (mgr/health.py) when fresh. The
        mon's own accounting wins on name collisions — it is
        authoritative for map-derived state."""
        checks: dict[str, dict] = {}
        down = [o.osd_id for o in self.osdmap.osds.values()
                if not o.up]
        if down:
            up = len(self.osdmap.osds) - len(down)
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_ERR" if up == 0
                else "HEALTH_WARN",
                "summary": f"{len(down)} osds down: {down}",
                "detail": [f"osd.{o} is down" for o in sorted(down)]}
        pgmap = self._pgmap()
        if pgmap["degraded_pgs"]:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{pgmap['degraded_pgs']} pgs degraded",
                "detail": []}
        notactive = sum(n for st, n in pgmap["by_state"].items()
                        if st != "active")
        if notactive:
            checks["PG_NOT_ACTIVE"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{notactive} pgs not active",
                "detail": [f"{n} pgs {st}" for st, n in
                           sorted(pgmap["by_state"].items())
                           if st != "active"]}
        rep = self._mgr_health
        if rep is not None and \
                time.monotonic() - rep[0] <= MGR_HEALTH_STALE:
            for name, chk in rep[1].get("checks", {}).items():
                if isinstance(chk, dict) and name not in checks:
                    checks[name] = chk
        return checks

    def _health_detail(self) -> dict:
        """The ``health detail`` answer: overall status + every named
        check with severity/summary/detail."""
        checks = self._health_checks()
        rep = self._mgr_health
        age = None
        if rep is not None:
            age = round(time.monotonic() - rep[0], 3)
        return {"status": self._worst_severity(checks),
                "checks": checks,
                "mgr_report_age_s": age}

    def _health(self, checks: dict | None = None) -> str:
        """The one-line answer, derived from the structured checks
        (summaries joined, worst severity as the prefix)."""
        if checks is None:
            checks = self._health_checks()
        if not checks:
            return "HEALTH_OK"
        status = self._worst_severity(checks)
        return status + ": " + "; ".join(
            c["summary"] for c in checks.values())
