"""Clay layered codec as a staged TPU pipeline.

The linearized flat matrix (models/clay.py) is bit-exact but dense:
for k=8,m=4 it spends ~20x the necessary FLOPs (density ~5%). The
layered algorithm itself is MXU/VPU-friendly when expressed over whole
planes instead of per-sub-chunk host loops:

  - the pairwise coupling transforms (C<->U) are 2x2 GF-constant maps
    applied elementwise across lanes — VPU work (8 masked XORs per GF
    constant multiply, fused by XLA);
  - each plane's MDS solve is ONE small GF matrix multiply batched
    over (planes-in-level x lanes) — the same bit-sliced MXU matmul
    every other codec uses;
  - the score-level ordering of ErasureCodeClay.cc:644-709 becomes a
    short static chain (<= m+1 stages) inside one jit.

``trace_layered`` symbolically executes the host algorithm's control
flow (which depends only on (q, t, erased)) and records vectorizable
op groups; ``build_transform`` compiles them into a jitted function
``C[q*t, ssc, L] -> C'`` with recovered nodes filled in. Signatures
are cached, so encode (erased = parity nodes) compiles once per
profile. Bit-exactness vs the host plane machinery is asserted in
tests/test_clay_device.py.

Measured (v5e, k=8,m=4,d=11 encode, 64 MiB batches): 4.7 GB/s — the
score-level chain inherently sweeps the full [q*t, ssc, L] working
set ~6x per level (permuted gathers + masked selects), so the DENSE
linearized signature matrix (models/clay.py, one [m*ssc, k*ssc]
matmul, ~9 GB/s despite 20x FLOP waste) remains the production device
path; this module is the faithful staged expression of the algorithm,
kept as the validated alternative and the basis for a future
plane-blocked kernel.

Round-3 finding (``build_encode_fast``): for the ENCODE erasure
pattern (all parities erased) the score-level chain collapses to ONE
active level, so encode is exactly three stages — a 2-term pairwise
pass over the data, ONE plane-wise [m,k] MDS matmul (RS-kernel
class, 561 GB/s in isolation on this chip), and a 2-term recouple
pass. The structured encoder below is bit-exact and does ~1/20 the
dense MACs, yet measures only 8.2 GB/s composed (vs 9.0 dense):
XLA inserts a layout copy between the gather/select producers and
the pallas custom call (a bare row-gather feeding the kernel already
drops it from 270 to 82 GB/s), and the per-slot constant-select
chains do not fuse into single passes.

Round-4 result (``build_encode_kernel``): the whole three-stage chain
inside ONE pallas kernel with the working set VMEM-resident. The key
moves: everything stays in ROW SPACE over [rows, T] lane tiles (no
layout changes exist to copy); the (node, plane) pair gathers become
0/1 ROUTING MATMULS on the MXU (<=1 one per row — exact bf16 byte
routing); per-slot GF coefficients are per-row VPU XOR chains; the
plane-wise MDS runs per plane over its contiguous z-major row group
as an [8m, 8kk] bit-matmul. ~2k MACs/byte vs the dense linearized
matrix's ~16k (dense measures ~9 GB/s because it is COMPUTE-bound at
64x the RS MAC count). Measured (v5e, k=8,m=4,d=11, 67 MB batches,
plateau method): **525 GB/s**, spread 0.0% — RS-kernel class, 58x the
dense path, 10x past the >= 50 target. Bit-exact vs the host layered
oracle (both pallas-TPU and interpret mode); production encode routes
here for pallas backends (models/clay.py _encode_chunks_lin).

The single-XLA-program experiment (``build_encode_fused``) measured
1.8 GB/s on chip — kept as the documented negative result: outside a
kernel, the row gathers materialize and the bit-plane expansion
amplifies HBM traffic ~30x.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.ops import bitmatrix, gf256


def _tpu_compiler_params(pltpu, **kw):
    """pltpu.CompilerParams across the jax version skew (older
    runtimes spell it TPUCompilerParams)."""
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


# -- static trace ------------------------------------------------------

@dataclass
class LevelOps:
    """Vectorizable op groups for one score level (all index arrays)."""
    # phase 1: U for intact nodes
    ident: list = field(default_factory=list)      # (node, z)
    pair_a: dict = field(default_factory=dict)     # variant -> [(nxy, z, nsw, zsw)]
    # per-plane MDS decode of erased U
    planes: list = field(default_factory=list)     # [z, ...]
    # phase 2: C for erased nodes
    ident2: list = field(default_factory=list)     # (node, z)
    type_c: dict = field(default_factory=dict)     # variant -> [(nxy, z, nsw, zsw)]
    pair_b: list = field(default_factory=list)     # (nxy, z, nsw, zsw)


def trace_layered(codec, erased: frozenset[int]) -> list[LevelOps]:
    """Replay _decode_layered's control flow (ErasureCodeClay.cc:
    644-709) recording ops instead of computing bytes. ``erased`` is
    the PADDED node-id set (virtual/parity fill to m, as the host path
    builds it)."""
    q, t = codec.q, codec.t
    ssc = codec.sub_chunk_no
    zvecs = [codec.get_plane_vector(z) for z in range(ssc)]
    order = [sum(1 for i in erased if i % q == zvecs[z][i // q])
             for z in range(ssc)]
    max_score = max(order) if erased else 0
    levels = []
    for score in range(max_score + 1):
        ops = LevelOps()
        planes = [z for z in range(ssc) if order[z] == score]
        for z in planes:
            zv = zvecs[z]
            for y in range(t):
                for x in range(q):
                    node_xy = q * y + x
                    if node_xy in erased:
                        continue
                    node_sw = q * y + zv[y]
                    if zv[y] == x:
                        ops.ident.append((node_xy, z))
                    elif zv[y] < x or node_sw in erased:
                        z_sw = codec._z_sw(z, x, zv[y], y)
                        variant = 1 if zv[y] > x else 0
                        ops.pair_a.setdefault(variant, []).append(
                            (node_xy, z, node_sw, z_sw))
        ops.planes = planes
        for z in planes:
            zv = zvecs[z]
            for node_xy in sorted(erased):
                x, y = node_xy % q, node_xy // q
                node_sw = q * y + zv[y]
                if zv[y] == x:
                    ops.ident2.append((node_xy, z))
                elif node_sw not in erased:
                    z_sw = codec._z_sw(z, x, zv[y], y)
                    variant = 1 if zv[y] > x else 0
                    ops.type_c.setdefault(variant, []).append(
                        (node_xy, z, node_sw, z_sw))
                elif zv[y] < x:
                    z_sw = codec._z_sw(z, x, zv[y], y)
                    ops.pair_b.append((node_xy, z, node_sw, z_sw))
        levels.append(ops)
    return levels


# -- pft coefficient extraction ----------------------------------------

def _pft_matrix(codec, want: list[int], known_slots: list[int]
                ) -> np.ndarray:
    """2x2 (or 1x2) GF matrix of one pairwise-transform solve, probed
    from the pft codec (GF-linear)."""
    rows = []
    for basis in range(len(known_slots)):
        known = {s: np.array([1 if i == basis else 0], dtype=np.uint8)
                 for i, s in enumerate(known_slots)}
        out = codec.pft.decode_chunks(want, known)
        rows.append([int(np.asarray(out[w])[0]) for w in want])
    return np.array(rows, dtype=np.uint8).T   # [len(want), len(known)]


def pft_coefficients(codec) -> dict:
    """All coefficient matrices the trace can reference, per slot
    variant (slot order (i0,i1,i2,i3) = (1,0,3,2) when zy > x)."""
    coeffs = {}
    for variant, slots in ((0, (0, 1, 2, 3)), (1, (1, 0, 3, 2))):
        i0, i1, i2, i3 = slots
        # pair_a: (U_xy, U_sw) from (C_xy, C_sw)
        m = _pft_matrix(codec, [i2, i3], [i0, i1])
        coeffs[("a", variant)] = m                      # [2, 2]
        # type_c: C_xy from (C_sw, U_xy)
        m = _pft_matrix(codec, [i0], [i1, i2])
        coeffs[("c", variant)] = m                      # [1, 2]
    # pair_b: (C_xy, C_sw) from (U_xy, U_sw); called with zv[y] < x
    # only, so slot order is fixed at variant 0
    coeffs[("b", 0)] = _pft_matrix(codec, [0, 1], [2, 3])
    return coeffs


# -- device execution ---------------------------------------------------

def _gf_scale(x, c: int):
    """x (*) c over GF(2^8), elementwise, for a static constant c:
    XOR of up-to-8 masked constant selects (VPU work XLA fuses)."""
    import jax.numpy as jnp
    if c == 0:
        return jnp.zeros_like(x)
    if c == 1:
        return x
    y = None
    for b in range(8):
        t = int(gf256.gf_mul(c, 1 << b))
        if t == 0:
            continue
        term = jnp.where((x >> b) & 1 == 1,
                         jnp.uint8(t), jnp.uint8(0))
        y = term if y is None else y ^ term
    return y


def _combine2(m: np.ndarray, a, b):
    """[out0, out1] = m @ [a, b] over GF, m a small host matrix."""
    outs = []
    for row in m:
        acc = _gf_scale(a, int(row[0])) ^ _gf_scale(b, int(row[1]))
        outs.append(acc)
    return outs


def _varmul_tables(coef: np.ndarray) -> list[tuple[int, np.ndarray]]:
    """Bit tables for an elementwise multiply by VARYING constants:
    y[e] = coef[e] (*) x[e] = XOR_b ((x>>b)&1) * gf_mul(coef, 2^b)[e].
    Returns only the bit planes with a nonzero table."""
    out = []
    for b in range(8):
        tab = gf256.gf_mul(coef, 1 << b)
        if tab.any():
            out.append((b, tab))
    return out


def _varmul(x, tables, jnp):
    """Apply _varmul_tables to x [qt, ssc, L] (tables broadcast over
    lanes). One fused XOR chain — no scatters, no per-pair gathers."""
    y = None
    for b, tab in tables:
        t = jnp.asarray(tab[:, :, None])
        term = jnp.where((x >> b) & 1 == 1, t, jnp.uint8(0))
        y = term if y is None else y ^ term
    if y is None:
        return jnp.zeros_like(x)
    return y


def build_transform(codec, erased: frozenset[int]):
    """Jitted ``C[q*t, ssc, L] uint8 -> C'`` filling erased nodes.
    ``erased``: padded node-id set, |erased| <= m.

    Executor shape: per level, phase 1 is ONE whole-array masked pass
    ``U' = sel(mask, a1(*)C + a2(*)C[perm], U)`` (a1/a2/perm are
    static [qt, ssc] tables), the MDS solve is one bit-sliced matmul
    over (planes-in-level x lanes), and phase 2 is one more masked
    pass over C — a handful of fused HBM passes per level instead of
    per-op-group scatters."""
    import jax
    import jax.numpy as jnp

    levels = trace_layered(codec, erased)
    coeffs = pft_coefficients(codec)
    qt = codec.q * codec.t
    ssc = codec.sub_chunk_no
    intact = [i for i in range(qt) if i not in erased]
    er = sorted(erased)
    dmat = _mds_decode_matrix(codec, intact, er)
    dbmat = bitmatrix.expand_bitmatrix(dmat).astype(np.int8)

    from ceph_tpu.ops.gf_jax import _bitsliced_matvec_device

    static = []
    for ops in levels:
        # phase 1 tables: U[n,z] = a1[n,z](*)C[n,z] ^ a2[n,z](*)C[perm]
        a1 = np.zeros((qt, ssc), dtype=np.uint8)
        a2 = np.zeros((qt, ssc), dtype=np.uint8)
        pn = np.tile(np.arange(qt, dtype=np.int32)[:, None], (1, ssc))
        pz = np.tile(np.arange(ssc, dtype=np.int32)[None, :], (qt, 1))
        mask_u = np.zeros((qt, ssc), dtype=bool)
        for n, z in ops.ident:
            a1[n, z] = 1
            mask_u[n, z] = True
        for v, lst in ops.pair_a.items():
            m = coeffs[("a", v)]
            for nxy, z, nsw, zsw in lst:
                # target (nxy, z): self C + partner C
                a1[nxy, z], a2[nxy, z] = int(m[0][0]), int(m[0][1])
                pn[nxy, z], pz[nxy, z] = nsw, zsw
                mask_u[nxy, z] = True
                # target (nsw, zsw): its self is C[nsw, zsw]
                a1[nsw, zsw], a2[nsw, zsw] = int(m[1][1]), int(m[1][0])
                pn[nsw, zsw], pz[nsw, zsw] = nxy, z
                mask_u[nsw, zsw] = True
        # phase 2 tables:
        #   C[n,z] = b1(*)C[perm2] ^ b2(*)U[n,z] ^ b3(*)U[perm2]
        b1 = np.zeros((qt, ssc), dtype=np.uint8)
        b2 = np.zeros((qt, ssc), dtype=np.uint8)
        b3 = np.zeros((qt, ssc), dtype=np.uint8)
        p2n = np.tile(np.arange(qt, dtype=np.int32)[:, None],
                      (1, ssc))
        p2z = np.tile(np.arange(ssc, dtype=np.int32)[None, :],
                      (qt, 1))
        mask_c = np.zeros((qt, ssc), dtype=bool)
        for n, z in ops.ident2:
            b2[n, z] = 1
            mask_c[n, z] = True
        for v, lst in ops.type_c.items():
            m = coeffs[("c", v)]
            for nxy, z, nsw, zsw in lst:
                b1[nxy, z] = int(m[0][0])
                b2[nxy, z] = int(m[0][1])
                p2n[nxy, z], p2z[nxy, z] = nsw, zsw
                mask_c[nxy, z] = True
        mb = coeffs[("b", 0)]
        for nxy, z, nsw, zsw in ops.pair_b:
            b2[nxy, z], b3[nxy, z] = int(mb[0][0]), int(mb[0][1])
            p2n[nxy, z], p2z[nxy, z] = nsw, zsw
            mask_c[nxy, z] = True
            b2[nsw, zsw], b3[nsw, zsw] = int(mb[1][1]), int(mb[1][0])
            p2n[nsw, zsw], p2z[nsw, zsw] = nxy, z
            mask_c[nsw, zsw] = True
        static.append({
            "planes": np.asarray(ops.planes, dtype=np.int32),
            "t_a1": _varmul_tables(a1), "t_a2": _varmul_tables(a2),
            "perm": (pn, pz), "mask_u": mask_u,
            "t_b1": _varmul_tables(b1), "t_b2": _varmul_tables(b2),
            "t_b3": _varmul_tables(b3),
            "perm2": (p2n, p2z), "mask_c": mask_c,
        })

    intact_idx = jnp.asarray(np.asarray(intact, dtype=np.int32))
    er_idx = jnp.asarray(np.asarray(er, dtype=np.int32))

    @jax.jit
    def transform(c_in):
        C = c_in
        U = jnp.zeros_like(C)
        L = C.shape[-1]
        for entry in static:
            # phase 1: one masked whole-array pass
            pn, pz = entry["perm"]
            cp = C[jnp.asarray(pn), jnp.asarray(pz)]
            cand = _varmul(C, entry["t_a1"], jnp) ^ \
                _varmul(cp, entry["t_a2"], jnp)
            U = jnp.where(jnp.asarray(entry["mask_u"])[:, :, None],
                          cand, U)
            # MDS decode of erased U on this level's planes
            if len(entry["planes"]):
                planes = jnp.asarray(entry["planes"])
                x = U[intact_idx][:, planes, :].reshape(
                    len(intact), -1)
                y = _bitsliced_matvec_device(jnp.asarray(dbmat), x)
                y = y.reshape(len(er), len(entry["planes"]), L)
                U = U.at[er_idx[:, None], planes[None, :]].set(y)
            # phase 2: one masked whole-array pass
            p2n, p2z = entry["perm2"]
            cp2 = C[jnp.asarray(p2n), jnp.asarray(p2z)]
            up2 = U[jnp.asarray(p2n), jnp.asarray(p2z)]
            cand = _varmul(cp2, entry["t_b1"], jnp) ^ \
                _varmul(U, entry["t_b2"], jnp) ^ \
                _varmul(up2, entry["t_b3"], jnp)
            C = jnp.where(jnp.asarray(entry["mask_c"])[:, :, None],
                          cand, C)
        return C

    return transform


def _mds_decode_matrix(codec, intact: list, er: list) -> np.ndarray:
    """[len(er), len(intact)] matrix recovering erased-U from intact-U
    (identical per plane), probed from the scalar MDS codec."""
    probe = {i: np.zeros(len(intact), dtype=np.uint8) for i in intact}
    for idx, i in enumerate(intact):
        probe[i][idx] = 1
    sol = codec.mds.decode_chunks(er, probe)
    return np.stack([np.asarray(sol[i], dtype=np.uint8) for i in er])


def build_encode_fast(codec, tables_only: bool = False):
    """Structured device ENCODE (the round-2 verdict's plane-blocked
    kernel, ErasureCodeClay.cc:644-709 coupling structure): for the
    all-parity erasure pattern the score-level chain collapses to ONE
    active level, so encode is exactly three stages —

      1. U_data = pairwise uncouple of C_data (2-term GF combos, one
         gather + two constant-table passes over the data array; the
         erased partners' C is zero by construction and drops out);
      2. U_parity = the plane-wise MDS encode — ONE [m,k] bit-sliced
         MXU matmul over (ssc x lanes), the same shape/throughput
         class as the plain RS kernel;
      3. C_parity = pairwise recouple (2-term combos reading U_parity
         and gathered C_data).

    vs the dense [m*ssc, k*ssc] signature matrix this does ~1/20 the
    MACs (the matrix is ~5% dense) and ~6 HBM passes instead of a
    compute-bound dense matmul. Returns a jitted
    ``[k, ssc, L] uint8 -> [m, ssc, L]`` (bit-exact vs the host
    layered machinery — gated in tests)."""
    import jax
    import jax.numpy as jnp

    q, t = codec.q, codec.t
    qt, ssc = q * t, codec.sub_chunk_no
    k, m = codec.k, codec.m
    erased = frozenset(codec._node_id(i) for i in range(k, k + m))
    levels = trace_layered(codec, erased)
    active = [ops for ops in levels
              if ops.ident or ops.pair_a or ops.planes]
    assert len(active) == 1 and sorted(active[0].planes) == \
        list(range(ssc)), "encode trace is not single-level"
    ops = active[0]
    coeffs = pft_coefficients(codec)
    # intact rows = data nodes (grid ids 0..k-1) PLUS the nu virtual
    # nodes (grid ids k..k+nu-1) of profiles where q does not divide
    # k+m: virtual C is zero, but virtual U mixes real data and feeds
    # the MDS solve, so they get real rows
    intact = [i for i in range(qt) if i not in erased]
    kk = len(intact)
    assert kk == k + codec.nu, (kk, k, codec.nu)
    er = sorted(erased)
    row_of = {n: idx for idx, n in enumerate(intact)}
    prow_of = {n: idx for idx, n in enumerate(er)}
    #: input embedding: padded row -> data chunk index (-1 = virtual)
    src = np.full(kk, -1, dtype=np.int32)
    for i in range(k):
        src[row_of[codec._node_id(i)]] = i

    # stage 1 tables over INTACT slots [kk, ssc]
    a1 = np.zeros((kk, ssc), dtype=np.uint8)
    a2 = np.zeros((kk, ssc), dtype=np.uint8)
    perm = np.zeros((kk, ssc), dtype=np.int32)   # flat intact-slot idx
    for n, z in ops.ident:
        a1[row_of[n], z] = 1
        perm[row_of[n], z] = row_of[n] * ssc + z
    for v, lst in ops.pair_a.items():
        mm = coeffs[("a", v)]
        for nxy, z, nsw, zsw in lst:
            r = row_of[nxy]
            a1[r, z], perm[r, z] = int(mm[0][0]), r * ssc + z
            if nsw in erased:
                # partner C is an erased node: zero by construction
                a2[r, z] = 0
            else:
                a2[r, z] = int(mm[0][1])
                perm[r, z] = row_of[nsw] * ssc + zsw
            rs = prow_of.get(nsw)
            if rs is None:
                r2 = row_of[nsw]
                a1[r2, zsw] = int(mm[1][1])
                a2[r2, zsw] = int(mm[1][0])
                perm[r2, zsw] = r * ssc + z
    dmat = _mds_decode_matrix(codec, intact, er)

    # stage 3 tables over PARITY slots [m, ssc]
    b1 = np.zeros((m, ssc), dtype=np.uint8)      # * C_data[perm_c]
    b2 = np.zeros((m, ssc), dtype=np.uint8)      # * U_par[self]
    b3 = np.zeros((m, ssc), dtype=np.uint8)      # * U_par[perm_u]
    perm_c = np.zeros((m, ssc), dtype=np.int32)
    perm_u = np.zeros((m, ssc), dtype=np.int32)
    for n, z in ops.ident2:
        b2[prow_of[n], z] = 1
    for v, lst in ops.type_c.items():
        mm = coeffs[("c", v)]
        for nxy, z, nsw, zsw in lst:
            r = prow_of[nxy]
            b1[r, z] = int(mm[0][0])
            perm_c[r, z] = row_of[nsw] * ssc + zsw
            b2[r, z] = int(mm[0][1])
    mb = coeffs[("b", 0)]
    for nxy, z, nsw, zsw in ops.pair_b:
        r, rs = prow_of[nxy], prow_of[nsw]
        b2[r, z], b3[r, z] = int(mb[0][0]), int(mb[0][1])
        perm_u[r, z] = rs * ssc + zsw
        b2[rs, zsw], b3[rs, zsw] = int(mb[1][1]), int(mb[1][0])
        perm_u[rs, zsw] = r * ssc + z

    tables = {
        "kk": kk, "ssc": ssc, "k": k, "m": m, "dmat": dmat,
        "t_a1": _varmul_tables(a1.reshape(-1, 1)),
        "t_a2": _varmul_tables(a2.reshape(-1, 1)),
        "t_b1": _varmul_tables(b1.reshape(-1, 1)),
        "t_b2": _varmul_tables(b2.reshape(-1, 1)),
        "t_b3": _varmul_tables(b3.reshape(-1, 1)),
        "perm": perm.reshape(-1), "perm_c": perm_c.reshape(-1),
        "perm_u": perm_u.reshape(-1), "src": src,
        "a1": a1.reshape(-1), "a2": a2.reshape(-1),
        "b1": b1.reshape(-1), "b2": b2.reshape(-1),
        "b3": b3.reshape(-1),
    }
    if tables_only:
        # kernel/fused builders want only the structure tables — skip
        # building the staged jit closures and device constants
        class _T:
            pass
        holder = _T()
        holder.tables = tables
        return holder
    from ceph_tpu.ops import backend as backend_mod
    try:
        resolved, _ = backend_mod.resolve(codec.backend)
    except KeyError:
        resolved = "jax"
    if resolved == "pallas":
        from ceph_tpu.ops.gf_pallas import matvec_device
    else:
        from ceph_tpu.ops.gf_jax import matvec_device
    t_a1, t_a2 = tables["t_a1"], tables["t_a2"]
    t_b1, t_b2, t_b3 = (tables["t_b1"], tables["t_b2"],
                        tables["t_b3"])
    perm_f = jnp.asarray(perm.reshape(-1))
    perm_cf = jnp.asarray(perm_c.reshape(-1))
    perm_uf = jnp.asarray(perm_u.reshape(-1))
    src_j = jnp.asarray(np.maximum(src, 0))
    virt = jnp.asarray((src < 0)[:, None, None])

    # the three stages live in two jitted pieces around the backend
    # matvec (itself jitted/bucketed); XLA fuses the elementwise
    # chains on each side
    @jax.jit
    def stage1(c_data):
        L = c_data.shape[-1]
        # embed the k data chunks into the kk intact rows (virtual
        # node rows are zero)
        padded = jnp.where(virt, jnp.uint8(0), c_data[src_j])
        flat = padded.reshape(kk * ssc, L)
        u_d = _varmul(flat[:, None, :], t_a1, jnp) ^ \
            _varmul(flat[perm_f][:, None, :], t_a2, jnp)
        return padded, u_d.reshape(kk, ssc * L)

    @jax.jit
    def stage3(padded, u_par):
        L = padded.shape[-1]
        flat_c = padded.reshape(kk * ssc, L)
        flat_u = u_par.reshape(m * ssc, L)
        out = _varmul(flat_c[perm_cf][:, None, :], t_b1, jnp) ^ \
            _varmul(flat_u[:, None, :], t_b2, jnp) ^ \
            _varmul(flat_u[perm_uf][:, None, :], t_b3, jnp)
        return out.reshape(m, ssc, L)

    def encode_fast(c_data):
        padded, u_d = stage1(c_data)
        u_p = matvec_device(dmat, u_d)       # [m, ssc*L], trace-safe
        u_p = u_p.reshape(m, ssc, padded.shape[-1])
        return stage3(padded, u_p)

    encode_fast.tables = tables
    return encode_fast


def build_encode_fused(codec):
    """Round-4: the three structured-encode stages as ONE XLA program
    (no custom-call boundaries, no per-stage jit seams). The round-3
    composition ran at 8.2 GB/s because each stage was its own jitted
    piece: XLA inserted layout copies into the pallas custom call and
    could not fuse the select chains across dispatch boundaries. Here
    the pairwise uncouple (gather + xor chains), the plane-wise MDS
    bit-sliced MXU matmul, and the recouple live in a single jit —
    XLA fuses the elementwise chains into the matmul's operand and
    result producers, and the working set streams through one fused
    program. Same tables, bit-exact with the host layered oracle.

    Returns jitted ``[k, ssc, L] uint8 -> [m, ssc, L]`` with
    L pow2-bucketed by the wrapper (bounded compiles, like every
    daemon-facing device entry)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import bitmatrix

    fast = build_encode_fast(codec, tables_only=True)
    tb = fast.tables
    kk, ssc, k, m = tb["kk"], tb["ssc"], tb["k"], tb["m"]
    bmat = jnp.asarray(
        bitmatrix.expand_bitmatrix(tb["dmat"]).astype(np.int8))
    t_a1, t_a2 = tb["t_a1"], tb["t_a2"]
    t_b1, t_b2, t_b3 = tb["t_b1"], tb["t_b2"], tb["t_b3"]
    perm_f = jnp.asarray(tb["perm"])
    perm_cf = jnp.asarray(tb["perm_c"])
    perm_uf = jnp.asarray(tb["perm_u"])
    src_j = jnp.asarray(np.maximum(tb["src"], 0))
    virt = jnp.asarray((tb["src"] < 0)[:, None, None])
    shifts = jnp.arange(8, dtype=jnp.uint8)

    @jax.jit
    def fused(c_data):
        L = c_data.shape[-1]
        padded = jnp.where(virt, jnp.uint8(0), c_data[src_j])
        flat = padded.reshape(kk * ssc, L)
        u_d = _varmul(flat[:, None, :], t_a1, jnp) ^ \
            _varmul(flat[perm_f][:, None, :], t_a2, jnp)
        u_d = u_d.reshape(kk, ssc * L)
        # plane-wise MDS encode, bit-sliced onto the MXU, inline
        dbits = ((u_d[:, None, :] >> shifts[None, :, None]) & 1
                 ).astype(jnp.int8).reshape(8 * kk, ssc * L)
        acc = jax.lax.dot_general(
            bmat, dbits, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        pbits = (acc & 1).astype(jnp.uint8).reshape(m, 8, ssc * L)
        weights = (jnp.uint8(1) << shifts)[None, :, None]
        u_p = (pbits * weights).sum(axis=1, dtype=jnp.uint32
                                    ).astype(jnp.uint8)
        flat_u = u_p.reshape(m * ssc, L)
        out = _varmul(flat[perm_cf][:, None, :], t_b1, jnp) ^ \
            _varmul(flat_u[:, None, :], t_b2, jnp) ^ \
            _varmul(flat_u[perm_uf][:, None, :], t_b3, jnp)
        return out.reshape(m, ssc, L)

    def encode(c_data):
        c_data = jnp.asarray(c_data, dtype=jnp.uint8)
        L = c_data.shape[-1]
        lb = 1 << 10
        while lb < L:
            lb <<= 1
        if lb != L:
            c_data = jnp.pad(c_data, ((0, 0), (0, 0), (0, lb - L)))
        out = fused(c_data)
        return out[:, :, :L] if lb != L else out

    encode.tables = tb
    return encode


def build_encode_kernel(codec, tile: int = 512):
    """Round-4: the WHOLE structured encode chain in ONE Pallas
    kernel with a VMEM-resident working set (the round-3 deferral's
    prescription). Everything runs in ROW SPACE over [rows, T] lane
    tiles, so no layout copies ever occur:

    - the pairwise couplings' (node, plane) gathers are ROW
      permutations of the tile — executed as MXU matmuls with 0/1
      routing matrices (<=1 one per row: bf16 products and f32 sums
      are exact byte routing);
    - the per-slot GF coefficients are per-row constant XOR chains on
      the VPU (the _varmul decomposition, tables as [rows, 1] refs);
    - the plane-wise MDS encode runs per plane z over the contiguous
      [z*kk, (z+1)*kk) row group: unpack bits -> one [8m, 8kk]
      bit-matmul on the MXU -> weighted-sum repack, all in VMEM.

    ~2k MACs/byte total vs the dense linearized matrix's ~16k (the
    measured reason dense tops out at ~9 GB/s: it is COMPUTE-bound at
    64x the RS MAC count). Bit-exact vs the host layered oracle.

    Returns ``[k, ssc, L] uint8 -> [m, ssc, L]`` with L pow2-bucketed.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ceph_tpu.ops import bitmatrix
    from ceph_tpu.ops.gf_pallas import _permute_bitmatrix

    fast = build_encode_fast(codec, tables_only=True)
    tb = fast.tables
    kk, ssc, k, m = tb["kk"], tb["ssc"], tb["k"], tb["m"]
    src = tb["src"]
    R_in, R_ud, R_out = k * ssc, kk * ssc, m * ssc

    def _col_of(intact_flat: int) -> int | None:
        j2, z = divmod(int(intact_flat), ssc)
        i = int(src[j2])
        return None if i < 0 else i * ssc + z

    # routing matrices (0/1, <=1 per row) + z-major coefficient tables
    p_self = np.zeros((R_ud, R_in), dtype=np.float32)
    p_a = np.zeros((R_ud, R_in), dtype=np.float32)
    a1z = np.zeros((R_ud, 1), dtype=np.uint8)
    a2z = np.zeros((R_ud, 1), dtype=np.uint8)
    a1, a2 = tb["a1"], tb["a2"]
    b1, b2, b3 = tb["b1"], tb["b2"], tb["b3"]
    perm, perm_c, perm_u = tb["perm"], tb["perm_c"], tb["perm_u"]
    for j2 in range(kk):
        for z in range(ssc):
            r = z * kk + j2                  # z-major u_d row
            flat = j2 * ssc + z              # node-major intact idx
            col = _col_of(flat)
            if col is not None:
                p_self[r, col] = 1.0
            a1z[r, 0] = a1[flat]
            colp = _col_of(perm[flat])
            if colp is not None and a2[flat]:
                p_a[r, colp] = 1.0
            a2z[r, 0] = a2[flat]
    p_c = np.zeros((R_out, R_in), dtype=np.float32)
    p_su = np.zeros((R_out, R_out), dtype=np.float32)
    p_u = np.zeros((R_out, R_out), dtype=np.float32)
    b1c = np.zeros((R_out, 1), dtype=np.uint8)
    b2c = np.zeros((R_out, 1), dtype=np.uint8)
    b3c = np.zeros((R_out, 1), dtype=np.uint8)
    for i in range(m):
        for z in range(ssc):
            r = i * ssc + z                  # node-major parity row
            b1c[r, 0], b2c[r, 0], b3c[r, 0] = (b1[r], b2[r], b3[r])
            colc = _col_of(perm_c[r])
            if colc is not None and b1[r]:
                p_c[r, colc] = 1.0
            p_su[r, z * m + i] = 1.0         # u_p rows are z-major
            i2, z2 = divmod(int(perm_u[r]), ssc)
            p_u[r, z2 * m + i2] = 1.0
    bmat = _permute_bitmatrix(
        np.asarray(tb["dmat"], dtype=np.uint8)).astype(np.float32)

    def _vartabs(coef: np.ndarray):
        """(bits tuple, stacked [P, rows] table array) for a varying
        constant multiply — stacked so the planes ride ONE kernel
        input ref instead of captured constants."""
        tabs = _varmul_tables(coef.reshape(-1, 1))
        if not tabs:
            return (), np.zeros((coef.size, 1), dtype=np.int32)
        bits = tuple(b for b, _ in tabs)
        # [rows, P] int32: slicing one plane keeps both dims (Mosaic
        # cannot insert a minor dim on sub-32-bit types) and the
        # whole select/xor chain runs in 32-bit lanes
        stacked = np.stack([t.reshape(-1) for _, t in tabs],
                           axis=1).astype(np.int32)
        return bits, stacked

    bits_a1, tab_a1 = _vartabs(a1z)
    bits_a2, tab_a2 = _vartabs(a2z)
    bits_b1, tab_b1 = _vartabs(b1c)
    bits_b2, tab_b2 = _vartabs(b2c)
    bits_b3, tab_b3 = _vartabs(b3c)

    def _vm(x, tab_ref, bits):
        """x int32 [rows, T]; tab_ref [rows, P] int32."""
        y = None
        for pi, b in enumerate(bits):
            t = tab_ref[:, pi:pi + 1]         # [rows, 1] int32
            term = jnp.where((x >> b) & 1 == 1, t, 0)
            y = term if y is None else y ^ term
        return jnp.zeros_like(x) if y is None else y

    def kernel(c_ref, ps_ref, pa_ref, pc_ref, psu_ref, pu_ref,
               bm_ref, ta1_ref, ta2_ref, tb1_ref, tb2_ref, tb3_ref,
               out_ref):
        c = c_ref[:]                          # [R_in, T] uint8
        # Mosaic has no direct u8<->bf16 casts: hop through int32;
        # every intermediate stays 32-bit until the final store
        cf = c.astype(jnp.int32).astype(jnp.bfloat16)
        route = lambda p_ref: jax.lax.dot_general(
            p_ref[:].astype(jnp.bfloat16), cf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        c_self = route(ps_ref)                # [R_ud, T] int32
        c_pair = route(pa_ref)
        u_d = _vm(c_self, ta1_ref, bits_a1) ^ \
            _vm(c_pair, ta2_ref, bits_a2)
        # plane-wise MDS over contiguous z-major row groups
        ups = []
        w = jnp.left_shift(
            1, jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0))
        for z in range(ssc):
            grp = u_d[z * kk:(z + 1) * kk]    # int32
            parts = []
            for cbit in range(8):
                parts.append((grp >> cbit) & 1)
            bits = jnp.concatenate(parts, axis=0)   # [8kk, T]
            acc = jax.lax.dot_general(
                bm_ref[:].astype(jnp.bfloat16),
                bits.astype(jnp.bfloat16),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            bbits = acc.astype(jnp.int32) & 1       # [8m, T]
            rows = []
            for i in range(m):
                bb = bbits[8 * i:8 * i + 8]
                rows.append(jnp.sum(bb * w, axis=0, keepdims=True))
            ups.append(jnp.concatenate(rows, axis=0))
        u_p = jnp.concatenate(ups, axis=0)    # int32 rows
        upf = u_p.astype(jnp.bfloat16)
        routeu = lambda p_ref: jax.lax.dot_general(
            p_ref[:].astype(jnp.bfloat16), upf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        cpart = jax.lax.dot_general(
            pc_ref[:].astype(jnp.bfloat16), cf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        out = _vm(cpart, tb1_ref, bits_b1) ^ \
            _vm(routeu(psu_ref), tb2_ref, bits_b2) ^ \
            _vm(routeu(pu_ref), tb3_ref, bits_b3)
        out_ref[:] = out.astype(jnp.uint8)

    consts = [jnp.asarray(p_self), jnp.asarray(p_a),
              jnp.asarray(p_c), jnp.asarray(p_su), jnp.asarray(p_u),
              jnp.asarray(bmat), jnp.asarray(tab_a1),
              jnp.asarray(tab_a2), jnp.asarray(tab_b1),
              jnp.asarray(tab_b2), jnp.asarray(tab_b3)]

    @functools.partial(jax.jit, static_argnames=("L",))
    def run_padded(cflat, L):
        grid = (L // tile,)
        whole = lambda shape: pl.BlockSpec(
            shape, lambda i: (0, 0), memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((R_in, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                whole(p_self.shape), whole(p_a.shape),
                whole(p_c.shape), whole(p_su.shape),
                whole(p_u.shape), whole(bmat.shape),
                whole(tab_a1.shape), whole(tab_a2.shape),
                whole(tab_b1.shape), whole(tab_b2.shape),
                whole(tab_b3.shape),
            ],
            out_specs=pl.BlockSpec((R_out, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((R_out, L), jnp.uint8),
            interpret=jax.default_backend() == "cpu",
        )(cflat, *consts)

    def encode(c_data):
        c_data = jnp.asarray(c_data, dtype=jnp.uint8)
        L = c_data.shape[-1]
        lb = tile
        while lb < L:
            lb <<= 1
        flat = c_data.reshape(R_in, L)
        if lb != L:
            flat = jnp.pad(flat, ((0, 0), (0, lb - L)))
        out = run_padded(flat, lb)
        if lb != L:
            out = out[:, :L]
        return out.reshape(m, ssc, L)

    encode.tables = tb
    return encode


def build_decode_tables(codec, erased: frozenset[int]) -> dict:
    """Global (level-independent) slot tables + per-level masks for
    the layered DECODE chain (decode_layered,
    src/erasure-code/clay/ErasureCodeClay.cc:644-709).

    Key round-5 observation: the per-slot coefficient and partner
    assignments of build_transform's per-level tables are GEOMETRIC —
    fixed by (slot, erased signature), independent of the score level
    (the pairing (n,z)<->(nsw,zsw) is an involution; each slot is
    consistently the low or the high member of its pair, and the
    erased-set membership that picks the coefficient variant is
    static). Only WHICH slots update varies by level. So one set of
    global tables + one mask column per level expresses the whole
    multi-level chain — which is what lets the decode kernel unroll
    the levels inside a single pallas program with shared routing
    matrices. Overlap consistency is asserted while merging.
    """
    levels = trace_layered(codec, erased)
    coeffs = pft_coefficients(codec)
    qt = codec.q * codec.t
    ssc = codec.sub_chunk_no

    a1 = np.zeros((qt, ssc), dtype=np.uint8)
    a2 = np.zeros((qt, ssc), dtype=np.uint8)
    pn = np.tile(np.arange(qt, dtype=np.int32)[:, None], (1, ssc))
    pz = np.tile(np.arange(ssc, dtype=np.int32)[None, :], (qt, 1))
    b1 = np.zeros((qt, ssc), dtype=np.uint8)
    b2 = np.zeros((qt, ssc), dtype=np.uint8)
    b3 = np.zeros((qt, ssc), dtype=np.uint8)
    p2n = np.tile(np.arange(qt, dtype=np.int32)[:, None], (1, ssc))
    p2z = np.tile(np.arange(ssc, dtype=np.int32)[None, :], (qt, 1))
    seen_u = np.zeros((qt, ssc), dtype=bool)
    seen_c = np.zeros((qt, ssc), dtype=bool)
    masks_u, masks_c, level_planes = [], [], []

    def put_u(n, z, v1, v2, tn, tz):
        if seen_u[n, z]:
            assert (a1[n, z], a2[n, z], pn[n, z], pz[n, z]) == \
                (v1, v2, tn, tz), "level-dependent U slot"
        seen_u[n, z] = True
        a1[n, z], a2[n, z] = v1, v2
        pn[n, z], pz[n, z] = tn, tz

    def put_c(n, z, v1, v2, v3, tn, tz):
        if seen_c[n, z]:
            assert (b1[n, z], b2[n, z], b3[n, z], p2n[n, z],
                    p2z[n, z]) == (v1, v2, v3, tn, tz), \
                "level-dependent C slot"
        seen_c[n, z] = True
        b1[n, z], b2[n, z], b3[n, z] = v1, v2, v3
        p2n[n, z], p2z[n, z] = tn, tz

    for ops in levels:
        mu = np.zeros((qt, ssc), dtype=bool)
        mc = np.zeros((qt, ssc), dtype=bool)
        for n, z in ops.ident:
            put_u(n, z, 1, 0, n, z)
            mu[n, z] = True
        for v, lst in ops.pair_a.items():
            mm = coeffs[("a", v)]
            for nxy, z, nsw, zsw in lst:
                put_u(nxy, z, int(mm[0][0]), int(mm[0][1]), nsw, zsw)
                mu[nxy, z] = True
                put_u(nsw, zsw, int(mm[1][1]), int(mm[1][0]), nxy, z)
                mu[nsw, zsw] = True
        for n, z in ops.ident2:
            put_c(n, z, 0, 1, 0, n, z)
            mc[n, z] = True
        for v, lst in ops.type_c.items():
            mm = coeffs[("c", v)]
            for nxy, z, nsw, zsw in lst:
                put_c(nxy, z, int(mm[0][0]), int(mm[0][1]), 0,
                      nsw, zsw)
                mc[nxy, z] = True
        mb = coeffs[("b", 0)]
        for nxy, z, nsw, zsw in ops.pair_b:
            put_c(nxy, z, 0, int(mb[0][0]), int(mb[0][1]), nsw, zsw)
            mc[nxy, z] = True
            put_c(nsw, zsw, 0, int(mb[1][1]), int(mb[1][0]), nxy, z)
            mc[nsw, zsw] = True
        masks_u.append(mu)
        masks_c.append(mc)
        level_planes.append(list(ops.planes))
    return {
        "a1": a1, "a2": a2, "pn": pn, "pz": pz,
        "b1": b1, "b2": b2, "b3": b3, "p2n": p2n, "p2z": p2z,
        "masks_u": masks_u, "masks_c": masks_c,
        "planes": level_planes,
    }


def build_transform_kernel(codec, erased: frozenset[int],
                           tile: int = 256):
    """Round-5: the WHOLE multi-level layered decode chain in ONE
    Pallas kernel — the decode counterpart of ``build_encode_kernel``
    (matching decode_layered, ErasureCodeClay.cc:644-709). The dense
    linearized decode matrix is COMPUTE-bound at ~5% density (14.4
    GB/s for decode-2, BASELINE.md); this runs the sparse structure
    directly:

    - state lives Z-MAJOR, each plane's node group PADDED to
      P = ceil(qt/8)*8 rows (row z*P + n): every per-plane MDS slice
      is then a CONTIGUOUS, sublane-ALIGNED static slice of a VMEM
      scratch ref — scratch + aligned in-place stores are what let
      Mosaic REUSE buffers across the ssc-plane unroll (the
      value-SSA formulation stacked every unrolled plane's temps:
      20.7 MiB scoped vmem vs the 16 MiB budget, chip-measured);
    - the node-major -> z-major embedding runs outside as one XLA
      transpose (its in-kernel [R, R] routing matrix was the largest
      single constant);
    - the global pairwise-coupling tables of build_decode_tables make
      the per-level work a shared routing matmul (S_pair) + per-row
      VPU coefficient chains + a per-level mask select — levels
      unroll statically inside the kernel;
    - each level's plane-wise MDS decode is one [8e, 8P] bit-matmul
      per plane group (zero columns at erased/pad nodes), recovered
      rows stored 8-aligned into a rec scratch and scattered back by
      one small routing matmul;
    - phase 2 computes candidates only for the e*ssc ERASED rows
      (C writes always target erased slots) — small matmuls.

    All routing constants are bf16 (0/1 and byte values are exact).
    Returns ``[qt, ssc, L] uint8 (erased rows zero) ->
    [e, ssc, L] uint8`` recovered C for sorted(erased).
    ``erased`` must be the PADDED node-id set (|erased| == m the way
    _decode_layered pads it).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ceph_tpu.ops.gf_pallas import _permute_bitmatrix

    tb = build_decode_tables(codec, erased)
    q, t = codec.q, codec.t
    qt, ssc = q * t, codec.sub_chunk_no
    P = ((qt + 7) // 8) * 8            # plane group rows, 8-aligned
    Rp = ssc * P                       # padded z-major state rows
    er = sorted(erased)
    e = len(er)
    E8 = ((e + 7) // 8) * 8            # rec rows per plane, 8-aligned
    intact = [i for i in range(qt) if i not in erased]
    n_levels = len(tb["masks_u"])

    # MDS decode matrix widened to P columns (zeros at erased + pad)
    dmat_small = _mds_decode_matrix(codec, intact, er)   # [e, kk]
    dmat_full = np.zeros((e, P), dtype=np.uint8)
    for col, n in enumerate(intact):
        dmat_full[:, n] = dmat_small[:, col]
    dbmat = _permute_bitmatrix(dmat_full)                # [8e, 8P]

    def zr(n, z):                      # padded z-major state row
        return z * P + n

    a1, a2, pn, pz = tb["a1"], tb["a2"], tb["pn"], tb["pz"]
    s_pair = np.zeros((Rp, Rp), dtype=np.float32)
    a1z = np.zeros((Rp, 1), dtype=np.uint8)
    a2z = np.zeros((Rp, 1), dtype=np.uint8)
    for n in range(qt):
        for z in range(ssc):
            r = zr(n, z)
            a1z[r, 0], a2z[r, 0] = a1[n, z], a2[n, z]
            if a2[n, z]:
                s_pair[r, zr(pn[n, z], pz[n, z])] = 1.0
    # recovered-U scatter: rec row z*E8 + j -> U row zr(er[j], z)
    s_back = np.zeros((Rp, ssc * E8), dtype=np.float32)
    for z in range(ssc):
        for j in range(e):
            s_back[zr(er[j], z), z * E8 + j] = 1.0
    # phase-2 tables over the e*ssc erased rows (plane-major rc order,
    # padded to E8 rows per plane so the scatter matrix is shared)
    b1, b2, b3 = tb["b1"], tb["b2"], tb["b3"]
    p2n, p2z = tb["p2n"], tb["p2z"]
    Rrc = ssc * E8
    p2c = np.zeros((Rrc, Rp), dtype=np.float32)
    s2u = np.zeros((Rrc, Rp), dtype=np.float32)
    p2u = np.zeros((Rrc, Rp), dtype=np.float32)
    b1c = np.zeros((Rrc, 1), dtype=np.uint8)
    b2c = np.zeros((Rrc, 1), dtype=np.uint8)
    b3c = np.zeros((Rrc, 1), dtype=np.uint8)
    for z in range(ssc):
        for j, n in enumerate(er):
            r = z * E8 + j
            b1c[r, 0], b2c[r, 0], b3c[r, 0] = \
                b1[n, z], b2[n, z], b3[n, z]
            if b1[n, z]:
                p2c[r, zr(p2n[n, z], p2z[n, z])] = 1.0
            if b2[n, z]:
                s2u[r, zr(n, z)] = 1.0
            if b3[n, z]:
                p2u[r, zr(p2n[n, z], p2z[n, z])] = 1.0
    # output extraction: out row j*ssc + z (node-major) <- state row
    R_out = e * ssc
    s_out = np.zeros((R_out, Rp), dtype=np.float32)
    for j, n in enumerate(er):
        for z in range(ssc):
            s_out[j * ssc + z, zr(n, z)] = 1.0
    # per-level masks as stacked int32 columns
    mu_cols = np.zeros((Rp, n_levels), dtype=np.int32)
    mmds_cols = np.zeros((Rp, n_levels), dtype=np.int32)
    mc_cols = np.zeros((Rp, n_levels), dtype=np.int32)
    for li in range(n_levels):
        mu, mc = tb["masks_u"][li], tb["masks_c"][li]
        for n in range(qt):
            for z in range(ssc):
                if mu[n, z]:
                    mu_cols[zr(n, z), li] = 1
                if mc[n, z]:
                    mc_cols[zr(n, z), li] = 1
        for z in tb["planes"][li]:
            for n in er:
                mmds_cols[zr(n, z), li] = 1

    bits_a1, tab_a1 = _vartabs_of(a1z)
    bits_a2, tab_a2 = _vartabs_of(a2z)
    bits_b1, tab_b1 = _vartabs_of(b1c)
    bits_b2, tab_b2 = _vartabs_of(b2c)
    bits_b3, tab_b3 = _vartabs_of(b3c)

    def _vm(x, tab_ref, bits):
        y = None
        for pi, b in enumerate(bits):
            tt = tab_ref[:, pi:pi + 1]
            term = jnp.where((x >> b) & 1 == 1, tt, 0)
            y = term if y is None else y ^ term
        return jnp.zeros_like(x) if y is None else y

    def kernel(c_ref, pair_ref, back_ref, p2c_ref, s2u_ref,
               p2u_ref, sout_ref, bm_ref, mu_ref, mmds_ref, mc_ref,
               ta1_ref, ta2_ref, tb1_ref, tb2_ref, tb3_ref, out_ref,
               cz_ref, u_ref, rec_ref):
        route = lambda p_ref, xf: jax.lax.dot_general(
            p_ref[:], xf,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        cz_ref[:] = c_ref[:].astype(jnp.int32)   # z-major state
        u_ref[:] = jnp.zeros_like(u_ref)
        w = jnp.left_shift(
            1, jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0))
        for li in range(n_levels):
            cz = cz_ref[:]
            czf = cz.astype(jnp.bfloat16)
            cand_u = _vm(cz, ta1_ref, bits_a1) ^ \
                _vm(route(pair_ref, czf), ta2_ref, bits_a2)
            u_ref[:] = jnp.where(mu_ref[:, li:li + 1] == 1, cand_u,
                                 u_ref[:])
            # plane-wise MDS over aligned scratch slices: every
            # iteration reads/writes fixed scratch rows, so the
            # unroll reuses one iteration's buffers
            for z in range(ssc):
                grp = u_ref[z * P:(z + 1) * P, :]
                parts = [(grp >> cbit) & 1 for cbit in range(8)]
                bits = jnp.concatenate(parts, axis=0)   # [8P, T]
                acc = jax.lax.dot_general(
                    bm_ref[:], bits.astype(jnp.bfloat16),
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                bbits = acc.astype(jnp.int32) & 1       # [8e, T]
                rows = [jnp.sum(bbits[8 * j:8 * j + 8] * w, axis=0,
                                keepdims=True) for j in range(e)]
                rows.append(jnp.zeros((E8 - e, grp.shape[-1]),
                                      jnp.int32))
                rec_ref[z * E8:(z + 1) * E8, :] = \
                    jnp.concatenate(rows, axis=0)
            u_ref[:] = jnp.where(
                mmds_ref[:, li:li + 1] == 1,
                route(back_ref, rec_ref[:].astype(jnp.bfloat16)),
                u_ref[:])
            # phase 2: candidates for the erased rows only
            uf = u_ref[:].astype(jnp.bfloat16)
            czf = cz_ref[:].astype(jnp.bfloat16)
            cand_c = _vm(route(p2c_ref, czf), tb1_ref, bits_b1) ^ \
                _vm(route(s2u_ref, uf), tb2_ref, bits_b2) ^ \
                _vm(route(p2u_ref, uf), tb3_ref, bits_b3)
            cz_ref[:] = jnp.where(
                mc_ref[:, li:li + 1] == 1,
                route(back_ref, cand_c.astype(jnp.bfloat16)),
                cz_ref[:])
        out = route(sout_ref, cz_ref[:].astype(jnp.bfloat16))
        out_ref[:] = out.astype(jnp.uint8)

    bf = lambda m2: jnp.asarray(m2, dtype=jnp.bfloat16)
    consts = [bf(s_pair), bf(s_back), bf(p2c),
              bf(s2u), bf(p2u), bf(s_out),
              bf(dbmat), jnp.asarray(mu_cols),
              jnp.asarray(mmds_cols), jnp.asarray(mc_cols),
              jnp.asarray(tab_a1), jnp.asarray(tab_a2),
              jnp.asarray(tab_b1), jnp.asarray(tab_b2),
              jnp.asarray(tab_b3)]
    const_shapes = [s_pair.shape, s_back.shape,
                    p2c.shape, s2u.shape, p2u.shape, s_out.shape,
                    dbmat.shape, mu_cols.shape, mmds_cols.shape,
                    mc_cols.shape, tab_a1.shape, tab_a2.shape,
                    tab_b1.shape, tab_b2.shape, tab_b3.shape]

    @functools.partial(jax.jit, static_argnames=("L",))
    def run_padded(cflat, L):
        grid = (L // tile,)
        whole = lambda shape: pl.BlockSpec(
            shape, lambda i: tuple(0 for _ in shape),
            memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((Rp, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)] +
                     [whole(s) for s in const_shapes],
            out_specs=pl.BlockSpec((R_out, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((R_out, L), jnp.uint8),
            scratch_shapes=[
                pltpu.VMEM((Rp, tile), jnp.int32),      # cz
                pltpu.VMEM((Rp, tile), jnp.int32),      # u
                pltpu.VMEM((ssc * E8, tile), jnp.int32),  # rec
            ],
            compiler_params=_tpu_compiler_params(
                pltpu,
                # the default scoped-vmem budget (16 MiB) is below
                # this kernel's resident set (multi-level unroll +
                # ~8 MiB of routing constants); raise toward the
                # physical VMEM so Mosaic stops refusing the fit
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=jax.default_backend() == "cpu",
        )(cflat, *consts)

    def transform(c_full):
        c_full = jnp.asarray(c_full, dtype=jnp.uint8)
        L = c_full.shape[-1]
        lb = tile
        while lb < L:
            lb <<= 1
        # z-major embedding + P-row plane-group padding happen HERE
        # as one XLA transpose+pad (one extra HBM pass) instead of an
        # in-kernel [R, R] routing matmul
        flat = jnp.pad(c_full.transpose(1, 0, 2),
                       ((0, 0), (0, P - qt), (0, 0))).reshape(Rp, L)
        if lb != L:
            flat = jnp.pad(flat, ((0, 0), (0, lb - L)))
        out = run_padded(flat, lb)
        if lb != L:
            out = out[:, :L]
        return out.reshape(e, ssc, L)

    transform.erased = er
    return transform


def _vartabs_of(coef: np.ndarray):
    """(bits tuple, stacked [rows, P] int32 table) — the shared
    varying-constant-multiply decomposition (see build_encode_kernel's
    _vartabs)."""
    tabs = _varmul_tables(coef.reshape(-1, 1))
    if not tabs:
        return (), np.zeros((coef.size, 1), dtype=np.int32)
    bits = tuple(b for b, _ in tabs)
    stacked = np.stack([t.reshape(-1) for _, t in tabs],
                       axis=1).astype(np.int32)
    return bits, stacked


def build_decode_matvec(codec, mat: np.ndarray, label: str = "decode"):
    """Round-6: pick block-sparse vs dense for a linearized signature
    matrix, BY MEASUREMENT on the device (the r5 verdict's
    prescription: a structured path becomes the default only when it
    measurably beats the dense path on-device; dense stays the
    automatic fallback).

    The sparse candidate is the gather-of-blocks kernel
    (ops/gf_block_sparse): the decode-2 matrix is ~31% occupied at
    [16, 8] plane-block granularity after greedy row clustering — a
    3.3x MXU cost cut over the dense [128, 640] sweep (encode matrix
    5.3x). The plan's static cost model gates obviously-dense
    matrices; when it predicts a win, both paths run a short
    best-of-N sample on the chip and the faster one is kept.

    ``CEPH_TPU_CLAY_SPARSE``: ``never``/``0`` forces dense,
    ``always``/``1`` forces sparse (tests exercise the kernel in
    interpret mode this way), default measures (TPU only — interpret
    mode has no meaningful timing, so CPU stays dense).

    Returns ``fn(x [k, N] uint8) -> np [m, N] uint8`` with
    ``fn.path`` in {"sparse", "dense"} and ``fn.measured`` carrying
    the calibration numbers for bench/BASELINE reporting.
    """
    import os
    import time
    import zlib

    import jax

    from ceph_tpu.ops import gf_block_sparse, gf_jax
    from ceph_tpu.utils.device_telemetry import telemetry

    mat = np.asarray(mat, dtype=np.uint8)
    sig = (f"[{mat.shape[0]}x{mat.shape[1]}]"
           f"#{zlib.crc32(mat.tobytes()):08x}")

    def dense_fn(x):
        return np.asarray(jax.device_get(gf_jax.matvec_device(mat, x)))

    def sparse_fn(x):
        return np.asarray(jax.device_get(
            gf_block_sparse.matvec_device(mat, x)))

    def done(fn, path, measured=None):
        fn.path = path
        fn.measured = measured or {}
        if measured:
            # every decided outcome lands in telemetry, forced/skipped
            # ones included — BENCH rounds carry their own explanation
            telemetry().note_calibration(label, sig, path, measured)
        return fn

    mode = os.environ.get("CEPH_TPU_CLAY_SPARSE", "auto").lower()
    if mode in ("0", "never", "off"):
        return done(dense_fn, "dense")
    if mode in ("1", "always", "force"):
        return done(sparse_fn, "sparse")
    plan = gf_block_sparse.plan_blocks(mat)
    if not plan.worthwhile or jax.default_backend() != "tpu":
        return done(dense_fn, "dense",
                    {"cost_frac": plan.cost_frac, "skipped": True})

    import jax.numpy as jnp
    sample = jnp.zeros((mat.shape[1], 1 << 15), jnp.uint8)

    def best_of(fn, reps: int = 3) -> float:
        fn(sample)                       # warm / compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(sample)
            best = min(best, time.perf_counter() - t0)
        return best

    try:
        t_dense = best_of(dense_fn)
        t_sparse = best_of(sparse_fn)
    except Exception:
        # a sparse-path fault must never take decode down: dense is
        # the always-working fallback
        return done(dense_fn, "dense", {"calibration_failed": True})
    measured = {"cost_frac": round(plan.cost_frac, 4),
                "dense_s": round(t_dense, 6),
                "sparse_s": round(t_sparse, 6),
                "label": label}
    if t_sparse < t_dense:
        return done(sparse_fn, "sparse", measured)
    return done(dense_fn, "dense", measured)


class ClayDeviceCodec:
    """Per-codec cache of compiled layered transforms, keyed by the
    padded erased-node signature (bounded: C(k+m, m) signatures exist
    and each holds a compiled executable)."""

    def __init__(self, codec) -> None:
        from ceph_tpu.utils.lru import BoundedLRU
        self.codec = codec
        self._fns: BoundedLRU = BoundedLRU(64)

    def transform(self, erased: frozenset[int], c_in: np.ndarray):
        """c_in: [q*t, ssc, L] uint8 (numpy or device array); returns
        the completed node array (device)."""
        import time as _time

        import jax.numpy as jnp

        from ceph_tpu.utils.device_telemetry import telemetry

        def build():
            # a signature rebuilt after LRU eviction IS a recompile in
            # the bug-class sense: the cache bound is undersized for
            # the live signature set
            t0 = _time.perf_counter()
            fn = build_transform(self.codec, erased)
            telemetry().note_compile(
                f"clay_transform(k={self.codec.k},m={self.codec.m})"
                f"er={sorted(erased)}", _time.perf_counter() - t0)
            return fn

        fn = self._fns.get_or_build(erased, build)
        return fn(jnp.asarray(c_in))
