"""vstart — boot a dev cluster in one process (src/vstart.sh role).

    python -m ceph_tpu.tools.vstart [-n N_OSDS] [--store memstore|blockstore]
        [--data DIR] [--ec k,m] [--prometheus] [--mgr]

Boots one mon + N OSDs, creates a replicated pool ``rbd`` and (with
--ec) an EC pool ``ecpool``, prints the mon address + asok paths, and
runs until SIGINT. Drive it with the ``ceph``/``rados`` CLIs:

    python -m ceph_tpu.tools.ceph_cli -m <addr> status
    python -m ceph_tpu.tools.rados_cli -m <addr> -p rbd bench 5 write
"""

from __future__ import annotations

import argparse
import json
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("-n", "--n-osds", type=int, default=3)
    ap.add_argument("--store", default="memstore",
                    choices=("memstore", "blockstore", "kstore"))
    ap.add_argument("--data", default=None,
                    help="data dir (blockstore)")
    ap.add_argument("--ec", default=None, metavar="K,M",
                    help="also create EC pool 'ecpool' with k,m")
    ap.add_argument("--prometheus", action="store_true",
                    help="serve /metrics on an ephemeral port")
    ap.add_argument("--mgr", action="store_true",
                    help="also boot a mgr (balancer/progress/telemetry)")
    args = ap.parse_args(argv)

    from ceph_tpu.qa.cluster import MiniCluster

    cluster = MiniCluster(n_osds=args.n_osds, store=args.store,
                          data_dir=args.data).start()
    cluster.create_pool("rbd", pg_num=8, size=min(3, args.n_osds))
    if args.ec:
        k, m = (int(x) for x in args.ec.split(","))
        cluster.create_ec_pool("ecpool", k=k, m=m)
    info = {
        "mon_addr": cluster.mon_addr,
        "mon_asok": cluster.mon.asok.path,
        "osd_asoks": {i: o.asok.path for i, o in cluster.osds.items()},
        "pools": ["rbd"] + (["ecpool"] if args.ec else []),
    }
    if args.mgr:
        mgr = cluster.start_mgr()
        info["mgr_asok"] = mgr.asok.path
    if args.prometheus:
        from ceph_tpu.utils.prometheus import MetricsServer
        ms = MetricsServer()
        info["metrics_url"] = f"http://127.0.0.1:{ms.start()}/metrics"
    print(json.dumps(info, indent=2), flush=True)
    print("cluster up — ctrl-c to stop", file=sys.stderr, flush=True)

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        signal.pause()
    cluster.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
