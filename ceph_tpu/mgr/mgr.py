"""Mgr daemon — hosts the orchestration modules (src/mgr/ role).

The reference ceph-mgr is a C++ daemon that aggregates daemon state and
embeds a Python interpreter running the pybind/mgr modules; commands
reach modules via ``ceph <module> <cmd>`` forwarded through mon->mgr.
Here the Mgr holds a mon session (RadosClient), ticks each module on
its own cadence, and routes ``<module> <sub>`` commands arriving on its
admin socket (``ceph_tpu.tools.ceph_cli daemon <mgr.asok> balancer
status`` — the ``ceph tell mgr`` seam).
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.utils import profiler as _prof
from ceph_tpu.utils.admin_socket import (
    AdminSocket,
    register_common_commands,
)
from ceph_tpu.utils.dout import Dout
from ceph_tpu.utils.perf_counters import PerfCounters

log = Dout("mgr")

#: default module set (the reference's always-on + default-on
#: modules). ``tuner`` loads LAST so it can wire itself to the
#: health engine; it is a literal NOOP unless tuner_enabled /
#: CEPH_TPU_TUNER turns it on (ISSUE 13).
DEFAULT_MODULES = ("balancer", "progress", "telemetry",
                   "dashboard", "health", "trace", "tuner")


class Mgr:
    def __init__(self, mon_addr: str, name: str = "x",
                 modules: tuple[str, ...] = DEFAULT_MODULES,
                 asok_dir: str | None = None,
                 auth: tuple[str, bytes] | None = None) -> None:
        self.name = name
        self.mon_addr = mon_addr
        self.rados = RadosClient(mon_addr, name=f"mgr.{name}", auth=auth)
        self.modules: dict[str, object] = {}
        self._module_names = modules
        self.logger = PerfCounters(f"mgr.{name}")
        self.logger.add_u64_counter("tick_rounds")
        self.logger.add_u64_counter("module_errors")
        self.asok = AdminSocket(f"mgr.{name}", directory=asok_dir)
        self._stop = threading.Event()
        self._tick_thread: threading.Thread | None = None
        self._status_cache: tuple[float, dict] = (0.0, {})

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Mgr":
        self.rados.connect()
        for mod_name in self._module_names:
            self.modules[mod_name] = self._load_module(mod_name)
        register_common_commands(self.asok, self.logger)
        for mod_name, mod in self.modules.items():
            for sub in getattr(mod, "COMMANDS", ("status",)):
                self.asok.register_command(
                    f"{mod_name} {sub}",
                    lambda args, m=mod, s=sub: self._asok_module(
                        m, s, args),
                    f"{mod_name} module: {sub}")
        self.asok.start()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"mgr.{self.name}-tick",
            daemon=True)
        self._tick_thread.start()
        log(1, f"mgr.{self.name} up (modules: "
            f"{', '.join(self.modules)})")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        for name, mod in self.modules.items():
            try:
                mod.shutdown()
            except Exception as exc:
                log(1, f"mgr module {name} shutdown failed: {exc!r}")
        self.asok.stop()
        self.rados.shutdown()

    def _load_module(self, name: str):
        import importlib
        pymod = importlib.import_module(f"ceph_tpu.mgr.{name}")
        return pymod.Module(self)

    # -- state accessors (what mgr_module.MgrModule calls) -------------

    def get_osdmap(self):
        return self.rados.monc.osdmap

    def get_status(self, max_age: float = 0.5) -> dict:
        """Mon status JSON, briefly cached (several modules sample it
        on close ticks)."""
        import json
        now = time.time()
        ts, cached = self._status_cache
        if now - ts < max_age:
            return cached
        code, _, data = self.mon_command(prefix="status")
        status = json.loads(data) if code == 0 and data else {}
        self._status_cache = (now, status)
        return status

    def mon_command(self, **cmd) -> tuple[int, str, bytes]:
        return self.rados.mon_command(cmd)

    # -- plumbing ------------------------------------------------------

    def _tick_loop(self) -> None:
        last: dict[str, float] = {}
        while not self._stop.wait(0.25):
            now = time.time()
            for name, mod in self.modules.items():
                period = getattr(mod, "TICK_PERIOD", 0.0)
                if period <= 0 or now - last.get(name, 0.0) < period:
                    continue
                last[name] = now
                _pstage = _prof.push_stage("mgr_tick")
                try:
                    mod.tick()
                except Exception as exc:
                    self.logger.inc("module_errors")
                    log(1, f"mgr module {name} tick failed: {exc!r}")
                finally:
                    _prof.pop_stage(_pstage)
            self.logger.inc("tick_rounds")

    def _asok_module(self, mod, sub: str, args: dict) -> dict:
        cmd = dict(args)
        cmd["prefix"] = sub
        code, msg, data = mod.handle_command(cmd)
        out: dict = {"code": code, "status": msg}
        if data:
            import json
            try:
                out["data"] = json.loads(data)
            except ValueError:
                out["data"] = data.decode(errors="replace")
        return out
