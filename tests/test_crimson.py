"""Crimson (shard-per-core, run-to-completion OSD) — ISSUE 18.

Two surfaces under test. The single-OSD flat path (the round-4
prototype's scenarios: boot + maps + beacons + replicated object
service) and the MAINLINE data path: a stock client against a crimson
MiniCluster serving EC pools through the real ECBackend — byte-
identical to the threaded OSD, per-PG ordered under concurrent
multi-connection load, zero lost acked writes under the msgr fault
family, and run-to-completion telemetry (no ``wq_continuation``
hops, ~one wakeup per reply frame).
"""

import asyncio
import concurrent.futures
import time

import pytest

from ceph_tpu.crimson import CrimsonOSD
from ceph_tpu.client.rados import RadosClient, RadosError
from ceph_tpu.parallel.mon import Monitor
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils.config import g_conf
from ceph_tpu.utils.dispatch_telemetry import telemetry


def _wait_up(mon, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if any(i.up for i in mon.osdmap.osds.values()):
            return
        time.sleep(0.05)
    raise TimeoutError("no OSD came up")


@pytest.fixture
def setup():
    mon = Monitor("a")
    mon_addr = mon.start()
    osd = CrimsonOSD(0, mon_addr)
    osd.start()
    yield mon, osd, mon_addr
    osd.stop()
    mon.stop()


# -- flat path (single reactor-sharded OSD, replicated pools) ----------

def test_crimson_osd_serves_stock_client(setup):
    mon, osd, mon_addr = setup
    _wait_up(mon)
    client = RadosClient(mon_addr).connect()
    try:
        code, outs, _ = client.mon_command(
            {"prefix": "osd pool create", "pool": "cr", "pg_num": "4",
             "size": "1"})
        assert code == 0, outs
        io = client.open_ioctx("cr")
        io.write_full("o", b"reactor" * 100)
        assert io.read("o") == b"reactor" * 100
        io.append("o", b"!")
        assert io.read("o") == b"reactor" * 100 + b"!"
        assert io.stat("o") == 701
        io.remove("o")
        with pytest.raises(RadosError):
            io.read("o")
    finally:
        client.shutdown()


def test_shared_nothing_sharding_and_parallel_pgs(setup):
    """PGs are statically placed on reactors (pg_to_shard role): every
    PG's data lives on exactly ONE reactor's store, multiple reactors
    carry load, and a stock client sees one coherent OSD."""
    mon, osd, mon_addr = setup
    _wait_up(mon)
    client = RadosClient(mon_addr).connect()
    try:
        code, outs, _ = client.mon_command(
            {"prefix": "osd pool create", "pool": "shards",
             "pg_num": "16", "size": "1"})
        assert code == 0, outs
        io = client.open_ioctx("shards")
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            list(pool.map(
                lambda i: io.write_full(f"obj{i}",
                                        b"s" * 512 + bytes([i])),
                range(48)))
        for i in range(48):
            assert io.read(f"obj{i}") == b"s" * 512 + bytes([i])
        stats = osd.shard_stats()
        assert len(stats) == osd.smp and osd.smp >= 2
        assert sum(1 for s in stats if s["ops"] > 0) >= 2, stats
        # shared-nothing: every PG collection exists on exactly one
        # reactor's store, and placement agrees with shard_of
        seen = []
        for r in osd.reactors:
            for cid in r.store.list_collections():
                seen.append(cid)
                pool_ps = cid.split("_", 1)[1].split("s")[0]
                pgid = tuple(int(x) for x in pool_ps.split("."))
                assert osd.shard_of(pgid) is r, (cid, r.idx)
        assert len(seen) == len(set(seen)), (
            "a PG's state exists on two reactors", seen)
        total = sum(len(r.store.list_objects(cid))
                    for r in osd.reactors
                    for cid in r.store.list_collections())
        assert total == 48
    finally:
        client.shutdown()


def test_per_pg_sequencer_orders_ops(setup):
    """Ops on ONE PG apply in arrival order even though handlers are
    coroutines (OrderedExclusivePhase role): concurrent appends from
    many client threads never lose bytes or interleave."""
    mon, osd, mon_addr = setup
    _wait_up(mon)
    client = RadosClient(mon_addr).connect()
    try:
        code, outs, _ = client.mon_command(
            {"prefix": "osd pool create", "pool": "seq",
             "pg_num": "1", "size": "1"})
        assert code == 0, outs
        io = client.open_ioctx("seq")
        io.write_full("log", b"")
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            list(pool.map(
                lambda i: io.append("log", bytes([i]) * 7),
                range(40)))
        data = io.read("log")
        assert len(data) == 40 * 7
        # no interleaving: the stream is 40 uniform 7-byte runs
        for off in range(0, len(data), 7):
            run = data[off:off + 7]
            assert run == run[:1] * 7, (off, run)
        io.setxattr("log", "who", b"crimson")
        assert io.getxattr("log", "who") == b"crimson"
    finally:
        client.shutdown()


def test_crimson_pgls_lists_every_pg(setup):
    """OSD_OP_LIST carries an explicit ps with an empty oid: crimson
    must route it by msg.ps (mapping "" through crush would fold all
    listings onto one PG and lose objects)."""
    mon, osd, mon_addr = setup
    _wait_up(mon)
    client = RadosClient(mon_addr).connect()
    try:
        code, outs, _ = client.mon_command(
            {"prefix": "osd pool create", "pool": "ls",
             "pg_num": "8", "size": "1"})
        assert code == 0, outs
        io = client.open_ioctx("ls")
        for i in range(24):
            io.write_full(f"k{i}", b"v")
        assert io.list_objects() == sorted(f"k{i}" for i in range(24))
    finally:
        client.shutdown()


# -- the beacon seam (satellite: injectable clock/interval) ------------

def test_beacon_loop_injectable_seam():
    """The beacon loop resolves its interval through the injectable
    seam every lap and sleeps through the injected sleeper — a test
    observes N beacons without ANY wall-clock heartbeat waits."""
    mon = Monitor("a")
    mon_addr = mon.start()
    laps = []

    async def fake_sleep(interval):
        laps.append(interval)
        if len(laps) >= 5:
            await asyncio.Event().wait()     # park forever
        await asyncio.sleep(0)

    osd = CrimsonOSD(0, mon_addr, beacon_interval=0.125,
                     beacon_sleep=fake_sleep)
    try:
        osd.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and osd.beacons_sent < 4:
            time.sleep(0.01)
        assert osd.beacons_sent >= 4
        # every lap read the injected interval, not the config Option
        assert laps[:4] == [0.125] * 4
        assert mon.osdmap.osds[0].up
    finally:
        osd.stop()
        mon.stop()


# -- the mainline EC data path on a crimson cluster --------------------

def test_stock_client_ec_roundtrip_on_crimson_cluster():
    """A stock objecter speaks to a 3-OSD crimson cluster serving an
    EC pool through the mainline ECBackend: full op surface, then
    wait_for_clean (eager PG instantiation on map updates)."""
    with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
        cluster.create_ec_pool("ec", k=2, m=1, pg_num=8)
        io = cluster.client().open_ioctx("ec")
        io.op_timeout = 30.0
        payload = b"crimson-ec" * 500
        io.write_full("obj", payload)
        assert io.read("obj") == payload
        io.append("obj", b"tail")
        assert io.read("obj") == payload + b"tail"
        assert io.stat("obj") == len(payload) + 4
        io.setxattr("obj", "k", b"v")
        assert io.getxattr("obj", "k") == b"v"
        for i in range(12):
            io.write_full(f"m{i}", bytes([i]) * 333)
        for i in range(12):
            assert io.read(f"m{i}") == bytes([i]) * 333
        assert set(io.list_objects()) >= {f"m{i}" for i in range(12)}
        io.remove("obj")
        with pytest.raises(RadosError):
            io.read("obj")
        cluster.wait_for_clean(timeout=15)


def test_byte_identical_readback_vs_threaded():
    """Wire compatibility pin: the SAME op sequence against a
    threaded and a crimson cluster reads back byte-identical — a
    client cannot tell which flavor answered."""
    def drive(flavor):
        out = {}
        with MiniCluster(n_osds=3, osd_flavor=flavor) as cluster:
            cluster.create_ec_pool("ab", k=2, m=1, pg_num=4)
            io = cluster.client().open_ioctx("ab")
            io.op_timeout = 30.0
            for i in range(6):
                io.write_full(f"o{i}", bytes([0x40 + i]) * (1000 + i))
            io.append("o0", b"-suffix")
            io.write_full("o1", b"overwritten")
            io.setxattr("o2", "tag", b"ab")
            for i in range(6):
                out[f"o{i}"] = io.read(f"o{i}")
            out["stat_o0"] = io.stat("o0")
            out["xattr_o2"] = io.getxattr("o2", "tag")
            out["ls"] = io.list_objects()
        return out

    assert drive("threaded") == drive("crimson")


def test_per_pg_ordering_under_concurrent_connections():
    """Satellite: the per-PG ordering property under concurrent
    MULTI-CONNECTION load. Several independent client connections
    hammer one PG (pg_num=1) with appends; the sequencer must keep
    every append atomic (uniform runs) and each connection's own ops
    in issue order, across coroutine await points."""
    with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
        cluster.create_ec_pool("ord", k=2, m=1, pg_num=1)
        setup_io = cluster.client().open_ioctx("ord")
        setup_io.op_timeout = 30.0
        setup_io.write_full("log", b"")
        n_conns, per_conn = 4, 6

        def hammer(c):
            client = cluster.client()
            io = client.open_ioctx("ord")
            io.op_timeout = 30.0
            for s in range(per_conn):
                io.append("log", bytes([16 * c + s]) * 5)
            client.shutdown()

        with concurrent.futures.ThreadPoolExecutor(n_conns) as pool:
            list(pool.map(hammer, range(n_conns)))
        data = setup_io.read("log")
        assert len(data) == n_conns * per_conn * 5
        runs = []
        for off in range(0, len(data), 5):
            run = data[off:off + 5]
            assert run == run[:1] * 5, (off, run)   # atomic append
            runs.append(run[0])
        # per-connection issue order is preserved in the object
        for c in range(n_conns):
            seq = [b % 16 for b in runs if b // 16 == c]
            assert seq == sorted(seq), (c, seq)
            assert len(seq) == per_conn


def test_dropped_frames_zero_lost_acked_writes():
    """Satellite: the msgr fault family against crimson. Client op
    AND reply frames (singleton + batch) are dropped mid-burst; the
    objecter resend ladder re-drives them, crimson's dup-op cache
    answers resends of already-applied writes without double-apply —
    zero lost acked writes, every read byte-exact."""
    from ceph_tpu.parallel import messages as M
    conf = g_conf()
    old_resend = conf["objecter_resend_interval"]
    conf.set("objecter_resend_interval", 0.3)
    try:
        with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
            reg = cluster.faults
            reg.reseed(11)
            cluster.create_ec_pool("dz", k=2, m=1, pg_num=4,
                                   backend="jax")
            io = cluster.client().open_ioctx("dz")
            io.op_timeout = 60.0
            payload_of = (lambda i: bytes(((i * 13 + j) & 0xFF)
                                          for j in range(4096)))
            io.write_full("warm", b"w")     # admission warm-up
            rules = [
                reg.add("msgr_drop", entity="client.*",
                        msg_type=M.MOSDOp.MSG_TYPE,
                        every=4, max_fires=3),
                reg.add("msgr_drop", entity="client.*",
                        msg_type=M.MOSDOpBatch.MSG_TYPE,
                        every=3, max_fires=3),
                reg.add("msgr_drop", entity="osd.*",
                        msg_type=M.MOSDOpReplyBatch.MSG_TYPE,
                        every=5, max_fires=2),
            ]
            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                list(pool.map(
                    lambda i: io.write_full(f"s{i}", payload_of(i)),
                    range(24)))
            for r in rules:
                r.remove()
            assert sum(r.fires for r in rules) >= 1
            for i in range(24):
                assert io.read(f"s{i}") == payload_of(i), \
                    f"s{i} lost or wrong"
    finally:
        conf.set("objecter_resend_interval", old_resend)


def test_rtc_telemetry_no_continuation_hops_single_wakeups():
    """The run-to-completion acceptance shape, as counters: a crimson
    write burst crosses ZERO ``wq_continuation`` hops (continuations
    resume inline on the owning reactor), every op's chain crosses
    the ``reactor_submit`` seam, and reply frames wake ~one client
    thread each (the batched-ack rule)."""
    with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
        cluster.create_ec_pool("tl", k=2, m=1, pg_num=4,
                               backend="jax")
        io = cluster.client().open_ioctx("tl")
        io.op_timeout = 30.0
        io.write_full("warm", b"w" * 1024)
        telemetry().reset()
        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            list(pool.map(
                lambda i: io.write_full(f"b{i}", b"x" * 8192),
                range(16)))
        for i in range(16):
            assert io.read(f"b{i}") == b"x" * 8192
        snap = telemetry().snapshot()
        c = snap["counters"]
        assert c["ophop_wq_continuation"] == 0, c
        assert c["ophop_wq_op"] == 0, c
        assert c["ophop_reactor_submit"] >= 32, c
        assert c["op_chains"] >= 32
        wf = snap["wakeups"]["wakeups_per_frame"]
        assert wf <= 1.05, snap["wakeups"]


def test_crimson_kill_revive_preserves_shard_data():
    """A revived crimson OSD gets its per-shard stores back (the
    threaded MiniCluster's store-cache rule): acked writes survive a
    kill/revive of any OSD with no recovery machinery in play."""
    with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
        cluster.create_ec_pool("kr", k=2, m=1, pg_num=4)
        io = cluster.client().open_ioctx("kr")
        io.op_timeout = 30.0
        for i in range(8):
            io.write_full(f"d{i}", bytes([i]) * 2048)
        victim = max(cluster.osds)
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=30)
        cluster.revive_osd(victim)
        cluster.wait_for_osds_up(timeout=15)
        for i in range(8):
            assert io.read(f"d{i}") == bytes([i]) * 2048


def test_crimson_multi_tenant_burst_attributes_flows():
    """ISSUE 20 satellite: crimson installs the flow context on its
    INLINE continuation path (no cross-thread queue to capture
    across), so a multi-tenant burst attributes per-tenant ops, bytes
    and store-txn costs with >=95% coverage — witness-armed, since
    the attribution seams run inside the reactors' submit halves and
    must not add a blocking edge the lock discipline forbids."""
    import json

    from ceph_tpu.analysis import lock_witness as lw
    from ceph_tpu.utils import flow_telemetry as ft

    env_armed = lw.env_enabled()
    if not env_armed:
        lw.enable()
    try:
        with MiniCluster(n_osds=3, osd_flavor="crimson") as cluster:
            cluster.create_ec_pool("mt", k=2, m=1, pg_num=4,
                                   backend="jax")
            client = cluster.client()
            warm = client.open_ioctx("mt")
            warm.op_timeout = 30.0
            warm.set_flow("warmup")
            warm.write_full("warm", b"w" * 1024)
            tel = ft.telemetry_if_exists()
            assert tel is not None, \
                "a tagged write must materialize the flows registry"
            tel.reset()
            tenants = ("acme", "globex", "initech")
            ios = []
            for t in tenants:
                tio = client.open_ioctx("mt")
                tio.op_timeout = 30.0
                tio.set_flow(t)
                ios.append(tio)

            def burst(i):
                tio = ios[i % len(ios)]
                tio.write_full(f"{tenants[i % 3]}_{i}", b"x" * 4096)
                assert tio.read(f"{tenants[i % 3]}_{i}") \
                    == b"x" * 4096

            with concurrent.futures.ThreadPoolExecutor(4) as pool:
                list(pool.map(burst, range(18)))

            tel = ft.telemetry()
            table = tel.flow_table()["flows"]
            for t in tenants:
                row = table.get(t)
                assert row is not None, (t, sorted(table))
                # each tenant: 6 writes + 6 reads attributed, bytes
                # both directions, and its EC sub-writes' store txn
                # bytes charged back to it on the serving reactors
                assert row["ops"] >= 12, (t, row)
                assert row["bytes_in"] >= 6 * 4096, (t, row)
                assert row["bytes_out"] >= 6 * 4096, (t, row)
                assert row["store_txn_bytes"] > 0, (t, row)
            att = tel.attribution()
            assert att["ops_pct"] >= 95.0, att
            assert att["bytes_pct"] >= 95.0, att
    finally:
        if not env_armed:
            rep = lw.report()
            bad = lw.unacknowledged(rep)
            lw.disable()
            lw.reset()
            assert not bad, (
                "unacknowledged witness findings on the multi-tenant "
                "crimson burst: " + json.dumps(bad, indent=1)[:2000])
