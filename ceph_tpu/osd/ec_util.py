"""Stripe math + batched encode/decode — the ECUtil role, TPU-batched.

Reference: src/osd/ECUtil.{h,cc}. ``stripe_info_t`` (ECUtil.h:27-80) maps
logical object offsets to stripes and chunk offsets; ``ECUtil::encode``
loops ``ec_impl->encode`` once per stripe_width window (ECUtil.cc:120-159).

The TPU translation (SURVEY.md §5 "stripe batch = leading vmap dim"): the
per-stripe loop disappears. For matrix codecs the position-wise math lets S
stripes fold into one [k, S*chunk_size] kernel call — one launch for a
whole append batch instead of S launches; the generic fallback loops for
codecs with cross-position structure (Clay).

``HashInfo`` is the cumulative per-shard crc xattr (ECUtil.h:101-162,
append logic ECUtil.cc:161-177, stored under the hinfo key :235): every
shard append folds the new chunk bytes into a running crc32c so scrub can
verify a shard without reading its peers.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.models.interface import ErasureCodeError
from ceph_tpu.utils import checksum

#: initial per-shard crc seed (the reference seeds with -1, ECUtil.h:117)
HINFO_SEED = 0xFFFFFFFF


@dataclass(frozen=True)
class StripeInfo:
    """stripe_width/chunk offset algebra (stripe_info_t, ECUtil.h:27-80)."""

    stripe_width: int   # k * chunk_size bytes of logical data per stripe
    chunk_size: int     # bytes per chunk per stripe

    def __post_init__(self):
        if self.stripe_width % self.chunk_size:
            raise ValueError(
                f"stripe_width {self.stripe_width} not a multiple of "
                f"chunk_size {self.chunk_size}")

    @property
    def k(self) -> int:
        return self.stripe_width // self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int,
                                    length: int) -> tuple[int, int]:
        """Expand [offset, offset+length) to stripe-aligned bounds
        (ECUtil.h:72-79)."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start


def encode(sinfo: StripeInfo, codec, data: bytes | np.ndarray,
           want: list[int] | None = None) -> dict[int, np.ndarray]:
    """Encode a stripe-aligned logical extent into per-shard buffers.

    data length must be a multiple of stripe_width; the result maps shard
    id -> concatenated chunk bytes across all S stripes (what each shard
    OSD stores contiguously). Matrix codecs encode all S stripes in ONE
    kernel call; others loop (ECUtil.cc:136-148 semantics).
    """
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8, copy=False).ravel()
    sw, cs = sinfo.stripe_width, sinfo.chunk_size
    if len(buf) % sw:
        raise ErasureCodeError(
            f"encode: length {len(buf)} not a multiple of stripe_width {sw}")
    s = len(buf) // sw
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    assert sw == k * cs, (sw, k, cs)
    want = list(range(n)) if want is None else list(want)
    # [S, k, cs] -> per-shard contiguous [S*cs]
    stripes = buf.reshape(s, k, cs)
    data_shards = stripes.transpose(1, 0, 2).reshape(k, s * cs)
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    out: dict[int, np.ndarray] = {}
    if isinstance(codec, MatrixErasureCode) and not codec.chunk_mapping:
        # position-wise codec: stripes fold into the byte axis
        parity = codec._matvec(codec.coding_matrix, data_shards)
        for i in want:
            out[i] = data_shards[i] if i < k else parity[i - k]
    else:
        per_stripe = [codec.encode_chunks(
            want, {j: stripes[si, j] for j in range(k)}) for si in range(s)]
        for i in want:
            if i < k:
                out[i] = data_shards[i]
            else:
                out[i] = np.concatenate([per_stripe[si][i] for si in range(s)])
    return out


def decode(sinfo: StripeInfo, codec, shards: dict[int, np.ndarray],
           want: list[int]) -> dict[int, np.ndarray]:
    """Reconstruct wanted shards from surviving per-shard buffers
    (ECUtil.cc:47-118). Shard buffers hold S concatenated chunks."""
    some = next(iter(shards.values()))
    cs = sinfo.chunk_size
    if len(some) % cs:
        raise ErasureCodeError(
            f"decode: shard length {len(some)} not a multiple of {cs}")
    s = len(some) // cs
    missing = [i for i in want if i not in shards]
    if not missing:
        return {i: np.asarray(shards[i], dtype=np.uint8) for i in want}
    from ceph_tpu.models.matrix_codec import MatrixErasureCode
    if isinstance(codec, MatrixErasureCode) and not codec.chunk_mapping:
        # one kernel call across all stripes
        return codec.decode_chunks(
            want, {i: np.asarray(v, dtype=np.uint8)
                   for i, v in shards.items()})
    out = {i: np.zeros(s * cs, dtype=np.uint8) for i in want}
    for si in range(s):
        got = codec.decode_chunks(
            want, {i: np.asarray(v[si * cs:(si + 1) * cs], dtype=np.uint8)
                   for i, v in shards.items()})
        for i in want:
            out[i][si * cs:(si + 1) * cs] = got[i]
    return out


class HashInfo:
    """Cumulative per-shard crc32c (ECUtil.h:101-162).

    Updated on every append; serialized as a shard xattr so
    handle_sub_read can verify a shard against it (ECBackend.cc:1032-1051).
    """

    def __init__(self, num_chunks: int) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [HINFO_SEED] * num_chunks

    def append(self, old_size: int, shard_chunks: dict[int, np.ndarray]):
        """Fold an append at chunk-offset ``old_size`` into the crcs
        (ECUtil.cc:161-177: appends must be contiguous)."""
        if old_size != self.total_chunk_size:
            raise ValueError(
                f"hinfo append at {old_size} != current size "
                f"{self.total_chunk_size} (appends must be contiguous)")
        sizes = {len(v) for v in shard_chunks.values()}
        if len(sizes) != 1:
            raise ValueError("hinfo append: unequal shard chunk sizes")
        for shard, data in shard_chunks.items():
            self.cumulative_shard_hashes[shard] = checksum.crc32c(
                data, self.cumulative_shard_hashes[shard])
        self.total_chunk_size += sizes.pop()

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def to_dict(self) -> dict:
        return {"total_chunk_size": self.total_chunk_size,
                "hashes": list(self.cumulative_shard_hashes)}

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        hi = cls(len(d["hashes"]))
        hi.total_chunk_size = d["total_chunk_size"]
        hi.cumulative_shard_hashes = list(d["hashes"])
        return hi


class StripeBatcher:
    """Device-side stripe batch accumulator (SURVEY.md §7.5, the novel
    piece): coalesce many small sub-writes into one kernel launch.

    Appends are queued host-side; ``flush()`` encodes everything queued in
    a single batched call and returns per-op shard buffers in submission
    order (commit order is preserved — the pipeline-ordering invariant of
    ECBackend::check_ops, ECBackend.cc:2107). Size-triggered auto-flush;
    the OSD write pipeline calls flush() at commit points.
    """

    def __init__(self, sinfo: StripeInfo, codec,
                 flush_bytes: int = 8 << 20) -> None:
        self.sinfo = sinfo
        self.codec = codec
        self.flush_bytes = flush_bytes
        self._pending: list[tuple[object, np.ndarray]] = []
        self._pending_bytes = 0

    def append(self, op_id, data: bytes | np.ndarray) -> None:
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data
        if len(buf) % self.sinfo.stripe_width:
            raise ErasureCodeError(
                f"append: {len(buf)} bytes not stripe-aligned")
        self._pending.append((op_id, buf))
        self._pending_bytes += len(buf)

    def should_flush(self) -> bool:
        return self._pending_bytes >= self.flush_bytes

    def flush(self) -> list[tuple[object, dict[int, np.ndarray]]]:
        """Encode all queued ops in one batch; returns [(op_id, shards)]
        in submission order."""
        if not self._pending:
            return []
        ops, bufs = zip(*self._pending)
        self._pending, self._pending_bytes = [], 0
        batch = np.concatenate(bufs)
        shards = encode(self.sinfo, self.codec, batch)
        results = []
        cs, sw = self.sinfo.chunk_size, self.sinfo.stripe_width
        off = 0  # in chunk units per shard
        for op_id, buf in zip(ops, bufs):
            nchunk = len(buf) // sw * cs
            results.append((op_id, {
                i: v[off:off + nchunk] for i, v in shards.items()}))
            off += nchunk
        return results
