"""Whole-stack continuous profiler — sampled Python flamegraphs with
stage attribution.

PR 6's gap report attributes the ~1000x daemon->engine gap to STAGES
(``commit_wait`` 38%, ``engine_stage_wait`` 28%, device compute 0.1%)
but cannot say which CODE inside a stage burns the time: the stage
timeline names intervals, not functions. This module is the missing
half — an in-process, low-overhead stack-sampling profiler that runs
continuously across every daemon thread (they share one process here,
the vstart model), so ROADMAP item 1's fan-out rewrite is aimed by
measurement instead of guesswork. "Understanding System
Characteristics of Online Erasure Coding" (PAPERS.md) is the prior:
EC hot-path pathologies are CPU-side and emergent under load —
exactly what an always-on sampler catches and a microbenchmark
misses.

Design:

- A sampler thread walks ``sys._current_frames()`` at a configurable
  rate (``profiler_hz``, default 50) and folds each thread's stack
  into flamegraph "folded" form (``frame;frame;frame``). Aggregation
  is FIXED MEMORY: at most ``profiler_max_stacks`` distinct folded
  stacks are kept; overflow samples still count (under a sentinel
  key) and are reported as ``dropped_stacks``.
- Wall vs CPU split per thread: each sweep reads every thread's
  CPU clock (``pthread_getcpuclockid``); a sample whose thread
  advanced its CPU time since the previous sweep is an on-CPU
  sample. Where the platform lacks the clock the split degrades to
  wall-only (never an error).
- **The stage join** (the key move): daemon hot loops mark the stage
  that owns the thread via :func:`push_stage`/:func:`pop_stage`
  (plain dict writes — allocation-free, always on, nanoseconds), so
  a sample lands attributed to the PR-6 stage vocabulary: the
  messenger loop is ``wire``, an op-wq worker is ``pg_process`` (or
  ``commit_wait`` for engine continuations), the engine thread is
  ``engine_stage_wait``/``device_finalize``, the mgr tick is
  ``mgr_tick``. Threads with no explicit region fall back to a
  module classifier (leaf-to-root walk for the first frame whose
  file maps to a known subsystem), so attribution stays high even
  for threads nobody instrumented.

OFF is the default and costs NOTHING: no sampler thread exists, no
sample objects are allocated (mirrors the tracing layer's zero-Spans
contract); the region marks daemons always perform are single dict
stores. ON at 50 Hz measures < 5% overhead on the cluster bench
quick run (BASELINE.md "Profiling the data plane" records the
number).

Export: ``profile start/stop/dump/flame/status`` on every daemon's
admin socket (the profiler is process-wide, like the device
registry), ``/api/profile`` + a dashboard panel, ``profiler_*``
PerfCounters (prometheus + flight recorder for free), and
``tools/gap_report.py --profile`` joining hot frames under the
stage-attribution table. ``tools/flame.py`` renders folded output.
"""

from __future__ import annotations

import sys
import threading
import time

from ceph_tpu.utils.perf_counters import PerfCounters, collection

#: thread ident -> the stage that owns the thread right now (the
#: sampler joins on this; writers use push_stage/pop_stage)
_thread_stage: dict[int, str] = {}

#: sample of a thread in no marked region and no classifiable frame
UNATTRIBUTED = "(unattributed)"

#: sentinel folded-stack key once the fixed-memory table is full
OVERFLOW_KEY = "[stack-table-full]"

#: frames deeper than this truncate (bounds the folded-key size)
_MAX_DEPTH = 48


def push_stage(stage: str) -> str | None:
    """Mark the calling thread as owned by ``stage``; returns the
    previous owner for :func:`pop_stage`. One dict store — safe to
    leave in hot paths with the profiler off."""
    ident = threading.get_ident()
    prev = _thread_stage.get(ident)
    _thread_stage[ident] = stage
    return prev


def pop_stage(prev: str | None) -> None:
    """Restore the previous owner saved by :func:`push_stage`."""
    ident = threading.get_ident()
    if prev is None:
        _thread_stage.pop(ident, None)
    else:
        _thread_stage[ident] = prev


#: file-substring -> stage bucket, tried leaf-to-root when no region
#: is marked. Canonical EC-write stage names where a subsystem maps
#: onto one; own labels otherwise (they group their own rows).
_CLASSIFY = (
    ("parallel/messenger", "wire"),
    ("parallel/messages", "wire"),
    ("utils/msgr_telemetry", "wire"),
    ("osd/device_engine", "engine_stage_wait"),
    ("osd/scrub_engine", "scrub"),
    ("osd/", "pg_process"),
    ("client/", "objecter_encode"),
    ("tools/rados_cli", "objecter_encode"),
    ("parallel/mon", "mon_tick"),
    ("parallel/auth", "mon_tick"),
    ("parallel/osdmap", "mon_tick"),
    ("parallel/crush", "pg_process"),
    ("mgr/", "mgr_tick"),
    ("store/", "store_commit"),
    ("ops/", "device_compute"),
    ("models/", "device_compute"),
    ("parallel/", "device_compute"),
    ("bench/", "bench_driver"),
    ("qa/", "bench_driver"),
    ("tests/", "bench_driver"),
    ("services/", "services"),
    ("ceph_tpu", "other"),
)


def _classify(files: list[str]) -> str:
    """Leaf-to-root: the first frame whose file maps to a known
    subsystem names the stage; stacks entirely outside the repo
    (pure stdlib threads) stay unattributed."""
    for fname in files:
        if "ceph_tpu" not in fname and "/repo/" not in fname:
            continue
        for needle, stage in _CLASSIFY:
            if needle in fname:
                return stage
    return UNATTRIBUTED


class StackProfiler:
    """One per process (the daemons share the process, so the sample
    tables are process-wide like the device registry). Construction
    is cheap and spawns NOTHING; only :meth:`start` creates the
    sampler thread."""

    def __init__(self, hz: float | None = None,
                 max_stacks: int | None = None) -> None:
        from ceph_tpu.utils.config import g_conf
        self._lock = threading.Lock()
        self.hz = float(hz if hz is not None
                        else g_conf()["profiler_hz"])
        if hz is None:
            # tuner-managed knob (ISSUE 13): a runtime profiler_hz
            # push retunes a RUNNING sampler — the loop re-derives
            # its interval from self.hz every sweep. An explicit hz
            # argument pins the rate for this profiler's lifetime.
            try:
                g_conf().add_observer("profiler_hz", self._on_hz)
            except Exception:
                pass
        self.max_stacks = int(max_stacks if max_stacks is not None
                              else g_conf()["profiler_max_stacks"])
        perf = collection().get("profiler")
        if perf is None:
            perf = collection().create("profiler")
            self._declare(perf)
        self.perf = perf
        self._thread: threading.Thread | None = None
        self._stop_ev = threading.Event()
        #: (stage, folded) -> [wall_samples, cpu_samples]
        self._stacks: dict[tuple[str, str], list[int]] = {}
        #: ident -> {"name", "wall", "cpu", "cpu_s", "_clk", "_last"}
        self._threads: dict[int, dict] = {}
        self._samples = 0
        self._cpu_samples = 0
        self._dropped = 0
        self._t_start = 0.0
        self._elapsed = 0.0

    @staticmethod
    def _declare(perf: PerfCounters) -> None:
        perf.add_u64_counter("profile_samples",
                             "thread-stack samples taken")
        perf.add_u64_counter("profile_cpu_samples",
                             "samples whose thread was on-CPU "
                             "(thread CPU clock advanced)")
        perf.add_u64_counter("profile_dropped_stacks",
                             "samples folded into the overflow "
                             "bucket (fixed-memory cap hit)")
        perf.add_u64_counter("profile_sweeps",
                             "sampler sweeps over all threads")
        perf.add_gauge("profile_running", "1 while sampling")
        perf.add_gauge("profile_hz", "configured sampling rate")
        perf.add_gauge("profile_unique_stacks",
                       "distinct folded stacks held (bounded)")
        perf.add_time_avg("profile_sweep_time",
                          "seconds per sampler sweep (the overhead "
                          "numerator: sweep_time.sum / elapsed)")

    def _on_hz(self, _name: str, value) -> None:
        with self._lock:
            self.hz = float(value)
        if self.running:
            self.perf.set_gauge("profile_hz", self.hz)

    # -- lifecycle ----------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self, hz: float | None = None) -> bool:
        """Start sampling (idempotent); returns whether a sampler was
        newly started."""
        with self._lock:
            if self.running:
                return False
            if hz:
                self.hz = float(hz)
            self._stop_ev.clear()
            self._t_start = time.monotonic()
            self.perf.set_gauge("profile_running", 1)
            self.perf.set_gauge("profile_hz", self.hz)
            self._thread = threading.Thread(
                target=self._run, name="py-profiler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> bool:
        """Stop sampling (idempotent); aggregated tables are kept for
        dump/flame until reset()."""
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop_ev.set()
        if thread is not None:
            thread.join(timeout=2)
        self.perf.set_gauge("profile_running", 0)
        with self._lock:
            if self._t_start:
                self._elapsed += time.monotonic() - self._t_start
                self._t_start = 0.0
        return thread is not None

    def reset(self) -> None:
        """Drop the aggregated tables (counters stay cumulative —
        they are process counters like every other registry)."""
        with self._lock:
            self._stacks.clear()
            self._threads.clear()
            self._samples = 0
            self._cpu_samples = 0
            self._dropped = 0
            self._elapsed = 0.0
            self._published = (0, 0, 0)
            if self._t_start:
                self._t_start = time.monotonic()
            self.perf.set_gauge("profile_unique_stacks", 0)

    # -- the sampler thread -------------------------------------------
    def _run(self) -> None:
        my_ident = threading.get_ident()
        # interval re-derives from self.hz each sweep so a runtime
        # profiler_hz push (the tuner's observability lever) retunes
        # a live sampler without a restart
        while not self._stop_ev.wait(1.0 / max(self.hz, 0.1)):
            t0 = time.perf_counter()
            try:
                self._sweep(my_ident)
            except Exception:
                pass               # a sweep fault must not kill the loop
            self.perf.tinc("profile_sweep_time",
                           time.perf_counter() - t0)
            self.perf.inc("profile_sweeps")

    def _thread_names(self) -> dict[int, str]:
        return {t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}

    def _cpu_advanced(self, ident: int, ent: dict) -> bool:
        """Did ``ident`` burn CPU since its last sweep? Uses the
        per-thread CPU clock; degrades to False (wall-only split)
        when the platform lacks it or the thread died."""
        clk = ent.get("_clk")
        if clk is False:          # probed before: clock unavailable
            return False
        try:
            if clk is None:
                clk = ent["_clk"] = time.pthread_getcpuclockid(ident)
            now = time.clock_gettime(clk)
        except (OSError, AttributeError, OverflowError):
            ent["_clk"] = False
            return False
        last = ent.get("_last")
        ent["_last"] = now
        if last is None:
            return False
        dt = now - last
        if dt > 0:
            ent["cpu_s"] += dt
        # any measurable CPU progress marks the sample on-CPU (a
        # thread parked in a lock/select advances by ~0)
        return dt > 1e-5

    def _sweep(self, my_ident: int) -> None:
        frames = sys._current_frames()
        names = self._thread_names()
        with self._lock:
            for ident, frame in frames.items():
                if ident == my_ident:
                    continue
                parts: list[str] = []
                files: list[str] = []
                depth = 0
                f = frame
                while f is not None and depth < _MAX_DEPTH:
                    code = f.f_code
                    parts.append(f"{_short(code.co_filename)}:"
                                 f"{code.co_name}")
                    files.append(code.co_filename)
                    f = f.f_back
                    depth += 1
                folded = ";".join(reversed(parts))
                stage = _thread_stage.get(ident) or _classify(files)
                ent = self._threads.get(ident)
                if ent is None:
                    ent = self._threads[ident] = {
                        "name": names.get(ident, f"tid-{ident}"),
                        "wall": 0, "cpu": 0, "cpu_s": 0.0}
                on_cpu = self._cpu_advanced(ident, ent)
                ent["wall"] += 1
                self._samples += 1
                key = (stage, folded)
                rec = self._stacks.get(key)
                if rec is None:
                    if len(self._stacks) >= self.max_stacks:
                        self._dropped += 1
                        key = (stage, OVERFLOW_KEY)
                        rec = self._stacks.get(key)
                        if rec is None:
                            rec = self._stacks[key] = [0, 0]
                    else:
                        rec = self._stacks[key] = [0, 0]
                rec[0] += 1
                if on_cpu:
                    rec[1] += 1
                    ent["cpu"] += 1
                    self._cpu_samples += 1
            n_unique = len(self._stacks)
            n_new = self._samples
            n_cpu = self._cpu_samples
            n_drop = self._dropped
        # prune stage marks left by dead threads (a worker that
        # exited inside a marked region): only idents we previously
        # sampled AND that no longer run are pruned, so a freshly
        # pushed mark from a thread born mid-sweep survives
        for ident in [i for i in list(_thread_stage)
                      if i not in frames and i in self._threads]:
            _thread_stage.pop(ident, None)
        # counters outside the table lock (they have their own)
        self.perf.set_gauge("profile_unique_stacks", n_unique)
        # set-to-absolute via inc deltas is racy across sweeps; the
        # sampler is the only writer, so plain incs per sweep are
        # exact — track deltas
        self._publish(n_new, n_cpu, n_drop)

    _published = (0, 0, 0)

    def _publish(self, samples: int, cpu: int, dropped: int) -> None:
        with self._lock:
            ps, pc, pd = self._published
            self._published = (samples, cpu, dropped)
        if samples > ps:
            self.perf.inc("profile_samples", samples - ps)
        if cpu > pc:
            self.perf.inc("profile_cpu_samples", cpu - pc)
        if dropped > pd:
            self.perf.inc("profile_dropped_stacks", dropped - pd)

    # -- views --------------------------------------------------------
    def elapsed(self) -> float:
        dt = self._elapsed
        if self._t_start:
            dt += time.monotonic() - self._t_start
        return dt

    def dump(self) -> dict:
        """JSON-able aggregate: totals, per-thread wall/CPU split,
        per-stage sample shares, attribution quality."""
        with self._lock:
            stacks = {k: list(v) for k, v in self._stacks.items()}
            threads = {i: {k: v for k, v in ent.items()
                           if not k.startswith("_")}
                       for i, ent in self._threads.items()}
            samples, cpu = self._samples, self._cpu_samples
            dropped = self._dropped
        by_stage: dict[str, dict] = {}
        for (stage, _folded), (w, c) in stacks.items():
            ent = by_stage.setdefault(stage,
                                      {"samples": 0, "cpu_samples": 0})
            ent["samples"] += w
            ent["cpu_samples"] += c
        hz = max(self.hz, 0.1)
        for ent in by_stage.values():
            ent["est_s"] = round(ent["samples"] / hz, 3)
        attributed = sum(ent["samples"]
                         for stage, ent in by_stage.items()
                         if stage != UNATTRIBUTED)
        return {
            "running": self.running,
            "hz": self.hz,
            "elapsed_s": round(self.elapsed(), 3),
            "samples": samples,
            "cpu_samples": cpu,
            "unique_stacks": len(stacks),
            "max_stacks": self.max_stacks,
            "dropped_stacks": dropped,
            "attributed_pct": round(100.0 * attributed / samples, 1)
            if samples else 0.0,
            "by_stage": dict(sorted(
                by_stage.items(),
                key=lambda kv: -kv[1]["samples"])),
            "threads": {ent["name"]: {
                "wall_samples": ent["wall"],
                "cpu_samples": ent["cpu"],
                "cpu_s": round(ent["cpu_s"], 4)}
                for ent in threads.values()},
        }

    def folded(self, cpu_only: bool = False) -> str:
        """Flamegraph folded format, one line per distinct stack:
        ``stage;frame;frame;frame count``. The stage is the root
        frame, so any flamegraph renderer groups by stage for free
        (tools/flame.py reads this)."""
        with self._lock:
            stacks = {k: list(v) for k, v in self._stacks.items()}
        lines = []
        for (stage, folded), (w, c) in sorted(
                stacks.items(), key=lambda kv: -kv[1][0]):
            n = c if cpu_only else w
            if n <= 0:
                continue
            lines.append(f"{stage};{folded} {n}")
        return "\n".join(lines)

    def top_frames(self, n: int = 10, cpu_only: bool = False
                   ) -> dict[str, list[dict]]:
        """Per-stage top-N hot frames by SELF (leaf-frame) samples —
        the gap report's join payload."""
        with self._lock:
            stacks = {k: list(v) for k, v in self._stacks.items()}
        agg: dict[str, dict[str, int]] = {}
        totals: dict[str, int] = {}
        for (stage, folded), (w, c) in stacks.items():
            count = c if cpu_only else w
            if count <= 0:
                continue
            leaf = folded.rsplit(";", 1)[-1]
            per = agg.setdefault(stage, {})
            per[leaf] = per.get(leaf, 0) + count
            totals[stage] = totals.get(stage, 0) + count
        out: dict[str, list[dict]] = {}
        for stage, per in agg.items():
            total = max(totals[stage], 1)
            out[stage] = [
                {"frame": frame, "samples": count,
                 "pct": round(100.0 * count / total, 1)}
                for frame, count in sorted(per.items(),
                                           key=lambda kv: -kv[1])[:n]]
        return out

    def status(self) -> dict:
        """The brief: running/hz/samples/overhead (asok ``profile
        status``, dashboard)."""
        sweep = self.perf.get("profile_sweep_time")
        elapsed = self.elapsed()
        overhead_pct = round(100.0 * sweep["sum"] / elapsed, 2) \
            if elapsed > 0 else 0.0
        with self._lock:
            samples, cpu = self._samples, self._cpu_samples
            unique, dropped = len(self._stacks), self._dropped
        return {"running": self.running, "hz": self.hz,
                "elapsed_s": round(elapsed, 3),
                "samples": samples, "cpu_samples": cpu,
                "unique_stacks": unique,
                "dropped_stacks": dropped,
                "sampler_overhead_pct": overhead_pct}


def _short(filename: str) -> str:
    """``.../ceph_tpu/osd/osd.py`` -> ``osd/osd.py`` (folded keys
    must stay readable and small)."""
    idx = filename.rfind("ceph_tpu/")
    if idx >= 0:
        return filename[idx + len("ceph_tpu/"):]
    return filename.rsplit("/", 1)[-1]


_module_lock = threading.Lock()
_profiler: StackProfiler | None = None


def profiler() -> StackProfiler:
    """The process-wide profiler (lazy: nothing exists until first
    use, and nothing SAMPLES until start())."""
    global _profiler
    with _module_lock:
        if _profiler is None:
            _profiler = StackProfiler()
        return _profiler


def profiler_if_exists() -> StackProfiler | None:
    """Zero-allocation peek (the OFF-cost contract: asking whether a
    profiler exists must not create one)."""
    return _profiler


def reset_for_tests() -> None:
    global _profiler
    with _module_lock:
        if _profiler is not None:
            _profiler.stop()
        collection().remove("profiler")
        _profiler = None
    _thread_stage.clear()


def register_asok(asok) -> None:
    """``profile start/stop/dump/flame/status`` on every daemon. The
    profiler is process-wide (daemons share the process), so any
    daemon's socket drives the same sampler — same contract as
    ``device perf dump``."""
    asok.register_command(
        "profile start",
        lambda a: (profiler().start(hz=a.get("hz")),
                   profiler().status())[1],
        "start the stack-sampling profiler ({hz} optional)")
    asok.register_command(
        "profile stop",
        lambda a: (profiler().stop(), profiler().status())[1],
        "stop the profiler (aggregates kept for dump/flame)")
    asok.register_command(
        "profile dump", lambda a: profiler().dump(),
        "sampled-stack aggregate: per-stage shares, wall/CPU split, "
        "attribution")
    asok.register_command(
        "profile flame",
        lambda a: {"folded": profiler().folded(
            cpu_only=bool(a.get("cpu")))},
        "flamegraph folded stacks (render with tools/flame.py)")
    asok.register_command(
        "profile status", lambda a: profiler().status(),
        "profiler brief: running/hz/samples/overhead")
