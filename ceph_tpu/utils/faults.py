"""faults — the process-wide, deterministically seeded fault registry.

The qa suites' scattered injection knobs (``ms_inject_socket_failures``,
``store.inject_data_error``, messenger ``blocked_peers``) each spoke a
private dialect, none was schedulable mid-run, and none could answer
"what fired, in what order?" after the fact. This module is the one
API the chaos harness, MiniCluster, the load generator, and tests
drive (the teuthology Thrasher + ``ms inject`` yamls role, unified):

- **Scoped rules** (:meth:`FaultRegistry.add`): each rule names a fault
  ``kind`` plus a match scope and firing policy —

  =================  ==================================================
  ``msgr_drop``      silently drop matching outbound/inbound frames
                     (the socket-failure / partition-window role)
  ``msgr_delay``     hold a matching frame ``delay_s`` before the wire
                     (congestion / slow-link windows)
  ``store_eio``      a matching store read answers EIO
                     (bluestore_debug_inject_read_err role)
  ``store_latency``  a matching store read stalls ``delay_s``
                     (a dying disk's long tail)
  ``engine_launch``  the device engine's next matching encode flush
                     launch raises (rides the existing failure-drain
                     path; ECBackend re-encodes on the host twin)
  ``engine_decode``  same for a signature-batched decode flush
  =================  ==================================================

  Scope fields: ``entity`` (sender, e.g. ``"osd.1"`` or ``"osd.*"``),
  ``peer`` (dest addr or entity), ``msg_type``, ``cid_prefix`` /
  ``oid_prefix`` for stores. Policy: ``p`` (probability), ``every``
  (every Nth match), ``max_fires``, ``delay_s``.

- **Determinism contract**: firing decisions are a pure function of
  ``(registry seed, rule id, per-rule match counter)`` — a stateless
  crc32-derived hash, NOT a shared RNG stream — so the i-th match of a
  rule decides identically across runs regardless of thread
  interleaving. Same seed + same rules + same match sequence => same
  fault sequence (pinned by tests/test_faults.py).

- **Schedule** (:meth:`schedule`): timed/op-counted actions
  (``kill_osd``, ``revive_osd``, arm-a-rule) the load generator pops
  via :meth:`pop_due` and executes against its MiniCluster — fault
  timing expressed in the workload's own clock.

- **Accounting**: every fire lands in the ``faults`` PerfCounters
  (prometheus + the ``fault status`` asok dump, test_counter_schema
  lint) and in a bounded event log (:meth:`fired`) for after-the-fact
  sequence comparison.

The hooks are free when idle: each hook is gated on a plain attribute
check against empty rule lists, no locks taken.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from collections import deque

from ceph_tpu.utils.dout import Dout
from ceph_tpu.utils.perf_counters import collection

log = Dout("faults")

MSGR_KINDS = ("msgr_drop", "msgr_delay")
STORE_KINDS = ("store_eio", "store_latency")
ENGINE_KINDS = ("engine_launch", "engine_decode")
KINDS = MSGR_KINDS + STORE_KINDS + ENGINE_KINDS

_EVENT_LOG_MAX = 4096

#: msg-type FAMILIES: a rule naming the singleton sub-write types also
#: matches their batched twins (ISSUE 9 — a chaos rule written against
#: MECSubWrite/MECSubWriteReply must keep biting when the bulk-ingest
#: path ships the same payload as one MECSubWriteBatch per peer, so a
#: dropped/delayed batch degrades exactly like N dropped singletons)
_MSG_TYPE_FAMILY = {
    30: (30, 67),     # MECSubWrite -> + MECSubWriteBatch
    31: (31, 68),     # MECSubWriteReply -> + MECSubWriteBatchReply
    # ISSUE 15: the streaming objecter's batched client frames — a
    # rule on MOSDOp/MOSDOpReply keeps biting when the client leg
    # coalesces the same writes into one MOSDOpBatch per (pool, PG),
    # so a dropped batched submit degrades exactly like N singleton
    # drops (and recovers the same way: per-op singleton resends)
    20: (20, 69),     # MOSDOp -> + MOSDOpBatch
    21: (21, 70),     # MOSDOpReply -> + MOSDOpReplyBatch
}


def _msg_type_matches(rule_type: int, msg_type: int) -> bool:
    return msg_type in _MSG_TYPE_FAMILY.get(rule_type, (rule_type,))


class InjectedFault(RuntimeError):
    """Raised for injected engine faults (flows down the engine's
    existing failure-drain / host-fallback path)."""


def _hash01(seed: int, rule_id: int, n: int) -> float:
    """Deterministic per-(rule, match-index) uniform in [0, 1): the
    decision function the determinism contract rests on. A full
    avalanche mixer (splitmix-style) — NOT a crc, whose linearity
    turns a seed change into a constant xor that can leave the
    compared low bits untouched."""
    x = (seed * 0x9E3779B9 + rule_id * 0x85EBCA6B
         + n * 0xC2B2AE35 + 0x5BF03635) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x / float(1 << 32)


class Rule:
    """One scoped fault rule. Matching is cheap string/prefix work;
    the fire decision is the stateless hash above."""

    __slots__ = ("rule_id", "kind", "entity", "peer", "msg_type",
                 "cid_prefix", "oid_prefix", "p", "every", "max_fires",
                 "delay_s", "fires", "matches", "_registry", "active")

    def __init__(self, rule_id: int, kind: str, *, entity: str = "*",
                 peer: str = "*", msg_type: int | None = None,
                 cid_prefix: str = "", oid_prefix: str = "",
                 p: float = 1.0, every: int | None = None,
                 max_fires: int | None = None, delay_s: float = 0.0,
                 registry: "FaultRegistry | None" = None) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.rule_id = rule_id
        self.kind = kind
        self.entity = entity
        self.peer = peer
        self.msg_type = msg_type
        self.cid_prefix = cid_prefix
        self.oid_prefix = oid_prefix
        self.p = p
        self.every = every
        self.max_fires = max_fires
        self.delay_s = delay_s
        self.fires = 0
        self.matches = 0
        self.active = True
        self._registry = registry

    def remove(self) -> None:
        if self._registry is not None:
            self._registry.remove(self)

    def _decide(self, seed: int) -> bool:
        """One match arrived: count it and decide (caller holds the
        registry lock). The decision for match #n is a pure function
        of (seed, rule_id, n)."""
        if not self.active:
            return False
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        n = self.matches
        self.matches += 1
        if self.every is not None:
            fire = (n % self.every) == self.every - 1
        else:
            fire = self.p >= 1.0 or _hash01(seed, self.rule_id, n) < self.p
        if fire:
            self.fires += 1
        return fire

    def describe(self) -> dict:
        return {"id": self.rule_id, "kind": self.kind,
                "entity": self.entity, "peer": self.peer,
                "msg_type": self.msg_type,
                "cid_prefix": self.cid_prefix,
                "oid_prefix": self.oid_prefix, "p": self.p,
                "every": self.every, "max_fires": self.max_fires,
                "delay_s": self.delay_s, "matches": self.matches,
                "fires": self.fires, "active": self.active}


def _match_name(pattern: str, name: str) -> bool:
    if pattern == "*" or pattern == name:
        return True
    return fnmatch.fnmatchcase(name, pattern)


class FaultRegistry:
    """Process-wide rule set + schedule + accounting. One instance per
    process through :func:`registry`; tests may build private ones."""

    def __init__(self, seed: int = 0, perf=None) -> None:
        self._lock = threading.Lock()
        self._seed = seed
        self._next_id = 1
        # split by hook family so the hot hooks gate on one attribute
        self._msgr_rules: list[Rule] = []
        self._store_rules: list[Rule] = []
        self._engine_rules: list[Rule] = []
        self._schedule: list[dict] = []
        self._events: deque = deque(maxlen=_EVENT_LOG_MAX)
        #: monotonic fire counter (NOT len(_events) — the bounded
        #: deque plateaus): the tracer's per-op fault-window probe
        #: compares this across a root span's lifetime
        self._fires_total = 0
        self._perf = perf

    # -- configuration ------------------------------------------------
    @property
    def seed(self) -> int:
        return self._seed

    def reseed(self, seed: int) -> None:
        """Set the decision seed and clear rules/schedule/log — the
        'fresh deterministic run' entry point."""
        with self._lock:
            self._seed = seed
            self._msgr_rules = []
            self._store_rules = []
            self._engine_rules = []
            self._schedule = []
            self._events.clear()

    def add(self, kind: str, **kw) -> Rule:
        with self._lock:
            rule = Rule(self._next_id, kind, registry=self, **kw)
            self._next_id += 1
            if kind in MSGR_KINDS:
                self._msgr_rules = self._msgr_rules + [rule]
            elif kind in STORE_KINDS:
                self._store_rules = self._store_rules + [rule]
            else:
                self._engine_rules = self._engine_rules + [rule]
        if self._perf is not None:
            self._perf.set_gauge("fault_rules", self.rule_count())
        return rule

    def remove(self, rule: Rule) -> None:
        with self._lock:
            rule.active = False
            self._msgr_rules = [r for r in self._msgr_rules
                                if r is not rule]
            self._store_rules = [r for r in self._store_rules
                                 if r is not rule]
            self._engine_rules = [r for r in self._engine_rules
                                  if r is not rule]
        if self._perf is not None:
            self._perf.set_gauge("fault_rules", self.rule_count())

    def rule_count(self) -> int:
        with self._lock:
            return (len(self._msgr_rules) + len(self._store_rules)
                    + len(self._engine_rules))

    def clear(self) -> None:
        self.reseed(self._seed)

    # -- accounting ---------------------------------------------------
    def _note(self, rule: Rule | None, kind: str, detail: str) -> None:
        with self._lock:
            self._fires_total += 1
            self._events.append(
                {"rule": rule.rule_id if rule else 0, "kind": kind,
                 "detail": detail,
                 "n": rule.fires if rule else 0})
        if self._perf is not None:
            self._perf.inc("faults_fired")
            key = f"faults_{kind}"
            try:
                self._perf.inc(key)
            except KeyError:
                pass
        log(5, f"fault fired: {kind} {detail}")

    def fired(self) -> list[dict]:
        """The bounded fire log, oldest first — the sequence two runs
        with the same seed + schedule compare for reproducibility."""
        with self._lock:
            return list(self._events)

    def describe(self) -> dict:
        with self._lock:
            rules = (self._msgr_rules + self._store_rules
                     + self._engine_rules)
            return {"seed": self._seed,
                    "rules": [r.describe() for r in rules],
                    "schedule": [dict(s) for s in self._schedule],
                    "fired": len(self._events)}

    # -- hooks (hot paths; free when no rules) ------------------------
    def message_fault(self, entity: str, peer: str, msg_type: int
                      ) -> tuple[bool, float]:
        """Outbound/inbound frame check: returns (drop, delay_s).
        Called from the messenger send path and the receive loop."""
        if not self._msgr_rules:
            return False, 0.0
        drop, delay = False, 0.0
        with self._lock:
            for rule in self._msgr_rules:
                if rule.msg_type is not None and \
                        not _msg_type_matches(rule.msg_type, msg_type):
                    continue
                if not _match_name(rule.entity, entity):
                    continue
                if not _match_name(rule.peer, peer):
                    continue
                if not rule._decide(self._seed):
                    continue
                if rule.kind == "msgr_drop":
                    drop = True
                else:
                    delay = max(delay, rule.delay_s)
                fired = rule
                self._fires_total += 1
                self._events.append(
                    {"rule": fired.rule_id, "kind": fired.kind,
                     "detail": f"{entity}->{peer} type={msg_type}",
                     "n": fired.fires})
        if drop or delay:
            if self._perf is not None:
                self._perf.inc("faults_fired")
                if drop:
                    self._perf.inc("faults_msgr_drop")
                if delay:
                    self._perf.inc("faults_msgr_delay")
        return drop, delay

    def store_read_fault(self, cid: str, oid: str
                         ) -> tuple[bool, float]:
        """Store read check: returns (eio, delay_s). The store sleeps
        the delay then raises its own EIOError when eio is set."""
        if not self._store_rules:
            return False, 0.0
        eio, delay = False, 0.0
        with self._lock:
            for rule in self._store_rules:
                if rule.cid_prefix and not cid.startswith(
                        rule.cid_prefix):
                    continue
                if rule.oid_prefix and not oid.startswith(
                        rule.oid_prefix):
                    continue
                if not rule._decide(self._seed):
                    continue
                if rule.kind == "store_eio":
                    eio = True
                else:
                    delay = max(delay, rule.delay_s)
                self._fires_total += 1
                self._events.append(
                    {"rule": rule.rule_id, "kind": rule.kind,
                     "detail": f"{cid}/{oid}", "n": rule.fires})
        if eio or delay:
            if self._perf is not None:
                self._perf.inc("faults_fired")
                if eio:
                    self._perf.inc("faults_store_eio")
                if delay:
                    self._perf.inc("faults_store_latency")
        return eio, delay

    def engine_fault(self, point: str) -> None:
        """Device-engine launch check (``point`` is ``"launch"`` for
        encode flushes, ``"decode"`` for decode flushes): raises
        InjectedFault when a matching rule fires — the engine's
        existing error paths turn that into a host fallback."""
        if not self._engine_rules:
            return
        kind = "engine_launch" if point == "launch" else "engine_decode"
        fired = None
        with self._lock:
            for rule in self._engine_rules:
                if rule.kind != kind:
                    continue
                if rule._decide(self._seed):
                    fired = rule
                    self._fires_total += 1
                    self._events.append(
                        {"rule": rule.rule_id, "kind": rule.kind,
                         "detail": point, "n": rule.fires})
                    break
        if fired is not None:
            if self._perf is not None:
                self._perf.inc("faults_fired")
                self._perf.inc(f"faults_{kind}")
            raise InjectedFault(
                f"injected {kind} fault (rule {fired.rule_id})")

    # -- action schedule ----------------------------------------------
    def schedule(self, action: str, *, at_s: float | None = None,
                 at_ops: int | None = None, **kw) -> dict:
        """Queue a timed/op-counted action for the workload driver
        (load_gen) to pop and execute: ``kill_osd``, ``revive_osd``,
        or anything the driver maps. Exactly one of ``at_s``
        (workload-elapsed seconds) / ``at_ops`` (completed-op count)
        must be given."""
        if (at_s is None) == (at_ops is None):
            raise ValueError("exactly one of at_s/at_ops required")
        ent = {"action": action, "at_s": at_s, "at_ops": at_ops,
               "done": False, **kw}
        with self._lock:
            self._schedule.append(ent)
        return ent

    def pop_due(self, elapsed_s: float, ops_done: int) -> list[dict]:
        """Actions whose trigger has passed and that have not fired
        yet; marks them fired and logs them (the driver executes)."""
        due = []
        with self._lock:
            for ent in self._schedule:
                if ent["done"]:
                    continue
                trig = ent["at_s"] is not None and \
                    elapsed_s >= ent["at_s"] or \
                    ent["at_ops"] is not None and ops_done >= ent["at_ops"]
                if trig:
                    ent["done"] = True
                    due.append(dict(ent))
                    self._fires_total += 1
                    self._events.append(
                        {"rule": 0, "kind": "action",
                         "detail": ent["action"],
                         "n": ent["at_ops"] if ent["at_ops"]
                         is not None else ent["at_s"]})
        if due and self._perf is not None:
            self._perf.inc("faults_fired", len(due))
            self._perf.inc("faults_actions", len(due))
        return due

    def note_action(self, action: str, detail: str = "") -> None:
        """Record an externally-executed fault action (MiniCluster's
        kill_osd/revive_osd land here) so the event log is the one
        place the whole fault sequence can be read back from."""
        self._note(None, "action", f"{action} {detail}".strip())
        if self._perf is not None:
            self._perf.inc("faults_actions")


# -- process-wide singleton --------------------------------------------

_lock = threading.Lock()
_registry: FaultRegistry | None = None


def _make_perf():
    perf = collection().get("faults")
    if perf is None:
        perf = collection().create("faults")
        perf.add_gauge("fault_rules", "scoped fault rules installed")
        perf.add_u64_counter("faults_fired",
                             "total injected-fault fires (all kinds)")
        perf.add_u64_counter("faults_msgr_drop",
                             "frames dropped by injection")
        perf.add_u64_counter("faults_msgr_delay",
                             "frames delayed by injection")
        perf.add_u64_counter("faults_store_eio",
                             "store reads answered injected EIO")
        perf.add_u64_counter("faults_store_latency",
                             "store reads stalled by injection")
        perf.add_u64_counter("faults_engine_launch",
                             "device encode launches failed by "
                             "injection")
        perf.add_u64_counter("faults_engine_decode",
                             "device decode flushes failed by "
                             "injection")
        perf.add_u64_counter("faults_actions",
                             "scheduled/driver fault actions executed "
                             "(osd kill/revive etc.)")
    return perf


def registry() -> FaultRegistry:
    """The process-wide registry (lazily created; counters attach to
    the global PerfCounters collection exactly once)."""
    global _registry
    with _lock:
        if _registry is None:
            _registry = FaultRegistry(perf=_make_perf())
        return _registry


def registry_if_exists() -> FaultRegistry | None:
    """The registry ONLY if something already created it — probes
    (autopsies, tracer fault windows) must not allocate one."""
    return _registry


def fire_count() -> int:
    """Monotonic total of fault fires (0 when no registry exists).
    The tracer samples this at root-span open and again at the tail
    decision: a delta means a fault fired inside the op's window."""
    reg = _registry
    if reg is None:
        return 0
    return reg._fires_total


def reset_for_tests(seed: int = 0) -> FaultRegistry:
    reg = registry()
    reg.reseed(seed)
    return reg


# -- module-level hook shims (importers stay one call away) ------------

def message_fault(entity: str, peer: str, msg_type: int
                  ) -> tuple[bool, float]:
    reg = _registry
    if reg is None or not reg._msgr_rules:
        return False, 0.0
    return reg.message_fault(entity, peer, msg_type)


def msgr_rules_active() -> bool:
    """Cheap probe for the messenger's loopback gate: while ANY msgr
    chaos rule is installed, in-process sends take the full TCP path,
    so drop/delay windows keep their exact wire semantics."""
    reg = _registry
    return reg is not None and bool(reg._msgr_rules)


def store_read_fault(cid: str, oid: str) -> tuple[bool, float]:
    reg = _registry
    if reg is None or not reg._store_rules:
        return False, 0.0
    return reg.store_read_fault(cid, oid)


def check_store_read(cid: str, oid: str) -> bool:
    """Convenience for stores: sleeps an injected latency inline and
    returns True when the read must answer EIO."""
    eio, delay = store_read_fault(cid, oid)
    if delay > 0:
        time.sleep(delay)
    return eio


def engine_fault(point: str) -> None:
    reg = _registry
    if reg is None or not reg._engine_rules:
        return
    reg.engine_fault(point)


def register_asok(asok) -> None:
    """``fault status`` on every daemon: rules, schedule, fire counts
    (the counters key mirrors the other registries' asok contract so
    the schema lint can hold it to the same bar)."""

    def _status(_args: dict) -> dict:
        reg = registry()
        out = reg.describe()
        out["counters"] = _make_perf().dump()
        out["recent"] = reg.fired()[-50:]
        return out

    asok.register_command(
        "fault status", _status,
        "fault-injection registry: rules, schedule, fire log")
