"""Sharded EC pipeline tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

from ceph_tpu.ops import gf256
from ceph_tpu.parallel import mesh as mesh_mod
from ceph_tpu.parallel import sharded_codec


@pytest.fixture(scope="module")
def mesh():
    import jax
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return mesh_mod.make_mesh(8)


def test_mesh_shape(mesh):
    assert mesh.shape["stripe"] * mesh.shape["shard"] == 8


def test_distributed_encode_matches_reference(mesh):
    k, m = 8, 3
    S, C = mesh.shape["stripe"] * 2, mesh.shape["shard"] * 64
    coding = gf256.rs_vandermonde_matrix(k, m)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(S, k, C), dtype=np.uint8)

    step = sharded_codec.make_encode_step(mesh, coding)
    chunks, csum = step(sharded_codec.shard_stripe_batch(mesh, data))
    chunks = np.asarray(chunks)

    n_shard = mesh.shape["shard"]
    c_l = C // n_shard
    for s in range(S):
        want_parity = gf256.gf_matvec_chunks(coding, data[s])
        got = chunks[s, k:]  # parity after the ppermute placement shift
        # undo the ring shift: local block b of output came from block b-1
        unshifted = np.concatenate(
            [got[:, ((b - 1) % n_shard) * c_l:((b - 1) % n_shard + 1) * c_l]
             for b in range(n_shard)], axis=1)
        # got block b holds parity computed on block b-1's bytes
        restored = np.zeros_like(got)
        for b in range(n_shard):
            src = (b - 1) % n_shard
            restored[:, src * c_l:(src + 1) * c_l] = \
                got[:, b * c_l:(b + 1) * c_l]
        assert np.array_equal(restored, want_parity), s
        assert np.array_equal(chunks[s, :k], data[s])
    del unshifted
    # checksum: byte sums per chunk position over whole batch
    want_csum = np.zeros(k + m, dtype=np.uint64)
    want_csum[:k] = data.astype(np.uint64).sum(axis=(0, 2))
    assert np.array_equal(np.asarray(csum)[:k].astype(np.uint64), want_csum[:k])


def test_distributed_degraded_read(mesh):
    k, m = 4, 2
    S, C = 2, mesh.shape["shard"] * 32
    coding = gf256.rs_vandermonde_matrix(k, m)
    gen = gf256.systematic_generator(coding)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(S, k, C), dtype=np.uint8)
    all_chunks = np.stack(
        [np.concatenate([d, gf256.gf_matvec_chunks(coding, d)]) for d in data])

    lost = [1, 4]
    present = [0, 2, 3, 5]
    surv = all_chunks[:, present]
    step = sharded_codec.make_degraded_read_step(mesh, gen, present, lost)
    rec, full = step(sharded_codec.shard_stripe_batch(mesh, surv))
    assert np.array_equal(np.asarray(rec), all_chunks[:, lost])
    assert np.array_equal(np.asarray(full), all_chunks[:, lost])
