"""More in-OSD object classes mirroring reference cls modules.

Reduction note shared by all of these: the reference keeps this state
in xattrs/omap alongside arbitrary object data (src/cls/*/cls_*.cc);
here the object's body IS the JSON state, matching the framework's
method contract (see ceph_tpu/cls/__init__.py). Semantics — error
codes, conditional checks, removal-on-last-ref — follow the reference
files cited per class.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.cls import REMOVE, register


def _state(obj: bytes | None, default):
    if not obj:
        return default
    try:
        return json.loads(obj)
    except ValueError:
        return default


# -- cls_version (src/cls/version/cls_version.cc): object version
# tracking with conditional checks --------------------------------------

@register("version", "set")
def _version_set(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {"ver": 0, "tag": ""})
    st["ver"] = int(req["ver"])
    st["tag"] = str(req.get("tag", st["tag"]))
    return 0, b"", json.dumps(st).encode()


@register("version", "inc")
def _version_inc(inp: bytes, obj: bytes | None):
    st = _state(obj, {"ver": 0, "tag": ""})
    st["ver"] += 1
    return 0, b"", json.dumps(st).encode()


@register("version", "read")
def _version_read(inp: bytes, obj: bytes | None):
    st = _state(obj, {"ver": 0, "tag": ""})
    return 0, json.dumps(st).encode(), None


@register("version", "check")
def _version_check(inp: bytes, obj: bytes | None):
    """input: {"ver": N, "op": "eq"|"gt"|"ge"} — -ECANCELED on
    mismatch (the reference's VER_COND checks)."""
    req = json.loads(inp)
    st = _state(obj, {"ver": 0, "tag": ""})
    have, want = st["ver"], int(req["ver"])
    ok = {"eq": have == want, "gt": have > want,
          "ge": have >= want}.get(req.get("op", "eq"), False)
    return (0 if ok else -125), b"", None     # -ECANCELED


# -- cls_refcount (src/cls/refcount/cls_refcount.cc): tagged
# references; the object disappears with its last ref ------------------

@register("refcount", "get")
def _refcount_get(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {"refs": []})
    tag = str(req["tag"])
    if tag not in st["refs"]:
        st["refs"].append(tag)
    return 0, b"", json.dumps(st).encode()


@register("refcount", "put")
def _refcount_put(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {"refs": []})
    tag = str(req["tag"])
    if tag in st["refs"]:
        st["refs"].remove(tag)
    elif st["refs"]:
        return -2, b"", None                  # unknown tag, refs live
    if not st["refs"]:
        # last reference dropped: the object goes away
        # (cls_rc_refcount_put -> cls_cxx_remove)
        return 0, b"", REMOVE
    return 0, b"", json.dumps(st).encode()


@register("refcount", "read")
def _refcount_read(inp: bytes, obj: bytes | None):
    st = _state(obj, {"refs": []})
    return 0, json.dumps(sorted(st["refs"])).encode(), None


@register("refcount", "set")
def _refcount_set(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    return 0, b"", json.dumps(
        {"refs": sorted(set(map(str, req["refs"])))}).encode()


# -- cls_numops (src/cls/numops/cls_numops.cc): server-side numeric
# read-modify-write ----------------------------------------------------

@register("numops", "add")
def _numops_add(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {})
    key, diff = str(req["key"]), float(req["value"])
    cur = float(st.get(key, 0))
    st[key] = cur + diff
    return 0, json.dumps({key: st[key]}).encode(), \
        json.dumps(st).encode()


@register("numops", "max")
def _numops_max(inp: bytes, obj: bytes | None):
    """Raise the counter to at least ``value`` (Lamport receive rule:
    a replicated event's origin sequence must never be re-minted
    locally). Returns the resulting value."""
    req = json.loads(inp)
    st = _state(obj, {})
    key, floor = str(req["key"]), float(req["value"])
    cur = float(st.get(key, 0))
    if floor <= cur:
        return 0, json.dumps({key: cur}).encode(), None
    st[key] = floor
    return 0, json.dumps({key: floor}).encode(), \
        json.dumps(st).encode()


@register("numops", "mul")
def _numops_mul(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {})
    key, f = str(req["key"]), float(req["value"])
    cur = float(st.get(key, 0))
    st[key] = cur * f
    return 0, json.dumps({key: st[key]}).encode(), \
        json.dumps(st).encode()


# -- cls_timeindex (src/cls/timeindex/cls_timeindex.cc): entries
# indexed by timestamp, range-listed and trimmed ------------------------

@register("timeindex", "add")
def _timeindex_add(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    entries = _state(obj, [])
    entries.append({"ts": float(req.get("ts", time.time())),
                    "key": str(req.get("key", "")),
                    "value": req.get("value", "")})
    entries.sort(key=lambda e: (e["ts"], e["key"]))
    return 0, b"", json.dumps(entries).encode()


@register("timeindex", "list")
def _timeindex_list(inp: bytes, obj: bytes | None):
    req = json.loads(inp) if inp else {}
    entries = _state(obj, [])
    lo = float(req.get("from", 0))
    hi = float(req.get("to", float("inf")))
    out = [e for e in entries if lo <= e["ts"] < hi]
    n = int(req.get("max_entries", len(out)))
    return 0, json.dumps(out[:n]).encode(), None


@register("timeindex", "trim")
def _timeindex_trim(inp: bytes, obj: bytes | None):
    req = json.loads(inp) if inp else {}
    entries = _state(obj, [])
    hi = float(req.get("to", 0))
    keep = [e for e in entries if e["ts"] >= hi]
    if len(keep) == len(entries):
        return -61, b"", None                 # -ENODATA: nothing cut
    return 0, b"", json.dumps(keep).encode()


# -- cls_statelog (src/cls/statelog/cls_statelog.cc): per-(client,
# op) state entries ----------------------------------------------------

@register("statelog", "add")
def _statelog_add(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {})
    key = f"{req['client']}/{req['op_id']}"
    st[key] = {"object": req.get("object", ""),
               "state": req["state"], "ts": time.time()}
    return 0, b"", json.dumps(st).encode()


@register("statelog", "list")
def _statelog_list(inp: bytes, obj: bytes | None):
    req = json.loads(inp) if inp else {}
    st = _state(obj, {})
    client = req.get("client")
    out = {k: v for k, v in st.items()
           if client is None or k.startswith(f"{client}/")}
    return 0, json.dumps(out).encode(), None


@register("statelog", "remove")
def _statelog_remove(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {})
    key = f"{req['client']}/{req['op_id']}"
    if key not in st:
        return -2, b"", None
    del st[key]
    return 0, b"", json.dumps(st).encode()


# -- cls_hello (src/cls/hello/cls_hello.cc): the reference's example
# class — kept because its tests exercise every framework seam ---------

@register("hello", "say_hello")
def _hello_say(inp: bytes, obj: bytes | None):
    who = inp.decode() or "world"
    return 0, f"Hello, {who}!".encode(), None


@register("hello", "record_hello")
def _hello_record(inp: bytes, obj: bytes | None):
    if obj is not None:
        return -17, b"", None                 # -EEXIST, as reference
    who = inp.decode() or "world"
    return 0, b"", f"Hello, {who}!".encode()


@register("hello", "replay")
def _hello_replay(inp: bytes, obj: bytes | None):
    if obj is None:
        return -2, b"", None
    return 0, bytes(obj), None


# -- cls_rbd (src/cls/rbd/cls_rbd.cc): image header + directory
# management. The directory methods are what make concurrent clients
# safe: image create/remove/rename mutate the shared rbd_directory
# ATOMICALLY in-OSD instead of a client-side read-modify-write --------

@register("rbd", "dir_add_image")
def _rbd_dir_add(inp: bytes, obj: bytes | None):
    """input: {"name", "meta"} -> -EEXIST when present."""
    req = json.loads(inp)
    d = _state(obj, {})
    if req["name"] in d:
        return -17, b"", None
    d[req["name"]] = req.get("meta", {})
    return 0, b"", json.dumps(d, sort_keys=True).encode()


@register("rbd", "dir_remove_image")
def _rbd_dir_remove(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    d = _state(obj, {})
    if req["name"] not in d:
        return -2, b"", None
    del d[req["name"]]
    return 0, b"", json.dumps(d, sort_keys=True).encode()


@register("rbd", "dir_rename_image")
def _rbd_dir_rename(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    d = _state(obj, {})
    if req["src"] not in d:
        return -2, b"", None
    if req["dst"] in d:
        return -17, b"", None
    d[req["dst"]] = d.pop(req["src"])
    return 0, b"", json.dumps(d, sort_keys=True).encode()


@register("rbd", "dir_update_image")
def _rbd_dir_update(inp: bytes, obj: bytes | None):
    """Merge metadata keys into an existing entry (size bumps)."""
    req = json.loads(inp)
    d = _state(obj, {})
    ent = d.get(req["name"])
    if ent is None:
        return -2, b"", None
    ent.update(req.get("meta", {}))
    return 0, b"", json.dumps(d, sort_keys=True).encode()


@register("rbd", "dir_list")
def _rbd_dir_list(inp: bytes, obj: bytes | None):
    return 0, json.dumps(_state(obj, {}), sort_keys=True).encode(), \
        None


# -- cls_user (src/cls/user/cls_user.cc): per-user bucket accounting
# for rgw (the user's bucket list + usage header) ----------------------

@register("user", "add_bucket")
def _user_add_bucket(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {"buckets": {}, "stats": {"count": 0, "bytes": 0}})
    b = st["buckets"].setdefault(
        req["bucket"], {"count": 0, "bytes": 0})
    b["count"] += int(req.get("count", 0))
    b["bytes"] += int(req.get("bytes", 0))
    st["stats"]["count"] = sum(x["count"]
                               for x in st["buckets"].values())
    st["stats"]["bytes"] = sum(x["bytes"]
                               for x in st["buckets"].values())
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("user", "remove_bucket")
def _user_remove_bucket(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {"buckets": {}, "stats": {"count": 0, "bytes": 0}})
    if st["buckets"].pop(req["bucket"], None) is None:
        return -2, b"", None
    st["stats"]["count"] = sum(x["count"]
                               for x in st["buckets"].values())
    st["stats"]["bytes"] = sum(x["bytes"]
                               for x in st["buckets"].values())
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("user", "get_header")
def _user_get_header(inp: bytes, obj: bytes | None):
    st = _state(obj, {"buckets": {}, "stats": {"count": 0, "bytes": 0}})
    return 0, json.dumps(
        {"stats": st["stats"],
         "buckets": sorted(st["buckets"])}).encode(), None


# -- cls_cas (src/cls/cas/cls_cas.cc): content-addressed chunk
# refcounting — a dedup chunk object lives while references exist ------

@register("cas", "chunk_create_or_get_ref")
def _cas_get_ref(inp: bytes, obj: bytes | None):
    """input: {"source"}: take a reference on this chunk (creating
    the ref set on first use)."""
    req = json.loads(inp)
    st = _state(obj, {"refs": []})
    if req["source"] not in st["refs"]:
        st["refs"].append(req["source"])
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("cas", "chunk_put_ref")
def _cas_put_ref(inp: bytes, obj: bytes | None):
    """Drop a reference; the LAST one removes the chunk object."""
    req = json.loads(inp)
    st = _state(obj, {"refs": []})
    if req["source"] not in st["refs"]:
        return -2, b"", None
    st["refs"].remove(req["source"])
    if not st["refs"]:
        return 0, b"", REMOVE
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("cas", "references")
def _cas_refs(inp: bytes, obj: bytes | None):
    return 0, json.dumps(_state(obj, {"refs": []})).encode(), None


# -- cls_otp (src/cls/otp/cls_otp.cc): server-side TOTP secrets; the
# check runs IN the OSD so the secret never leaves it -------------------

def _totp(secret_hex: str, t: int, step: int = 30,
          digits: int = 6) -> str:
    import hashlib
    import hmac as _hmac
    counter = int(t // step).to_bytes(8, "big")
    mac = _hmac.new(bytes.fromhex(secret_hex), counter,
                    hashlib.sha1).digest()
    off = mac[-1] & 0xF
    code = (int.from_bytes(mac[off:off + 4], "big") & 0x7FFFFFFF) \
        % (10 ** digits)
    return f"{code:0{digits}d}"


@register("otp", "create")
def _otp_create(inp: bytes, obj: bytes | None):
    """input: {"id", "secret" (hex), "step"?, "digits"?}."""
    req = json.loads(inp)
    st = _state(obj, {})
    if req["id"] in st:
        return -17, b"", None
    st[req["id"]] = {"secret": req["secret"],
                     "step": int(req.get("step", 30)),
                     "digits": int(req.get("digits", 6))}
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("otp", "remove")
def _otp_remove(inp: bytes, obj: bytes | None):
    req = json.loads(inp)
    st = _state(obj, {})
    if st.pop(req["id"], None) is None:
        return -2, b"", None
    return 0, b"", json.dumps(st, sort_keys=True).encode()


@register("otp", "check")
def _otp_check(inp: bytes, obj: bytes | None):
    """input: {"id", "token", "t"}: verify with a ±1-step window (the
    reference tolerates clock skew the same way)."""
    req = json.loads(inp)
    st = _state(obj, {})
    ent = st.get(req["id"])
    if ent is None:
        return -2, b"", None
    t = float(req["t"])
    # tolerate integer tokens: '12345' must match code '012345'
    token = str(req["token"]).zfill(ent["digits"])
    ok = any(_totp(ent["secret"], t + d * ent["step"], ent["step"],
                   ent["digits"]) == token
             for d in (-1, 0, 1))
    return 0, json.dumps({"ok": ok}).encode(), None
