"""ceph_tpu — a TPU-native erasure-coded storage framework.

A from-scratch, TPU-first framework with the capabilities of Ceph
(reference: nautilus-dev snapshot). The erasure-coding hot path
(Reed-Solomon / SHEC / LRC / Clay encode/decode) runs as batched
GF(2^8) bit-sliced matrix multiplies on the TPU MXU via JAX/XLA,
behind a plugin boundary semantically equivalent to Ceph's
``ErasureCodeInterface`` / ``ErasureCodePluginRegistry``
(reference: src/erasure-code/ErasureCodeInterface.h:155-464,
src/erasure-code/ErasureCodePlugin.h:31-79).

Layers (bottom-up, mirroring SURVEY.md §1):
  - ``ceph_tpu.utils``     — buffers, config, perf counters, logging, checksums
  - ``ceph_tpu.ops``       — GF(2^8) math core, JAX/Pallas kernels, native C++ fallbacks
  - ``ceph_tpu.models``    — erasure-code codec plugins (the "model zoo")
  - ``ceph_tpu.parallel``  — device meshes, sharded codecs, messenger, CRUSH, mon
  - ``ceph_tpu.store``     — local object stores (MemStore, BlockStore)
  - ``ceph_tpu.osd``       — stripe engine + EC backend write/read/recovery pipeline
"""

__version__ = "0.1.0"
