"""mgr plane: balancer (upmap), progress, telemetry.

Mirrors the reference's mgr module roles (src/pybind/mgr/{balancer,
progress,telemetry}) and the OSDMap pg_upmap_items mechanics the
balancer drives (OSDMap::calc_pg_upmaps / osd pg-upmap-items)."""

import json
import os
import time

import pytest

from ceph_tpu.parallel import crush
from ceph_tpu.parallel.osdmap import OSDMap
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils.admin_socket import asok_command
from ceph_tpu.utils.config import g_conf


def make_map(n_osds: int = 5, pg_num: int = 32, size: int = 2) -> OSDMap:
    m = OSDMap()
    m.crush.add_bucket("default", "root")
    for i in range(n_osds):
        host = f"host{i}"
        m.crush.add_bucket(host, "host", parent="default")
        m.crush.add_device(i, host)
        m.add_osd(i)
        m.mark_up(i, f"127.0.0.1:{7000 + i}")
    m.crush.add_rule(crush.Rule("data", "default", "host", "firstn"))
    m.create_pool("p", pg_num, "data", size=size, min_size=1)
    m.epoch = 1
    return m


def test_pg_upmap_items_remaps_up_set():
    m = make_map()
    pid = m.pool_by_name["p"]
    ps = 0
    up, _, _ = m.pg_to_up_acting(pid, ps)
    target = next(o for o in m.osds if o not in up)
    m.pg_upmap_items[(pid, ps)] = [(up[0], target)]
    up2, acting2, _ = m.pg_to_up_acting(pid, ps)
    assert up2 == [target] + up[1:]
    assert acting2 == up2
    # a down target is ignored (the PG falls back to raw CRUSH)
    m.mark_down(target)
    up3, _, _ = m.pg_to_up_acting(pid, ps)
    assert up3 == up
    # wire roundtrip carries upmaps (v2 field)
    m2 = OSDMap.decode(m.encode())
    assert m2.pg_upmap_items == m.pg_upmap_items


class _FakeMgr:
    """Just enough Mgr surface for module unit tests."""

    def __init__(self, osdmap):
        self.osdmap = osdmap
        self.mon_addr = "127.0.0.1:1"
        self.commands = []

    def get_osdmap(self):
        return self.osdmap

    def get_status(self):
        return {"health": "HEALTH_OK", "pgmap": {"degraded_pgs": 0}}

    def mon_command(self, **cmd):
        self.commands.append(cmd)
        # apply like the mon would — including its validation, so any
        # planner/mon semantic divergence fails the test
        key = (int(cmd["pool"]), int(cmd["ps"]))
        pairs = [(int(f), int(t)) for f, t in json.loads(cmd["items"])]
        err = self.osdmap.validate_upmap_items(key[0], key[1], pairs)
        if err is not None:
            return err[0], err[1], b""
        self.osdmap.pg_upmap_items[key] = pairs
        return 0, "ok", b""


def test_balancer_reduces_spread():
    from ceph_tpu.mgr import balancer
    m = make_map(n_osds=5, pg_num=32, size=2)
    mgr = _FakeMgr(m)
    mod = balancer.Module(mgr)
    before = mod.eval()
    assert before["osds"] == 5
    plan = mod.optimize(max_optimizations=64)
    assert plan, f"no plan though spread={before['spread']}"
    code, msg = mod.execute(plan)
    assert code == 0, msg
    after = mod.eval()
    assert after["spread"] < before["spread"], (before, after)
    # moves respected the host failure domain: no duplicate hosts per PG
    pid = m.pool_by_name["p"]
    for ps in range(32):
        up, _, _ = m.pg_to_up_acting(pid, ps)
        hosts = [balancer.Module._domain_of(m, o, "host") for o in up]
        assert len(set(hosts)) == len(hosts), (ps, up)


def test_telemetry_report_shape():
    from ceph_tpu.mgr import telemetry
    mod = telemetry.Module(_FakeMgr(make_map()))
    report = mod.compile_report()
    assert report["osd"]["count"] == 5
    assert report["pools"][0]["type"] == "replicated"
    assert len(report["cluster_id"]) == 16
    code, _, data = mod.handle_command({"prefix": "show"})
    assert code == 0 and json.loads(data)["report_version"] == 1
    # send is gated on opt-in
    code, msg, _ = mod.handle_command({"prefix": "send"})
    assert code != 0


def test_progress_tracks_degraded_episode():
    from ceph_tpu.mgr import progress
    mgr = _FakeMgr(make_map())
    mod = progress.Module(mgr)
    mgr.get_status = lambda: {"pgmap": {"degraded_pgs": 4}}
    mod.tick()
    assert mod.events["recovery"]["baseline"] == 4
    mgr.get_status = lambda: {"pgmap": {"degraded_pgs": 1}}
    mod.tick()
    assert mod.events["recovery"]["progress"] == pytest.approx(0.75)
    mgr.get_status = lambda: {"pgmap": {"degraded_pgs": 0}}
    mod.tick()
    assert "recovery" not in mod.events
    assert mod.completed and mod.completed[-1]["progress"] == 1.0


def test_mgr_daemon_in_cluster():
    """Full plane: mgr daemon against a live cluster; balancer moves
    PGs via mon commands and data stays readable after backfill."""
    with MiniCluster(n_osds=4) as c:
        rados = c.client()
        c.create_pool("bal", pg_num=16, size=2)
        io = rados.open_ioctx("bal")
        blobs = {f"o{i}": os.urandom(16_000) for i in range(12)}
        for o, b in blobs.items():
            io.write_full(o, b)
        mgr = c.start_mgr()
        # telemetry over the asok (the 'ceph daemon mgr.x ...' path)
        out = asok_command(mgr.asok.path, "telemetry show")
        assert out["code"] == 0
        assert out["data"]["osd"]["count"] == 4
        # balancer: optimize + execute through the mon
        out = asok_command(mgr.asok.path, "balancer eval")
        before = out["data"]["spread"]
        out = asok_command(mgr.asok.path, "balancer optimize", max="32")
        plan = out["data"]
        if plan:  # a 4-osd/16-pg map is usually imbalanced, not always
            out = asok_command(mgr.asok.path, "balancer execute")
            assert out["code"] == 0, out
            epoch = c.epoch()
            rados.wait_for_epoch(epoch, timeout=10)
            c.wait_for_clean(timeout=30)
            out = asok_command(mgr.asok.path, "balancer eval")
            assert out["data"]["spread"] <= before
            dump = json.loads(c.mon_cmd(prefix="osd dump")[2])
            assert dump["pg_upmap_items"]
            # SECOND round must also validate: the command replaces a
            # PG's whole pair list, so re-sent pairs must be accepted
            # (regression: validating against the post-upmap set made
            # every second round fail with -22)
            out = asok_command(mgr.asok.path, "balancer optimize",
                               max="32")
            if out["data"]:
                out = asok_command(mgr.asok.path, "balancer execute")
                assert out["code"] == 0, out
                c.wait_for_clean(timeout=30)
        # mon rejects an upmap that collapses the up set to one osd
        pid = c.mon.osdmap.pool_by_name["bal"]
        raw = c.mon.osdmap.pg_to_raw_up(pid, 0)
        spare = next(o for o in range(4) if o not in raw)
        code, msg, _ = c.mon_cmd(
            prefix="osd pg-upmap-items", pool=str(pid), ps="0",
            items=json.dumps([[raw[0], spare], [raw[1], spare]]))
        assert code != 0 and "duplicate" in msg, (code, msg)
        for o, b in blobs.items():
            assert io.read(o) == b


def test_balancer_second_round_and_down_target():
    """Regression: plans must use the map's remap semantics (pairs with
    a down target are ignored) and must validate exactly as the mon
    does, so a second optimize round after installed upmaps — or after
    a remap target died — still converges instead of erroring."""
    from ceph_tpu.mgr import balancer
    m = make_map(n_osds=6, pg_num=32, size=2)
    mgr = _FakeMgr(m)
    mod = balancer.Module(mgr)
    for _ in range(3):                       # several rounds must apply
        plan = mod.optimize(max_optimizations=16)
        if not plan:
            break
        code, msg = mod.execute(plan)
        assert code == 0, msg
    assert mod.eval()["spread"] <= 1
    # kill a remap target: the mapping ignores its pairs; planning must
    # keep working against the surviving topology
    targets = {t for items in m.pg_upmap_items.values()
               for _, t in items}
    if targets:
        dead = sorted(targets)[0]
        m.mark_down(dead)
        plan = mod.optimize(max_optimizations=16)
        code, msg = mod.execute(plan)
        assert code == 0, msg


def test_dashboard_module_serves_cluster_state():
    """dashboard role (pybind/mgr/dashboard, reduced): HTML overview +
    JSON API over the mgr's cluster view."""
    import urllib.request
    with MiniCluster(n_osds=3) as c:
        c.create_pool("dash", pg_num=4, size=2)
        mgr = c.start_mgr()
        out = asok_command(mgr.asok.path, "dashboard on")
        assert out["code"] == 0
        st = asok_command(mgr.asok.path, "dashboard status")
        url = st["data"]["url"]
        assert st["data"]["serving"] and url
        health = json.loads(urllib.request.urlopen(
            url + "api/health", timeout=10).read())
        assert health["status"].startswith("HEALTH")
        osds = json.loads(urllib.request.urlopen(
            url + "api/osds", timeout=10).read())
        assert len(osds) == 3 and all(v["up"] for v in osds.values())
        pools = json.loads(urllib.request.urlopen(
            url + "api/pools", timeout=10).read())
        assert pools["dash"]["type"] == "replicated"
        page = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "ceph_tpu cluster" in page and "osd.0" in page
        assert asok_command(mgr.asok.path, "dashboard off")["code"] == 0
