"""GF(2^8) math core tests — field axioms, matrix gens, inversion, bitmatrix.

Mirrors the reference's per-plugin math validation (encode/decode round trips,
all-erasure sweeps — src/test/erasure-code/TestErasureCodeIsa.cc,
TestErasureCodeJerasure.cc) at the pure-math layer.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ops import bitmatrix, gf256


def test_tables_consistent():
    # exp/log roundtrip
    for a in range(1, 256):
        assert gf256.GF_EXP[gf256.GF_LOG[a]] == a
    # generator 2 has full order 255
    seen = set()
    x = 1
    for _ in range(255):
        seen.add(x)
        x = int(gf256.gf_mul(x, 2))
    assert len(seen) == 255


def test_field_axioms_sampled():
    rng = np.random.default_rng(0)
    a, b, c = rng.integers(0, 256, size=(3, 512), dtype=np.uint8)
    assert np.array_equal(gf256.gf_mul(a, b), gf256.gf_mul(b, a))
    assert np.array_equal(
        gf256.gf_mul(a, gf256.gf_mul(b, c)),
        gf256.gf_mul(gf256.gf_mul(a, b), c),
    )
    # distributivity over XOR
    assert np.array_equal(
        gf256.gf_mul(a, b ^ c), gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
    )
    # multiplicative inverse
    nz = a[a != 0]
    assert np.all(gf256.gf_mul(nz, gf256.gf_inv(nz)) == 1)


def test_poly_is_0x11d():
    # 2*128 = 256 -> reduced by 0x11d -> 0x1d
    assert int(gf256.gf_mul(2, 128)) == 0x1D


def test_invert_matrix_roundtrip():
    rng = np.random.default_rng(1)
    for n in (1, 2, 4, 8):
        while True:
            m = rng.integers(0, 256, size=(n, n), dtype=np.uint8)
            try:
                inv = gf256.invert_matrix(m)
                break
            except ValueError:
                continue
        assert np.array_equal(
            gf256.gf_matmul(m, inv), np.eye(n, dtype=np.uint8)
        )


def test_invert_singular_raises():
    m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        gf256.invert_matrix(m)


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (7, 3), (8, 3), (8, 4), (12, 4)])
def test_vandermonde_is_mds(k, m):
    """Every k-subset of generator rows must be invertible (MDS property)."""
    gen = gf256.systematic_generator(gf256.rs_vandermonde_matrix(k, m))
    for rows in itertools.combinations(range(k + m), k):
        gf256.invert_matrix(gen[list(rows)])  # raises if singular


@pytest.mark.parametrize("k,m", [(4, 2), (8, 3), (21, 4)])
def test_isa_rs_matrix_mds_within_envelope(k, m):
    """ISA Vandermonde is MDS only inside k<=32,m<=4 (m=4 => k<=21):
    reference clamps at ErasureCodeIsa.cc:330-360."""
    gen = gf256.systematic_generator(gf256.rs_matrix_isa(k, m))
    for rows in itertools.combinations(range(k + m), k):
        gf256.invert_matrix(gen[list(rows)])


@pytest.mark.parametrize(
    "k,m",
    [(4, 2), (8, 3),
     pytest.param(20, 10, marks=pytest.mark.slow)])  # ~50 s sweep
def test_cauchy_is_mds(k, m):
    gen = gf256.systematic_generator(gf256.cauchy_matrix_isa(k, m))
    rng = np.random.default_rng(2)
    combos = list(itertools.combinations(range(k + m), k))
    if len(combos) > 300:
        combos = [combos[i] for i in rng.choice(len(combos), 300, replace=False)]
    for rows in combos:
        gf256.invert_matrix(gen[list(rows)])


def test_encode_decode_roundtrip_all_erasures():
    """Full encode + decode for every 1- and 2-erasure combination (the
    reference ISA unit test 'probes all possible failure scenarios'
    — isa/README)."""
    k, m, n = 8, 3, 128
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    gen = gf256.systematic_generator(gf256.rs_vandermonde_matrix(k, m))
    chunks = np.concatenate([data, gf256.gf_matvec_chunks(gen[k:], data)], axis=0)
    all_ids = list(range(k + m))
    for r in (1, 2, 3):
        for lost in itertools.combinations(all_ids, r):
            present = [i for i in all_ids if i not in lost][: k]
            dm = gf256.decode_matrix(gen, present, list(lost))
            rec = gf256.gf_matvec_chunks(dm, chunks[present])
            assert np.array_equal(rec, chunks[list(lost)]), (lost,)


def test_bitmatrix_matches_gf_matmul():
    """The bit-sliced binary matmul must be byte-identical to the GF matmul
    (this equality is the corpus gate for the TPU kernel)."""
    rng = np.random.default_rng(4)
    for k, m in [(2, 1), (4, 2), (8, 3)]:
        mat = rng.integers(0, 256, size=(m, k), dtype=np.uint8)
        data = rng.integers(0, 256, size=(k, 256), dtype=np.uint8)
        want = gf256.gf_matvec_chunks(mat, data)
        bmat = bitmatrix.expand_bitmatrix(mat)
        got = bitmatrix.bitsliced_matvec(bmat, data)
        assert np.array_equal(want, got)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(5)
    d = rng.integers(0, 256, size=(5, 77), dtype=np.uint8)
    assert np.array_equal(bitmatrix.pack_bits(bitmatrix.unpack_bits(d)), d)
