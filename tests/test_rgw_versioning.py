"""rgw versioning + lifecycle + ACLs (src/rgw/rgw_op.cc versioned
object paths, rgw_lc.cc RGWLC::process, rgw_acl_s3.cc canned ACLs)."""

import os
import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.services.rgw import (RGWError, RGWGateway, RGWServer,
                                   sign_request)
from ceph_tpu.services.rgw_lc import LifecycleProcessor


@pytest.fixture(scope="module")
def setup():
    with MiniCluster(n_osds=3) as c:
        rados = c.client()
        c.create_pool("rgwver", pg_num=4, size=2)
        io = rados.open_ioctx("rgwver")
        srv = RGWServer(io)
        port = srv.start()
        yield io, srv.gateway, f"http://127.0.0.1:{port}"
        srv.stop()


def _req(url, method="GET", data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    return urllib.request.urlopen(req, timeout=10)


# -- versioning ---------------------------------------------------------

def test_olh_equal_seq_tie_repoints_deterministically(setup):
    """Regression (rgw.py OLH winner check): two generations with an
    EQUAL (origin seq, zone) pair used to compare by object identity
    against whatever max() returned first — on a tie the index
    repoint was silently skipped. _gen_order now tie-breaks on vid
    (a total order), and the winner check compares vids."""
    _, gw, _ = setup
    gw.create_bucket("tieb")
    gw.set_versioning("tieb", "Enabled")
    gw.put_object("tieb", "k", b"local")
    v1 = gw.last_version_id
    s1 = gw._ver_entries("tieb", "k")[v1]["oseq"][0]
    # an equal-(seq, zone) generation whose vid orders AFTER v1 wins
    # the tie and must repoint the index (the skipped-repoint bug:
    # max() returned v1's entry first and the identity check failed)
    gw.put_object("tieb", "k", b"tie-wins", version_id="vzz-tie",
                  oseq=[s1, ""])
    assert gw.get_object("tieb", "k")[0] == b"tie-wins"
    assert gw.list_objects("tieb", prefix="k")["k"]["vid"] == \
        "vzz-tie"
    # an equal pair whose vid orders BEFORE the current must NOT
    # displace it — the tie resolves the same way on every zone
    gw.put_object("tieb", "k", b"tie-loses", version_id="v-low",
                  oseq=[s1, ""])
    assert gw.get_object("tieb", "k")[0] == b"tie-wins"
    assert gw.list_objects("tieb", prefix="k")["k"]["vid"] == \
        "vzz-tie"
    # by-id access to every generation still works
    assert gw.get_object("tieb", "k", version_id=v1)[0] == b"local"
    assert gw.get_object("tieb", "k",
                         version_id="v-low")[0] == b"tie-loses"


def test_versioned_put_get_delete_cycle(setup):
    _, gw, _ = setup
    gw.create_bucket("vb")
    gw.put_object("vb", "pre", b"pre-versioning")   # null version era
    gw.set_versioning("vb", "Enabled")
    assert gw.get_versioning("vb") == "Enabled"
    gw.put_object("vb", "k", b"one")
    v1 = gw.last_version_id
    gw.put_object("vb", "k", b"two")
    v2 = gw.last_version_id
    assert v1 != v2
    # plain GET -> latest; by-id GET -> that generation
    assert gw.get_object("vb", "k")[0] == b"two"
    assert gw.get_object("vb", "k", version_id=v1)[0] == b"one"
    assert gw.get_object("vb", "k", version_id=v2)[0] == b"two"
    # delete -> marker; data retained
    marker = gw.delete_object("vb", "k")
    assert marker is not None
    with pytest.raises(RGWError) as ei:
        gw.get_object("vb", "k")
    assert ei.value.status == 404
    assert gw.get_object("vb", "k", version_id=v1)[0] == b"one"
    # removing the marker resurfaces the latest generation
    gw.delete_object("vb", "k", version_id=marker)
    assert gw.get_object("vb", "k")[0] == b"two"
    # permanently deleting the current surfaces the previous
    gw.delete_object("vb", "k", version_id=v2)
    assert gw.get_object("vb", "k")[0] == b"one"
    with pytest.raises(RGWError):
        gw.get_object("vb", "k", version_id=v2)


def test_null_version_preserved_on_enable(setup):
    """S3: the pre-versioning generation survives as version 'null'."""
    _, gw, _ = setup
    gw.put_object("vb", "pre", b"pre-versioning-2") \
        if "pre" not in gw.list_objects("vb") else None
    gw.put_object("vb", "pre", b"after-enable")
    vids = {e["vid"]: e for e in gw.list_versions("vb", prefix="pre")}
    assert "null" in vids
    assert gw.get_object("vb", "pre", version_id="null")[0] == \
        b"pre-versioning"
    assert gw.get_object("vb", "pre")[0] == b"after-enable"


def test_suspended_overwrites_null_only(setup):
    _, gw, _ = setup
    gw.create_bucket("sb")
    gw.set_versioning("sb", "Enabled")
    gw.put_object("sb", "x", b"kept")
    kept = gw.last_version_id
    gw.set_versioning("sb", "Suspended")
    gw.put_object("sb", "x", b"null-1")
    assert gw.last_version_id == "null"
    gw.put_object("sb", "x", b"null-2")
    vids = [e["vid"] for e in gw.list_versions("sb", prefix="x")]
    assert vids.count("null") == 1          # null overwritten in place
    assert gw.get_object("sb", "x")[0] == b"null-2"
    assert gw.get_object("sb", "x", version_id=kept)[0] == b"kept"


def test_versioning_over_http(setup):
    _, _, base = setup
    _req(f"{base}/hv", "PUT")
    body = (b'<VersioningConfiguration>'
            b'<Status>Enabled</Status></VersioningConfiguration>')
    _req(f"{base}/hv?versioning", "PUT", data=body)
    doc = ET.fromstring(_req(f"{base}/hv?versioning").read())
    assert doc.findtext("Status") == "Enabled"
    r = _req(f"{base}/hv/doc.txt", "PUT", data=b"v1")
    vid1 = r.headers["x-amz-version-id"]
    r = _req(f"{base}/hv/doc.txt", "PUT", data=b"v2")
    vid2 = r.headers["x-amz-version-id"]
    assert vid1 != vid2
    assert _req(f"{base}/hv/doc.txt").read() == b"v2"
    assert _req(f"{base}/hv/doc.txt?versionId={vid1}").read() == b"v1"
    # DELETE lays a marker and says so
    r = _req(f"{base}/hv/doc.txt", "DELETE")
    assert r.headers["x-amz-delete-marker"] == "true"
    marker = r.headers["x-amz-version-id"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(f"{base}/hv/doc.txt")
    assert ei.value.code == 404
    # ListObjectVersions shows both generations + the marker
    doc = ET.fromstring(_req(f"{base}/hv?versions").read())
    vids = [v.findtext("VersionId") for v in doc.findall("Version")]
    dms = [d.findtext("VersionId")
           for d in doc.findall("DeleteMarker")]
    assert set(vids) == {vid1, vid2} and dms == [marker]
    # delete the marker by id -> key resurfaces
    _req(f"{base}/hv/doc.txt?versionId={marker}", "DELETE")
    assert _req(f"{base}/hv/doc.txt").read() == b"v2"


# -- lifecycle ----------------------------------------------------------

def test_lifecycle_expires_current_and_noncurrent(setup):
    _, gw, _ = setup
    gw.create_bucket("lc")
    gw.set_versioning("lc", "Enabled")
    gw.put_object("lc", "logs/old", b"gen1")
    gw.put_object("lc", "logs/old", b"gen2")
    gw.put_object("lc", "keep/fresh", b"fresh")
    # drive the clock explicitly through process(now=...) — the
    # reference's rgw_lc_debug_interval idea without the race the old
    # 0.1 s-day + sleep() version had (one slow cluster put aged gen1
    # past BOTH thresholds before the first pass ever ran)
    proc = LifecycleProcessor(gw, day_seconds=10.0)
    gw.set_lifecycle("lc", [
        {"id": "expire-logs", "prefix": "logs/", "status": "Enabled",
         "days": 1, "noncurrent_days": 2}])
    newest = max(float(e["mtime"]) for e in
                 gw.list_versions("lc", prefix="logs/old"))
    stats = proc.process(now=newest + 15.0)   # > 1 day, < 2 days
    assert stats["expired"] == 1          # marker laid on logs/old
    with pytest.raises(RGWError):
        gw.get_object("lc", "logs/old")
    assert gw.get_object("lc", "keep/fresh")[0] == b"fresh"
    gens = [e for e in gw.list_versions("lc", prefix="logs/old")
            if not e.get("dm")]
    assert len(gens) == 2                 # data retained
    stats = proc.process(now=newest + 25.0)   # now older than 2 days
    assert stats["noncurrent_reaped"] == 2
    # the same pass sweeps the now-orphaned delete marker
    assert stats["markers_cleaned"] == 1
    assert gw.list_versions("lc", prefix="logs/old") == []
    # a quiesced pass reaps nothing more — assert on the lifecycle
    # counters only (process() also reports deferred-GC keys whose
    # exact set may grow; the r5 gc_entries/gc_objects addition broke
    # the old exact-dict assert)
    stats = proc.process(now=newest + 25.0)
    assert stats["expired"] == 0
    assert stats["noncurrent_reaped"] == 0
    assert stats["markers_cleaned"] == 0


def test_lifecycle_unversioned_deletes_for_good(setup):
    _, gw, _ = setup
    gw.create_bucket("lcu")
    gw.put_object("lcu", "tmp/a", b"x")
    gw.put_object("lcu", "data/b", b"y")
    proc = LifecycleProcessor(gw, day_seconds=0.1)
    gw.set_lifecycle("lcu", [
        {"id": "tmp", "prefix": "tmp/", "status": "Enabled",
         "days": 1}])
    time.sleep(0.12)
    stats = proc.process()
    assert stats["expired"] == 1
    assert "tmp/a" not in gw.list_objects("lcu")
    assert "data/b" in gw.list_objects("lcu")


def test_lifecycle_over_http(setup):
    _, _, base = setup
    _req(f"{base}/hlc", "PUT")
    body = (b"<LifecycleConfiguration><Rule><ID>r1</ID>"
            b"<Filter><Prefix>tmp/</Prefix></Filter>"
            b"<Status>Enabled</Status>"
            b"<Expiration><Days>30</Days></Expiration>"
            b"</Rule></LifecycleConfiguration>")
    _req(f"{base}/hlc?lifecycle", "PUT", data=body)
    doc = ET.fromstring(_req(f"{base}/hlc?lifecycle").read())
    rule = doc.find("Rule")
    assert rule.findtext("ID") == "r1"
    assert rule.find("Filter").findtext("Prefix") == "tmp/"
    assert rule.find("Expiration").findtext("Days") == "30.0"
    _req(f"{base}/hlc?lifecycle", "DELETE")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(f"{base}/hlc?lifecycle")
    assert ei.value.code == 404
    assert ET.fromstring(ei.value.read()).findtext("Code") == \
        "NoSuchLifecycleConfiguration"


# -- ACLs ---------------------------------------------------------------

@pytest.fixture(scope="module")
def authed(setup):
    io, _, _ = setup
    creds = {"OWNER": "s1", "OTHER": "s2"}
    srv = RGWServer(io, auth=creds)
    port = srv.start()
    yield srv.gateway, f"http://127.0.0.1:{port}", port, creds
    srv.stop()


def _signed(base, port, access, secret, path, method="GET", data=b"",
            query="", headers=None):
    url = f"{base}{path}" + (f"?{query}" if query else "")
    h = {"Host": f"127.0.0.1:{port}"}
    h.update(headers or {})
    h.update(sign_request(method, path, query, h, data, access,
                          secret))
    req = urllib.request.Request(url, data=data or None,
                                 method=method, headers=h)
    return urllib.request.urlopen(req, timeout=10)


def _status(fn):
    try:
        fn()
        return 200
    except urllib.error.HTTPError as exc:
        return exc.code


def test_canned_acls_enforced(authed):
    gw, base, port, creds = authed

    def owner(path, method="GET", data=b"", query="", headers=None):
        return _signed(base, port, "OWNER", "s1", path, method, data,
                       query, headers)

    def other(path, method="GET", data=b"", query="", headers=None):
        return _signed(base, port, "OTHER", "s2", path, method, data,
                       query, headers)

    owner("/private", "PUT")
    owner("/private/secret.txt", "PUT", data=b"classified")
    # owner full access; other keyholder and anonymous: denied
    assert owner("/private/secret.txt").read() == b"classified"
    assert _status(lambda: other("/private/secret.txt")) == 403
    assert _status(lambda: _req(f"{base}/private/secret.txt")) == 403
    assert _status(lambda: other("/private", "DELETE")) == 403

    # public-read: anyone reads, only the owner writes
    owner("/pub", "PUT", headers={"x-amz-acl": "public-read"})
    owner("/pub/page.html", "PUT", data=b"<html>")
    assert _req(f"{base}/pub/page.html").read() == b"<html>"
    assert other("/pub/page.html").read() == b"<html>"
    assert _status(lambda: other("/pub/x", "PUT", data=b"no")) == 403
    assert _status(
        lambda: _req(f"{base}/pub/x", "PUT", data=b"no")) == 403

    # public-read-write: any keyholder and anonymous may write
    owner("/drop", "PUT", headers={"x-amz-acl": "public-read-write"})
    other("/drop/from-other", "PUT", data=b"o")
    _req(f"{base}/drop/from-anon", "PUT", data=b"a")
    assert _req(f"{base}/drop/from-other").read() == b"o"

    # authenticated-read: any keyholder reads, anonymous does not
    owner("/ar", "PUT", headers={"x-amz-acl": "authenticated-read"})
    owner("/ar/f", "PUT", data=b"members-only")
    assert other("/ar/f").read() == b"members-only"
    assert _status(lambda: _req(f"{base}/ar/f")) == 403

    # owner-only subresources
    assert _status(lambda: other(
        "/private", "PUT",
        data=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>",
        query="versioning")) == 403
    assert _status(lambda: other("/private", "GET",
                                 query="acl")) == 403
    # object ACL override: one public object in a private bucket
    owner("/private/open.txt", "PUT", data=b"open",
          headers={"x-amz-acl": "public-read"})
    assert _req(f"{base}/private/open.txt").read() == b"open"
    assert _status(lambda: _req(f"{base}/private/secret.txt")) == 403
    # ACL document shape
    doc = ET.fromstring(owner("/pub", query="acl").read())
    assert doc.find("Owner").findtext("ID") == "OWNER"
    uris = [g.findtext("Grantee/URI")
            for g in doc.find("AccessControlList")]
    assert any(u and u.endswith("AllUsers") for u in uris)
    # anonymous bucket creation: denied
    assert _status(lambda: _req(f"{base}/anonbkt", "PUT")) == 403


def test_multipart_into_versioned_bucket(setup):
    """Multipart complete must keep the versioned data pointer and
    carry the multipart etag into the generation record."""
    _, gw, _ = setup
    gw.create_bucket("mpv")
    gw.set_versioning("mpv", "Enabled")
    up = gw.initiate_multipart("mpv", "big")
    p1 = os.urandom(1 << 20)
    p2 = os.urandom(100)
    e1 = gw.upload_part("mpv", "big", up, 1, p1)
    e2 = gw.upload_part("mpv", "big", up, 2, p2)
    etag = gw.complete_multipart("mpv", "big", up, [(1, e1), (2, e2)])
    assert etag.endswith("-2")
    data, meta = gw.get_object("mpv", "big")
    assert data == p1 + p2
    assert meta["etag"] == etag and meta.get("vid")
    gens = {e["vid"]: e for e in gw.list_versions("mpv",
                                                  prefix="big")}
    assert gens[meta["vid"]]["etag"] == etag


def test_suspended_deletes_do_not_accumulate_markers(setup):
    _, gw, _ = setup
    gw.create_bucket("sdm")
    gw.set_versioning("sdm", "Suspended")
    gw.put_object("sdm", "k", b"data")
    for _ in range(3):
        assert gw.delete_object("sdm", "k") == "null"
    vers = gw.list_versions("sdm", prefix="k")
    assert len(vers) == 1 and vers[0]["dm"] \
        and vers[0]["vid"] == "null"


def test_object_acl_survives_version_resurface(authed):
    """Deleting the current generation by id must not strip the
    resurfaced generation's object ACL back to the bucket default."""
    gw, base, port, _ = authed
    gw.create_bucket("aclver", owner="OWNER", acl="public-read")
    gw.set_versioning("aclver", "Enabled")
    gw.put_object("aclver", "k", b"gen1", acl="private",
                  owner="OWNER")
    v1 = gw.last_version_id
    gw.put_object("aclver", "k", b"gen2", acl="private",
                  owner="OWNER")
    v2 = gw.last_version_id
    assert _status(lambda: _req(f"{base}/aclver/k")) == 403
    gw.delete_object("aclver", "k", version_id=v2)
    # gen1 resurfaced — still private, despite the public-read bucket
    assert gw.get_object("aclver", "k")[0] == b"gen1"
    assert _status(lambda: _req(f"{base}/aclver/k")) == 403


def test_anonymous_denied_on_ownerless_bucket(authed):
    """An authed server never serves anonymous requests to buckets
    without ACL metadata (the pre-ACL always-signed behavior)."""
    gw, base, port, _ = authed
    gw.create_bucket("legacy")          # library API: no owner
    gw.put_object("legacy", "o", b"x")
    assert _status(lambda: _req(f"{base}/legacy/o")) == 403
    # ...but any authenticated principal still has full access
    assert _signed(base, port, "OTHER", "s2",
                   "/legacy/o").read() == b"x"


# -- multisite replication of versioned objects -------------------------

def test_multisite_replicates_versions(setup):
    io, _, _ = setup
    from ceph_tpu.services.rgw_sync import RGWSyncAgent
    src = RGWGateway(io.client.open_ioctx("rgwver"), zone_log=True)
    # second zone in its own pool
    io.client.mon_command({"prefix": "osd pool create",
                           "pool": "rgwver2", "pg_num": 4,
                           "size": 2})
    dst = RGWGateway(io.client.open_ioctx("rgwver2"))
    agent = RGWSyncAgent(src, dst)

    src.create_bucket("ms")
    src.set_versioning("ms", "Enabled")
    src.put_object("ms", "doc", b"gen-1")
    v1 = src.last_version_id
    agent.sync_once()                    # full sync of generation 1
    assert dst.get_versioning("ms") == "Enabled"
    assert dst.get_object("ms", "doc")[0] == b"gen-1"
    # incremental: new generation + delete marker, ids preserved
    src.put_object("ms", "doc", b"gen-2")
    v2 = src.last_version_id
    marker = src.delete_object("ms", "doc")
    agent.sync_once()
    dst_vers = {e["vid"]: e for e in dst.list_versions("ms",
                                                       prefix="doc")}
    assert set(dst_vers) == {v1, v2, marker}
    assert dst_vers[marker]["dm"] and dst_vers[marker]["is_current"]
    with pytest.raises(RGWError):
        dst.get_object("ms", "doc")
    assert dst.get_object("ms", "doc", version_id=v2)[0] == b"gen-2"
    # marker removal replicates; latest resurfaces in the peer zone
    src.delete_object("ms", "doc", version_id=marker)
    agent.sync_once()
    assert dst.get_object("ms", "doc")[0] == b"gen-2"


def test_gc_reaps_orphaned_tails_after_crash_mid_delete(setup):
    """r5 deferred GC (RGWGC::process, src/rgw/rgw_gc.cc:257): a
    gateway that dies mid-delete leaves striped tail pieces; the gc
    enrollment survives and the lifecycle worker's gc pass reaps
    them, space accounted."""
    from ceph_tpu.client.striper import StripedObject
    _, gw, _ = setup
    gw.create_bucket("gcb")
    payload = os.urandom(3 << 20)     # 3 pieces at 1 MiB layout
    gw.put_object("gcb", "victim", payload)
    soid = "gcb/victim"
    pieces_before = [n for n in gw.io.list_objects()
                     if n.startswith(soid + ".")]
    assert len(pieces_before) >= 2, pieces_before
    # crash mid-delete: the remove dies after the first piece
    orig_remove = StripedObject.remove
    calls = {"n": 0}

    def dying_remove(self):
        # rip out one piece, then "crash" (exception unwinds the
        # gateway delete before it de-enrolls)
        self.io.remove(self._piece(0))
        raise ConnectionError("gateway died mid-delete")

    StripedObject.remove = dying_remove
    try:
        with pytest.raises(ConnectionError):
            gw.delete_object("gcb", "victim")
    finally:
        StripedObject.remove = orig_remove
    # the enrollment survived the crash; tails still on disk
    assert soid in gw.gc_list()
    leftovers = [n for n in gw.io.list_objects()
                 if n.startswith(soid + ".")]
    assert leftovers, "crash simulation left no tails"
    # the lifecycle worker's pass reaps them (gc defer elapsed)
    time.sleep(2.1)
    proc = LifecycleProcessor(gw)
    stats = proc.process()
    assert stats["gc_entries"] == 1
    assert stats["gc_objects"] >= len(leftovers)
    assert [n for n in gw.io.list_objects()
            if n.startswith(soid + ".")] == []
    assert gw.gc_list() == {}
    # a healthy delete leaves no enrollment behind
    gw.put_object("gcb", "fine", b"x" * 100)
    gw.delete_object("gcb", "fine")
    assert gw.gc_list() == {}


def test_gc_stale_enrollment_spares_reuploaded_object(setup,
                                                      monkeypatch):
    """Regression (rgw.py gc_process generation tags): a reaper pass
    that read its pending set BEFORE a concurrent re-upload of the
    same key used to reap by untagged name-prefix — deleting the
    re-uploaded object's LIVE pieces. Each write generation now
    carries a tag (stripe meta + per-piece gc_tag xattr) recorded in
    the enrollment, and the reaper only touches matching pieces."""
    from ceph_tpu.client.striper import StripedObject
    _, gw, _ = setup
    gw.create_bucket("gcrace")
    gw.put_object("gcrace", "obj", os.urandom(2 << 20))
    soid = "gcrace/obj"
    old_tag = StripedObject(gw.io, soid).tag
    assert old_tag, "write generations must be tagged"
    # the crash-then-reupload interleaving, deterministically:
    # 1. a delete enrolls generation A and dies before removing
    #    anything (and before de-enrolling)
    gw._gc_enroll(soid, old_tag)
    # 2. the gc pass reads its pending set NOW (stale snapshot) ...
    stale = {soid: (time.time() - 3600.0, old_tag)}
    # 3. ... while the key is concurrently re-uploaded: replace
    #    semantics clear the enrollment and lay generation B's pieces
    new_payload = os.urandom(2 << 20)
    gw.put_object("gcrace", "obj", new_payload)
    new_tag = StripedObject(gw.io, soid).tag
    assert new_tag and new_tag != old_tag
    # 4. the reaper resumes with the stale snapshot: nothing of
    #    generation B may be touched
    monkeypatch.setattr(gw, "_gc_pending", lambda: stale)
    stats = gw.gc_process(grace=0)
    assert stats["entries"] == 1
    assert stats["objects"] == 0, stats   # no live piece reaped
    data, _meta = gw.get_object("gcrace", "obj")
    assert data == new_payload, \
        "stale gc enrollment reaped the re-uploaded object's pieces"
    # the guard is generation-keyed, not a blanket no-op: the SAME
    # stale entry against generation-A pieces still reaps (the
    # orphan case) — re-enroll and crash a real delete of gen B
    monkeypatch.undo()
    orig_remove = StripedObject.remove
    monkeypatch.setattr(StripedObject, "remove",
                        lambda self: (_ for _ in ()).throw(
                            ConnectionError("died mid-delete")))
    with pytest.raises(ConnectionError):
        gw.delete_object("gcrace", "obj")
    monkeypatch.setattr(StripedObject, "remove", orig_remove)
    assert soid in gw.gc_list()
    time.sleep(0.01)
    stats = gw.gc_process(grace=0)
    assert stats["objects"] > 0, stats    # the orphaned gen-B pieces
    assert [n for n in gw.io.list_objects()
            if n.startswith(soid + ".")] == []
    assert gw.gc_list() == {}
