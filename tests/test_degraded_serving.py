"""Degraded-mode serving under sustained faults (ISSUE 8).

The tier-1 end of the chaos scenario family: a MiniCluster takes an
OSD kill MID-BURST while client load runs, and the acceptance bars are
asserted exactly as the issue names them — zero lost acked writes,
zero wrong bytes, health back to HEALTH_OK after recovery, and the
batched decode-on-read route coalescing same-signature degraded reads
into fewer engine flushes than ops. The long-thrash variants (multiple
kill/revive cycles, msgr fault windows, open-loop pacing) ride tier-2
behind ``@pytest.mark.slow``.
"""

import threading
import time

import pytest

from ceph_tpu.bench.load_gen import (
    LoadGen,
    LoadSpec,
    Zipf,
    _hash01,
    payload_for,
    verify_payload,
)
from ceph_tpu.qa.cluster import MiniCluster
from ceph_tpu.utils import faults
from ceph_tpu.utils.config import g_conf


@pytest.fixture
def fast_death():
    """Tighten failure detection so kill->down takes ~1s, and hand
    every test a freshly-seeded process-wide fault registry."""
    conf = g_conf()
    old = {k: conf[k] for k in ("osd_heartbeat_interval",
                                "osd_heartbeat_grace")}
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 0.8)
    faults.reset_for_tests(seed=0)
    yield
    faults.reset_for_tests(seed=0)
    for k, v in old.items():
        conf.set(k, v)


# -- workload-model determinism (no cluster: pure functions) -----------

def test_op_stream_reproduces_per_seed():
    """The load generator's op kinds and key choices are hash-derived
    from (seed, op index): the same seed replays the same workload,
    a different seed decorrelates it — the other half of the
    reproducibility contract next to the fault registry's."""
    z = Zipf(64, 0.99)

    def stream(seed, n=200):
        return [(z.rank(_hash01(seed, "key", i)),
                 _hash01(seed, "rw", i) < 0.5) for i in range(n)]

    assert stream(7) == stream(7)
    assert stream(7) != stream(8)
    # zipf skew is real: the hottest key dominates a uniform share
    ranks = [r for r, _ in stream(7, 500)]
    assert ranks.count(0) > 500 / 64 * 3


def test_payload_verification_catches_corruption():
    data = payload_for("lg_00001", 7, 4096)
    assert verify_payload(data) == ("lg_00001", 7)
    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    with pytest.raises(ValueError):
        verify_payload(bytes(flipped))
    # a mix of two valid payloads (torn write) must not verify either
    other = payload_for("lg_00001", 8, 4096)
    torn = data[:2048] + other[2048:]
    with pytest.raises(ValueError):
        verify_payload(torn)


# -- the tier-1 chaos scenario -----------------------------------------

def test_midburst_kill_zero_lost_writes_and_health_recovers(fast_death):
    """The acceptance scenario: the fault schedule kills an OSD
    MID-BURST (at an op-count mark, while client ops are in flight),
    the full phase ladder runs under load, and afterwards every acked
    write reads back bit-exact, nothing was lost, no wrong bytes were
    ever returned, client p99 in the degraded/recovering phases stays
    inside the documented QoS bar, and health returns to HEALTH_OK."""
    with MiniCluster(n_osds=3) as cluster:
        reg = cluster.faults
        reg.reseed(11)
        victim = 2
        reg.schedule("kill_osd", at_ops=25, osd=victim)
        cluster.create_ec_pool("dg", k=2, m=1, pg_num=4)
        spec = LoadSpec(n_keys=12, obj_size=4096, read_frac=0.5,
                        concurrency=3, phase_seconds=0.8, seed=11)
        gen = LoadGen(cluster, "dg", spec)
        out = gen.run(victim_osd=victim, clean_timeout=40.0)

        # durability bars: zero lost acked writes, zero wrong bytes
        assert out["verify"]["lost_acked"] == []
        assert out["verify"]["wrong_bytes"] == []
        assert out["verify"]["corruptions"] == []
        # the burst really ran in every phase
        for ph in out["phases"]:
            assert ph["ops"] > 0, ph
        # no op errored: in-flight ops at the kill were resent and
        # completed through the degraded route
        assert sum(p["errors"] for p in out["phases"]) == 0, \
            [p["error_kinds"] for p in out["phases"]]
        # the QoS bar (degraded + recovering phases only)
        assert out["qos"]["within_bar"], out["qos"]
        # health transited and recovered
        assert out["phases"][1]["health"]["status"] != "HEALTH_OK"
        assert out["phases"][-1]["health"]["status"] == "HEALTH_OK"
        # the scheduled mid-burst kill fired exactly once, and the
        # whole fault sequence reads back from the one event log
        acts = [e for e in out["fault_log"] if e["kind"] == "action"]
        assert [a["detail"] for a in acts] == [
            "kill_osd", f"kill_osd osd.{victim}",
            f"revive_osd osd.{victim}"]
        # the degraded phase actually served reads through shard
        # reconstruction (the previously-silent counter, ISSUE 8)
        degraded = sum(o.logger.get("degraded_reads")
                       for o in cluster.osds.values())
        assert degraded > 0
        # ...and the new counters reach the prometheus exposition
        # while the daemons live (the test_counter_schema lint only
        # sees process-wide registries; the per-OSD keys are pinned
        # here where an OSD exists)
        from ceph_tpu.utils import prometheus
        text = prometheus.render_text()
        assert "ceph_tpu_degraded_reads" in text
        assert "ceph_tpu_read_retries" in text
        assert "ceph_tpu_read_retry_attempts_bucket" in text
        assert "ceph_tpu_faults_fired" in text


def test_dropped_subwrite_batch_degrades_like_singletons(fast_death):
    """Satellite (ISSUE 9): a dropped MECSubWriteBatch must retry/
    degrade exactly like N dropped MECSubWrites. The chaos rule is
    written against the SINGLETON sub-write type — the registry's
    msg-type FAMILY matching must make it bite the batch frames the
    bulk-ingest path actually ships — and the client resend ladder
    re-drives every affected write: zero lost acked writes, every
    readback byte-exact."""
    from ceph_tpu.parallel import messages as M
    conf = g_conf()
    old_resend = conf["objecter_resend_interval"]
    conf.set("objecter_resend_interval", 0.3)
    try:
        with MiniCluster(n_osds=3) as cluster:
            reg = cluster.faults
            reg.reseed(11)
            cluster.create_ec_pool("bd", k=2, m=1, pg_num=8,
                                   backend="jax")
            io = cluster.client().open_ioctx("bd")
            io.op_timeout = 60.0
            payloads = {f"bd{i}": bytes(((i * 37 + j) & 0xFF)
                                        for j in range(8192))
                        for i in range(24)}
            # warm a few writes so the drop window hits MID-burst
            for oid in list(payloads)[:4]:
                io.write_full(oid, payloads[oid])
            rule = reg.add("msgr_drop", entity="osd.*",
                           msg_type=M.MECSubWrite.MSG_TYPE,
                           every=4, max_fires=3)
            import concurrent.futures
            with concurrent.futures.ThreadPoolExecutor(4) as pool:
                list(pool.map(
                    lambda oid: io.write_full(oid, payloads[oid]),
                    list(payloads)[4:]))
            rule.remove()
            # every acked write survives, byte-exact (zero lost)
            for oid, want in payloads.items():
                assert io.read(oid) == want, f"{oid} lost or wrong"
            # the rule REALLY fired, and on batch frames: family
            # matching mapped the singleton type onto type 67
            assert rule.fires >= 1
            fired_types = [e["detail"] for e in reg.fired()
                           if e["kind"] == "msgr_drop"]
            assert any(
                f"type={M.MECSubWriteBatch.MSG_TYPE}" in d
                for d in fired_types), fired_types
    finally:
        conf.set("objecter_resend_interval", old_resend)


def test_concurrent_degraded_reads_coalesce_into_fewer_flushes(
        fast_death):
    """The batched decode-on-read pin: N concurrent degraded reads of
    same-signature objects (same survivor set, same missing set —
    the post-failure steady state) must produce FEWER engine decode
    flushes than N. The engine thread is held busy while the reads
    stage, so their reconstructs pile up in the queue and the drain
    groups them by erasure signature."""
    n_objects = 6
    with MiniCluster(n_osds=3) as cluster:
        rados = cluster.client()
        # pg_num=1: every object shares one acting set, so one dead
        # data shard degrades them all with the SAME signature
        cluster.create_ec_pool("co", k=2, m=1, pg_num=1,
                               backend="jax")
        io = rados.open_ioctx("co")
        blobs = {f"co{i}": payload_for(f"co{i}", i, 16384)
                 for i in range(n_objects)}
        for oid, blob in blobs.items():
            io.write_full(oid, blob)

        osdmap = cluster.mon.osdmap
        pool_id = osdmap.pool_by_name["co"]
        _, acting, primary = osdmap.pg_to_up_acting(pool_id, 0)
        # kill the osd holding data position 1 (never the primary):
        # every full-object read now misses chunk 1 -> one shared
        # erasure signature across all degraded reads
        victim = acting[1] if acting[1] != primary else acting[0]
        victim_pos = acting.index(victim)
        assert victim_pos < 2, "victim must hold a data chunk"
        epoch = cluster.epoch()
        cluster.kill_osd(victim)
        cluster.wait_for_osd_down(victim, timeout=30)
        rados.wait_for_epoch(epoch + 1, timeout=10)

        engine = cluster.osds[primary].device_engine()
        f0 = engine.stats["decode_flushes"]
        o0 = engine.stats["decode_ops"]

        # hold the engine on aux work while every read stages its
        # reconstruct; the queue drain then coalesces them
        holder = threading.Thread(
            target=lambda: engine.run_sync(lambda: time.sleep(0.6)),
            daemon=True)
        results: dict[str, bytes] = {}

        def read_one(oid):
            results[oid] = io.read(oid)

        holder.start()
        time.sleep(0.05)            # engine is inside the sleep
        readers = [threading.Thread(target=read_one, args=(oid,),
                                    daemon=True) for oid in blobs]
        for t in readers:
            t.start()
        for t in readers:
            t.join(timeout=30)
        holder.join(timeout=30)

        # bit-exact through the batched route
        for oid, blob in blobs.items():
            assert results.get(oid) == blob, oid
        ops_delta = engine.stats["decode_ops"] - o0
        flush_delta = engine.stats["decode_flushes"] - f0
        assert ops_delta == n_objects, (ops_delta, flush_delta)
        assert 1 <= flush_delta < n_objects, (ops_delta, flush_delta)


def test_ec_read_error_names_unreachable_shards(fast_death):
    """The terminal ECReadError diagnostic (ISSUE 8 satellite): when
    the ladder exhausts its attempts the error must name the
    unreachable shard set and their OSDs, not just a count."""
    from ceph_tpu.osd.ec_backend import ECBackend
    with MiniCluster(n_osds=3) as cluster:
        rados = cluster.client()
        cluster.create_ec_pool("er", k=2, m=1, pg_num=1)
        io = rados.open_ioctx("er")
        io.op_timeout = 30.0
        io.write_full("victim_obj", b"x" * 8192)
        # EIO every shard of the object on every store: no readable
        # set can ever assemble, the ladder must exhaust AND say who
        reg = cluster.faults
        reg.add("store_eio", oid_prefix="victim_obj")
        # drop the ladder to 2 attempts with ~ms backoff so the test
        # measures the message, not the wait
        conf = g_conf()
        old = (ECBackend.MAX_READ_ATTEMPTS,
               conf["osd_ec_read_backoff_base"],
               conf["osd_ec_read_backoff_max"])
        ECBackend.MAX_READ_ATTEMPTS = 2
        conf.set("osd_ec_read_backoff_base", 0.001)
        conf.set("osd_ec_read_backoff_max", 0.004)
        try:
            with pytest.raises(Exception) as ei:
                io.read("victim_obj")
            msg = str(ei.value)
            assert "victim_obj" in msg
            assert "attempts" in msg
            assert "shards" in msg, msg
        finally:
            ECBackend.MAX_READ_ATTEMPTS = old[0]
            conf.set("osd_ec_read_backoff_base", old[1])
            conf.set("osd_ec_read_backoff_max", old[2])


def test_backoff_sleep_is_bounded_and_jittered(fast_death):
    """The retry ladder's backoff policy: exponential from the base,
    capped, full-jittered (never synchronizing concurrent retriers
    into a storm — the pathology the online-EC study measures)."""
    from ceph_tpu.osd import ec_backend as eb
    conf = g_conf()
    conf.set("osd_ec_read_backoff_base", 0.02)
    conf.set("osd_ec_read_backoff_max", 0.5)
    slept = []

    class _Probe(eb.ECBackend):
        def __init__(self):       # no cluster needed for the policy
            pass

    orig_sleep = eb.time.sleep
    eb.time.sleep = slept.append
    try:
        probe = _Probe()
        for attempt in range(12):
            probe._backoff_sleep(attempt)
    finally:
        eb.time.sleep = orig_sleep
    for attempt, s in enumerate(slept):
        ceil = min(0.5, 0.02 * (1 << attempt))
        assert ceil * 0.5 <= s <= ceil, (attempt, s)
    # capped: deep attempts never exceed the ceiling
    assert max(slept) <= 0.5
    # jittered: not all identical once the cap dominates
    assert len({round(s, 6) for s in slept[-6:]}) > 1


# -- tier-2: sustained thrash ------------------------------------------

@pytest.mark.slow
def test_sustained_thrash_qos_and_durability(fast_death):
    """The long variant: messenger fault windows + store latency +
    TWO kill/revive cycles under open-loop zipfian load. The QoS and
    durability bars must hold across the whole run, and the engine
    must not storm (no ENGINE_STALL / SLOW_OPS in the final brief)."""
    from ceph_tpu.parallel import messages as M
    with MiniCluster(n_osds=4) as cluster:
        reg = cluster.faults
        reg.reseed(23)
        # a lossy, slow window for the whole run. Drops are scoped to
        # heartbeats (grace absorbs them); the DATA path gets delay +
        # store-latency windows — a dropped sub-write has no
        # retransmit below the client resend ladder, so blanket drops
        # measure the resend backoff (seconds), not degraded serving
        reg.add("msgr_drop", entity="osd.*", p=0.05,
                msg_type=M.MPing.MSG_TYPE)
        reg.add("msgr_delay", entity="osd.*", delay_s=0.01, p=0.05)
        reg.add("store_latency", delay_s=0.005, p=0.1)
        cluster.create_ec_pool("th", k=2, m=1, pg_num=8)
        spec = LoadSpec(n_keys=32, obj_size=8192, read_frac=0.6,
                        concurrency=4, open_loop_rate=120.0,
                        phase_seconds=2.0, seed=23)
        gen = LoadGen(cluster, "th", spec)
        out = gen.run(victim_osd=3, clean_timeout=60.0)
        assert out["verify"]["lost_acked"] == []
        assert out["verify"]["wrong_bytes"] == []
        assert out["verify"]["corruptions"] == []
        assert out["qos"]["within_bar"], out["qos"]
        final = out["phases"][-1]["health"]
        assert final["status"] == "HEALTH_OK", final
        assert "ENGINE_STALL" not in final["checks"]
        assert "SLOW_OPS" not in final["checks"]

        # second cycle on a different victim, same registry run: the
        # cluster takes sustained repeated faults, not one blip
        epoch = cluster.epoch()
        cluster.kill_osd(1)
        cluster.wait_for_osd_down(1, timeout=30)
        cluster.client().wait_for_epoch(epoch + 1, timeout=10)
        gen._run_phase("degraded2", 1.5, on_action=gen._exec_action)
        cluster.revive_osd(1)
        cluster.wait_for_osds_up(timeout=15)
        cluster.wait_for_clean(timeout=60)
        gen._run_phase("recovered2", 1.0, on_action=gen._exec_action)
        v = gen.final_verify()
        assert v["lost_acked"] == [] and v["wrong_bytes"] == []
        assert gen.phase_reports[-1]["health"]["status"] == "HEALTH_OK"
        # the msgr window really fired (and deterministically per the
        # registry contract pinned in test_faults)
        kinds = {e["kind"] for e in reg.fired()}
        assert "msgr_drop" in kinds
